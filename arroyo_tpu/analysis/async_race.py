"""arroyosan static half 1: interprocedural await-point race detector.

The bugs that have cost this repo the most were asyncio concurrency
bugs, not kernel math — the PR 3 mid-rescale disable toggle that could
strand a job in RESCALING was caught only by hand review.  This pass
automates that review: it builds a per-class field-access model over
the runtime packages and flags the two shapes that bite:

**cross-task-race** — a ``self.<field>`` mutated from two or more
*task entry points* (coroutines handed to ``asyncio.create_task`` /
``ensure_future`` / ``gather`` / ``loop.create_task``) where at least
one access sequence on the field *crosses an await* outside any
``async with`` lock.  Two tasks interleave at every await point; a
read-modify-write window spanning one is a lost-update/torn-state race
exactly like a data race under threads.

**cancel-window** — the PR 3 class: a task entry whose ``asyncio.Task``
handle is stored on the instance and ``.cancel()``-ed elsewhere in the
class, reaching (through un-``shield``-ed call edges) a method that
writes a field before an await and touches it again after.
Cancellation lands *at* the await, so the post-await access never runs
and the field is stranded mid-update — unless the await is wrapped in
``asyncio.shield`` (the call edge is then excluded), the post-await
access sits in a ``finally`` (cancellation still runs it), or a lock
serializes the window.

Facts collected per method: (entry-point reachability, field
read/write order, await points with shield/lock context, self-call
edges).  Reachability is the transitive closure of ``self.<m>()`` call
edges within the class; spawn sites anywhere in the scanned packages
nominate entry points by method name (``ensure_future(runner.start())``
marks every scoped class's async ``start`` as an entry).

Scope: ``engine/``, ``controller/``, ``autoscale/``, ``worker/``,
``network/`` — the asyncio runtime.  Ops/kernels are pure-ish batch
functions with no task concurrency and stay out.

False-positive escape: the standard inline waiver
(``# arroyolint: disable=async-race -- reason``) on the flagged write.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, call_name

PASS_ID = "async-race"

_SCOPE_RE = re.compile(
    r"(^|/)arroyo_tpu/(engine|controller|autoscale|worker|network)/"
    r"[^/]+\.py$")

_SPAWN_CALLS = {"asyncio.create_task", "asyncio.ensure_future",
                "ensure_future", "create_task"}
_LOCK_NAME_RE = re.compile(r"lock|mutex|sem", re.I)


def in_scope(path: str) -> bool:
    return bool(_SCOPE_RE.search(path.replace("\\", "/")))


# -- per-method fact extraction ---------------------------------------------

# ordered event kinds recorded while walking a method body:
#   ('read'|'write', field, line, in_finally)
#   ('await', shielded, locked, line)
@dataclass
class MethodFacts:
    name: str
    is_async: bool
    lineno: int
    events: List[tuple] = field(default_factory=list)
    # self.<m>() call edges: (callee, shield-wrapped)
    calls: List[Tuple[str, bool]] = field(default_factory=list)
    # self-methods spawned as tasks from this method
    spawns_self: Set[str] = field(default_factory=set)
    # self.<f> fields assigned a spawn result: field -> entry method
    task_fields: Dict[str, str] = field(default_factory=dict)
    # self.<f>.cancel() targets
    cancels: Set[str] = field(default_factory=set)

    def fields_written(self) -> Set[str]:
        return {e[1] for e in self.events if e[0] == "write"}

    def fields_read(self) -> Set[str]:
        return {e[1] for e in self.events if e[0] == "read"}


def _spawned_methods(call: ast.Call) -> List[Tuple[bool, str]]:
    """For ``create_task/ensure_future/gather`` spawn sites, every
    coroutine-factory method being spawned: (receiver_is_self, name).
    ``gather`` takes several coroutines — all are task entries."""
    name = call_name(call)
    base = name.split(".")[-1]
    if base not in ("create_task", "ensure_future", "gather"):
        return []
    out: List[Tuple[bool, str]] = []
    for arg in call.args:
        if isinstance(arg, ast.Call) and isinstance(arg.func,
                                                    ast.Attribute):
            recv_self = (isinstance(arg.func.value, ast.Name)
                         and arg.func.value.id == "self")
            out.append((recv_self, arg.func.attr))
    return out


class _MethodScan(ast.NodeVisitor):
    """One method body -> ordered access/await events + call edges.

    Nested function defs are skipped (they are separate coroutines /
    executor helpers); ``async with`` on a lock-ish context raises the
    lock depth; ``asyncio.shield(...)`` marks both the await point and
    the call edges under it."""

    def __init__(self, facts: MethodFacts):
        self.f = facts
        self.lock_depth = 0
        self.shield_depth = 0
        self.finally_depth = 0

    # -- structure ---------------------------------------------------------

    def visit_FunctionDef(self, node):  # nested defs: separate coroutines
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        lockish = any(
            _LOCK_NAME_RE.search(ast.unparse(item.context_expr))
            for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        # __aenter__ suspends either way: a lock acquisition awaits
        # *locked* (while held, no peer enters the same section); any
        # other async context (streams, sessions) is a genuine await
        # point that opens a race/cancellation window
        self.f.events.append(
            ("await", self.shield_depth > 0,
             lockish or self.lock_depth > 0, node.lineno))
        if lockish:
            self.lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            self.lock_depth -= 1
            # __aexit__ releases and suspends again, outside the lock
            self.f.events.append(
                ("await", self.shield_depth > 0, self.lock_depth > 0,
                 node.lineno))

    def visit_Try(self, node: ast.Try) -> None:
        for stmt in node.body:
            self.visit(stmt)
        for handler in node.handlers:
            self.visit(handler)
        for stmt in node.orelse:
            self.visit(stmt)
        self.finally_depth += 1
        for stmt in node.finalbody:
            self.visit(stmt)
        self.finally_depth -= 1

    # -- events ------------------------------------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        shielded = self.shield_depth > 0
        inner = node.value
        if isinstance(inner, ast.Call) \
                and call_name(inner).endswith("shield"):
            shielded = True
            self.shield_depth += 1
            self.generic_visit(node)
            self.shield_depth -= 1
        else:
            self.generic_visit(node)
        self.f.events.append(
            ("await", shielded, self.lock_depth > 0, node.lineno))

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name.endswith("shield") and name.split(".")[0] in (
                "asyncio", "shield"):
            self.shield_depth += 1
            self.generic_visit(node)
            self.shield_depth -= 1
        else:
            self.generic_visit(node)
        # self.<m>() call edge
        if isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            self.f.calls.append((node.func.attr, self.shield_depth > 0))
        # self.<f>.cancel()
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "cancel" \
                and isinstance(node.func.value, ast.Attribute) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == "self":
            self.f.cancels.add(node.func.value.attr)
        for recv_self, meth in _spawned_methods(node):
            if recv_self:
                self.f.spawns_self.add(meth)

    def visit_Assign(self, node: ast.Assign) -> None:
        # rhs first (reads happen before the store)
        self.visit(node.value)
        # task-handle fields: self.F = asyncio.ensure_future(self.M())
        if isinstance(node.value, ast.Call):
            spawned = [m for recv_self, m
                       in _spawned_methods(node.value) if recv_self]
            if spawned:
                for tgt in node.targets:
                    if self._self_field(tgt) is not None:
                        self.f.task_fields[self._self_field(tgt)] = \
                            spawned[0]
        for tgt in node.targets:
            self.visit(tgt)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        # self.x += y is a read-modify-write
        fld = self._self_field(node.target)
        if fld is not None:
            self.f.events.append(("read", fld, node.lineno,
                                  self.finally_depth > 0))
        self.visit(node.value)
        if fld is not None:
            self.f.events.append(("write", fld, node.lineno,
                                  self.finally_depth > 0))
        else:
            self.visit(node.target)

    @staticmethod
    def _self_field(node) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr
        return None

    def visit_Attribute(self, node: ast.Attribute) -> None:
        fld = self._self_field(node)
        if fld is not None:
            kind = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read")
            self.f.events.append((kind, fld, node.lineno,
                                  self.finally_depth > 0))
        self.generic_visit(node)


# -- per-class model --------------------------------------------------------


@dataclass
class ClassModel:
    name: str
    path: str
    lineno: int
    methods: Dict[str, MethodFacts] = field(default_factory=dict)
    init_fields: Set[str] = field(default_factory=set)

    def reachable(self, entry: str, unshielded_only: bool = False
                  ) -> Set[str]:
        """Methods reachable from ``entry`` via self-call edges
        (optionally excluding edges wrapped in asyncio.shield)."""
        seen: Set[str] = set()
        stack = [entry]
        while stack:
            m = stack.pop()
            if m in seen or m not in self.methods:
                continue
            seen.add(m)
            for callee, shielded in self.methods[m].calls:
                if unshielded_only and shielded:
                    continue
                stack.append(callee)
        return seen

    def cancelled_entries(self) -> Dict[str, str]:
        """entry method -> cancelling method, for task-handle fields
        that some method of this class ``.cancel()``s."""
        fields_to_entry: Dict[str, str] = {}
        for mf in self.methods.values():
            fields_to_entry.update(mf.task_fields)
        out: Dict[str, str] = {}
        for mf in self.methods.values():
            for fld in mf.cancels:
                if fld in fields_to_entry:
                    out[fields_to_entry[fld]] = mf.name
        return out


def _collect_classes(files: Dict[str, tuple]) -> List[ClassModel]:
    models: List[ClassModel] = []
    for path, (tree, _lines) in sorted(files.items()):
        if not in_scope(path):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cm = ClassModel(node.name, path, node.lineno)
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                mf = MethodFacts(item.name,
                                 isinstance(item, ast.AsyncFunctionDef),
                                 item.lineno)
                scan = _MethodScan(mf)
                for stmt in item.body:
                    scan.visit(stmt)
                cm.methods[item.name] = mf
                if item.name == "__init__":
                    cm.init_fields |= mf.fields_written()
            models.append(cm)
    return models


def _global_spawned_names(files: Dict[str, tuple]) -> Set[str]:
    """Method names spawned as tasks anywhere in scope (the
    cross-class half of entry-point discovery: the engine spawns
    ``runner.start()``, a runner spawns ``pump.run()``)."""
    names: Set[str] = set()
    for path, (tree, _lines) in files.items():
        if not in_scope(path):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                for _recv_self, meth in _spawned_methods(node):
                    names.add(meth)
    return names


# -- the two race rules -----------------------------------------------------


def _crossing_window(mf: MethodFacts, fld: str,
                     need_unshielded: bool = False,
                     write_before: bool = False) -> Optional[tuple]:
    """An access sequence on ``fld`` that crosses an await point:
    (pre_line, await_line, post_line, post_in_finally) or None.

    ``need_unshielded`` restricts to awaits outside asyncio.shield
    (cancellation analysis); locked awaits never open a window.
    ``write_before`` requires the pre-await access to be a write (the
    stranded-mid-update shape)."""
    pre: Optional[tuple] = None
    awaited: Optional[tuple] = None
    for ev in mf.events:
        if ev[0] == "await":
            _, shielded, locked, line = ev
            if locked or (need_unshielded and shielded):
                continue
            if pre is not None:
                awaited = ev
            continue
        kind, f, line, in_finally = ev
        if f != fld:
            continue
        if awaited is not None and pre is not None:
            return (pre[2], awaited[3], line, in_finally)
        if kind == "write" or not write_before:
            pre = ev
    return None


def _field_has_write(model: ClassModel, methods: Set[str],
                     fld: str) -> Optional[tuple]:
    for m in methods:
        mf = model.methods.get(m)
        if mf is None:
            continue
        for ev in mf.events:
            if ev[0] == "write" and ev[1] == fld:
                return (m, ev[2])
    return None


def check_project(files: Dict[str, tuple]) -> List[Finding]:
    findings: List[Finding] = []
    global_spawns = _global_spawned_names(files)
    for model in _collect_classes(files):
        entries = sorted(
            m for m, mf in model.methods.items()
            if mf.is_async and m != "__init__"
            and (m in global_spawns
                 or any(m in other.spawns_self
                        for other in model.methods.values())))
        if not entries:
            continue
        reach = {e: model.reachable(e) for e in entries}

        # rule 1: cross-task field race
        for fld in sorted({f for mf in model.methods.values()
                           for f in mf.fields_written()}):
            writers = [e for e in entries
                       if _field_has_write(model, reach[e], fld)]
            if len(writers) < 2:
                continue
            # a window crossing an await in any involved entry makes the
            # interleaving observable; all-locked access sets are safe
            window = None
            for e in writers:
                for m in reach[e]:
                    mf = model.methods.get(m)
                    if mf is None:
                        continue
                    w = _crossing_window(mf, fld)
                    if w is not None:
                        window = (m, w)
                        break
                if window:
                    break
            if window is None:
                continue
            meth, (pre, aw, post, _fin) = window
            wm, wline = _field_has_write(model, reach[writers[0]], fld)
            findings.append(Finding(
                PASS_ID, "cross-task-race", model.path, wline,
                f"{model.name}.{fld} is mutated from {len(writers)} task "
                f"entry points ({', '.join(writers)}) and "
                f"{model.name}.{meth}() holds an access window across an "
                f"await (lines {pre}->{aw}->{post}) with no asyncio.Lock "
                "— concurrent tasks interleave at every await point"))

        # rule 2: cancellation strands an await-crossing mutation
        for entry, canceller in sorted(model.cancelled_entries().items()):
            for m in sorted(model.reachable(entry,
                                            unshielded_only=True)):
                mf = model.methods.get(m)
                if mf is None:
                    continue
                for fld in sorted(mf.fields_written()):
                    w = _crossing_window(mf, fld, need_unshielded=True,
                                         write_before=True)
                    if w is None:
                        continue
                    pre, aw, post, post_in_finally = w
                    if post_in_finally:
                        continue  # cancellation still runs finally
                    findings.append(Finding(
                        PASS_ID, "cancel-window", model.path, pre,
                        f"{model.name}.{m}() (task entry "
                        f"{model.name}.{entry}, cancelled by "
                        f"{canceller}()) writes self.{fld} before the "
                        f"await at line {aw} and touches it at line "
                        f"{post}; cancellation lands at the await and "
                        f"strands self.{fld} mid-update — wrap the "
                        "await in asyncio.shield or move the recovery "
                        "into a finally"))
    return findings


def check(tree: ast.AST, lines: Sequence[str], path: str
          ) -> List[Finding]:
    """Single-file convenience wrapper (tests, ad-hoc runs)."""
    return check_project({path: (tree, list(lines))})
