"""arroyolint — streaming-invariant static analysis for arroyo_tpu.

Two halves, mirroring how the reference leans on rustc to reject whole
bug classes before they run:

1. **Codebase lints** (AST passes over the package, ``python -m
   arroyo_tpu.analysis``): checkpoint-state arity/schema consistency
   (the round-5 Nexmark 3-vs-4 unpack crash class), blocking calls in
   async hot paths, implicit host<->device syncs in operator
   steady-state code, trace purity of functions handed to
   ``jax.jit``/``pallas_call``, and drift between ``rpc.proto`` and the
   hand-surgered ``rpc_pb2.py`` descriptors.

2. **Plan-time validation** (``validate_program`` + ``plan_report``):
   graph-level invariants over ``graph.logical.Program`` — keyed-state
   operators behind shuffle edges, watermark/window consistency, join
   key-schema agreement, no dangling nodes — plus **shardcheck**
   (``shardcheck.py``), the sharding & transfer verifier that proves
   ``predicted_reshards == 0`` at plan time and is cross-checked
   against the live ``reshard_transfers`` counter by the smoke
   model-drift gate.  Run at pipeline-create time (api/rest.py) and
   before compilation (engine/build.py).

Findings support inline waivers::

    something_flagged()  # arroyolint: disable=<pass> -- reason

plus a checked-in baseline (tools/arroyolint_baseline.json) for
accepted pre-existing findings; the CI gate requires zero findings that
are neither waived nor baselined.
"""

from .core import (  # noqa: F401
    DEFAULT_BASELINE,
    Finding,
    load_baseline,
    run_analysis,
    write_baseline,
)
from .plan_validator import (  # noqa: F401
    PlanDiagnostic,
    PlanValidationError,
    check_program,
    plan_report,
    validate_program,
)

__all__ = [
    "Finding", "run_analysis", "load_baseline", "write_baseline",
    "DEFAULT_BASELINE", "PlanDiagnostic", "PlanValidationError",
    "check_program", "plan_report", "validate_program",
]
