"""arroyosan static half 2: barrier/watermark protocol checker.

The streaming runtime's control-event contract (the state machine the
runtime sanitizer asserts dynamically):

    BUFFERED --flush--> FLUSHED --handle/forward--> (next message)

A handler that buffers record fragments (the input coalescer, a chain
buffer — anything with ``.flush_all()`` / ``.pending``) must drain that
buffer **before** handling or forwarding a Watermark, Barrier or
Stop/EndOfData: a buffered batch that is reordered past a watermark can
make a window fire without it, past a barrier it lands in the wrong
epoch, past end-of-stream it is silently dropped.  PR 4's coalescer
pinned this ordering with tests; this pass pins it structurally so a
refactor of the task loop can't quietly reorder the flush.

Model: inside any function that manages a flushable buffer, find the
branches dispatching on a control-message kind
(``msg.kind == MessageKind.WATERMARK`` / ``BARRIER`` / ``STOP`` /
``END_OF_DATA`` / ``msg.is_end``) and walk each branch's statements in
order with the BUFFERED→FLUSHED state machine: reaching a
control-handling call (``observe_watermark``, ``run_checkpoint``,
``counter.observe``, ``mark_closed``, ``handle_watermark``,
``broadcast``) while no flush has appeared earlier in the branch is a
finding.

Scope: ``engine/*.py`` — the task loop, chained execution and the
coalescer live there.  The flush itself is usually conditional
(``if coal.pending: ... flush_all()``); any statement *containing* a
flush call counts as the flush step, since the guard is exactly
"pending implies flush".
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Set

from .core import Finding, call_name

PASS_ID = "protocol"

_SCOPE_RE = re.compile(r"(^|/)engine/[^/]+\.py$")

_FLUSH_ATTRS = {"flush_all"}
_BUFFER_ATTRS = {"flush_all", "pending"}

# calls that consume/forward the control event — reaching one of these
# while buffered data may still sit in the coalescer breaks ordering
_HANDLE_ATTRS = {
    "observe_watermark",  # watermark advancement
    "run_checkpoint",  # barrier -> snapshot
    "observe",  # CheckpointCounter.observe (alignment bookkeeping)
    "mark_closed",  # end-of-input alignment re-check
    "handle_watermark",
    "_advance_watermark",
    "broadcast",  # forwarding control downstream
}

_CONTROL_KINDS = {"WATERMARK", "BARRIER", "STOP", "END_OF_DATA"}


def in_scope(path: str) -> bool:
    return bool(_SCOPE_RE.search(path.replace("\\", "/")))


def _control_kind_of(test: ast.expr) -> Optional[str]:
    """'watermark'/'barrier'/'end' when ``test`` dispatches on a control
    message kind, else None."""
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute):
            if node.attr == "is_end":
                return "end"
            if node.attr in _CONTROL_KINDS and isinstance(
                    node.value, ast.Name) \
                    and node.value.id == "MessageKind":
                return node.attr.lower()
    return None


def _contains_attr_call(node: ast.AST, attrs: Set[str]) -> Optional[ast.Call]:
    """First ``<x>.<attr>()`` call under ``node``, not descending into
    nested function defs (separate scopes, scanned on their own)."""
    stack: List[ast.AST] = [node]
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and sub is not node:
            continue
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in attrs:
            return sub
        stack.extend(ast.iter_child_nodes(sub))
    return None


def _own_nodes(fn) -> List[ast.AST]:
    """Nodes belonging to ``fn``'s own body — nested function defs are
    separate scopes (they get their own _FnScan) and must not be
    evaluated against the enclosing function's flush state machine."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


class _FnScan:
    def __init__(self, path: str, fn) -> None:
        self.path = path
        self.fn = fn
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        own = _own_nodes(self.fn)
        # only functions that actually manage a flushable buffer (in
        # their OWN body) are bound by the ordering contract
        if not any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr in _FLUSH_ATTRS for n in own):
            return []
        for node in own:
            if isinstance(node, ast.If):
                kind = _control_kind_of(node.test)
                if kind is not None:
                    self._check_branch(kind, node)
        return self.findings

    def _check_branch(self, kind: str, branch: ast.If) -> None:
        """BUFFERED -> FLUSHED state machine over the branch body."""
        flushed = False
        for stmt in branch.body:
            if _contains_attr_call(stmt, _FLUSH_ATTRS) is not None:
                flushed = True
                continue
            handle = _contains_attr_call(stmt, _HANDLE_ATTRS)
            if handle is not None and not flushed:
                self.findings.append(Finding(
                    PASS_ID, "control-before-flush", self.path,
                    handle.lineno,
                    f"{self.fn.name}(): {kind} handled via "
                    f".{handle.func.attr}() before the buffered records "
                    "were flushed — a fragment still in the coalescer "
                    f"would be reordered past the {kind} "
                    "(flush-before-control ordering)"))
                return  # one finding per branch is enough signal


def check(tree: ast.AST, lines: Sequence[str], path: str,
          force: bool = False) -> List[Finding]:
    if not force and not in_scope(path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_FnScan(path, node).run())
    return findings
