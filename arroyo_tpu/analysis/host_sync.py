"""Implicit host<->device sync detector for operator steady-state code.

Scope: ``ops/*.py`` and ``engine/operators_*.py`` — the per-batch hot
paths where an accidental device->host readback serializes the XLA
dispatch pipeline (on a tunneled TPU each sync is a network round
trip).  Flags:

- ``np.asarray(x)`` / ``np.array(x)`` on a non-literal — materializes
  device output on the host
- ``<x>.item()``, ``<x>.block_until_ready()``, ``jax.device_get(...)``
- ``float(x)`` / ``int(x)`` whose argument contains a ``jnp.*`` call
  (scalarizing a traced value forces a sync)

Functions whose names mark checkpoint/debug paths
(checkpoint/snapshot/restore/debug/on_start/on_close/pre_checkpoint)
are exempt — those are *supposed* to materialize state on the host.
Pre-existing intentional readbacks (pane emission) live in the
baseline; the gate exists to catch new ones.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from .core import Finding, call_name

PASS_ID = "host-sync"

_SCOPE_RE = re.compile(r"(^|/)(ops/[^/]+\.py|engine/operators_[^/]+\.py)$")
_EXEMPT_FN_RE = re.compile(
    r"checkpoint|snapshot|restore|debug|on_start|on_close|handle_commit"
    # latency-observatory stamp sites (obs/latency.py): _lat_track /
    # _lat_consume read the host wall clock (now_micros / monotonic) to
    # stamp or judge a sampled batch — host-clock reads, never a
    # device readback, so new flag kinds must not indict them
    r"|_lat_")


def in_scope(path: str) -> bool:
    return bool(_SCOPE_RE.search(path.replace("\\", "/")))


# dtype metadata, not device computation: scalarizing these never syncs
_JNP_METADATA = {"jnp.finfo", "jnp.iinfo", "jax.numpy.finfo",
                 "jax.numpy.iinfo"}


def _contains_jnp_call(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if (name.startswith("jnp.") or name.startswith("jax.numpy.")) \
                    and name not in _JNP_METADATA:
                return True
    return False


def _flag_for(call: ast.Call) -> Optional[tuple]:
    name = call_name(call)
    if name in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
        if call.args and not isinstance(call.args[0], (ast.Constant,
                                                       ast.List,
                                                       ast.Tuple)):
            return ("asarray", f"{name}() forces a device->host "
                    "transfer when fed a device array")
    if name.endswith(".item") and not call.args:
        return ("item", ".item() scalarizes on the host — a blocking "
                "device sync")
    if name.endswith(".block_until_ready"):
        return ("block-until-ready", "block_until_ready() outside a "
                "checkpoint/debug path serializes dispatch")
    if name in ("jax.device_get",):
        return ("device-get", "jax.device_get() is an explicit host "
                "readback in steady-state code")
    if name in ("float", "int") and call.args \
            and _contains_jnp_call(call.args[0]):
        return ("scalarize", f"{name}() of a jnp expression forces a "
                "blocking device sync")
    return None


class _Scan(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self.fn_stack: List[str] = []

    def _visit_fn(self, node) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _exempt(self) -> bool:
        return any(_EXEMPT_FN_RE.search(name) for name in self.fn_stack)

    def visit_Call(self, node: ast.Call) -> None:
        if not self._exempt():
            hit = _flag_for(node)
            if hit:
                code, msg = hit
                self.findings.append(
                    Finding(PASS_ID, code, self.path, node.lineno, msg))
        self.generic_visit(node)


def check(tree: ast.AST, lines, path: str,
          force: bool = False) -> List[Finding]:
    if not force and not in_scope(path):
        return []
    scan = _Scan(path)
    scan.visit(tree)
    return scan.findings
