"""Fluvio source/sink.

Analog of the reference's fluvio connector (/root/reference/arroyo-worker/src/
connectors/fluvio/{source.rs,sink.rs}; metadata
/root/reference/arroyo-connectors/src/fluvio.rs): the source stripes topic
partitions across subtasks, stores ``partition -> next offset`` in global
state table 'f' (source.rs:214-223 writes offset+1 at checkpoint) and resumes
absolutely; a partition that appears only after a restore starts from the
beginning so no data is dropped (source.rs:144-152).  The sink is
at-least-once: every row is produced eagerly and the producer is flushed on
the checkpoint barrier (sink.rs:81-83) — fluvio has no transactions, unlike
the kafka sink.

Endpoint is pluggable like kafka's bootstrap: ``endpoint='memory://<name>'``
drives the in-process :class:`InMemoryKafkaBroker` log (partition/offset
semantics are identical); anything else needs the ``fluvio`` client library,
surfaced as a clear error where it is absent.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Literal, Optional

from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import Operator, SourceFinishType, SourceOperator
from ..formats import make_format
from ..state.tables import TableDescriptor, global_table
from ..types import Batch, StopMode
from .kafka import InMemoryKafkaBroker
from .registry import ConnectorMeta, register_connector


class FluvioConfig(BaseModel):
    topic: str
    endpoint: Optional[str] = None  # None = 'default cluster' (needs client)
    offset: Literal["earliest", "latest"] = "earliest"  # when no stored state
    format: str = "json"
    format_options: Dict[str, Any] = {}
    batch_size: Optional[int] = None
    max_messages: Optional[int] = None  # bounded runs (tests)


def _broker(endpoint: Optional[str]) -> InMemoryKafkaBroker:
    if endpoint and endpoint.startswith("memory://"):
        return InMemoryKafkaBroker.get(endpoint[len("memory://"):])
    raise RuntimeError(
        "real Fluvio requires the fluvio client library, which is not "
        "available in this environment; use endpoint='memory://<name>'")


class FluvioSource(SourceOperator):
    """Partition-striped fluvio consumer with absolute-offset resume
    (source.rs:95-166)."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("fluvio_source")
        self.cfg = FluvioConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    def tables(self) -> List[TableDescriptor]:
        # table 'f': partition -> next offset to read (source.rs:44-46)
        return [global_table("f", "fluvio source state")]

    async def run(self, ctx: Context) -> SourceFinishType:
        broker = _broker(self.cfg.endpoint)
        state = ctx.state.get_global_keyed_state("f")
        n_parts = broker.partitions(self.cfg.topic)
        me, n = ctx.task_info.task_index, ctx.task_info.parallelism
        my_parts = [p for p in range(n_parts) if p % n == me]
        if not my_parts:
            # more subtasks than partitions (source.rs:185-189): finish; the
            # runner emits the final watermark so downstream isn't held back
            return SourceFinishType.FINAL

        # restore: absolute offsets where known; a brand-new partition after
        # a restore reads from the beginning, else the configured mode
        has_state = any(state.get(p) is not None for p in range(n_parts))
        offsets: Dict[int, int] = {}
        for p in my_parts:
            stored = state.get(p)
            if stored is not None:
                offsets[p] = stored
            elif has_state or self.cfg.offset == "earliest":
                offsets[p] = 0
            else:
                offsets[p] = len(broker.topics[self.cfg.topic][p].log)

        runner = getattr(ctx, "_runner", None)
        batch_size = self.cfg.batch_size or config().target_batch_size
        total = 0
        idle_spins = 0
        # source-side coalescing: partition fetches returning small
        # fragments accumulate at the boundary and decode/emit as one
        # target-size batch (the runner flushes before checkpoints and
        # stop, so offsets recorded at fetch time stay exactly-once)
        batcher = self.make_batcher(ctx, self.fmt.batch, batch_size)
        while True:
            got = 0
            for p in my_parts:
                recs = broker.fetch(self.cfg.topic, p, offsets[p], batch_size,
                                    read_committed=False)
                if recs:
                    got += len(recs)
                    total += len(recs)
                    # arroyolint: disable=row-loop -- per-record value gather is the broker API's shape; decode is batched downstream
                    await batcher.add([r.value for r in recs])
                    offsets[p] = recs[-1].offset + 1
                    state.insert(p, offsets[p])  # next offset (source.rs:221)
            await batcher.maybe_flush()
            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return (SourceFinishType.GRACEFUL
                            if cm.stop_mode != StopMode.IMMEDIATE
                            else SourceFinishType.IMMEDIATE)
            if self.cfg.max_messages is not None and total >= self.cfg.max_messages:
                return SourceFinishType.FINAL
            if got == 0:
                idle_spins += 1
                if self.cfg.max_messages is not None and idle_spins > 50:
                    return SourceFinishType.FINAL  # bounded test run drained
                await asyncio.sleep(0.01)
            else:
                idle_spins = 0
                await asyncio.sleep(0)


class FluvioSink(Operator):
    """At-least-once producer: rows go out as they arrive; the checkpoint
    barrier is a flush point (sink.rs:81-98)."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("fluvio_sink")
        self.cfg = FluvioConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    async def on_start(self, ctx: Context) -> None:
        # resolve the producer up front so a bad endpoint fails at operator
        # startup, not at the first batch (sink.rs:65-79 does the same)
        self._producer = _broker(self.cfg.endpoint)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        for payload in self.fmt.serialize_batch(batch):
            self._producer.produce(self.cfg.topic, payload)

    async def pre_checkpoint(self, barrier, ctx: Context) -> None:
        # the in-memory log is durable on produce; a real producer would
        # flush() here (sink.rs:82)
        return None


register_connector(ConnectorMeta(
    name="fluvio",
    description="fluvio source (absolute-offset resume) / at-least-once sink",
    source_factory=FluvioSource,
    sink_factory=FluvioSink,
    config_model=FluvioConfig,
))
