"""Minimal Confluent Schema Registry client (stdlib only).

The reference's kafka connector resolves confluent-framed payloads
against a schema registry (arroyo-worker/src/connectors/kafka/mod.rs
confluent handling); this is the TPU build's equivalent: register a
schema under a subject (returning the id embedded in the 5-byte wire
header) and fetch writer schemas by id for decoding.  REST surface per
the Confluent API: ``POST /subjects/{subject}/versions`` and
``GET /schemas/ids/{id}``.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Union


class SchemaRegistryError(RuntimeError):
    pass


class SchemaRegistryClient:
    """Tiny blocking client; callers cache instances per URL.  Both
    directions memoize (ids are immutable in the registry model)."""

    def __init__(self, url: str, timeout: float = 10.0,
                 auth: Optional[str] = None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.auth = auth  # "user:pass" basic auth, if the registry needs it
        self._by_id: Dict[int, Dict[str, Any]] = {}
        self._ids: Dict[str, int] = {}  # subject \x00 schema-json -> id

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.url + path, method=method,
            data=(json.dumps(body).encode() if body is not None else None),
            headers={
                "Content-Type": "application/vnd.schemaregistry.v1+json"})
        if self.auth:
            import base64

            req.add_header("Authorization", "Basic " + base64.b64encode(
                self.auth.encode()).decode())
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            raise SchemaRegistryError(
                f"{method} {path} -> {e.code}: "
                f"{e.read().decode(errors='replace')[:200]}")
        except (urllib.error.URLError, OSError) as e:
            raise SchemaRegistryError(f"{method} {path} failed: {e}")

    def register(self, subject: str,
                 schema: Union[str, Dict[str, Any]],
                 schema_type: str = "AVRO") -> int:
        """Register (idempotently) and return the global schema id."""
        text = schema if isinstance(schema, str) else json.dumps(schema)
        key = f"{subject}\x00{text}"
        if key in self._ids:
            return self._ids[key]
        body: Dict[str, Any] = {"schema": text}
        if schema_type != "AVRO":  # AVRO is the registry default
            body["schemaType"] = schema_type
        resp = self._request(
            "POST", f"/subjects/{subject}/versions", body)
        sid = int(resp["id"])
        self._ids[key] = sid
        return sid

    def get_schema(self, schema_id: int) -> Dict[str, Any]:
        """Fetch a (writer) schema by the id from the wire header."""
        if schema_id in self._by_id:
            return self._by_id[schema_id]
        resp = self._request("GET", f"/schemas/ids/{schema_id}")
        schema = json.loads(resp["schema"])
        self._by_id[schema_id] = schema
        return schema


_clients: Dict[str, SchemaRegistryClient] = {}


def registry_client(url: str) -> SchemaRegistryClient:
    """Shared per-URL client (schema caches amortize across operators)."""
    if url not in _clients:
        _clients[url] = SchemaRegistryClient(url)
    return _clients[url]
