"""Preview (grpc) sink: streams results to the controller, which fans them
out to SubscribeToOutput subscribers — the reference's GrpcSink feeding the
console's output pane (arroyo-worker/src/connectors/sinks/mod.rs:11-80)."""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ..config import config
from ..engine.context import Context
from ..engine.operator import Operator
from ..network.data_plane import _encode_batch
from ..rpc.transport import RpcClient
from ..types import Batch
from .registry import ConnectorMeta, register_connector

logger = logging.getLogger(__name__)


class PreviewSink(Operator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("preview_sink")
        self.controller_addr = cfg.get("controller_addr") or \
            config().controller_addr.replace("http://", "")
        self.client: Optional[RpcClient] = None

    async def on_start(self, ctx: Context) -> None:
        self.client = RpcClient(self.controller_addr, "ControllerGrpc")

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        try:
            await self.client.call("SendSinkData", {
                "job_id": ctx.task_info.job_id,
                "operator_id": ctx.task_info.operator_id,
                "batch": _encode_batch(batch),
                "done": False,
            })
        except Exception as e:
            logger.warning("preview sink send failed: %s", e)

    async def on_close(self, ctx: Context) -> None:
        try:
            await self.client.call("SendSinkData", {
                "job_id": ctx.task_info.job_id,
                "operator_id": ctx.task_info.operator_id,
                "batch": b"", "done": True,
            })
            await self.client.close()
        except Exception:
            pass


register_connector(ConnectorMeta(
    name="preview",
    description="stream results to the controller (console output pane)",
    sink_factory=PreviewSink,
))
