"""Connector registry — analog of the reference's ``trait Connector``
metadata crate (/root/reference/arroyo-connectors/src/lib.rs:71-111): each
connector registers factories producing source/sink physical operators from a
validated config dict (pydantic models play the role of the JSON-schema
``connector-schemas/*/table.json`` files)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..engine.operator import Operator, SourceOperator


@dataclass
class ConnectorMeta:
    name: str
    description: str
    source_factory: Optional[Callable[[Dict[str, Any]], SourceOperator]] = None
    sink_factory: Optional[Callable[[Dict[str, Any]], Operator]] = None
    config_model: Optional[type] = None  # pydantic model for validation

    @property
    def supports_source(self) -> bool:
        return self.source_factory is not None

    @property
    def supports_sink(self) -> bool:
        return self.sink_factory is not None


_REGISTRY: Dict[str, ConnectorMeta] = {}


def register_connector(meta: ConnectorMeta) -> None:
    _REGISTRY[meta.name] = meta


def get_connector(name: str) -> ConnectorMeta:
    _ensure_builtin()
    if name not in _REGISTRY:
        raise KeyError(f"unknown connector: {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_connectors() -> List[ConnectorMeta]:
    _ensure_builtin()
    return sorted(_REGISTRY.values(), key=lambda m: m.name)


def validate_config(name: str, config: Dict[str, Any]) -> Dict[str, Any]:
    """Connector::validate analog: run config through the pydantic model."""
    meta = get_connector(name)
    if meta.config_model is not None:
        return meta.config_model(**config).model_dump()
    return config


def make_source(name: str, config: Dict[str, Any]) -> SourceOperator:
    meta = get_connector(name)
    if not meta.supports_source:
        raise ValueError(f"connector {name} does not support sources")
    return meta.source_factory(validate_config(name, config))


def make_sink(name: str, config: Dict[str, Any]) -> Operator:
    meta = get_connector(name)
    if not meta.supports_sink:
        raise ValueError(f"connector {name} does not support sinks")
    return meta.sink_factory(validate_config(name, config))


_loaded = False


def _ensure_builtin() -> None:
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import impulse, single_file, blackhole, memory, nexmark, preview  # noqa: F401
    for mod in ("filesystem", "http_connectors", "kafka",
                "websocket_connector", "kinesis", "fluvio"):
        try:
            __import__(f"arroyo_tpu.connectors.{mod}")
        except ImportError:
            pass
