"""Impulse source: rate-limited counter generator
(/root/reference/arroyo-worker/src/connectors/impulse.rs) — the standard
benchmark/test source.  Emits batches of {counter: u64, subtask_index: u64}
with exactly-once resume from a global state table holding the next counter."""

from __future__ import annotations

import asyncio
import time as _time
from typing import Any, Dict, List, Optional

import numpy as np
from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import SourceFinishType, SourceOperator
from ..state.tables import TableDescriptor, TableType, global_table
from ..types import Batch, StopMode, now_micros
from .registry import ConnectorMeta, register_connector


class ImpulseConfig(BaseModel):
    event_rate: float = 1_000_000.0  # events/sec across the source
    event_time_interval_micros: Optional[int] = None  # synthetic event time step
    message_count: Optional[int] = None  # total events; None = unbounded
    batch_size: Optional[int] = None
    # pin the event-time origin (nexmark's base_time_micros analog):
    # deterministic window alignment for tests/benches; default wallclock
    base_time_micros: Optional[int] = None


class ImpulseSource(SourceOperator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("impulse")
        self.cfg = ImpulseConfig(**cfg)
        self.counter = 0

    def tables(self) -> List[TableDescriptor]:
        return [global_table("i", "impulse source state")]

    async def run(self, ctx: Context) -> SourceFinishType:
        state = ctx.state.get_global_keyed_state("i")
        saved = state.get(ctx.task_info.task_index)
        saved_base = None
        if saved is not None:
            self.counter, saved_base = saved

        par = ctx.task_info.parallelism
        rate = self.cfg.event_rate / par
        total = None
        if self.cfg.message_count is not None:
            per = self.cfg.message_count // par
            extra = 1 if ctx.task_info.task_index < self.cfg.message_count % par else 0
            total = per + extra
        batch_size = self.cfg.batch_size or config().target_batch_size
        interval = self.cfg.event_time_interval_micros
        t0_wall = _time.monotonic()
        emitted_since_start = 0
        # event-time base must survive restarts so restored events land in
        # the same windows as the checkpointed state
        base_event_time = (saved_base if saved_base is not None
                           else (self.cfg.base_time_micros
                                 if self.cfg.base_time_micros is not None
                                 else now_micros()))

        runner = getattr(ctx, "_runner", None)
        from ..obs import latency as _latency
        from ..obs import profiler

        prof = profiler.active()
        while total is None or self.counter < total:
            frame = (prof.begin(ctx.task_info.operator_id, "source_decode")
                     if prof is not None else None)
            n = batch_size if total is None else min(batch_size, total - self.counter)
            counters = np.arange(self.counter, self.counter + n, dtype=np.uint64)
            if interval:
                ts = base_event_time + (counters.astype(np.int64) * interval)
            else:
                ts = np.full(n, now_micros(), dtype=np.int64)
            batch = Batch(ts, {
                "counter": counters,
                "subtask_index": np.full(n, ctx.task_info.task_index, dtype=np.uint64),
            })
            if frame is not None:
                prof.end(frame)
            _latency.maybe_stamp(ctx.task_info.operator_id, batch)
            await ctx.collect(batch)
            self.counter += n
            state.insert(ctx.task_info.task_index,
                         (self.counter, base_event_time))

            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return (SourceFinishType.GRACEFUL
                            if cm.stop_mode != StopMode.IMMEDIATE
                            else SourceFinishType.IMMEDIATE)

            emitted_since_start += n
            if rate > 0:
                expected = emitted_since_start / rate
                ahead = expected - (_time.monotonic() - t0_wall)
                if ahead > 0:
                    await asyncio.sleep(ahead)
                else:
                    await asyncio.sleep(0)
            else:
                await asyncio.sleep(0)
        return SourceFinishType.FINAL


register_connector(ConnectorMeta(
    name="impulse",
    description="rate-limited counter source",
    source_factory=ImpulseSource,
    config_model=ImpulseConfig,
))
