"""single_file source/sink — the golden-file test workhorse
(/root/reference/arroyo-worker/src/connectors/single_file/): source reads a
JSON-lines file emitting one record per line with exactly-once resume (lines
read stored in state); sink appends JSON lines to a file."""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np
from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import Operator, SourceFinishType, SourceOperator
from ..formats import JsonFormat, encode_json_lines, fast_decode_enabled
from ..state.tables import TableDescriptor, global_table
from ..types import Batch, StopMode, now_micros
from .registry import ConnectorMeta, register_connector


class SingleFileConfig(BaseModel):
    path: str
    # source: parse each line as a JSON object into columns
    timestamp_field: Optional[str] = None  # else now()


def _rows_to_batch(rows: List[Dict[str, Any]], ts_field: Optional[str]) -> Batch:
    cols: Dict[str, List[Any]] = {}
    # arroyolint: disable=row-loop -- the ARROYO_FAST_DECODE=0 escape hatch IS the pinned legacy per-row pivot
    for r in rows:
        for k in r:
            cols.setdefault(k, [])
    # arroyolint: disable=row-loop -- the ARROYO_FAST_DECODE=0 escape hatch IS the pinned legacy per-row pivot
    for r in rows:
        for k in cols:
            cols[k].append(r.get(k))
    np_cols = {}
    for k, vs in cols.items():
        arr = np.array(vs)
        if arr.dtype == object:
            try:
                arr = arr.astype(np.int64)
            except (ValueError, TypeError):
                try:
                    arr = arr.astype(np.float64)
                except (ValueError, TypeError):
                    arr = np.array(vs, dtype=object)
        np_cols[k] = arr
    if ts_field and ts_field in np_cols:
        ts = np_cols[ts_field].astype(np.int64)
    else:
        ts = np.full(len(rows), now_micros(), dtype=np.int64)
    return Batch(ts, np_cols)


class SingleFileSource(SourceOperator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("single_file_source")
        self.cfg = SingleFileConfig(**cfg)
        # vectorized decode rides the shared serde layer; the format
        # instance carries the stream's locked schema across batches
        self.fmt = JsonFormat()

    def tables(self) -> List[TableDescriptor]:
        return [global_table("f", "single file source state")]

    async def run(self, ctx: Context) -> SourceFinishType:
        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL  # single-reader source
        state = ctx.state.get_global_keyed_state("f")
        start_line = state.get("lines_read") or 0
        runner = getattr(ctx, "_runner", None)
        batch_size = config().target_batch_size

        def _read_lines() -> List[bytes]:
            # arroyolint: disable=row-loop -- one readlines() call per file, not a steady-state row loop
            with open(self.cfg.path, "rb") as f:
                return f.readlines()

        # a large input file must not stall every subtask on the worker
        # while it loads — read it off the event loop
        lines = await asyncio.get_event_loop().run_in_executor(
            None, _read_lines)
        from ..obs import latency as _latency
        from ..obs import profiler

        prof = profiler.active()
        i = start_line
        while i < len(lines):
            frame = (prof.begin(ctx.task_info.operator_id, "source_decode")
                     if prof is not None else None)
            chunk = lines[i:i + batch_size]
            payloads = [l for l in chunk if l.strip()]
            if not payloads:
                batch = None
            elif fast_decode_enabled():
                # whole chunk in one columnar parse (formats.py fast
                # path: pyarrow NDJSON or the bulk array parse)
                batch = self.fmt.batch(payloads, self.cfg.timestamp_field)
            else:
                # legacy path, bit-for-bit: per-line json.loads into the
                # connector's historical ad-hoc pivot
                # arroyolint: disable=row-loop -- the ARROYO_FAST_DECODE=0 escape hatch IS the pinned legacy per-row path
                rows = [json.loads(l) for l in chunk if l.strip()]
                batch = _rows_to_batch(rows, self.cfg.timestamp_field)
            if frame is not None:
                prof.end(frame)
            if batch is not None:
                _latency.maybe_stamp(ctx.task_info.operator_id, batch)
                await ctx.collect(batch)
            i += len(chunk)
            state.insert("lines_read", i)
            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return (SourceFinishType.GRACEFUL
                            if cm.stop_mode != StopMode.IMMEDIATE
                            else SourceFinishType.IMMEDIATE)
            await asyncio.sleep(0)
        return SourceFinishType.FINAL


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.bool_):
        return bool(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


class SingleFileSink(Operator):
    """Writes one JSON object per record.  Exactly-once across restarts: the
    file byte offset is checkpointed (table 'o'), and on restore the file is
    truncated back to the last checkpointed offset before appending — rows
    written after the failed epoch are discarded and re-produced."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("single_file_sink")
        self.cfg = SingleFileConfig(**cfg)
        self._file = None

    def tables(self):
        from ..state.tables import global_table

        return [global_table("o", "committed file offset")]

    async def on_start(self, ctx: Context) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.cfg.path)), exist_ok=True)
        # line-buffered: an IMMEDIATE-stopped run never runs on_close, and
        # a block-buffered file object flushing its residue at finalization
        # — at its stale pre-truncate offset — would punch a zero-filled
        # hole into the file the restored run is appending to
        if ctx.state.restore_epoch is not None:
            offset = ctx.state.get_global_keyed_state("o").get("offset") or 0
            # arroyolint: disable=async-blocking -- once-per-task local open/truncate at restore, not a hot path
            with open(self.cfg.path, "ab") as f:
                pass  # ensure exists
            # arroyolint: disable=async-blocking -- once-per-task local open/truncate at restore, not a hot path
            with open(self.cfg.path, "r+b") as f:
                f.truncate(offset)
            # arroyolint: disable=async-blocking -- once-per-task local open at task start, not a hot path
            self._file = open(self.cfg.path, "a", buffering=1)
        else:
            # arroyolint: disable=async-blocking -- once-per-task local open at task start, not a hot path
            self._file = open(self.cfg.path, "w", buffering=1)

    async def pre_checkpoint(self, barrier, ctx: Context) -> None:
        self._file.flush()
        ctx.state.get_global_keyed_state("o").insert(
            "offset", self._file.tell())

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        from ..obs import profiler

        prof = profiler.active()
        frame = (prof.begin(ctx.task_info.operator_id, "emit_encode")
                 if prof is not None else None)
        # vectorized encode: one cell pass per column + one template
        # substitution per row (formats.encode_json_lines), falling back
        # to the legacy per-row dumps for inexpressible columns or under
        # ARROYO_FAST_DECODE=0.  The NaN literal matches _json_default's
        # legacy output.  One write per batch either way: line buffering
        # then flushes once here, so no residue outlives the batch
        # without paying a syscall per row.
        lines = (encode_json_lines(batch, nan_literal="NaN")
                 if fast_decode_enabled() else None)
        if lines is not None:
            out = "\n".join(lines) + "\n" if lines else ""
        else:
            names = list(batch.columns)
            cols = [batch.columns[n] for n in names]
            # arroyolint: disable=row-loop -- the ARROYO_FAST_DECODE=0 escape hatch IS the pinned legacy per-row path
            out = "".join(
                json.dumps({n: c[i] for n, c in zip(names, cols)},
                           default=_json_default) + "\n"
                for i in range(len(batch)))
        self._file.write(out)
        if frame is not None:
            prof.end(frame)

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        self._file.flush()
        await super().handle_watermark(watermark, ctx)

    async def on_close(self, ctx: Context) -> None:
        self._file.flush()
        self._file.close()


register_connector(ConnectorMeta(
    name="single_file",
    description="JSON-lines file source/sink for tests and golden files",
    source_factory=SingleFileSource,
    sink_factory=SingleFileSink,
    config_model=SingleFileConfig,
))
