"""Blackhole sink (/root/reference/arroyo-worker/src/connectors/blackhole.rs):
discards everything — used for benchmarking the upstream pipeline."""

from __future__ import annotations

from typing import Any, Dict

from ..engine.context import Context
from ..engine.operator import Operator
from ..types import Batch
from .registry import ConnectorMeta, register_connector


class BlackholeSink(Operator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("blackhole")
        self.rows = 0

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        self.rows += len(batch)


register_connector(ConnectorMeta(
    name="blackhole",
    description="discard sink for benchmarks",
    sink_factory=BlackholeSink,
))
