"""In-memory vec source/sink for unit tests (plays the role the reference's
test harness queues play, engine.rs:316-343)."""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

import numpy as np

from ..engine.context import Context
from ..engine.operator import Operator, SourceFinishType, SourceOperator
from ..types import Batch
from .registry import ConnectorMeta, register_connector

_SINKS: Dict[str, List[Batch]] = {}
_SINK_ARRIVALS: Dict[str, List[float]] = {}


def sink_output(name: str) -> List[Batch]:
    return _SINKS.setdefault(name, [])


def sink_arrivals(name: str) -> List[float]:
    """Wallclock (time.monotonic — same clock the rate-limited sources
    pace on) arrival time of each sink batch: the measurement end of the
    bench's end-to-end latency probe."""
    return _SINK_ARRIVALS.setdefault(name, [])


def clear_sink(name: str) -> None:
    _SINKS.pop(name, None)
    _SINK_ARRIVALS.pop(name, None)


class MemorySource(SourceOperator):
    """Emits a preloaded list of batches, then finishes."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("memory_source")
        self.batches: List[Batch] = cfg.get("batches", [])

    async def run(self, ctx: Context) -> SourceFinishType:
        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL  # single-reader source
        runner = getattr(ctx, "_runner", None)
        from ..obs import latency as _latency
        for b in self.batches:
            _latency.maybe_stamp(ctx.task_info.operator_id, b)
            await ctx.collect(b)
            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return SourceFinishType.GRACEFUL
            await asyncio.sleep(0)
        return SourceFinishType.FINAL


class MemorySink(Operator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("memory_sink")
        self.name = cfg.get("name", "default")

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        import time

        sink_output(self.name).append(batch)
        sink_arrivals(self.name).append(time.monotonic())


register_connector(ConnectorMeta(
    name="memory",
    description="in-memory batches source/sink for tests",
    source_factory=MemorySource,
    sink_factory=MemorySink,
))
