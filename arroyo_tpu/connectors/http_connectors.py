"""HTTP-family connectors: SSE source, polling-HTTP source, webhook sink.

Analogs of the reference's sse / polling_http / webhook connectors
(/root/reference/arroyo-worker/src/connectors/{sse.rs,polling_http.rs,
webhook.rs}): event-stream and poll-based ingestion with exactly-once resume
state, and an at-least-once HTTP POST sink with bounded in-flight requests.

All use aiohttp; payload decoding goes through the shared Format layer
(arroyo_tpu.formats), so json/raw/debezium all work.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import Operator, SourceFinishType, SourceOperator
from ..formats import Format, make_format
from ..state.tables import TableDescriptor, global_table
from ..types import Batch, StopMode
from .registry import ConnectorMeta, register_connector


def _parse_headers(raw: Optional[str]) -> Dict[str, str]:
    """'K1: v1,K2: v2' header string, as the reference's connector configs.

    Splits only on commas that start a new ``Name:`` pair, so header values
    containing commas (Accept lists, dates) survive intact."""
    import re

    out: Dict[str, str] = {}
    if raw:
        # lookahead covers the full RFC 7230 token charset (underscores,
        # dots, ...), not just alphanumerics-and-dash
        for part in re.split(
                r",(?=\s*[!#$%&'*+.^_`|~0-9A-Za-z-]+\s*:)", raw):
            if ":" in part:
                k, v = part.split(":", 1)
                out[k.strip()] = v.strip()
    return out


class SseConfig(BaseModel):
    endpoint: str
    events: Optional[str] = None  # comma-separated event-type filter
    headers: Optional[str] = None
    format: str = "json"
    format_options: Dict[str, Any] = {}


class SseSource(SourceOperator):
    """Server-sent-events source (sse.rs): subscribes to an event stream,
    filters by event type, and checkpoints the SSE ``id:`` field so a restart
    resumes via the Last-Event-ID header.

    Reconnect semantics: a transport error mid-stream triggers an automatic
    reconnect with Last-Event-ID (per the SSE spec; the reference's
    eventsource client does the same), while a clean server EOF ends the
    stream (FINAL)."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("sse_source")
        self.cfg = SseConfig(**cfg)
        self.fmt: Format = make_format(self.cfg.format, **self.cfg.format_options)

    def tables(self) -> List[TableDescriptor]:
        return [global_table("e", "sse last event id")]

    async def run(self, ctx: Context) -> SourceFinishType:
        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL
        import aiohttp

        state = ctx.state.get_global_keyed_state("e")
        last_id: Optional[str] = state.get("last_id")
        events = ({e.strip() for e in self.cfg.events.split(",")}
                  if self.cfg.events else None)
        runner = getattr(ctx, "_runner", None)
        batch_size = config().target_batch_size
        headers = _parse_headers(self.cfg.headers)
        headers.setdefault("Accept", "text/event-stream")
        if last_id:
            headers["Last-Event-ID"] = last_id

        # source-side coalescing: SSE events are tiny fragments — the
        # boundary batcher assembles target-size batches and the
        # vectorized format decode parses each batch in one pass.  The
        # last event id is recorded at PARSE time (resume position at
        # fetch time); the runner flushes buffered events before any
        # checkpoint snapshots it, so restores never skip a buffered row.
        # batch_always: SSE buffered events to batch_size itself before
        # the batcher existed, so ARROYO_COALESCE=0 must keep that
        # batching (it only drops the linger), not emit per event.
        batcher = self.make_batcher(ctx, self.fmt.batch, batch_size,
                                    batch_always=True)

        backoff = 0.1
        async with aiohttp.ClientSession() as session:
            while True:
                if last_id is not None:
                    headers["Last-Event-ID"] = str(last_id)
                try:
                    async with session.get(self.cfg.endpoint,
                                           headers=headers) as resp:
                        resp.raise_for_status()
                        backoff = 0.1
                        ev_type, ev_data, ev_id = "message", [], None
                        async for raw in resp.content:
                            line = (raw.decode("utf-8", "replace")
                                    .rstrip("\n").rstrip("\r"))
                            if line == "":  # dispatch event
                                if ev_data and (events is None
                                                or ev_type in events):
                                    await batcher.add(
                                        ["\n".join(ev_data).encode()])
                                if ev_id is not None:
                                    last_id = ev_id
                                    state.insert("last_id", last_id)
                                ev_type, ev_data, ev_id = "message", [], None
                                await batcher.maybe_flush()
                            elif line.startswith("event:"):
                                ev_type = line[6:].strip()
                            elif line.startswith("data:"):
                                ev_data.append(line[5:].lstrip())
                            elif line.startswith("id:"):
                                ev_id = line[3:].strip()
                            if runner is not None:
                                cm = await runner.poll_source_control()
                                if cm is not None and cm.kind == "stop":
                                    await batcher.flush()
                                    return (SourceFinishType.GRACEFUL
                                            if cm.stop_mode != StopMode.IMMEDIATE
                                            else SourceFinishType.IMMEDIATE)
                except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                    # transport error mid-stream: reconnect with
                    # Last-Event-ID.  Flush first — the backoff sleep
                    # always overshoots the linger bound, and the
                    # pre-batcher code flushed here too
                    await batcher.flush()
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 5.0)
                    continue
                break  # clean server EOF ends the stream
        await batcher.flush()
        return SourceFinishType.FINAL


class PollingHttpConfig(BaseModel):
    endpoint: str
    poll_interval_ms: int = 1000
    method: str = "GET"
    body: Optional[str] = None
    headers: Optional[str] = None
    format: str = "json"
    format_options: Dict[str, Any] = {}
    emit_behavior: str = "all"  # 'all' | 'changed' (dedupe identical bodies)
    max_polls: Optional[int] = None  # tests / bounded runs


class PollingHttpSource(SourceOperator):
    """Polls an HTTP endpoint on an interval (polling_http.rs); in 'changed'
    mode only emits when the response body differs from the previous poll."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("polling_http_source")
        self.cfg = PollingHttpConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    def tables(self) -> List[TableDescriptor]:
        return [global_table("h", "polling http state")]

    async def run(self, ctx: Context) -> SourceFinishType:
        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL
        import aiohttp

        state = ctx.state.get_global_keyed_state("h")
        polls = state.get("polls") or 0
        last_body: Optional[bytes] = None
        runner = getattr(ctx, "_runner", None)
        headers = _parse_headers(self.cfg.headers)
        # source-side coalescing: each poll yields ONE payload — without
        # the boundary batcher every poll paid a full decode + collect +
        # downstream envelope.  Poll counts are recorded at fetch time;
        # the runner flushes buffered bodies before checkpoints/stop, so
        # resume semantics are unchanged.
        batcher = self.make_batcher(ctx, self.fmt.batch, 0)

        async with aiohttp.ClientSession() as session:
            while self.cfg.max_polls is None or polls < self.cfg.max_polls:
                async with session.request(
                        self.cfg.method, self.cfg.endpoint, headers=headers,
                        data=self.cfg.body) as resp:
                    resp.raise_for_status()
                    body = await resp.read()
                polls += 1
                if self.cfg.emit_behavior == "all" or body != last_body:
                    last_body = body
                    await batcher.add([body])
                state.insert("polls", polls)
                if runner is not None:
                    cm = await runner.poll_source_control()
                    if cm is not None and cm.kind == "stop":
                        return (SourceFinishType.GRACEFUL
                                if cm.stop_mode != StopMode.IMMEDIATE
                                else SourceFinishType.IMMEDIATE)
                sleep_secs = self.cfg.poll_interval_ms / 1000
                if sleep_secs >= batcher.linger:
                    # the next wait would overshoot the linger bound: a
                    # buffered body must not be delayed a whole poll
                    # interval (slow polls emit per poll, as pre-batcher)
                    await batcher.flush()
                else:
                    await batcher.maybe_flush()
                await asyncio.sleep(sleep_secs)
        return SourceFinishType.FINAL


class WebhookConfig(BaseModel):
    endpoint: str
    headers: Optional[str] = None
    format: str = "json"
    format_options: Dict[str, Any] = {}
    max_inflight: int = 50


class WebhookSink(Operator):
    """POSTs each row to an endpoint (webhook.rs) with a bounded in-flight
    window; watermark/checkpoint barriers drain in-flight requests, so
    delivery is at-least-once relative to the last checkpoint."""

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("webhook_sink")
        self.cfg = WebhookConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)
        self._session = None
        self._inflight: set = set()

    async def on_start(self, ctx: Context) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(
            headers=_parse_headers(self.cfg.headers))

    async def _drain(self) -> None:
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=False)
            self._inflight.clear()

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        for payload in self.fmt.serialize_batch(batch):
            while len(self._inflight) >= self.cfg.max_inflight:
                done, self._inflight = await asyncio.wait(
                    self._inflight, return_when=asyncio.FIRST_COMPLETED)
                for d in done:
                    # arroyolint: disable=async-blocking -- d comes from asyncio.wait's done set; .result() only propagates errors
                    d.result()

            async def post(p=payload):
                async with self._session.post(self.cfg.endpoint, data=p) as r:
                    r.raise_for_status()

            self._inflight.add(asyncio.ensure_future(post()))

    async def pre_checkpoint(self, barrier, ctx: Context) -> None:
        await self._drain()

    async def on_close(self, ctx: Context) -> None:
        await self._drain()
        if self._session is not None:
            await self._session.close()


register_connector(ConnectorMeta(
    name="sse", description="server-sent events source",
    source_factory=SseSource, config_model=SseConfig))
register_connector(ConnectorMeta(
    name="polling_http", description="polling HTTP source",
    source_factory=PollingHttpSource, config_model=PollingHttpConfig))
register_connector(ConnectorMeta(
    name="webhook", description="HTTP POST sink",
    sink_factory=WebhookSink, config_model=WebhookConfig))
