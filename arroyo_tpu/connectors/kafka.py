"""Kafka source/sink with exactly-once semantics.

Analog of the reference's kafka connector (/root/reference/arroyo-worker/src/
connectors/kafka/): the source owns a subset of partitions per subtask,
stores per-partition offsets in global state table 's' (source/mod.rs:117-266)
and resumes by seeking; the sink is transactional — rows are produced inside
a transaction that is only committed in the second phase of the checkpoint
(exactly-once, mirroring the reference's TwoPhaseCommitter kafka sink).

The broker client is pluggable: ``bootstrap_servers='memory://<name>'`` uses
the in-process :class:`InMemoryKafkaBroker` (the test rig — the reference's
kafka tests likewise drive a real local broker by hand, kafka/source/test.rs);
anything else routes through :class:`AioKafkaBroker`, an aiokafka-backed
adapter (clear error when the library is absent).  Real-broker integration
tests live in tests/test_kafka_integration.py (``pytest -m kafka`` with
``KAFKA_BOOTSTRAP`` set).  Confluent-framed payloads resolve writer schemas
through :mod:`.schema_registry` when ``format_options.schema_registry_url``
is configured.
"""

from __future__ import annotations

import asyncio
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import SourceFinishType, SourceOperator
from ..formats import make_format
from ..state.tables import TableDescriptor, global_table
from ..types import StopMode
from .registry import ConnectorMeta, register_connector
from .two_phase import TwoPhaseCommitterSink


class KafkaConfig(BaseModel):
    bootstrap_servers: str
    topic: str
    group_id: Optional[str] = None
    format: str = "json"
    offset: str = "earliest"  # 'earliest' | 'latest' when no stored state
    read_mode: str = "read_committed"
    batch_size: Optional[int] = None
    format_options: Dict[str, Any] = {}  # e.g. avro schema / framing opts
    client_configs: Dict[str, str] = {}
    max_messages: Optional[int] = None  # bounded runs (tests)


# ---------------------------------------------------------------------------
# In-memory broker (test rig / memory:// bootstrap)
# ---------------------------------------------------------------------------


@dataclass
class _KRecord:
    partition: int
    offset: int
    key: Optional[bytes]
    value: bytes


@dataclass
class _Partition:
    log: List[Tuple[Optional[bytes], bytes]] = field(default_factory=list)
    # offsets of records whose producing transaction committed
    committed_watermark: int = 0  # LSO: records below this are committed


class InMemoryKafkaBroker:
    """A tiny transactional log: partitions, append, fetch-from-offset, and
    transaction begin/commit/abort with a last-stable-offset, enough to test
    exactly-once source resume and transactional sink semantics."""

    _instances: Dict[str, "InMemoryKafkaBroker"] = {}

    def __init__(self) -> None:
        self.topics: Dict[str, List[_Partition]] = {}
        self._txns: Dict[str, List[Tuple[str, int, Optional[bytes], bytes]]] = {}

    @classmethod
    def get(cls, name: str) -> "InMemoryKafkaBroker":
        return cls._instances.setdefault(name, cls())

    @classmethod
    def reset(cls, name: str) -> None:
        cls._instances.pop(name, None)

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        self.topics.setdefault(topic, [_Partition() for _ in range(partitions)])

    def partitions(self, topic: str) -> int:
        self.create_topic(topic)
        return len(self.topics[topic])

    def latest_offset(self, topic: str, partition: int) -> int:
        self.create_topic(topic)
        return len(self.topics[topic][partition].log)

    # -- produce ------------------------------------------------------

    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None,
                partition: Optional[int] = None) -> int:
        self.create_topic(topic)
        parts = self.topics[topic]
        p = (partition if partition is not None
             else (hash(key) if key else len(parts[0].log)) % len(parts))
        parts[p].log.append((key, value))
        off = len(parts[p].log) - 1
        parts[p].committed_watermark = len(parts[p].log)
        return off

    def begin_txn(self, txn_id: str) -> None:
        self._txns[txn_id] = []

    def produce_txn(self, txn_id: str, topic: str, value: bytes,
                    key: Optional[bytes] = None,
                    partition: Optional[int] = None) -> None:
        self.create_topic(topic)
        p = (partition if partition is not None
             else 0 if key is None else hash(key) % self.partitions(topic))
        self._txns[txn_id].append((topic, p, key, value))

    def commit_txn(self, txn_id: str) -> None:
        for topic, p, key, value in self._txns.pop(txn_id, []):
            part = self.topics[topic][p]
            part.log.append((key, value))
            part.committed_watermark = len(part.log)

    def abort_txn(self, txn_id: str) -> None:
        self._txns.pop(txn_id, None)

    # -- fetch --------------------------------------------------------

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int, read_committed: bool = True) -> List[_KRecord]:
        self.create_topic(topic)
        part = self.topics[topic][partition]
        hi = part.committed_watermark if read_committed else len(part.log)
        out = []
        for off in range(max(offset, 0), min(hi, offset + max_records)):
            key, value = part.log[off]
            out.append(_KRecord(partition, off, key, value))
        return out

    def fetch_values(self, topic: str, partition: int, offset: int,
                     max_records: int, read_committed: bool = True
                     ) -> Tuple[List[bytes], int]:
        """Bulk fetch: (payload values, last offset) without per-record
        envelope objects — the source hot loop's path (a per-message
        namedtuple costs more than the json parse at high rates)."""
        self.create_topic(topic)
        part = self.topics[topic][partition]
        hi = part.committed_watermark if read_committed else len(part.log)
        a, b = max(offset, 0), min(hi, offset + max_records)
        if b <= a:
            return [], offset - 1
        return [v for _, v in part.log[a:b]], b - 1


# ---------------------------------------------------------------------------
# Real-broker adapter (aiokafka)
# ---------------------------------------------------------------------------


class AioKafkaBroker:
    """Adapter exposing the ``InMemoryKafkaBroker`` fetch/produce surface
    over aiokafka for real brokers (kafka/source/mod.rs + sink analog).

    Methods are coroutines (call sites await when the broker returns an
    awaitable).  The transactional sink keeps one producer per OPEN
    transaction: a sealed-but-uncommitted epoch parks its producer until
    the commit phase, and new inserts draw a fresh producer — Kafka
    permits one in-flight transaction per producer, and the two-phase
    protocol overlaps epochs (the reference's rdkafka sink does the
    same via transactional producer instances)."""

    def __init__(self, bootstrap: str, client_configs: Dict[str, str]):
        try:
            import aiokafka  # noqa: F401
        except ImportError:
            raise RuntimeError(
                "real Kafka requires aiokafka (pip install aiokafka); "
                "use bootstrap_servers='memory://<name>' for the "
                "in-process broker")
        self.bootstrap = bootstrap
        self.client_configs = client_configs
        self._consumer = None
        self._isolation = True
        self._positions: Dict[Any, int] = {}  # tp -> next expected offset
        self._producers: Dict[str, Any] = {}  # txn_id -> started producer

    async def _get_consumer(self, read_committed: bool = True):
        # the isolation level is fixed at construction: recreate the
        # consumer if a different level is requested later (read_mode is
        # per-source, so in practice this happens at most once)
        if self._consumer is not None and self._isolation != read_committed:
            await self._consumer.stop()
            self._consumer = None
            self._positions.clear()
        if self._consumer is None:
            from aiokafka import AIOKafkaConsumer

            self._consumer = AIOKafkaConsumer(
                bootstrap_servers=self.bootstrap,
                enable_auto_commit=False,
                isolation_level=("read_committed" if read_committed
                                 else "read_uncommitted"),
                **self.client_configs)
            self._isolation = read_committed
            await self._consumer.start()
        return self._consumer

    async def partitions(self, topic: str,
                         read_committed: bool = True) -> int:
        c = await self._get_consumer(read_committed)
        parts = c.partitions_for_topic(topic)
        if not parts:
            # topic metadata may not be cached yet: .topics() forces a
            # metadata fetch (a bare sleep would wait out
            # metadata_max_age_ms, default 5 min)
            await c.topics()
            parts = c.partitions_for_topic(topic)
        if not parts:
            # guessing a partition count would silently strand data on
            # the unguessed partitions for the lifetime of the job
            raise RuntimeError(
                f"kafka topic {topic!r} has no partition metadata at "
                f"{self.bootstrap}; does the topic exist?")
        return len(parts)

    async def latest_offset(self, topic: str, partition: int) -> int:
        from aiokafka import TopicPartition

        c = await self._get_consumer(self._isolation)
        offs = await c.end_offsets([TopicPartition(topic, partition)])
        return int(next(iter(offs.values())))

    async def fetch(self, topic: str, partition: int, offset: int,
                    max_records: int, read_committed: bool = True
                    ) -> List[_KRecord]:
        from aiokafka import TopicPartition

        c = await self._get_consumer(read_committed)
        tp = TopicPartition(topic, partition)
        # accumulate the assignment and keep positions: an unconditional
        # assign+seek would discard aiokafka's prefetch buffer per call.
        # assign() REPLACES the whole subscription and resets every
        # partition's fetch position — all cached positions invalidate
        if tp not in c.assignment():
            c.assign(sorted(c.assignment() | {tp}))
            self._positions.clear()
        want = max(offset, 0)
        if self._positions.get(tp) != want:
            c.seek(tp, want)
        data = await c.getmany(tp, timeout_ms=50, max_records=max_records)
        recs = data.get(tp, [])
        if recs:
            self._positions[tp] = recs[-1].offset + 1
        else:
            self._positions[tp] = want
        # arroyolint: disable=row-loop -- aiokafka hands back per-record objects; this is client-API framing, not decode
        return [_KRecord(partition, m.offset, m.key, m.value)
                for m in recs]

    # -- transactional produce ----------------------------------------

    async def begin_txn(self, txn_id: str) -> None:
        from aiokafka import AIOKafkaProducer

        prod = AIOKafkaProducer(
            bootstrap_servers=self.bootstrap, transactional_id=txn_id,
            **self.client_configs)
        await prod.start()
        await prod.begin_transaction()
        self._producers[txn_id] = prod

    async def produce_txn(self, txn_id: str, topic: str, value: bytes,
                          key: Optional[bytes] = None,
                          partition: Optional[int] = None) -> None:
        await self._producers[txn_id].send(topic, value=value, key=key,
                                           partition=partition)

    async def commit_txn(self, txn_id: str) -> None:
        prod = self._producers.pop(txn_id, None)
        if prod is None:
            # a pre-committed epoch recovered after a crash: Kafka's
            # transaction protocol cannot commit a previous producer
            # incarnation's transaction — re-initializing the
            # transactional id FENCES and ABORTS it (aiokafka exposes no
            # resume API; the reference's rdkafka sink shares this
            # limitation).  Failing loudly keeps the loss visible instead
            # of silently dropping the epoch while offsets advance.
            raise RuntimeError(
                f"cannot commit recovered kafka transaction {txn_id!r}: "
                "the producing session died before its commit phase and "
                "Kafka aborts in-flight transactions on producer "
                "re-initialization; the epoch's rows were not published")
        await prod.commit_transaction()
        await prod.stop()

    async def abort_txn(self, txn_id: str) -> None:
        prod = self._producers.pop(txn_id, None)
        if prod is not None:
            await prod.abort_transaction()
            await prod.stop()

    async def close(self) -> None:
        if self._consumer is not None:
            await self._consumer.stop()
            self._consumer = None
        for txn in list(self._producers):
            await self.abort_txn(txn)


def make_broker(bootstrap_servers: str, client_configs: Dict[str, str]):
    """memory:// -> in-process broker; anything else -> aiokafka."""
    if bootstrap_servers.startswith("memory://"):
        return InMemoryKafkaBroker.get(bootstrap_servers[len("memory://"):])
    return AioKafkaBroker(bootstrap_servers, client_configs)


async def _aw(v):
    """Await-tolerant call result: the in-memory broker is sync, the
    aiokafka adapter returns coroutines."""
    import inspect

    if inspect.isawaitable(v):
        return await v
    return v


# ---------------------------------------------------------------------------
# Source
# ---------------------------------------------------------------------------


class KafkaSource(SourceOperator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("kafka_source")
        self.cfg = KafkaConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    def tables(self) -> List[TableDescriptor]:
        # table 's': partition -> last-read offset (source/mod.rs:155-175)
        return [global_table("s", "kafka partition offsets")]

    def _broker(self):
        return make_broker(self.cfg.bootstrap_servers,
                           self.cfg.client_configs)

    async def run(self, ctx: Context) -> SourceFinishType:
        broker = self._broker()
        try:
            return await self._run(broker, ctx)
        finally:
            closer = getattr(broker, "close", None)
            if closer is not None:
                await _aw(closer())

    async def _run(self, broker, ctx: Context) -> SourceFinishType:
        state = ctx.state.get_global_keyed_state("s")
        read_committed = self.cfg.read_mode == "read_committed"
        # real-broker adapter: create the consumer at the configured
        # isolation level up front (it is fixed per consumer instance)
        warm = getattr(broker, "_get_consumer", None)
        if warm is not None:
            await warm(read_committed)
            n_parts = await _aw(broker.partitions(self.cfg.topic,
                                                  read_committed))
        else:
            n_parts = await _aw(broker.partitions(self.cfg.topic))
        me, n = ctx.task_info.task_index, ctx.task_info.parallelism
        my_parts = [p for p in range(n_parts) if p % n == me]
        if not my_parts:
            return SourceFinishType.FINAL

        offsets: Dict[int, int] = {}
        for p in my_parts:
            stored = state.get(p)
            if stored is not None:
                offsets[p] = stored + 1
            elif self.cfg.offset == "latest":
                offsets[p] = await _aw(
                    broker.latest_offset(self.cfg.topic, p))
            else:
                offsets[p] = 0

        runner = getattr(ctx, "_runner", None)
        batch_size = self.cfg.batch_size or config().target_batch_size
        total = 0
        idle_spins = 0
        bulk = getattr(broker, "fetch_values", None)
        # source-side coalescing: partition fetches that return small
        # fragments accumulate at the boundary and decode/emit as ONE
        # target-size batch (the runner flushes before checkpoints and
        # stop, so offsets recorded at fetch time stay exactly-once)
        batcher = self.make_batcher(ctx, self.fmt.batch, batch_size)
        while True:
            got = 0
            for p in my_parts:
                # both broker surfaces normalize to (values, last_offset)
                # so the consume bookkeeping below exists exactly once
                if bulk is not None:
                    vals, last = await _aw(bulk(
                        self.cfg.topic, p, offsets[p], batch_size,
                        read_committed))
                else:
                    recs = await _aw(broker.fetch(
                        self.cfg.topic, p, offsets[p], batch_size,
                        read_committed))
                    # arroyolint: disable=row-loop -- per-record value gather is the broker API's shape; decode is batched downstream
                    vals = [r.value for r in recs]
                    last = recs[-1].offset if recs else offsets[p] - 1
                if vals:
                    got += len(vals)
                    total += len(vals)
                    await batcher.add(vals)
                    offsets[p] = last + 1
                    state.insert(p, last)
            await batcher.maybe_flush()
            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return (SourceFinishType.GRACEFUL
                            if cm.stop_mode != StopMode.IMMEDIATE
                            else SourceFinishType.IMMEDIATE)
            if self.cfg.max_messages is not None and total >= self.cfg.max_messages:
                return SourceFinishType.FINAL
            if got == 0:
                idle_spins += 1
                if self.cfg.max_messages is not None and idle_spins > 50:
                    return SourceFinishType.FINAL  # bounded test run drained
                await asyncio.sleep(0.01)
            else:
                idle_spins = 0
                await asyncio.sleep(0)


# ---------------------------------------------------------------------------
# Sink (transactional, exactly-once)
# ---------------------------------------------------------------------------


class KafkaSink(TwoPhaseCommitterSink):
    _txn_counter = itertools.count()

    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("kafka_sink")
        self.cfg = KafkaConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)
        self._txn_id: Optional[str] = None

    def _broker(self):
        if getattr(self, "_b", None) is None:
            self._b = make_broker(self.cfg.bootstrap_servers,
                                  self.cfg.client_configs)
        return self._b

    async def committer_init(self, recovery_state, ctx: Context) -> None:
        self._subtask = ctx.task_info.task_index

    async def _ensure_txn(self) -> str:
        if self._txn_id is None:
            self._txn_id = (f"arroyo-{self.cfg.topic}-{self._subtask}-"
                            f"{next(self._txn_counter)}")
            await _aw(self._broker().begin_txn(self._txn_id))
        return self._txn_id

    async def insert_batch(self, batch, ctx: Context) -> None:
        txn = await self._ensure_txn()
        broker = self._broker()
        for payload in self.fmt.serialize_batch(batch):
            await _aw(broker.produce_txn(txn, self.cfg.topic, payload))

    async def committer_checkpoint(self, epoch: int, stopping: bool,
                                   ctx: Context):
        # Seal the open transaction as the pre-commit unit; a fresh txn
        # starts on the next insert.  Commit happens in phase two.
        txn, self._txn_id = self._txn_id, None
        pre = {txn: {"txn_id": txn}} if txn is not None else {}
        return None, pre

    async def committer_commit(self, epoch: int, pre_commits, ctx: Context) -> None:
        broker = self._broker()
        for pc in pre_commits.values():
            await _aw(broker.commit_txn(pc["txn_id"]))

    async def on_close(self, ctx: Context) -> None:
        # stream ended without a final barrier: commit the dangling txn so
        # graceful end-of-data flushes (barrier-stopped runs never hit this
        # with an open txn)
        if self._txn_id is not None:
            await _aw(self._broker().commit_txn(self._txn_id))
            self._txn_id = None
        broker = getattr(self, "_b", None)
        closer = getattr(broker, "close", None)
        if closer is not None:
            await _aw(closer())


register_connector(ConnectorMeta(
    name="kafka",
    description="kafka source (offset state) / transactional exactly-once sink",
    source_factory=KafkaSource,
    sink_factory=KafkaSink,
    config_model=KafkaConfig,
))
