"""Nexmark event generator — faithful vectorized port of the reference's
generator (/root/reference/arroyo-worker/src/connectors/nexmark/mod.rs:27-120,
280-770): same proportions (person:auction:bid = 1:3:46), id spaces
(FIRST_PERSON_ID/FIRST_AUCTION_ID = 1000), hot-key ratios (hot sellers 3/4 at
HOT_SELLER_RATIO=100 granularity, hot auctions 1/2 at 100, hot bidders 3/4 at
100), out-of-order event times via the (event_number * 953) % 50 shuffle, the
price distribution 10^U(0,6)*100, bounded in-flight auctions (100) and active
people (1000), and the same exactly-once resume state (config, event_count) in
a global table (mod.rs:80-120).

The per-event Rust loop becomes one vectorized numpy pass per batch: all id
arithmetic is closed-form in the event index, so a whole batch of events is
produced with ~20 array ops.  Event batches use the union-column layout
{event_type, person_*, auction_*, bid_*} mirroring Event{person, bid, auction}
(arroyo-types/src/lib.rs:697-732).
"""

from __future__ import annotations

import asyncio
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import SourceFinishType, SourceOperator
from ..state.tables import TableDescriptor, global_table
from ..types import Batch, StopMode, now_micros
from .registry import ConnectorMeta, register_connector

# Constants (mod.rs:27-44)
HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100
HOT_CHANNELS_RATIO = 2
CHANNELS_NUMBER = 10_000
HOT_SELLER_RATIO = 100
PERSON_ID_LEAD = 10
AUCTION_ID_LEAD = 10
FIRST_AUCTION_ID = 1000
FIRST_PERSON_ID = 1000
FIRST_CATEGORY_ID = 10
NUM_CATEGORIES = 5
MIN_STRING_LENGTH = 3

FIRST_NAMES = ["Peter", "Paul", "Luke", "John", "Saul", "Vicky", "Kate",
               "Julie", "Sarah", "Deiter", "Walter"]
LAST_NAMES = ["Shultz", "Abrams", "Spencer", "White", "Bartels", "Walton",
              "Smith", "Jones", "Noris"]
US_CITIES = ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland",
             "Bend", "Redmond", "Seattle", "Kent", "Cheyenne"]
US_STATES = ["AZ", "CA", "ID", "OR", "WA", "WY"]
HOT_CHANNELS = ["Google", "Facebook", "Baidu", "Apple"]
HOT_URLS = [
    "https://www.nexmark.com/abo/eoci/cidro/item.htm?query=1",
    "https://www.nexmark.com/eoax/oad/cidro/item.htm?query=1",
    "https://www.nexmark.com/abo/jack/cidro/item.htm?query=1",
    "https://www.nexmark.com/abo/micah/cidro/item.htm?query=1",
]

EVENT_PERSON, EVENT_AUCTION, EVENT_BID = 0, 1, 2


class NexmarkConfig(BaseModel):
    """NexmarkConfig defaults (mod.rs:405-445)."""

    event_rate: float = 100_000.0
    runtime_secs: Optional[float] = None  # num_events = rate * runtime
    num_events: Optional[int] = None
    person_proportion: int = 1
    auction_proportion: int = 3
    bid_proportion: int = 46
    hot_seller_ratio: int = 4  # P(hot) = 1 - 1/ratio
    hot_auction_ratio: int = 2
    hot_bidders_ratio: int = 4
    num_inflight_auctions: int = 100
    num_active_people: int = 1000
    out_of_order_group_size: int = 50
    generate_strings: bool = True
    rate_limited: bool = True  # False: generate as fast as possible (bench)
    batch_size: Optional[int] = None
    base_time_micros: Optional[int] = None  # pin event-time origin (bench
    # latency math needs wall(T) = wall_base + (T - base_time)/1e6 exactly)
    # planner-injected projection pushdown: physical columns the query
    # reads; None = generate everything.  Unused column families (notably
    # the string columns) are skipped entirely.
    projection: Optional[List[str]] = None


class NexmarkGenerator:
    """Deterministic batch generator for one split (GeneratorConfig,
    mod.rs:490-560).  All id computations are vectorized closed forms."""

    def __init__(self, cfg: NexmarkConfig, base_time_micros: int,
                 first_event_id: int, max_events: int, first_event_number: int,
                 seed: int):
        self.cfg = cfg
        self.base_time = int(base_time_micros)
        self.first_event_id = first_event_id
        self.max_events = max_events
        self.first_event_number = first_event_number
        self.total_prop = (cfg.person_proportion + cfg.auction_proportion
                           + cfg.bid_proportion)
        # projection pushdown: None = every column wanted
        self._want = (None if cfg.projection is None
                      else set(cfg.projection))
        # inter_event_delay covers the whole generator fleet (mod.rs:331-335):
        # delay = 1e6 / rate * n_generators
        self.rng = np.random.default_rng(seed)
        # independent per-family streams (the reference seeds per event id,
        # mod.rs:387-391, so families never share randomness): projection
        # pushdown can then skip a family without perturbing the others —
        # generation is exactly projection-invariant
        self._rngs = {fam: np.random.default_rng([seed, i])
                      for i, fam in enumerate(
                          ("auction", "bid", "person_s", "auction_s",
                           "bid_s"))}
        self.events_so_far = 0

    def set_rate(self, rate: float, n_generators: int) -> None:
        self.inter_event_delay = max(int(1_000_000.0 / rate * n_generators), 1)

    # -- RNG stream snapshot (exactly-once resume) -------------------------
    # The per-family streams advance as generation runs, so a resumed
    # generator must land every stream in the exact position the
    # delivered prefix left it — otherwise post-restore events differ
    # from the uninterrupted run.  Snapshotting the PCG64 states gives
    # O(1) restore (the alternative, replay-burning the prefix, is kept
    # as the fallback for checkpoints written before states were saved).

    def snapshot_rng_state(self) -> Dict[str, Any]:
        states = {fam: rng.bit_generator.state
                  for fam, rng in self._rngs.items()}
        states["__base"] = self.rng.bit_generator.state
        return states

    def restore_rng_state(self, states: Dict[str, Any]) -> None:
        for fam, rng in self._rngs.items():
            if fam in states:
                rng.bit_generator.state = states[fam]
        if "__base" in states:
            self.rng.bit_generator.state = states["__base"]

    @property
    def has_next(self) -> bool:
        return self.events_so_far < self.max_events

    # -- id arithmetic (vectorized ports of mod.rs:463-560) ----------------

    def _adjusted_event_number(self, num_events: np.ndarray) -> np.ndarray:
        n = self.cfg.out_of_order_group_size
        en = self.first_event_number + num_events
        base = (en // n) * n
        offset = (en * 953) % n
        return base + offset

    def _last_base0_person_id(self, event_id: np.ndarray) -> np.ndarray:
        pp, tp = self.cfg.person_proportion, self.total_prop
        epoch = event_id // tp
        offset = np.minimum(event_id % tp, pp - 1)
        return epoch * pp + offset

    def _last_base0_auction_id(self, event_id: np.ndarray) -> np.ndarray:
        pp, ap, tp = (self.cfg.person_proportion, self.cfg.auction_proportion,
                      self.total_prop)
        epoch = event_id // tp
        offset = event_id % tp
        about_person = offset < pp
        about_bid = offset >= pp + ap
        adj_epoch = np.where(about_person, epoch - 1, epoch)
        adj_offset = np.where(about_person | about_bid, ap - 1,
                              np.clip(offset - pp, 0, ap - 1))
        return adj_epoch * ap + adj_offset

    def _next_base0_person_id(self, event_id: np.ndarray,
                              num_people: Optional[np.ndarray] = None,
                              rng=None) -> np.ndarray:
        rng = rng or self.rng
        if num_people is None:
            num_people = self._last_base0_person_id(event_id)
        active = np.minimum(num_people, self.cfg.num_active_people)
        n = (rng.random(len(event_id)) * (active + PERSON_ID_LEAD)).astype(np.int64)
        return num_people - active + n

    def _next_base0_auction_id(self, event_id: np.ndarray,
                               max_a: Optional[np.ndarray] = None,
                               rng=None) -> np.ndarray:
        if max_a is None:
            max_a = self._last_base0_auction_id(event_id)
        rng = rng or self.rng
        min_a = np.maximum(max_a - self.cfg.num_inflight_auctions, 0)
        span = max_a + 1 + AUCTION_ID_LEAD - min_a
        return min_a + (rng.random(len(event_id)) * span).astype(np.int64)

    def _timestamp_for(self, event_number: np.ndarray) -> np.ndarray:
        return self.base_time + self.inter_event_delay * event_number

    def _next_price(self, n: int, rng=None) -> np.ndarray:
        rng = rng or self.rng
        return (np.power(10.0, rng.random(n) * 6.0) * 100.0).astype(np.int64)

    def _rand_strings(self, n: int, max_len: int, rng=None) -> np.ndarray:
        """Vectorized alphanumeric strings with the reference's U(3, max_len)
        length distribution (mod.rs:404-409)."""
        if n == 0:
            return np.zeros(0, dtype=object)
        rng = rng or self.rng
        lengths = rng.integers(MIN_STRING_LENGTH, max(max_len, MIN_STRING_LENGTH + 1), n)
        alphabet = np.frombuffer(
            b"ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789",
            dtype="S1")
        maxl = int(lengths.max())
        chars = alphabet[rng.integers(0, 62, (n, maxl))]
        flat = chars.view(f"S{maxl}").reshape(n).astype(str)
        return np.array([s[:l] for s, l in zip(flat, lengths)], dtype=object)

    # -- batch generation ---------------------------------------------------

    def next_batch(self, size: int) -> Tuple[Batch, np.ndarray]:
        """Generate the next ``size`` events; returns (batch, wallclock_event_numbers)."""
        n = min(size, self.max_events - self.events_so_far)
        i = np.arange(self.events_so_far, self.events_so_far + n, dtype=np.int64)
        self.events_so_far += n

        adj = self._adjusted_event_number(i)
        event_id = self.first_event_id + adj
        ts = self._timestamp_for(adj)  # event time (out of order)
        rem = event_id % self.total_prop

        pp, ap = self.cfg.person_proportion, self.cfg.auction_proportion
        is_person = rem < pp
        is_auction = (~is_person) & (rem < pp + ap)
        is_bid = ~(is_person | is_auction)

        etype = np.full(n, EVENT_BID, dtype=np.int8)
        etype[is_person] = EVENT_PERSON
        etype[is_auction] = EVENT_AUCTION

        cols: Dict[str, np.ndarray] = {"event_type": etype}
        # projection pushdown: skip whole column families the query never
        # reads (column order/rng draws stay deterministic per family for a
        # given projection, so exactly-once resume regenerates identically)
        want = self._want

        def w(*names: str) -> bool:
            return want is None or any(c in want for c in names)

        # shared closed forms computed once (the Rust generator recomputes
        # them per event; here per batch)
        last_person = self._last_base0_person_id(event_id)
        last_auction = self._last_base0_auction_id(event_id)

        # persons (next_person, mod.rs:545-587)
        if w("person_id"):
            cols["person_id"] = np.where(
                is_person, last_person + FIRST_PERSON_ID, 0)

        # auctions (next_auction, mod.rs:419-462)
        if w("auction_id", "auction_seller", "auction_category",
             "auction_initial_bid", "auction_reserve", "auction_expires",
             "auction_datetime"):
            rng_a = self._rngs["auction"]
            hot_seller = rng_a.random(n) * self.cfg.hot_seller_ratio >= 1.0
            seller = np.where(
                hot_seller,
                (last_person // HOT_SELLER_RATIO) * HOT_SELLER_RATIO,
                self._next_base0_person_id(event_id, last_person, rng=rng_a)
            ) + FIRST_PERSON_ID
            a_id = last_auction + FIRST_AUCTION_ID
            category = FIRST_CATEGORY_ID + rng_a.integers(
                0, NUM_CATEGORIES, n)
            initial_bid = self._next_price(n, rng=rng_a)
            reserve = initial_bid + self._next_price(n, rng=rng_a)
            # next_auction_length_ms (mod.rs:530-548)
            num_events_for_auctions = (
                self.cfg.num_inflight_auctions * self.total_prop) // ap
            horizon = self.inter_event_delay * num_events_for_auctions
            horizon_ms = max(horizon // 1000, 1)
            length_ms = 1 + np.maximum(
                (rng_a.random(n) * (horizon_ms * 2)).astype(np.int64), 1)
            expires = ts + length_ms * 1000
            cols["auction_id"] = np.where(is_auction, a_id, 0)
            cols["auction_seller"] = np.where(is_auction, seller, 0)
            cols["auction_category"] = np.where(is_auction, category, 0)
            cols["auction_initial_bid"] = np.where(is_auction, initial_bid, 0)
            cols["auction_reserve"] = np.where(is_auction, reserve, 0)
            cols["auction_expires"] = np.where(is_auction, expires, 0)
            cols["auction_datetime"] = np.where(is_auction, ts, 0)

        # bids (next_bid, mod.rs:588-631)
        if w("bid_auction", "bid_bidder", "bid_price", "bid_datetime"):
            rng_b = self._rngs["bid"]
            hot_auction = rng_b.random(n) * self.cfg.hot_auction_ratio >= 1.0
            bid_auction = np.where(
                hot_auction,
                (last_auction // HOT_AUCTION_RATIO) * HOT_AUCTION_RATIO,
                self._next_base0_auction_id(event_id, last_auction, rng=rng_b)
            ) + FIRST_AUCTION_ID
            hot_bidder = rng_b.random(n) * self.cfg.hot_bidders_ratio >= 1.0
            bidder = np.where(
                hot_bidder,
                (last_person // HOT_BIDDER_RATIO) * HOT_BIDDER_RATIO,
                self._next_base0_person_id(event_id, last_person, rng=rng_b)
            ) + FIRST_PERSON_ID
            bid_price = self._next_price(n, rng=rng_b)
            cols["bid_auction"] = np.where(is_bid, bid_auction, 0)
            cols["bid_bidder"] = np.where(is_bid, bidder, 0)
            cols["bid_price"] = np.where(is_bid, bid_price, 0)
            cols["bid_datetime"] = np.where(is_bid, ts, 0)

        if self.cfg.generate_strings and w(
                "person_name", "person_email", "person_city", "person_state",
                "person_extra"):
            np_idx = is_person.nonzero()[0]
            npn = len(np_idx)
            name = np.empty(n, dtype=object); name[:] = ""
            email = np.empty(n, dtype=object); email[:] = ""
            city = np.empty(n, dtype=object); city[:] = ""
            state = np.empty(n, dtype=object); state[:] = ""
            extra_p = np.empty(n, dtype=object); extra_p[:] = ""
            if npn:
                rng_ps = self._rngs["person_s"]
                fn = np.array(FIRST_NAMES, dtype=object)[rng_ps.integers(0, len(FIRST_NAMES), npn)]
                ln = np.array(LAST_NAMES, dtype=object)[rng_ps.integers(0, len(LAST_NAMES), npn)]
                name[np_idx] = fn + " " + ln
                email[np_idx] = (self._rand_strings(npn, 7, rng=rng_ps) + "@"
                                 + self._rand_strings(npn, 5, rng=rng_ps) + ".com")
                city[np_idx] = np.array(US_CITIES, dtype=object)[rng_ps.integers(0, len(US_CITIES), npn)]
                state[np_idx] = np.array(US_STATES, dtype=object)[rng_ps.integers(0, len(US_STATES), npn)]
                # padding to avg_person_byte_size=200 (next_extra_string,
                # mod.rs:406-416, 619-620); content is never queried
                extra_p[np_idx] = self._rand_strings(npn, 140, rng=rng_ps)
            cols["person_name"] = name
            cols["person_email"] = email
            cols["person_city"] = city
            cols["person_state"] = state
            cols["person_extra"] = extra_p

        if self.cfg.generate_strings and w(
                "auction_item_name", "auction_description", "auction_extra"):
            na_idx = is_auction.nonzero()[0]
            item_name = np.empty(n, dtype=object); item_name[:] = ""
            desc = np.empty(n, dtype=object); desc[:] = ""
            extra_a = np.empty(n, dtype=object); extra_a[:] = ""
            if len(na_idx):
                rng_as = self._rngs["auction_s"]
                item_name[na_idx] = self._rand_strings(len(na_idx), 20, rng=rng_as)
                desc[na_idx] = self._rand_strings(len(na_idx), 100, rng=rng_as)
                # padding to avg_auction_byte_size=500 (mod.rs:444-449)
                extra_a[na_idx] = self._rand_strings(len(na_idx), 330,
                                                     rng=rng_as)
            cols["auction_item_name"] = item_name
            cols["auction_description"] = desc
            cols["auction_extra"] = extra_a

        if self.cfg.generate_strings and w("bid_channel", "bid_url",
                                           "bid_extra"):
            nb_idx = is_bid.nonzero()[0]
            channel = np.empty(n, dtype=object); channel[:] = ""
            url = np.empty(n, dtype=object); url[:] = ""
            extra_b = np.empty(n, dtype=object); extra_b[:] = ""
            if len(nb_idx):
                nb = len(nb_idx)
                rng_bs = self._rngs["bid_s"]
                hot_ch = (rng_bs.random(nb) * HOT_CHANNELS_RATIO).astype(np.int64) > 0
                hidx = rng_bs.integers(0, 4, nb)
                cold_id = rng_bs.integers(0, CHANNELS_NUMBER, nb)
                ch = np.where(hot_ch, np.array(HOT_CHANNELS, dtype=object)[hidx],
                              np.char.add("channel-", cold_id.astype(str)).astype(object))
                u = np.where(hot_ch, np.array(HOT_URLS, dtype=object)[hidx],
                             np.char.add(
                                 "https://www.nexmark.com/item.htm?query=1&channel_id=",
                                 cold_id.astype(str)).astype(object))
                channel[nb_idx] = ch
                url[nb_idx] = u
                # padding to avg_bid_byte_size=100 (mod.rs:571-575)
                extra_b[nb_idx] = self._rand_strings(nb, 20, rng=rng_bs)
            cols["bid_channel"] = channel
            cols["bid_url"] = url
            cols["bid_extra"] = extra_b

        return Batch(ts, cols), i


def make_splits(cfg: NexmarkConfig, base_time: int, parallelism: int
                ) -> List[Tuple[int, int, int]]:
    """GeneratorConfig::split (mod.rs:382-402): divide max_events among
    generators; returns (first_event_id, max_events, first_event_number)."""
    num_events = cfg.num_events
    if num_events is None and cfg.runtime_secs is not None:
        num_events = int(cfg.event_rate * cfg.runtime_secs)
    if num_events is None:
        num_events = 2**62
    if parallelism == 1:
        return [(1, num_events, 1)]
    sub = num_events // parallelism
    out = []
    first_id = 1
    for i in range(parallelism):
        me = num_events - sub * (parallelism - 1) if i == parallelism - 1 else sub
        out.append((first_id, me, 1))
        first_id += me
    return out


class NexmarkSource(SourceOperator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("nexmark")
        self.cfg = NexmarkConfig(**cfg)

    def tables(self) -> List[TableDescriptor]:
        return [global_table("s", "nexmark source state")]

    async def run(self, ctx: Context) -> SourceFinishType:
        state = ctx.state.get_global_keyed_state("s")
        saved = state.get(ctx.task_info.task_index)
        par = ctx.task_info.parallelism
        rng_states = None
        if saved is not None:
            base_time, split, count = saved[:3]
            rng_states = saved[3] if len(saved) > 3 else None
        else:
            base_time = (self.cfg.base_time_micros
                         if self.cfg.base_time_micros is not None
                         else now_micros())
            split = make_splits(self.cfg, base_time, par)[ctx.task_info.task_index]
            count = 0

        gen = NexmarkGenerator(self.cfg, base_time, split[0], split[1], split[2],
                               seed=ctx.task_info.task_index)
        gen.set_rate(self.cfg.event_rate, par)

        batch_size = self.cfg.batch_size or config().target_batch_size
        if count and rng_states is not None:
            # O(1) resume: land every RNG stream in the exact position
            # the delivered prefix left it (see snapshot_rng_state)
            gen.restore_rng_state(rng_states)
            gen.events_so_far = count
        elif count:
            # Pre-snapshot checkpoint: replay-burn to the position.
            # Draws are blocked per call site within each generated
            # batch, so the burn must regenerate with the SAME batch
            # size the original delivery used — then every stream lands
            # exactly where the uninterrupted run would have it.  Cost:
            # one vectorized pass over the already-delivered prefix.
            while gen.events_so_far < count and gen.has_next:
                gen.next_batch(min(batch_size, count - gen.events_so_far))
            if gen.events_so_far != count:
                raise RuntimeError(
                    f"nexmark resume burn landed at {gen.events_so_far} "
                    f"events but the checkpoint recorded {count}; the "
                    "resumed stream would not be the delivered stream. "
                    "Possible causes: the table's num_events/batch_size/"
                    "event_rate config changed since the checkpoint was "
                    "written (config drift), or the checkpoint predates "
                    "RNG-state snapshots and its count is not reachable "
                    "with the current batch size")
        runner = getattr(ctx, "_runner", None)
        wall_base = _time.monotonic() - (gen.inter_event_delay * count) / 1e6
        from ..obs import latency as _latency
        from ..obs import perf, profiler

        prof = profiler.active()
        op_id = ctx.task_info.operator_id

        # anchors for the bench's end-to-end latency math: event with
        # time T is emitted at wall_base + (T - base_time)/1e6
        perf.note("nexmark_wall_base", wall_base)
        perf.note("nexmark_base_time", base_time)

        # PREFETCH: generate batch N+1 on a worker thread while batch N
        # flows through the (largely GIL-releasing numpy/XLA) pipeline.
        # Exactly-once stays intact because the checkpointed count is
        # captured WITH each batch at generation time — a barrier between
        # emit and prefetch never records the in-flight batch's events.
        loop = asyncio.get_running_loop()

        def gen_next():
            # executor thread: generation/decode cost lands in the
            # `source_decode` phase directly (no nesting off-loop) —
            # the measured half of "the host path" on ingest
            t0 = _time.perf_counter() if prof is not None else 0.0
            b, nums = gen.next_batch(batch_size)
            # RNG states are captured WITH the count at generation time,
            # so a barrier between emit and prefetch checkpoints a
            # consistent (count, stream-position) pair
            out = b, nums, gen.events_so_far, gen.snapshot_rng_state()
            if prof is not None:
                prof.add(op_id, "source_decode",
                         _time.perf_counter() - t0)
            return out

        # emission log for the latency bench: (cummax event time, wall) per
        # batch — latency is then measured against when the watermark-
        # advancing event actually left the source, not an idealized rate
        # schedule (only kept for rate-limited runs; bench-sized logs)
        emit_log: list = []
        if self.cfg.rate_limited:
            perf.note("nexmark_emit_log", emit_log)

        fut = loop.run_in_executor(None, gen_next) if gen.has_next else None
        while fut is not None:
            batch, nums, count_after, rng_snap = await fut
            fut = (loop.run_in_executor(None, gen_next)
                   if gen.has_next else None)
            _latency.maybe_stamp(ctx.task_info.operator_id, batch)
            await ctx.collect(batch)
            if self.cfg.rate_limited and len(batch):
                mx = int(np.max(batch.timestamp))
                if not emit_log or mx > emit_log[-1][0]:
                    emit_log.append((mx, _time.monotonic()))
            # the 4-tuple (incl. the RNG snapshot captured WITH the count)
            # is what makes the O(1) restore path live: a barrier now
            # checkpoints a consistent (count, stream-position) pair
            state.insert(ctx.task_info.task_index,
                         (base_time, split, count_after, rng_snap))
            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return (SourceFinishType.GRACEFUL
                            if cm.stop_mode != StopMode.IMMEDIATE
                            else SourceFinishType.IMMEDIATE)
            if self.cfg.rate_limited and len(nums):
                target_wall = wall_base + (gen.inter_event_delay * int(nums[-1] + 1)) / 1e6
                ahead = target_wall - _time.monotonic()
                if ahead > 0:
                    await asyncio.sleep(ahead)
                else:
                    await asyncio.sleep(0)
            else:
                await asyncio.sleep(0)
        return SourceFinishType.FINAL


register_connector(ConnectorMeta(
    name="nexmark",
    description="Nexmark benchmark event generator",
    source_factory=NexmarkSource,
    config_model=NexmarkConfig,
))
