"""AWS Kinesis source/sink (the reference's kinesis connector,
/root/reference/arroyo-worker/src/connectors/kinesis/).

No AWS SDK lives in this image, so the client is a minimal stdlib
SigV4-signed JSON API client (the same dependency-free pattern as the
in-cluster Kubernetes client): ListShards / GetShardIterator /
GetRecords for the source, PutRecords for the sink.  Tests inject a
fake client with the same four methods.

Exactly-once resume mirrors the kafka connector: per-shard last-read
sequence numbers live in GlobalKeyedState table 's' and seek with
AFTER_SEQUENCE_NUMBER on restore (the reference checkpoints
SequenceNumber the same way).
"""

from __future__ import annotations

import asyncio
import base64
import datetime
import hashlib
import hmac
import json
import os
import time
from typing import Any, Dict, List, Literal, Optional

from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import Operator, SourceFinishType, SourceOperator
from ..formats import make_format
from ..state.tables import TableDescriptor, global_table
from ..types import Batch, StopMode
from .registry import ConnectorMeta, register_connector


class KinesisConfig(BaseModel):
    stream_name: str
    region: str = "us-east-1"
    format: str = "json"
    format_options: Dict[str, Any] = {}
    batch_size: Optional[int] = None
    max_messages: Optional[int] = None  # bounded runs (tests)
    offset: Literal["earliest", "latest"] = "earliest"
    partition_key_field: Optional[str] = None  # sink routing
    endpoint_url: Optional[str] = None  # localstack/testing


class KinesisClient:
    """Stdlib SigV4 client for the Kinesis JSON API."""

    SERVICE = "kinesis"

    def __init__(self, region: str, endpoint_url: Optional[str] = None,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 session_token: Optional[str] = None):
        self.region = region
        self.endpoint = endpoint_url or \
            f"https://kinesis.{region}.amazonaws.com"
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY")
        self.session_token = session_token or os.environ.get(
            "AWS_SESSION_TOKEN")
        if not self.access_key or not self.secret_key:
            raise RuntimeError(
                "kinesis needs AWS credentials "
                "(AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY)")

    # -- SigV4 (stdlib) ----------------------------------------------------

    def _sign(self, body: bytes, target: str) -> Dict[str, str]:
        t = datetime.datetime.now(datetime.timezone.utc)
        amz_date = t.strftime("%Y%m%dT%H%M%SZ")
        datestamp = t.strftime("%Y%m%d")
        host = self.endpoint.split("://", 1)[1].split("/", 1)[0]
        headers = {
            "content-type": "application/x-amz-json-1.1",
            "host": host,
            "x-amz-date": amz_date,
            "x-amz-target": target,
        }
        if self.session_token:
            headers["x-amz-security-token"] = self.session_token
        signed = ";".join(sorted(headers))
        canonical = "POST\n/\n\n" + "".join(
            f"{k}:{headers[k]}\n" for k in sorted(headers)) + \
            f"\n{signed}\n{hashlib.sha256(body).hexdigest()}"
        scope = f"{datestamp}/{self.region}/{self.SERVICE}/aws4_request"
        to_sign = ("AWS4-HMAC-SHA256\n" + amz_date + "\n" + scope + "\n"
                   + hashlib.sha256(canonical.encode()).hexdigest())

        def hm(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hm(("AWS4" + self.secret_key).encode(), datestamp)
        k = hm(k, self.region)
        k = hm(k, self.SERVICE)
        k = hm(k, "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")
        return headers

    def _call(self, action: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        import urllib.error
        import urllib.request

        body = json.dumps(payload).encode()
        # throttling (ProvisionedThroughputExceeded / LimitExceeded), 5xx,
        # and transport-level failures (connection reset, DNS, timeout) are
        # transient: retry with exponential backoff, as the AWS SDKs do
        delay = 0.2
        for attempt in range(6):
            headers = self._sign(body, f"Kinesis_20131202.{action}")
            req = urllib.request.Request(self.endpoint, data=body,
                                         headers=headers, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")
                transient = e.code >= 500 or (
                    e.code == 400 and ("ThroughputExceeded" in detail
                                       or "LimitExceeded" in detail))
                if not transient or attempt == 5:
                    raise RuntimeError(
                        f"kinesis {action} failed ({e.code}): {detail[:300]}")
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                if attempt == 5:
                    raise RuntimeError(f"kinesis {action} failed: {e}")
            time.sleep(delay)
            delay = min(delay * 2, 5.0)
        raise AssertionError("unreachable")

    # -- API surface the connector uses ------------------------------------

    def list_shards(self, stream: str) -> List[str]:
        out = self._call("ListShards", {"StreamName": stream})
        return [s["ShardId"] for s in out.get("Shards", [])]

    def get_shard_iterator(self, stream: str, shard_id: str,
                           after_seq: Optional[str],
                           latest: bool) -> str:
        req: Dict[str, Any] = {"StreamName": stream, "ShardId": shard_id}
        if after_seq is not None:
            req["ShardIteratorType"] = "AFTER_SEQUENCE_NUMBER"
            req["StartingSequenceNumber"] = after_seq
        else:
            req["ShardIteratorType"] = "LATEST" if latest \
                else "TRIM_HORIZON"
        return self._call("GetShardIterator", req)["ShardIterator"]

    def get_records(self, iterator: str, limit: int) -> Dict[str, Any]:
        """-> {"Records": [{"Data": b64, "SequenceNumber": ...}],
        "NextShardIterator": ...}"""
        return self._call("GetRecords",
                          {"ShardIterator": iterator, "Limit": limit})

    def put_records(self, stream: str,
                    records: List[Dict[str, str]]) -> None:
        # PutRecords throttling surfaces as HTTP 200 with per-record
        # failures (FailedRecordCount > 0): retry exactly the failed
        # subset with backoff, the way the AWS SDKs do
        pending = records
        delay = 0.2
        for attempt in range(6):
            out = self._call("PutRecords",
                             {"StreamName": stream, "Records": pending})
            if not out.get("FailedRecordCount", 0):
                return
            results = out.get("Records", [])
            pending = [r for r, res in zip(pending, results)
                       if res.get("ErrorCode")] or pending
            time.sleep(delay)
            delay = min(delay * 2, 5.0)
        raise RuntimeError(
            f"kinesis PutRecords: {len(pending)} records still failing "
            "after retries")


_TEST_CLIENTS: Dict[str, Any] = {}


def register_test_client(stream: str, client: Any) -> None:
    """Testing hook: inject a fake client for ``stream``."""
    _TEST_CLIENTS[stream] = client


def unregister_test_client(stream: str) -> None:
    _TEST_CLIENTS.pop(stream, None)


def _owns_shard(shard_id: str, task_index: int, parallelism: int) -> bool:
    """Deterministic shard->subtask assignment, stable across reshards: a
    shard's owner depends only on its id, never on its position in the
    (changing) ListShards result."""
    h = int.from_bytes(hashlib.md5(shard_id.encode()).digest()[:8], "big")
    return h % parallelism == task_index


def _client_for(cfg: KinesisConfig):
    if cfg.stream_name in _TEST_CLIENTS:
        return _TEST_CLIENTS[cfg.stream_name]
    return KinesisClient(cfg.region, cfg.endpoint_url)


class KinesisSource(SourceOperator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("kinesis_source")
        self.cfg = KinesisConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    def tables(self) -> List[TableDescriptor]:
        # table 's': shard_id -> last-read sequence number
        return [global_table("s", "kinesis shard sequence numbers")]

    async def run(self, ctx: Context) -> SourceFinishType:
        client = _client_for(self.cfg)
        state = ctx.state.get_global_keyed_state("s")
        loop = asyncio.get_event_loop()
        me, n = ctx.task_info.task_index, ctx.task_info.parallelism

        async def open_iter(sh: str) -> str:
            return await loop.run_in_executor(
                None, client.get_shard_iterator, self.cfg.stream_name, sh,
                state.get(sh), self.cfg.offset == "latest")

        # shards this subtask has fully drained (NextShardIterator == None,
        # i.e. closed by a reshard); they stay in ListShards for the whole
        # retention window and must not be re-opened
        drained: set = set()
        iters: Dict[str, str] = {}

        async def discover() -> None:
            fresh = await loop.run_in_executor(
                None, client.list_shards, self.cfg.stream_name)
            for sh in sorted(fresh):
                if (_owns_shard(sh, me, n) and sh not in iters
                        and sh not in drained):
                    iters[sh] = await open_iter(sh)

        await discover()
        # A subtask with no shards today must keep polling (a reshard can
        # create child shards that hash to it tomorrow) — but it must also
        # declare itself IDLE so the job-wide min-watermark doesn't stall
        # on its silence (the reference broadcasts Watermark::Idle for the
        # no-partitions case, fluvio/source.rs:185-189).
        from ..types import Message, Watermark

        idle_declared = False
        if not iters:
            await ctx.broadcast(Message.wm(Watermark.idle()))
            idle_declared = True

        runner = getattr(ctx, "_runner", None)
        # the real GetRecords API rejects Limit > 10000
        batch_size = min(self.cfg.batch_size
                         or config().target_batch_size, 10_000)
        # bounded runs are the test rig: poll fast. Unbounded runs pace idle
        # polling to stay within the 5 reads/sec/shard API limit.
        idle_sleep = 0.05 if self.cfg.max_messages is not None else 0.2
        total = 0
        idle_spins = 0
        loops = 0
        # source-side coalescing: shard reads returning small fragments
        # accumulate at the boundary and decode as one target-size batch
        # (the runner flushes before checkpoints/stop, so sequence
        # numbers recorded at fetch time stay exactly-once)
        batcher = self.make_batcher(ctx, self.fmt.batch, batch_size)
        while True:
            loops += 1
            if loops % 200 == 0 or (not iters and loops % 20 == 0):
                await discover()  # resharding: pick up new child shards
            if not iters and not idle_declared:
                # all owned shards just closed: stop holding the watermark
                await batcher.flush()
                await ctx.broadcast(Message.wm(Watermark.idle()))
                idle_declared = True
            elif iters:
                idle_declared = False
            got = 0
            for sh in list(iters):
                out = await loop.run_in_executor(
                    None, client.get_records, iters[sh], batch_size)
                recs = out.get("Records", [])
                if recs:
                    got += len(recs)
                    total += len(recs)
                    # arroyolint: disable=row-loop -- Kinesis wraps each record base64; one C-level b64decode per record, decode is batched downstream
                    payloads = [base64.b64decode(r["Data"]) for r in recs]
                    await batcher.add(payloads)
                    state.insert(sh, recs[-1]["SequenceNumber"])
                nxt = out.get("NextShardIterator")
                if nxt is None:  # shard closed (reshard): stop reading it
                    del iters[sh]
                    drained.add(sh)
                else:
                    iters[sh] = nxt
            if runner is not None:
                cm = await runner.poll_source_control()
                if cm is not None and cm.kind == "stop":
                    return (SourceFinishType.GRACEFUL
                            if cm.stop_mode != StopMode.IMMEDIATE
                            else SourceFinishType.IMMEDIATE)
            await batcher.maybe_flush()
            if (self.cfg.max_messages is not None
                    and total >= self.cfg.max_messages):
                return SourceFinishType.FINAL
            if got == 0:
                idle_spins += 1
                if self.cfg.max_messages is not None and idle_spins > 50:
                    return SourceFinishType.FINAL  # bounded run drained
                await asyncio.sleep(idle_sleep)
            else:
                idle_spins = 0
                await asyncio.sleep(0)


class KinesisSink(Operator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("kinesis_sink")
        self.cfg = KinesisConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    async def on_start(self, ctx: Context) -> None:
        self.client = _client_for(self.cfg)

    def _encode_records(self, batch: Batch) -> List[Dict[str, str]]:
        """Serialize + base64-frame one batch (executor thread: the
        per-record b64/str work is CPU the event loop must not carry)."""
        payloads = self.fmt.serialize_batch(batch)
        pk_col = (batch.columns.get(self.cfg.partition_key_field)
                  if self.cfg.partition_key_field else None)
        # arroyolint: disable=row-loop -- PutRecords requires one framed dict per record; runs on an executor thread
        return [{
            "Data": base64.b64encode(p).decode(),
            "PartitionKey": str(pk_col[i]) if pk_col is not None
            else str(i % 256),
        } for i, p in enumerate(payloads)]

    async def process_batch(self, batch: Batch, ctx: Context,
                            side: int = 0) -> None:
        loop = asyncio.get_running_loop()
        # encode off-loop: JSON render + per-record base64 on a worker
        # thread so sibling subtasks keep the event loop
        records = await loop.run_in_executor(
            None, self._encode_records, batch)
        # Kinesis caps PutRecords at 500 records per call
        for i in range(0, len(records), 500):
            await loop.run_in_executor(
                None, self.client.put_records, self.cfg.stream_name,
                records[i:i + 500])


register_connector(ConnectorMeta(
    name="kinesis",
    description="AWS Kinesis source/sink (SigV4 stdlib client)",
    source_factory=KinesisSource,
    sink_factory=KinesisSink,
    config_model=KinesisConfig,
))
