"""Websocket source (/root/reference/arroyo-worker/src/connectors/
websocket.rs): connects to a ws:// endpoint, optionally sends a subscription
message, and emits every received text/binary frame through the Format layer.
No exactly-once replay is possible (the stream is ephemeral), matching the
reference's semantics — state records only a monotonically increasing count
for observability."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pydantic import BaseModel

from ..config import config
from ..engine.context import Context
from ..engine.operator import SourceFinishType, SourceOperator
from ..formats import make_format
from ..state.tables import TableDescriptor, global_table
from ..types import StopMode
from .registry import ConnectorMeta, register_connector


class WebsocketConfig(BaseModel):
    endpoint: str
    subscription_message: Optional[str] = None
    format: str = "json"
    format_options: Dict[str, Any] = {}


class WebsocketSource(SourceOperator):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("websocket_source")
        self.cfg = WebsocketConfig(**cfg)
        self.fmt = make_format(self.cfg.format, **self.cfg.format_options)

    def tables(self) -> List[TableDescriptor]:
        return [global_table("w", "websocket message count")]

    async def run(self, ctx: Context) -> SourceFinishType:
        if ctx.task_info.task_index != 0:
            return SourceFinishType.FINAL
        import websockets

        state = ctx.state.get_global_keyed_state("w")
        count = state.get("messages") or 0
        runner = getattr(ctx, "_runner", None)
        batch_size = config().target_batch_size
        pending: List[bytes] = []

        async with websockets.connect(self.cfg.endpoint) as ws:
            if self.cfg.subscription_message:
                await ws.send(self.cfg.subscription_message)
            while True:
                try:
                    msg = await ws.recv()
                except websockets.ConnectionClosedOK:
                    break
                pending.append(msg if isinstance(msg, bytes) else msg.encode())
                count += 1
                if len(pending) >= batch_size:
                    await ctx.collect(self.fmt.batch(pending))
                    pending = []
                    state.insert("messages", count)
                if runner is not None:
                    cm = await runner.poll_source_control()
                    if cm is not None and cm.kind == "stop":
                        if pending:
                            await ctx.collect(self.fmt.batch(pending))
                        return (SourceFinishType.GRACEFUL
                                if cm.stop_mode != StopMode.IMMEDIATE
                                else SourceFinishType.IMMEDIATE)
        if pending:
            await ctx.collect(self.fmt.batch(pending))
            state.insert("messages", count)
        return SourceFinishType.FINAL


register_connector(ConnectorMeta(
    name="websocket", description="websocket subscription source",
    source_factory=WebsocketSource, config_model=WebsocketConfig))
