"""FileSystem sink — parquet/JSON part files with exactly-once commit.

Analog of the reference's FileSystemSink (/root/reference/arroyo-worker/src/
connectors/filesystem/mod.rs:44-350): rows are buffered and flushed as part
files; at each checkpoint barrier in-flight parts are *staged* (the multipart
-upload analog: written under ``.staging/``) and recorded as pre-commit data;
the commit phase atomically promotes staged parts to their final names.  A
crash between checkpoint and commit re-commits on restore; a crash before the
checkpoint drops the staged parts (they are never promoted), so output is
exactly-once.

Part naming: ``part-{subtask:04d}-{seq:06d}.{ext}`` under the configured
path, matching the reference's per-subtask monotonic numbering.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, List, Literal, Optional, Tuple

from pydantic import BaseModel

from ..engine.context import Context
from ..formats import batch_to_rows, _py
from ..types import Batch
from ..utils.storage import StorageProvider
from .registry import ConnectorMeta, register_connector
from .two_phase import TwoPhaseCommitterSink


class FileSystemConfig(BaseModel):
    path: str  # directory URL: file:///..., memory://..., s3://... via fsspec
    # newline-delimited json | parquet; a typo must fail at plan time, not
    # silently fall back to json
    format: Literal["json", "parquet"] = "json"
    rows_per_file: int = 1_000_000  # roll part when exceeded


class FileSystemSink(TwoPhaseCommitterSink):
    def __init__(self, cfg: Dict[str, Any]):
        super().__init__("filesystem_sink")
        self.cfg = FileSystemConfig(**cfg)
        self.storage = StorageProvider.for_url(self.cfg.path)
        self._rows: List[Dict[str, Any]] = []
        self._staged_parts: List[str] = []
        self._seq = 0
        self._subtask = 0

    # -- committer hooks ----------------------------------------------

    async def committer_init(self, recovery_state: Optional[Any],
                             ctx: Context) -> None:
        self._subtask = ctx.task_info.task_index
        if recovery_state:
            self._seq = int(recovery_state.get("next_seq", 0))

    async def committer_post_restore(self, ctx: Context) -> None:
        # Drop orphaned staged parts from a crashed epoch.  This runs only
        # after restored pre-commits were re-committed (and their staged
        # files promoted away), so anything still under .staging/ for this
        # subtask was never pre-committed and its rows will be re-produced.
        for key in self.storage.list(".staging/"):
            if f"part-{self._subtask:04d}-" in key:
                self.storage.delete_if_present(key)

    async def insert_batch(self, batch: Batch, ctx: Context) -> None:
        self._rows.extend(batch_to_rows(batch))
        while len(self._rows) >= self.cfg.rows_per_file:
            chunk, self._rows = (self._rows[:self.cfg.rows_per_file],
                                 self._rows[self.cfg.rows_per_file:])
            self._stage(chunk)

    def _part_name(self) -> str:
        ext = "parquet" if self.cfg.format == "parquet" else "json"
        name = f"part-{self._subtask:04d}-{self._seq:06d}.{ext}"
        self._seq += 1
        return name

    def _encode(self, rows: List[Dict[str, Any]]) -> bytes:
        if self.cfg.format == "parquet":
            import pyarrow as pa
            import pyarrow.parquet as pq

            # arroyolint: disable=row-loop -- once per rows_per_file staged part, not per batch; parquet's writer takes a pylist
            cleaned = [{k: _py(v) for k, v in r.items()} for r in rows]
            table = pa.Table.from_pylist(cleaned)
            buf = io.BytesIO()
            pq.write_table(table, buf, compression="zstd")
            return buf.getvalue()
        # arroyolint: disable=row-loop -- two-phase sink buffers row dicts across batches for rows_per_file chunking; runs once per staged part
        return b"".join(
            json.dumps(r, default=_py).encode() + b"\n" for r in rows)

    def _stage(self, rows: List[Dict[str, Any]]) -> None:
        if not rows:
            return
        name = self._part_name()
        self.storage.put(f".staging/{name}", self._encode(rows))
        self._staged_parts.append(name)

    async def committer_checkpoint(
            self, epoch: int, stopping: bool,
            ctx: Context) -> Tuple[Any, Dict[str, Any]]:
        self._stage(self._rows)
        self._rows = []
        staged = self._staged_parts
        self._staged_parts = []
        recovery = {"next_seq": self._seq}
        pre_commits = {name: {"staged": f".staging/{name}", "final": name}
                       for name in staged}
        return recovery, pre_commits

    def _promote(self, staged: str, final: str) -> None:
        # idempotent: already-promoted parts (commit retried after a crash
        # mid-commit) are skipped
        if self.storage.exists(staged):
            self.storage.put(final, self.storage.get(staged))
            self.storage.delete_if_present(staged)

    async def committer_commit(self, epoch: int, pre_commits: Dict[str, Any],
                               ctx: Context) -> None:
        for _, pc in sorted(pre_commits.items()):
            self._promote(pc["staged"], pc["final"])

    async def on_close(self, ctx: Context) -> None:
        # Graceful end-of-stream without a final barrier: flush remaining
        # rows straight to final parts (no barrier will come to commit them).
        if self._rows:
            name = self._part_name()
            self.storage.put(name, self._encode(self._rows))
            self._rows = []
        for name in self._staged_parts:
            self._promote(f".staging/{name}", name)
        self._staged_parts = []


register_connector(ConnectorMeta(
    name="filesystem",
    description="parquet/json part-file sink with exactly-once two-phase commit",
    sink_factory=FileSystemSink,
    config_model=FileSystemConfig,
))
