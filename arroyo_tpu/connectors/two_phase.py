"""TwoPhaseCommitter — exactly-once sink protocol.

Analog of the reference's ``trait TwoPhaseCommitter`` (/root/reference/
arroyo-worker/src/connectors/two_phase_committer.rs:39-61): a sink buffers
writes, and at each checkpoint barrier produces *pre-commit* data that is
persisted with the snapshot (table write-behavior CommitWrites).  Once the
controller has sealed the whole checkpoint it sends a Commit control message
and the sink finalizes the pre-committed work (finish multipart uploads,
commit the kafka transaction, rename staged files).  On restore, un-committed
pre-commits from the restored epoch are re-committed before processing
resumes — giving exactly-once output.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..engine.context import Context
from ..engine.operator import Operator
from ..state.tables import (
    TableDescriptor,
    TableType,
    WriteBehavior,
)
from ..types import Batch, CheckpointBarrier

# Reserved table names, mirroring the reference's single-char convention:
# 'r' — recovery state (committer-internal, restored on restart)
# 'p' — pre-commit data (CommitWrites: surfaced to the controller)
RECOVERY_TABLE = "r"
PRECOMMIT_TABLE = "p"


class TwoPhaseCommitterSink(Operator):
    """Base class for exactly-once sinks.  Subclasses implement the four
    committer hooks (two_phase_committer.rs:39-61):

    - ``committer_init(ctx)`` — open connections, restore from
      ``recovery_state`` (may be None).
    - ``insert_batch(batch, ctx)`` — buffer/stage a batch of rows.
    - ``committer_checkpoint(epoch, stopping, ctx) -> (recovery, pre_commits)``
      — flush staged data to its pre-committed location; return committer
      recovery state plus a dict of pre-commit entries.
    - ``committer_commit(epoch, pre_commits, ctx)`` — atomically finalize.
    """

    def tables(self) -> List[TableDescriptor]:
        return [
            TableDescriptor(RECOVERY_TABLE, TableType.GLOBAL,
                            "two-phase committer recovery state"),
            TableDescriptor(PRECOMMIT_TABLE, TableType.GLOBAL,
                            "pre-commit data awaiting the commit phase",
                            write_behavior=WriteBehavior.COMMIT_WRITES),
        ]

    # -- committer hooks (override) -----------------------------------

    async def committer_init(self, recovery_state: Optional[Any],
                             ctx: Context) -> None:
        pass

    async def insert_batch(self, batch: Batch, ctx: Context) -> None:
        raise NotImplementedError

    async def committer_checkpoint(
            self, epoch: int, stopping: bool,
            ctx: Context) -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    async def committer_commit(self, epoch: int, pre_commits: Dict[str, Any],
                               ctx: Context) -> None:
        raise NotImplementedError

    # -- Operator plumbing (final) ------------------------------------

    async def committer_post_restore(self, ctx: Context) -> None:
        """Called after restored pre-commits have been re-committed; safe
        point to garbage-collect staged artifacts that no pre-commit
        references (they belong to an epoch that never sealed)."""
        pass

    # -- Operator plumbing (final) ------------------------------------

    async def on_start(self, ctx: Context) -> None:
        # Pre-commit entries are keyed by epoch so a commit for epoch N can
        # never finalize epoch N+1's still-unsealed work (the reference keys
        # committing state by checkpoint id, checkpointer.rs:83-110).
        pre = ctx.state.get_global_keyed_state(PRECOMMIT_TABLE)
        rec = ctx.state.get_global_keyed_state(RECOVERY_TABLE)
        await self.committer_init(rec.get("state"), ctx)
        if ctx.state.restore_epoch is not None:
            # Re-commit anything pre-committed before the crash: the
            # controller guarantees the restored checkpoint was fully sealed,
            # so these writes belong to it and must become visible
            # (scheduling.rs:300-510 loads committing state on restore).
            for epoch, pending in sorted(pre.get_all().items()):
                if pending:
                    await self.committer_commit(epoch, pending, ctx)
                pre.remove(epoch)
        await self.committer_post_restore(ctx)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        await self.insert_batch(batch, ctx)

    async def pre_checkpoint(self, barrier: CheckpointBarrier, ctx: Context) -> None:
        recovery, pre_commits = await self.committer_checkpoint(
            barrier.epoch, barrier.then_stop, ctx)
        rec = ctx.state.get_global_keyed_state(RECOVERY_TABLE)
        rec.insert("state", recovery)
        if pre_commits:
            pre = ctx.state.get_global_keyed_state(PRECOMMIT_TABLE)
            pre.insert(barrier.epoch, pre_commits)

    def has_pending_commits(self, ctx: Context) -> bool:
        return len(ctx.state.get_global_keyed_state(PRECOMMIT_TABLE)) > 0

    async def handle_commit(self, epoch: int, ctx: Context) -> None:
        pre = ctx.state.get_global_keyed_state(PRECOMMIT_TABLE)
        for e, pending in sorted(pre.get_all().items()):
            if e <= epoch:
                if pending:
                    await self.committer_commit(e, pending, ctx)
                pre.remove(e)
