"""SPMD windowed aggregation over a device mesh — the multi-chip data plane.

One jitted step does what a whole tier of the reference's distributed runtime
does per batch (collector hash routing engine.rs:183-240 + TCP shuffle
network_manager.rs + per-subtask window state):

1. **route**: each ``source`` shard computes the key-range owner of every row
   (``server_for_hash``) and exchanges rows with ``all_to_all`` over the
   ``keys`` mesh axis (ICI traffic, not host TCP);
2. **merge**: each key shard maintains its keyed bin state as a
   *sorted-key table* ``(keys_sorted[C], bins[A, C, B])`` — functional,
   static-shaped, fully inside jit: new keys are merged via sort+unique,
   existing bins re-gathered by searchsorted, incoming rows scatter-added;
3. **fire**: panes whose window end <= the global watermark are aggregated
   with the same gather+reduce used single-chip and emitted as dense
   (key, pane, value) tensors with a validity mask.

State is a pytree sharded with ``PartitionSpec(None, 'keys')``; everything
composes with pjit/shard_map so XLA inserts the collectives.

Timestamps are handled as int32 *bin indices relative to a host-supplied
base* so the step stays correct with x64 disabled.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

from ..types import U64_MAX

EMPTY_KEY = np.uint64(0xFFFF_FFFF_FFFF_FFFF)  # sentinel: empty slot


class SpmdWindowState(NamedTuple):
    """Per-shard keyed bin state (sharded on the second axis)."""

    keys: "jax.Array"  # uint32[S, C] *compressed* key ids (see note below)
    keys_hi: "jax.Array"  # uint32[S, C] high bits of the u64 key hash
    bins: "jax.Array"  # f32[A, S, C, B] per-agg per-key per-bin accumulators
    counts: "jax.Array"  # i32[S, C, B]


def _split_u64(kh: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """u64 -> (lo32, hi32) uint32 pair (x64-safe device representation)."""
    kh = kh.astype(np.uint64)
    return ((kh & np.uint64(0xFFFF_FFFF)).astype(np.uint32),
            (kh >> np.uint64(32)).astype(np.uint32))


class SpmdWindowEngine:
    """Builds the jitted SPMD step for a sliding/tumbling COUNT/SUM window
    (the Nexmark q5/q7 hot path) over a (source, keys) mesh."""

    def __init__(self, mesh, n_aggs: int = 1, capacity: int = 4096,
                 n_bins: int = 16, window_bins: int = 5,
                 rows_per_shard: int = 2048):
        self.mesh = mesh
        self.A = n_aggs
        self.C = capacity
        self.B = n_bins
        self.W = window_bins
        self.N = rows_per_shard
        self.n_key_shards = mesh.shape["keys"]
        self.n_src_shards = mesh.shape["source"]
        self._step = None

    # -- state init --------------------------------------------------------

    def init_state(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        S = self.n_key_shards
        shard = NamedSharding(self.mesh, P(None, "keys"))
        shard_b = NamedSharding(self.mesh, P(None, None, "keys"))
        with self.mesh:
            return SpmdWindowState(
                keys=jax.device_put(
                    jnp.full((1, S * self.C), 0xFFFF_FFFF, jnp.uint32), shard),
                keys_hi=jax.device_put(
                    jnp.full((1, S * self.C), 0xFFFF_FFFF, jnp.uint32), shard),
                bins=jax.device_put(
                    jnp.zeros((self.A, 1, S * self.C, self.B)), shard_b),
                counts=jax.device_put(
                    jnp.zeros((1, S * self.C, self.B), jnp.int32), shard_b[
                        :] if False else NamedSharding(
                            self.mesh, P(None, "keys"))),
            )

    # -- the step ----------------------------------------------------------

    def build_step(self):
        """Returns step(state, rows, watermark_bin) -> (state, emitted).

        rows: dict of arrays sharded on the ``source`` axis:
          key_lo/key_hi: uint32[R], bin_idx: int32[R] (relative bins),
          values: f32[A, R], valid: bool[R]
        watermark_bin: int32 scalar — fire panes with end <= this bin.
        emitted: (keys_lo, keys_hi, pane_end, aggs, mask) dense tensors.
        """
        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        A, C, B, W = self.A, self.C, self.B, self.W
        nk = self.n_key_shards

        def local_step(keys_lo, keys_hi, bins, counts, r_lo, r_hi, r_bin,
                       r_val, r_ok, wm_bin):
            # keys_*: [1, C]; bins: [A, 1, C, B]; counts: [1, C, B]
            # r_*: [src_shards * cap] rows routed to this key shard
            keys_lo, keys_hi = keys_lo[0], keys_hi[0]
            bins = bins[:, 0]
            counts = counts[0]

            # ---- merge keys: combined sorted table of old + incoming
            key64_old = (keys_hi.astype(jnp.uint64) << 32) if False else None
            # x64-safe 64-bit compare via (hi, lo) lexicographic packing into
            # f64-free int32 pairs: sort by (hi, lo) using a single fused
            # uint32->uint64-free trick: interleave into two sort passes.
            # Simpler: sort by hi then stable-sort by ... JAX sort supports
            # multiple operands lexicographically via jax.lax.sort.
            inc_lo = jnp.where(r_ok, r_lo, jnp.uint32(0xFFFF_FFFF))
            inc_hi = jnp.where(r_ok, r_hi, jnp.uint32(0xFFFF_FFFF))
            all_hi = jnp.concatenate([keys_hi, inc_hi])
            all_lo = jnp.concatenate([keys_lo, inc_lo])
            s_hi, s_lo = jax.lax.sort((all_hi, all_lo), num_keys=2)
            is_first = jnp.ones_like(s_hi, dtype=bool).at[1:].set(
                (s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]))
            # compact unique keys into the first C slots (drop overflow)
            rank = jnp.cumsum(is_first) - 1  # unique index per sorted row
            new_keys_hi = jnp.full((C,), jnp.uint32(0xFFFF_FFFF), jnp.uint32)
            new_keys_lo = jnp.full((C,), jnp.uint32(0xFFFF_FFFF), jnp.uint32)
            slot_ok = is_first & (rank < C)
            tgt = jnp.where(slot_ok, rank, C)
            new_keys_hi = new_keys_hi.at[tgt.clip(0, C)].set(
                jnp.where(slot_ok, s_hi, jnp.uint32(0xFFFF_FFFF)), mode="drop")
            new_keys_lo = new_keys_lo.at[tgt.clip(0, C)].set(
                jnp.where(slot_ok, s_lo, jnp.uint32(0xFFFF_FFFF)), mode="drop")

            def lookup(q_hi, q_lo):
                # binary search (hi, lo) in the new sorted key table
                def cmp_le(a_hi, a_lo, b_hi, b_lo):
                    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))

                lo_i = jnp.zeros(q_hi.shape, jnp.int32)
                hi_i = jnp.full(q_hi.shape, C, jnp.int32)

                def body(_, lh):
                    lo_i, hi_i = lh
                    mid = (lo_i + hi_i) // 2
                    m_hi = new_keys_hi[mid]
                    m_lo = new_keys_lo[mid]
                    le = cmp_le(q_hi, q_lo, m_hi, m_lo)
                    # searching for first slot >= q
                    ge_q = (m_hi > q_hi) | ((m_hi == q_hi) & (m_lo >= q_lo))
                    lo_i = jnp.where(ge_q, lo_i, mid + 1)
                    hi_i = jnp.where(ge_q, mid, hi_i)
                    return lo_i, hi_i

                lo_i, hi_i = jax.lax.fori_loop(
                    0, int(np.ceil(np.log2(max(C, 2)))) + 1, body,
                    (lo_i, hi_i))
                idx = lo_i.clip(0, C - 1)
                found = (new_keys_hi[idx] == q_hi) & (new_keys_lo[idx] == q_lo)
                return idx, found

            # ---- re-map old bins to new slots
            old_idx, old_found = lookup(keys_hi, keys_lo)
            new_bins = jnp.zeros_like(bins)
            new_counts = jnp.zeros_like(counts)
            scatter_to = jnp.where(old_found, old_idx, C)
            new_bins = new_bins.at[:, scatter_to.clip(0, C - 1)].add(
                jnp.where(old_found[None, :, None], bins, 0.0))
            new_counts = new_counts.at[scatter_to.clip(0, C - 1)].add(
                jnp.where(old_found[:, None], counts, 0))

            # ---- scatter incoming rows
            row_idx, row_found = lookup(r_hi, r_lo)
            ok = r_ok & row_found
            si = jnp.where(ok, row_idx, 0)
            bi = jnp.where(ok, r_bin, 0).clip(0, B - 1)
            new_counts = new_counts.at[si, bi].add(jnp.where(ok, 1, 0))
            for a in range(A):
                new_bins = new_bins.at[a, si, bi].add(
                    jnp.where(ok, r_val[a], 0.0))

            # ---- fire panes: pane ends 0..B-1 relative bins, fire <= wm_bin
            pane_ends = jnp.arange(B, dtype=jnp.int32)
            offs = jnp.arange(W, dtype=jnp.int32) - (W - 1)
            win = pane_ends[:, None] + offs[None, :]  # [B, W]
            ring = jnp.mod(win, B)
            win_ok = (win >= 0) & (pane_ends[:, None] <= wm_bin)
            gat = new_bins[:, :, ring]  # [A, C, B, W]
            sums = jnp.sum(jnp.where(win_ok[None, None], gat, 0.0), axis=-1)
            cnt_g = new_counts[:, ring]
            cnts = jnp.sum(jnp.where(win_ok[None], cnt_g, 0), axis=-1)
            emit_mask = (cnts > 0) & (pane_ends[None, :] <= wm_bin) & (
                new_keys_hi[:, None] != jnp.uint32(0xFFFF_FFFF))

            # ---- evict fired bins (end-of-window bins <= wm_bin - W + 1)
            evict = jnp.arange(B, dtype=jnp.int32)[None, :] <= (wm_bin - W + 1)
            new_counts = jnp.where(evict, 0, new_counts)
            new_bins = jnp.where(evict[None], 0.0, new_bins)

            return (new_keys_lo[None], new_keys_hi[None], new_bins[:, None],
                    new_counts[None], sums, cnts, emit_mask)

        def route_and_step(state: SpmdWindowState, rows: Dict, wm_bin):
            # rows arrive sharded on 'source'; route to key owners via
            # all_to_all inside shard_map
            def routed(r_lo, r_hi, r_bin, r_val, r_ok):
                # shapes per (source, keys) shard: [N/nk rows]
                # dest shard for each row
                dest = (r_hi >> jnp.uint32(32 - _log2(nk))).astype(jnp.int32) \
                    if nk > 1 else jnp.zeros(r_lo.shape, jnp.int32)
                # bucket rows by dest with fixed per-dest capacity: 2x the
                # uniform expectation so hash imbalance doesn't drop rows
                # (static shapes are an XLA requirement; the binomial tail
                # above 2x mean is negligible for hashed keys)
                cap = max(4 * (r_lo.shape[0] // max(nk, 1)), 16)
                order = jnp.argsort(dest)
                r_lo, r_hi = r_lo[order], r_hi[order]
                r_bin, r_ok = r_bin[order], r_ok[order]
                r_val = r_val[:, order]
                # position within destination bucket
                onehot = jax.nn.one_hot(dest[order], nk, dtype=jnp.int32)
                pos_in = jnp.cumsum(onehot, axis=0) - onehot
                pos = jnp.sum(pos_in * onehot, axis=1)
                slot_ok = pos < cap
                tgt = dest[order] * cap + jnp.where(slot_ok, pos, 0)
                buf_lo = jnp.zeros((nk * cap,), jnp.uint32).at[tgt].set(
                    jnp.where(slot_ok, r_lo, 0), mode="drop")
                buf_hi = jnp.zeros((nk * cap,), jnp.uint32).at[tgt].set(
                    jnp.where(slot_ok, r_hi, 0), mode="drop")
                buf_bin = jnp.zeros((nk * cap,), jnp.int32).at[tgt].set(
                    jnp.where(slot_ok, r_bin, 0), mode="drop")
                buf_ok = jnp.zeros((nk * cap,), bool).at[tgt].set(
                    r_ok & slot_ok, mode="drop")
                buf_val = jnp.zeros((A, nk * cap)).at[:, tgt].set(
                    jnp.where(slot_ok, r_val, 0.0), mode="drop")
                # exchange: split axis 0 into nk chunks, swap across 'keys'
                if nk > 1:
                    buf_lo = jax.lax.all_to_all(
                        buf_lo.reshape(nk, cap), "keys", 0, 0,
                        tiled=False).reshape(-1)
                    buf_hi = jax.lax.all_to_all(
                        buf_hi.reshape(nk, cap), "keys", 0, 0,
                        tiled=False).reshape(-1)
                    buf_bin = jax.lax.all_to_all(
                        buf_bin.reshape(nk, cap), "keys", 0, 0,
                        tiled=False).reshape(-1)
                    buf_ok = jax.lax.all_to_all(
                        buf_ok.reshape(nk, cap), "keys", 0, 0,
                        tiled=False).reshape(-1)
                    buf_val = jax.lax.all_to_all(
                        buf_val.reshape(A, nk, cap), "keys", 1, 1,
                        tiled=False).reshape(A, -1)
                # gather contributions from all source shards
                buf_lo = jax.lax.all_gather(buf_lo, "source").reshape(-1)
                buf_hi = jax.lax.all_gather(buf_hi, "source").reshape(-1)
                buf_bin = jax.lax.all_gather(buf_bin, "source").reshape(-1)
                buf_ok = jax.lax.all_gather(buf_ok, "source").reshape(-1)
                buf_val = jax.lax.all_gather(
                    buf_val, "source", axis=1).reshape(A, -1)
                return buf_lo, buf_hi, buf_bin, buf_val, buf_ok

            def shard_fn(keys_lo, keys_hi, bins, counts,
                         r_lo, r_hi, r_bin, r_val, r_ok, wm):
                b_lo, b_hi, b_bin, b_val, b_ok = routed(
                    r_lo, r_hi, r_bin, r_val, r_ok)
                return local_step(keys_lo, keys_hi, bins, counts,
                                  b_lo, b_hi, b_bin, b_val, b_ok, wm[0])

            out = shard_map(
                shard_fn, mesh=self.mesh,
                in_specs=(P(None, "keys"), P(None, "keys"),
                          P(None, None, "keys"), P(None, "keys"),
                          P(("source", "keys")), P(("source", "keys")),
                          P(("source", "keys")),
                          P(None, ("source", "keys")),
                          P(("source", "keys")), P(None)),
                out_specs=(P(None, "keys"), P(None, "keys"),
                           P(None, None, "keys"), P(None, "keys"),
                           P(None, "keys"), P("keys"), P("keys")),
                check_vma=False,
            )(state.keys, state.keys_hi, state.bins, state.counts,
              rows["key_lo"], rows["key_hi"], rows["bin_idx"],
              rows["values"], rows["valid"],
              jnp.asarray([wm_bin], jnp.int32))
            new_state = SpmdWindowState(out[0], out[1], out[2], out[3])
            emitted = {"aggs": out[4], "counts": out[5], "mask": out[6]}
            return new_state, emitted

        import jax

        self._step = jax.jit(route_and_step)
        return self._step


def _log2(n: int) -> int:
    return int(np.log2(n))


def make_example_rows(n_rows: int, n_src_shards: int, n_aggs: int,
                      mesh=None, seed: int = 0):
    """Example routed-row input (host): random keys and bins."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    kh = rng.integers(0, 1 << 63, n_rows, dtype=np.uint64) * 2
    lo, hi = _split_u64(kh)
    rows = {
        "key_lo": jnp.asarray(lo),
        "key_hi": jnp.asarray(hi),
        "bin_idx": jnp.asarray(rng.integers(0, 4, n_rows), jnp.int32),
        "values": jnp.asarray(rng.random((n_aggs, n_rows)), jnp.float32),
        "valid": jnp.ones((n_rows,), bool),
    }
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows = {
            k: jax.device_put(v, NamedSharding(
                mesh, P(("source", "keys")) if v.ndim == 1
                else P(None, ("source", "keys"))))
            for k, v in rows.items()
        }
    return rows
