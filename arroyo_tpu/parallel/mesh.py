"""Device mesh helpers for SPMD streaming execution.

The reference scales out by running operator subtasks on worker processes
connected by a TCP data plane (arroyo-worker/src/network_manager.rs); the TPU
build instead shards the *keyed state* across a mesh axis and exchanges rows
with XLA collectives over ICI (SURVEY.md §2 "Distributed communication
backend").  Mesh axes:

* ``source`` — data-parallel axis: independent source partitions (the analog
  of source subtasks / reference data parallelism #1)
* ``keys``   — state-sharding axis: contiguous u64 key ranges, one per shard
  (``server_for_hash`` semantics, arroyo-types/src/lib.rs:822-836)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def make_mesh(n_devices: Optional[int] = None, source: int = 1,
              keys: Optional[int] = None):
    """Build a (source, keys) mesh over the available devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    if keys is None:
        keys = n // source
    assert source * keys == len(devs), (
        f"mesh {source}x{keys} != {len(devs)} devices")
    arr = np.array(devs).reshape(source, keys)
    return Mesh(arr, ("source", "keys"))


def key_shard_spec():
    from jax.sharding import PartitionSpec as P

    return P(None, "keys")


def row_shard_spec():
    from jax.sharding import PartitionSpec as P

    return P("source")
