"""Ring-parallel pane aggregation over the bin (time) dimension — the
engine's sequence-parallelism discipline (SURVEY §5: "window panes =
sequence blocks; ring-style rotation of bins across devices via
``ppermute`` when a single key's window exceeds one device's memory").

The keyed mesh state (parallel/mesh_window.py) shards the KEY dimension;
this kernel shards the BIN dimension instead, for the degenerate-skew
case where ONE key's window spans more bins than a single device can
hold (a very long window with a very short slide).  Layout: the global
bin ring ``[n_bins]`` lives block-sharded over a 1-D ``("bins",)`` mesh,
shard d holding bins ``[d*Bl, (d+1)*Bl)``.  A pane ending at bin t
aggregates bins ``(t-W, t]``, which crosses shard boundaries whenever
W > 1: each shard needs a HALO of the previous shards' trailing bins.

The halo moves like a ring-attention block pass: ``ceil((W-1)/Bl)``
``ppermute`` rotations forward around the ring, each shard accumulating
the received block into its sliding prefix (contributions that would
wrap past global bin 0 are masked to the aggregation identity).  Compute
stays fully on-device and per-step communication is one block — the
standard ring-parallel cost model (the public ring-attention recipe
applied to window panes instead of attention blocks).

The reference has no analog (its per-key window state lives on one
subtask, aggregating_window.rs); this is TPU-first scale-out headroom.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

from ..graph.logical import AggKind
from ..ops.keyed_bins import _init_value


@functools.lru_cache(maxsize=64)
def _ring_step(kind: str, nk: int, Bl: int, W: int):
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map  # jax >= 0.5 top-level export
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh_window import _keys_mesh

    ident = _init_value(AggKind(kind))
    additive = kind in ("sum", "count")
    mesh = _keys_mesh(nk)
    n_rot = max((W - 1 + Bl - 1) // Bl, 0)  # ring rotations needed

    def combine(a, b):
        return jnp.minimum(a, b) if kind == "min" else jnp.maximum(a, b)

    def sliding(ext):
        """Width-W aggregate ending at each of the LAST Bl positions of
        ``ext`` (length (n_rot+1)*Bl >= W + Bl - 1)."""
        if additive:
            c = jnp.cumsum(ext)
            lo = jnp.arange(Bl) + (ext.shape[0] - Bl) - W
            hi = jnp.arange(Bl) + (ext.shape[0] - Bl)
            return c[hi] - jnp.where(lo >= 0, c[jnp.maximum(lo, 0)], 0.0)
        # min/max: van Herk block decomposition — per W-block running
        # extrema from both directions, then window [j-W+1, j] =
        # combine(suffix[j-W+1], prefix[j]).  O(L) memory (a naive
        # [Bl, W] gather would materialize the very windows this module
        # exists to avoid holding).
        import jax.lax as lax

        L = ext.shape[0]
        P = ((L + W - 1) // W) * W
        x = jnp.concatenate(
            [jnp.full((P - L,), ident, ext.dtype), ext]).reshape(-1, W)
        op = lax.cummax if kind == "max" else lax.cummin
        pre = op(x, axis=1).reshape(-1)
        suf = op(x[:, ::-1], axis=1)[:, ::-1].reshape(-1)
        j = jnp.arange(P - Bl, P)  # the last Bl padded positions
        # j >= W-1 always: P >= L >= W + Bl - 1, so j - W + 1 >= 0
        return combine(suf[j - W + 1], pre[j])

    def shard_fn(local):  # [Bl] per shard
        d = jax.lax.axis_index("keys")
        # accumulate halos: blocks from shards d-1, d-2, ... d-n_rot
        ext = local
        block = local
        for r in range(1, n_rot + 1):
            block = jax.lax.ppermute(
                block, "keys", perm=[(i, (i + 1) % nk) for i in range(nk)])
            # the block now held came from shard d-r; wrap-around past
            # global bin 0 contributes the identity
            valid = d - r >= 0
            ext = jnp.concatenate(
                [jnp.where(valid, block, ident), ext])
        return sliding(ext)

    fn = shard_map(shard_fn, mesh=mesh, in_specs=P("keys"),
                   out_specs=P("keys"))
    sharding = NamedSharding(mesh, P("keys"))
    return jax.jit(fn), sharding


@functools.lru_cache(maxsize=64)
def _ring_step_2d(kind: str, nk: int, C: int, Bl: int, W: int):
    """[C, n_bins] variant of :func:`_ring_step`: every key's bin ring is
    aggregated at once, bin axis block-sharded, ``ppermute`` halos —
    the engine's long-window emission kernel (KeyedBinState._emit_ring
    selects it instead of the [C, k, W] gather when W is large)."""
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map  # jax >= 0.5 top-level export
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh_window import _keys_mesh

    ident = _init_value(AggKind(kind))
    additive = kind in ("sum", "count")
    mesh = _keys_mesh(nk)
    n_rot = max((W - 1 + Bl - 1) // Bl, 0)

    def combine(a, b):
        return jnp.minimum(a, b) if kind == "min" else jnp.maximum(a, b)

    def sliding(ext):  # [C, L] -> [C, Bl]
        L = ext.shape[1]
        if additive:
            c = jnp.cumsum(ext, axis=1)
            lo = jnp.arange(Bl) + (L - Bl) - W
            hi = jnp.arange(Bl) + (L - Bl)
            head = jnp.where(lo >= 0, c[:, jnp.maximum(lo, 0)], 0.0)
            return c[:, hi] - head
        import jax.lax as lax

        Pp = ((L + W - 1) // W) * W
        x = jnp.concatenate(
            [jnp.full((ext.shape[0], Pp - L), ident, ext.dtype), ext],
            axis=1).reshape(ext.shape[0], -1, W)
        op = lax.cummax if kind == "max" else lax.cummin
        pre = op(x, axis=2).reshape(ext.shape[0], -1)
        suf = op(x[:, :, ::-1], axis=2)[:, :, ::-1].reshape(
            ext.shape[0], -1)
        j = jnp.arange(Pp - Bl, Pp)
        return combine(suf[:, j - W + 1], pre[:, j])

    def shard_fn(local):  # [C, Bl] per shard
        d = jax.lax.axis_index("keys")
        ext = local
        block = local
        for r in range(1, n_rot + 1):
            block = jax.lax.ppermute(
                block, "keys", perm=[(i, (i + 1) % nk) for i in range(nk)])
            valid = d - r >= 0
            ext = jnp.concatenate(
                [jnp.where(valid, block, ident), ext], axis=1)
        return sliding(ext)

    fn = shard_map(shard_fn, mesh=mesh, in_specs=P(None, "keys"),
                   out_specs=P(None, "keys"))
    sharding = NamedSharding(mesh, P(None, "keys"))
    return jax.jit(fn), sharding


def ring_pane_aggregate_2d(bins: "np.ndarray", width_bins: int, kind: str,
                           n_shards: int) -> np.ndarray:
    """[C, n_bins] batch form of :func:`ring_pane_aggregate`."""
    import jax
    import jax.numpy as jnp

    if kind not in ("sum", "count", "min", "max"):
        raise ValueError(f"ring_pane_aggregate_2d: unsupported {kind!r}")
    C, n = bins.shape
    assert n % n_shards == 0
    fn, sharding = _ring_step_2d(kind, n_shards, C, n // n_shards,
                                 int(width_bins))
    dev = jax.device_put(jnp.asarray(bins, jnp.float64), sharding)
    return np.asarray(jax.device_get(fn(dev)))


def ring_pane_aggregate(bins: np.ndarray, width_bins: int, kind: str,
                        n_shards: int) -> np.ndarray:
    """Aggregate of the trailing ``width_bins`` bins ending at every bin
    position, computed with the bin dimension block-sharded over
    ``n_shards`` devices and halos exchanged by ring ``ppermute``.

    ``bins`` length must divide evenly by ``n_shards``; positions whose
    window starts before bin 0 aggregate only the existing prefix
    (identity-padded), matching a stream's warm-up panes.
    """
    import jax
    import jax.numpy as jnp

    if kind not in ("sum", "count", "min", "max"):
        # avg must divide by the per-pane non-null count — callers
        # combine a sum ring with a count ring instead (as keyed_bins
        # does); accepting 'avg' here would silently return sums
        raise ValueError(f"ring_pane_aggregate: unsupported kind {kind!r}")
    n = len(bins)
    assert n % n_shards == 0, "bin count must divide the shard count"
    Bl = n // n_shards
    assert width_bins >= 1
    fn, sharding = _ring_step(kind, n_shards, Bl, int(width_bins))
    dev = jax.device_put(jnp.asarray(bins, jnp.float64), sharding)
    return np.asarray(jax.device_get(fn(dev)))
