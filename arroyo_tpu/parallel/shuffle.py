"""Sharded-by-default data plane: on-device co-located shuffles and the
resharding invariant.

This module generalizes ``mesh_window.py``'s route step — bucket rows by
their destination shard, exchange buckets with one ``all_to_all`` over
ICI — into a reusable exchange any SHUFFLE edge can ride when its
producer and consumer subtasks are **co-located** (same process, same
mesh).  The host path (``native.partition_route`` into per-subtask
queues, or the TCP data plane across workers) remains the fallback:
``ARROYO_MESH=off`` reproduces the host topology bit-for-bit.

Two measured invariants live here, so "no resharding" is a number and
not a hope:

* **reshard counter** (``ensure_sharded``): a device-resident array that
  reaches a kernel whose explicit ``in_shardings`` contract it does not
  satisfy is re-placed — and counted (``perf`` counter
  ``reshard_transfers``, prometheus ``arroyo_worker_reshards_total``,
  profiler phase ``reshard``).  Operator kernels compile with matched
  ``out_shardings``/``in_shardings`` (SNIPPETS [1][2]), so chained
  dispatches hand off pre-partitioned device arrays and this counter
  stays **0 in steady state** — asserted by the smoke gate and recorded
  per bench run.  Host->device staging of fresh row batches is counted
  separately (``mesh_ingest_transfers``): it is the expected ingest
  boundary, not a resharding defect.
* **collective counter** (``shuffle_collectives`` /
  ``arroyo_worker_shuffle_collectives_total`` + profiler phase
  ``shuffle_collective``): every on-device exchange that replaced a host
  shuffle.  A co-located SHUFFLE edge carried here moves **zero**
  data-plane frames.

Destination semantics are bit-identical to the host Collector's
(``server_for_hash``: ``min(kh // (U64_MAX // n), n - 1)``), and the
exchange preserves the host path's row order per destination (stable by
destination, original order within), so mesh-on and mesh-off runs emit
identical rows — pinned by the smoke equivalence gate.

Knobs (docs/operations.md):
  ARROYO_SHUFFLE_DEVICE=auto|on|off   co-located device shuffle.  auto =
      on when the mesh is active AND the backend is a real accelerator
      (on the CPU backend the "device" is the same core, so the exchange
      is pure overhead — same policy as ARROYO_DEVICE_JOIN); on forces
      it (the CPU test mesh uses this for parity gates).
  ARROYO_MESH=auto|off|<n>            the mesh itself (mesh_window.py).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import perf, profiler
from ..types import U64_MAX, Batch

# perf-counter keys (cheap process-wide ints; prometheus mirrors ride
# the increment sites)
RESHARDS = "reshard_transfers"
COLLECTIVES = "shuffle_collectives"
COLLECTIVE_ROWS = "shuffle_collective_rows"
HOST_ROUTES = "shuffle_host_routes"
INGEST_TRANSFERS = "mesh_ingest_transfers"

_MIN_ROWS = 256  # per-slice row floor (power-of-two bucketed)


def _bucket(n: int, floor: int = _MIN_ROWS) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def device_shuffle_enabled(n_dests: int) -> bool:
    """Should an ``n_dests``-way co-located SHUFFLE edge ride the device
    exchange?  Requires the mesh on with enough devices and a
    power-of-two fan-out; ``auto`` additionally requires a non-CPU
    backend (device hop on the CPU backend is pure overhead)."""
    mode = os.environ.get("ARROYO_SHUFFLE_DEVICE", "auto").lower()
    if mode in ("off", "0", "false", "none"):
        return False
    if n_dests < 2 or n_dests & (n_dests - 1):
        return False
    from .mesh_window import mesh_key_shards

    if mesh_key_shards() < n_dests:
        return False
    import jax

    if not jax.config.jax_enable_x64:
        return False  # u64 key hashes would truncate inside jit
    if mode == "on":
        return True
    return jax.default_backend() != "cpu"


def keys_sharding(nk: int, *spec_axes) -> Any:
    """NamedSharding over the ``("keys",)`` mesh — the one axis every
    sharded operator kernel partitions on."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh_window import _keys_mesh

    return NamedSharding(_keys_mesh(nk), P(*spec_axes))


def partition_device(p: int) -> Optional[Any]:
    """Mesh device owning join-state partition ``p`` (round-robin over
    the active mesh), or None when the mesh is off — hot join rings then
    stay on the default device exactly as before.  Spreading rings over
    the same ``("keys",)`` mesh axis the window state shards on keeps
    q7/q8-style joins from funneling every hot partition through one
    chip."""
    from .mesh_window import mesh_key_shards

    nk = mesh_key_shards()
    if nk <= 1:
        return None
    import jax

    return jax.devices()[p % nk]


def shuffle_stats() -> Dict[str, int]:
    """Process-wide sharded-data-plane counter snapshot (bench lines and
    tests read deltas of this)."""
    return {
        "reshards": perf.counter(RESHARDS),
        "collectives": perf.counter(COLLECTIVES),
        "collective_rows": perf.counter(COLLECTIVE_ROWS),
        "host_routes": perf.counter(HOST_ROUTES),
        "ingest_transfers": perf.counter(INGEST_TRANSFERS),
    }


# ---------------------------------------------------------------------------
# resharding invariant
# ---------------------------------------------------------------------------


def ensure_sharded(arr: Any, sharding: Any, op_id: str = "__mesh__") -> Any:
    """Return ``arr`` guaranteed to satisfy ``sharding``.

    Device-resident arrays that already match pass through untouched —
    the zero-cost steady state.  A mismatch is an **implicit reshard**:
    counted, profiled (``reshard`` phase), and re-placed, so a kernel
    whose inputs arrive mis-partitioned still computes correctly while
    the regression is measured instead of silently absorbed by XLA.
    Host (numpy) inputs are ingest staging, counted separately."""
    import jax

    cur = getattr(arr, "sharding", None)
    if cur is None:
        perf.count(INGEST_TRANSFERS)
        return jax.device_put(arr, sharding)
    if cur == sharding:
        return arr
    try:
        if cur.is_equivalent_to(sharding, getattr(arr, "ndim", 1)):
            return arr
    except Exception:
        pass
    perf.count(RESHARDS)
    from ..obs.metrics import reshard_counter

    reshard_counter().inc()
    prof = profiler.active()
    frame = (prof.begin(op_id, "reshard") if prof is not None else None)
    try:
        return jax.device_put(arr, sharding)
    finally:
        if frame is not None:
            prof.end(frame)


# ---------------------------------------------------------------------------
# co-located on-device shuffle
# ---------------------------------------------------------------------------
#
# Payload model: a keyed Batch is packed into two stacked transports —
# one f64 stack (float columns; f32 round-trips losslessly through f64)
# and one i64 stack (ints, bools, and u64 bit-views including key_hash
# and the timestamp) — so the whole exchange is THREE all_to_all calls
# (f-stack, i-stack, validity) regardless of column count.  Object
# (string) columns cannot ride the device; such edges fall back to the
# host route, sticky per edge so the output sharding spec never flips
# mid-stream (the sanitizer's sharding-stability invariant).


@functools.lru_cache(maxsize=128)
def _route_step(nk: int, nf: int, ni: int, N: int):
    """shard_map exchange: each of the ``nk`` mesh slices holds N rows
    (data-parallel), buckets them by ``server_for_hash`` destination and
    exchanges buckets with ``all_to_all``.  Per-slice bucket capacity is
    N (a slice holds at most N rows total), so routing structurally
    cannot drop rows.  Returns, per shard, that shard's rows from every
    source slice in source order — globally the host path's stable
    destination order."""
    import inspect

    import jax
    import jax.numpy as jnp

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh_window import _keys_mesh

    range_size = np.uint64(int(U64_MAX) // nk)

    def shard_fn(kh, fv, iv, ok):
        # per-slice views: kh u64[N] (routing only — the VALUE already
        # rides the i-stack's reserved slot 1, so exchanging it again
        # would be a third collective's worth of dead volume);
        # fv f64[nf, N]; iv i64[ni, N]; ok bool[N]
        dest = jnp.minimum((kh // range_size).astype(jnp.int32), nk - 1)
        dest = jnp.where(ok, dest, 0)
        onehot = jax.nn.one_hot(dest, nk, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.sum(pos * onehot, axis=1)
        tgt = dest * N + pos  # pos < N structurally: slice holds N rows
        buf_ok = jnp.zeros((nk * N,), bool).at[tgt].set(ok, mode="drop")
        buf_f = jnp.zeros((nf, nk * N), jnp.float64).at[:, tgt].set(
            jnp.where(ok, fv, 0.0), mode="drop") if nf else \
            jnp.zeros((0, nk * N), jnp.float64)
        buf_i = jnp.zeros((ni, nk * N), jnp.int64).at[:, tgt].set(
            jnp.where(ok, iv, 0), mode="drop") if ni else \
            jnp.zeros((0, nk * N), jnp.int64)
        buf_ok = jax.lax.all_to_all(
            buf_ok.reshape(nk, N), "keys", 0, 0).reshape(-1)
        if nf:
            buf_f = jax.lax.all_to_all(
                buf_f.reshape(nf, nk, N), "keys", 1, 1).reshape(nf, -1)
        if ni:
            buf_i = jax.lax.all_to_all(
                buf_i.reshape(ni, nk, N), "keys", 1, 1).reshape(ni, -1)
        return buf_ok, buf_f, buf_i

    mesh = _keys_mesh(nk)
    _params = inspect.signature(shard_map).parameters
    _check_kw = ({"check_vma": False} if "check_vma" in _params
                 else {"check_rep": False} if "check_rep" in _params
                 else {})
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("keys"), P(None, "keys"), P(None, "keys"), P("keys")),
        out_specs=(P("keys"), P(None, "keys"), P(None, "keys")),
        **_check_kw)
    shard1 = NamedSharding(mesh, P("keys"))
    stack = NamedSharding(mesh, P(None, "keys"))
    # explicit in/out shardings: inputs staged by route() already carry
    # exactly these placements, so the dispatch never implicitly
    # re-partitions (SNIPPETS [1]: matched axis resources)
    return jax.jit(fn,
                   in_shardings=(shard1, stack, stack, shard1),
                   out_shardings=(shard1, stack, stack))


# column transport kinds
_F_KINDS = "f"          # float -> f64 stack
_I_KINDS = "iub?mM"     # int/uint/bool (u64 as bit-view) -> i64 stack


def _to_i64(v: np.ndarray) -> np.ndarray:
    if v.dtype == np.uint64:
        return v.view(np.int64)  # bit-preserving
    if v.dtype == np.int64:
        return v
    return v.astype(np.int64)


def _from_i64(v: np.ndarray, dtype: np.dtype) -> np.ndarray:
    if dtype == np.uint64:
        return v.view(np.uint64)
    if dtype == np.bool_:
        return v != 0
    return v.astype(dtype)


class DeviceShuffle:
    """Route keyed batches across ``n`` co-located destinations with one
    on-device all_to_all exchange per batch.  ``route`` returns the
    per-destination sub-batches (only non-empty ones) or ``None`` when
    this edge cannot ride the device (non-numeric columns, sticky), in
    which case the caller takes the host path."""

    def __init__(self, n: int, op_id: str = ""):
        self.n = n
        self.op_id = op_id
        self._disabled = False  # sticky host fallback (sharding-stable)
        self._mesh_sh: Optional[Tuple[Any, Any]] = None

    def _shardings(self):
        if self._mesh_sh is None:
            self._mesh_sh = (keys_sharding(self.n, "keys"),
                             keys_sharding(self.n, None, "keys"))
        return self._mesh_sh

    def _plan(self, batch: Batch) -> Optional[List[Tuple[str, str, Any, int]]]:
        """(name, stack, dtype, index) per column, or None if any column
        cannot ride the device transport."""
        plan: List[Tuple[str, str, Any, int]] = []
        nf = 0
        ni = 2  # i-stack slots 0/1 reserved: timestamp, key_hash bit-view
        for name, v in batch.columns.items():
            k = v.dtype.kind
            if k in _F_KINDS:
                plan.append((name, "f", v.dtype, nf))
                nf += 1
            elif k in "iub":
                plan.append((name, "i", v.dtype, ni))
                ni += 1
            else:
                return None
        return plan

    def route(self, batch: Batch
              ) -> Optional[List[Tuple[int, Batch]]]:
        if self._disabled or batch.key_hash is None:
            return None
        plan = self._plan(batch)
        if plan is None:
            self._disabled = True  # sticky: the edge's output sharding
            # spec must not flip batch to batch
            return None
        import jax

        nk = self.n
        m = len(batch)
        N = _bucket(-(-m // nk))
        total = nk * N
        nf = sum(1 for _c, s, _d, _i in plan if s == "f")
        ni = 2 + sum(1 for _c, s, _d, _i in plan if s == "i")

        kh_p = np.zeros(total, np.uint64)
        kh_p[:m] = batch.key_hash
        ok_p = np.zeros(total, bool)
        ok_p[:m] = True
        fv = np.zeros((nf, total), np.float64)
        iv = np.zeros((ni, total), np.int64)
        iv[0, :m] = batch.timestamp
        iv[1, :m] = _to_i64(batch.key_hash)
        for name, stack, _dt, idx in plan:
            if stack == "f":
                fv[idx, :m] = batch.columns[name]
            else:
                iv[idx, :m] = _to_i64(batch.columns[name])

        shard1, stacked = self._shardings()
        prof = profiler.active()
        frame = (prof.begin(self.op_id, "shuffle_collective")
                 if prof is not None else None)
        try:
            step = _route_step(nk, nf, ni, N)
            out_ok, out_f, out_i = step(
                jax.device_put(kh_p, shard1),
                jax.device_put(fv, stacked),
                jax.device_put(iv, stacked),
                jax.device_put(ok_p, shard1))
            # one transfer per output buffer; each destination's rows are
            # the d-th block of nk*N entries
            ok_h = np.asarray(jax.device_get(out_ok))
            f_h = np.asarray(jax.device_get(out_f)) if nf else None
            i_h = np.asarray(jax.device_get(out_i))
        finally:
            if frame is not None:
                prof.end(frame)
        perf.count(COLLECTIVES)
        perf.count(COLLECTIVE_ROWS, m)
        # device-memory ledger (obs/latency.py): the staging stacks are
        # the shuffle's transient device footprint for this batch
        perf.note("shuffle_stack_bytes",
                  int(kh_p.nbytes + ok_p.nbytes + fv.nbytes + iv.nbytes))
        from ..obs.metrics import shuffle_collective_counter

        shuffle_collective_counter().inc()

        block = nk * N
        parts: List[Tuple[int, Batch]] = []
        for d in range(nk):
            sel = ok_h[d * block:(d + 1) * block]
            if not sel.any():
                continue
            lo = d * block
            idxs = np.nonzero(sel)[0] + lo
            cols: Dict[str, np.ndarray] = {}
            for name, stack, dt, idx in plan:
                if stack == "f":
                    col = f_h[idx][idxs]
                    cols[name] = (col if dt == np.float64
                                  else col.astype(dt))
                else:
                    cols[name] = _from_i64(i_h[idx][idxs], dt)
            sub = Batch(i_h[0][idxs], cols,
                        _from_i64(i_h[1][idxs], np.dtype(np.uint64)),
                        batch.key_cols, lat_stamp=batch.lat_stamp)
            parts.append((d, sub))
        return parts
