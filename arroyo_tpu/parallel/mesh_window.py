"""Mesh-sharded keyed bin aggregation — the engine's multi-chip data plane.

This is the production form of the SPMD windowed-aggregation step: the
same keyed bin-ring state as :class:`~arroyo_tpu.ops.keyed_bins.KeyedBinState`
but sharded over a 1-D ``("keys",)`` device mesh, so the reference's entire
scale-out tier — collector hash routing
(/root/reference/arroyo-worker/src/engine.rs:183-240) plus the TCP shuffle
(/root/reference/arroyo-worker/src/network_manager.rs:221-307) — becomes ONE
jitted step whose shuffle is ``jax.lax.all_to_all`` over ICI:

1. **route**: incoming rows (sharded over the mesh as the data-parallel
   axis) compute their key-range owner (``server_for_hash`` semantics:
   top bits of the u64 key hash) and exchange buckets with ``all_to_all``;
2. **merge**: each key shard keeps a *sorted* uint64 key table (EMPTY
   sentinel padding) plus per-channel bin accumulators ``[n_ch, C, B]``;
   new keys merge via one fused ``lax.sort``, old state re-scatters to the
   new slot layout, and routed rows scatter-add/min/max in;
3. **fire**: pane emission and eviction are separate jitted calls driven
   by the host watermark, identical in semantics to the single-device
   ``KeyedBinState`` (panes fire once, in order, per key).

Zero-loss guarantees are HOST-enforced (the device never silently drops):

* per-slice row buffers are sized to the padded batch, so the route
  bucketing structurally cannot overflow — a device-side counter proves it;
* the host key directory tracks per-shard key cardinality exactly and
  grows device capacity BEFORE a batch that would overflow dispatches —
  the device key-drop counter proves it;
* bin-ring occupancy is linear (base-relative, rolled on watermark
  advance) and the host grows ``B`` when data runs ahead of the watermark.

Aggregate channels reuse the null-skipping layout of ``keyed_bins``:
hidden additive validity-count channels per column-reading agg, min/max
as native scatter-min/max (VERDICT round-1 item #5: min/max support,
no silent drops, overflow counters).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..graph.logical import AggKind, AggSpec
from ..ops.keyed_bins import (
    NEG_INF,
    POS_INF,
    KeyedBinState,
    _bucket,
    _init_value,
    build_channels,
    channel_inits,
    channel_input,
    directory_insert,
    preaggregate,
)

EMPTY = np.uint64(0xFFFF_FFFF_FFFF_FFFF)  # sentinel: empty key slot
_MIN_ROWS = 256  # per-slice row-buffer floor (power-of-two bucketed)


def mesh_key_shards() -> int:
    """Number of key shards the engine should use: ``ARROYO_MESH`` = 'off'
    (1), an explicit integer, or 'auto' (largest power of two <= device
    count — the planner's "use the mesh when there is one" policy)."""
    import os

    import jax

    mode = os.environ.get("ARROYO_MESH", "auto").lower()
    if mode in ("off", "0", "1", "none"):
        return 1
    n = len(jax.devices())
    if mode.isdigit():
        # routing uses the top log2(nk) key bits, so the shard count must
        # be a power of two — round down, and never exceed the devices
        n = min(int(mode), n)
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


@functools.lru_cache(maxsize=8)
def _keys_mesh(nk: int):
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    assert len(devs) >= nk, f"mesh wants {nk} devices, have {len(devs)}"
    return Mesh(np.array(devs[:nk]), ("keys",))


def _init_filled(ch_kinds: Tuple[str, ...], shape: Tuple[int, ...]
                 ) -> np.ndarray:
    """[n_ch, *shape] float32 array filled with each channel's identity."""
    out = np.zeros((len(ch_kinds),) + shape, np.float64)
    for j, k in enumerate(ch_kinds):
        out[j] = _init_value(AggKind(k))
    return out


def _channel_rows(aggs, ch_kinds, valid_of, agg_inputs, n) -> np.ndarray:
    """[n_ch, n] per-row channel contributions, nulls masked to identity
    (shared semantics: ops/keyed_bins.channel_input)."""
    vals = np.zeros((len(ch_kinds), n), dtype=np.float64)
    for j in range(len(ch_kinds)):
        vals[j] = channel_input(aggs, ch_kinds, valid_of, j, agg_inputs, n)
    return vals


# ---------------------------------------------------------------------------
# jitted steps (cached per shape signature)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _update_step(ch_kinds: Tuple[str, ...], nk: int, C: int, B: int, N: int,
                 shift: int = 0):
    """shard_map step: route rows over the mesh, merge keys, scatter bins.

    Global shapes: keys u64[nk*C]; bins f32[n_ch, nk*C, B];
    counts i32[nk*C, B]; of i32[nk, 2] (route-drop, key-drop counters);
    rows: key u64[nk*N], bin i32[nk*N], vals f32[n_ch, nk*N], ok bool[nk*N].

    ``shift`` skips the top key-hash bits already consumed by subtask
    key ranges (``set_route_shift``): at operator parallelism P > 1 each
    subtask only ever sees a 1/P top-bit slice, and routing on those
    same bits would funnel the whole mesh onto ~nk/P devices.

    Compiled with explicit ``in_shardings``/``out_shardings`` over the
    ``("keys",)`` axis (SNIPPETS [1][2]): state outputs carry exactly
    the shardings the next call's inputs declare, so chained dispatches
    hand off pre-partitioned device arrays with zero implicit
    resharding — measured by ``parallel/shuffle.ensure_sharded``.
    """
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map  # jax >= 0.5 top-level export
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_ch = len(ch_kinds)
    lg = int(np.log2(nk)) if nk > 1 else 0
    inits = tuple(float(_init_value(AggKind(k))) for k in ch_kinds)

    def shard_fn(keys, bins, counts, of, r_key, r_bin, r_vals, r_ok):
        # per-shard views: keys u64[C]; bins [n_ch, C, B]; counts [C, B];
        # of i32[1, 2]; rows: this slice's N rows
        # ---- route: bucket rows by destination shard, all_to_all over ICI
        if nk > 1:
            routed = (r_key << np.uint64(shift)) if shift else r_key
            dest = (routed >> np.uint64(64 - lg)).astype(jnp.int32)
            order = jnp.argsort(dest)
            d_s = dest[order]
            k_s, b_s = r_key[order], r_bin[order]
            v_s, ok_s = r_vals[:, order], r_ok[order]
            onehot = jax.nn.one_hot(d_s, nk, dtype=jnp.int32)
            pos = (jnp.cumsum(onehot, axis=0) - onehot)
            pos = jnp.sum(pos * onehot, axis=1)
            # bucket capacity == slice size N: a slice holds at most N rows
            # total, so per-dest position can never reach N — structurally
            # zero route drops; the counter proves it stays that way
            slot_ok = pos < N
            route_drop = jnp.sum(ok_s & ~slot_ok)
            tgt = d_s * N + jnp.where(slot_ok, pos, 0)
            buf_key = jnp.full((nk * N,), EMPTY, jnp.uint64).at[tgt].set(
                jnp.where(ok_s & slot_ok, k_s, EMPTY), mode="drop")
            buf_bin = jnp.zeros((nk * N,), jnp.int32).at[tgt].set(
                jnp.where(slot_ok, b_s, 0), mode="drop")
            buf_ok = jnp.zeros((nk * N,), bool).at[tgt].set(
                ok_s & slot_ok, mode="drop")
            buf_val = jnp.zeros((n_ch + 1, nk * N),
                                jnp.float64).at[:, tgt].set(
                jnp.where(slot_ok, v_s, 0.0), mode="drop")
            buf_key = jax.lax.all_to_all(
                buf_key.reshape(nk, N), "keys", 0, 0).reshape(-1)
            buf_bin = jax.lax.all_to_all(
                buf_bin.reshape(nk, N), "keys", 0, 0).reshape(-1)
            buf_ok = jax.lax.all_to_all(
                buf_ok.reshape(nk, N), "keys", 0, 0).reshape(-1)
            buf_val = jax.lax.all_to_all(
                buf_val.reshape(n_ch + 1, nk, N), "keys", 1,
                1).reshape(n_ch + 1, -1)
        else:
            route_drop = jnp.int32(0)
            buf_key = jnp.where(r_ok, r_key, EMPTY)
            buf_bin, buf_ok, buf_val = r_bin, r_ok, r_vals
        R = buf_key.shape[0]

        # ---- merge: one fused sort of (old keys ++ incoming keys)
        all_keys = jnp.concatenate([keys, buf_key])
        s_keys, = jax.lax.sort((all_keys,), num_keys=1)
        is_first = jnp.ones_like(s_keys, dtype=bool).at[1:].set(
            s_keys[1:] != s_keys[:-1])
        is_real = is_first & (s_keys != EMPTY)
        rank = jnp.cumsum(is_real) - 1
        key_drop = jnp.sum(is_real & (rank >= C))
        slot_ok2 = is_real & (rank < C)
        tgt2 = jnp.where(slot_ok2, rank, C)
        new_keys = jnp.full((C,), EMPTY, jnp.uint64).at[tgt2].set(
            jnp.where(slot_ok2, s_keys, EMPTY), mode="drop")

        def count_less(table, q_sorted):
            # #(table < q_i) per (sorted) query — searchsorted-left
            # semantics without jnp.searchsorted, which lowers to a
            # sequential per-bit scan on TPU (measured 78 ms per 16k
            # queries; BASELINE.md round-4).  Stable argsort of the
            # concatenation with queries FIRST (equal table entries sort
            # after equal queries), inverse-permute, subtract own rank.
            nq = q_sorted.shape[0]
            nt = nq + table.shape[0]
            o = jnp.argsort(jnp.concatenate([q_sorted, table]),
                            stable=True)
            inv = jnp.zeros(nt, jnp.int32).at[o].set(
                jnp.arange(nt, dtype=jnp.int32))
            return inv[:nq] - jnp.arange(nq, dtype=jnp.int32)

        # ---- re-map old per-key state into the new slot layout
        # (keys is sorted: it was built as new_keys by the previous step)
        old_idx = count_less(new_keys, keys).clip(0, C - 1)
        old_found = (new_keys[old_idx] == keys) & (keys != EMPTY)
        o_tgt = jnp.where(old_found, old_idx, C)
        new_counts = jnp.zeros_like(counts).at[o_tgt].add(
            jnp.where(old_found[:, None], counts, 0), mode="drop")
        chs = []
        for j, kind in enumerate(ch_kinds):
            base = jnp.full((C, B), inits[j], jnp.float64)
            src = jnp.where(old_found[:, None], bins[j],
                            jnp.float64(inits[j]))
            if kind in ("sum", "count"):
                ch = base.at[o_tgt].add(
                    jnp.where(old_found[:, None], bins[j], 0.0), mode="drop")
            elif kind == "min":
                ch = base.at[o_tgt].min(src, mode="drop")
            else:  # max
                ch = base.at[o_tgt].max(src, mode="drop")
            chs.append(ch)

        # ---- scatter routed cells (host pre-aggregated per (key, bin):
        # row 0 of the value payload is the per-cell ROW COUNT)
        qo = jnp.argsort(buf_key, stable=True)
        row_idx = jnp.zeros(R, jnp.int32).at[qo].set(
            count_less(new_keys, buf_key[qo])).clip(0, C - 1)
        row_found = (new_keys[row_idx] == buf_key) & buf_ok
        si = jnp.where(row_found, row_idx, C)
        bi = jnp.where(row_found, buf_bin, 0).clip(0, B - 1)
        new_counts = new_counts.at[si, bi].add(
            jnp.where(row_found, buf_val[0], 0.0).astype(new_counts.dtype),
            mode="drop")
        for j, kind in enumerate(ch_kinds):
            x = buf_val[j + 1]
            if kind in ("sum", "count"):
                chs[j] = chs[j].at[si, bi].add(
                    jnp.where(row_found, x, 0.0), mode="drop")
            elif kind == "min":
                chs[j] = chs[j].at[si, bi].min(
                    jnp.where(row_found, x, POS_INF), mode="drop")
            else:
                chs[j] = chs[j].at[si, bi].max(
                    jnp.where(row_found, x, NEG_INF), mode="drop")
        new_bins = jnp.stack(chs)
        new_of = of + jnp.stack([route_drop, key_drop]).astype(jnp.int32)[
            None, :]
        return new_keys, new_bins, new_counts, new_of

    mesh = _keys_mesh(nk)
    # replication checking was renamed check_rep -> check_vma across jax
    # releases; disable whichever this jax spells
    import inspect

    _params = inspect.signature(shard_map).parameters
    _check_kw = ({"check_vma": False} if "check_vma" in _params
                 else {"check_rep": False} if "check_rep" in _params
                 else {})
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("keys"), P(None, "keys", None), P("keys", None),
                  P("keys", None), P("keys"), P("keys"),
                  P(None, "keys"), P("keys")),
        out_specs=(P("keys"), P(None, "keys", None), P("keys", None),
                   P("keys", None)),
        **_check_kw,
    )
    s1 = NamedSharding(mesh, P("keys"))
    s_bins = NamedSharding(mesh, P(None, "keys", None))
    s2 = NamedSharding(mesh, P("keys", None))
    s_vals = NamedSharding(mesh, P(None, "keys"))
    return jax.jit(fn,
                   in_shardings=(s1, s_bins, s2, s2, s1, s1, s_vals, s1),
                   out_shardings=(s1, s_bins, s2, s2))


@functools.lru_cache(maxsize=256)
def _fire_step(ch_kinds: Tuple[str, ...], nk: int, C: int, B: int, W: int):
    """Pane emission: aggregate window bins for panes in
    [first_rel, wm_rel].  Pure read — eviction is the separate roll step.
    Explicit in/out shardings: the state arrives exactly as the update
    step left it (no implicit resharding between chained dispatches)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # panes at relative index 0..B+W-2: the last ring bin (B-1) still
    # feeds panes up to B-1+W-1, which must be emittable on final flush
    PANES = B + W - 1

    def run(keys, bins, counts, lims):
        first_rel, wm_rel = lims[0], lims[1]
        pane = jnp.arange(PANES, dtype=jnp.int32)
        offs = jnp.arange(W, dtype=jnp.int32) - (W - 1)
        win = pane[:, None] + offs[None, :]  # [PANES, W] linear bin index
        win_ok = (win >= 0) & (win < B)
        wc = win.clip(0, B - 1)
        pane_ok = (pane >= first_rel) & (pane <= wm_rel)
        cnt_g = counts[:, wc]  # [CT, PANES, W]
        cnts = jnp.sum(jnp.where(win_ok[None], cnt_g, 0), axis=-1)
        outs = []
        for j, kind in enumerate(ch_kinds):
            g = bins[j][:, wc]
            if kind in ("sum", "count"):
                r = jnp.sum(jnp.where(win_ok[None], g, 0.0), axis=-1)
            elif kind == "min":
                r = jnp.min(jnp.where(win_ok[None], g, POS_INF), axis=-1)
            else:
                r = jnp.max(jnp.where(win_ok[None], g, NEG_INF), axis=-1)
            outs.append(r)
        mask = pane_ok[None, :] & (cnts > 0) & (keys != EMPTY)[:, None]
        return (jnp.stack(outs) if outs else
                jnp.zeros((0,) + cnts.shape)), cnts, mask

    mesh = _keys_mesh(nk)
    s1 = NamedSharding(mesh, P("keys"))
    s_bins = NamedSharding(mesh, P(None, "keys", None))
    s2 = NamedSharding(mesh, P("keys", None))
    rep = NamedSharding(mesh, P())
    return jax.jit(run,
                   in_shardings=(s1, s_bins, s2, rep),
                   out_shardings=(NamedSharding(mesh, P(None, "keys",
                                                        None)), s2, s2))


@functools.lru_cache(maxsize=256)
def _reset_span_step(ch_kinds: Tuple[str, ...], nk: int, C: int, B: int):
    """Reset the relative bin columns in [lims[0], lims[1]] to each
    channel's identity (counts to 0) — the barrier-drain half of the
    factor-pane path: drained cells must read as empty for the next
    fire WITHOUT moving the ring base the way the roll step does.
    Output shardings match the update step's state inputs."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    inits = tuple(float(_init_value(AggKind(k))) for k in ch_kinds)

    def run(bins, counts, lims):
        idx = jnp.arange(B, dtype=jnp.int32)
        m = (idx >= lims[0]) & (idx <= lims[1])
        counts = jnp.where(m[None, :], 0, counts)
        outs = [jnp.where(m[None, :], jnp.float64(inits[j]), bins[j])
                for j in range(len(ch_kinds))]
        return jnp.stack(outs), counts

    mesh = _keys_mesh(nk)
    s_bins = NamedSharding(mesh, P(None, "keys", None))
    s2 = NamedSharding(mesh, P("keys", None))
    return jax.jit(run,
                   in_shardings=(s_bins, s2, NamedSharding(mesh, P())),
                   out_shardings=(s_bins, s2))


@functools.lru_cache(maxsize=256)
def _roll_step(ch_kinds: Tuple[str, ...], nk: int, C: int, B: int):
    """Evict bins below the new base: shift the linear bin axis left by
    ``shift`` and fill the tail with each channel's identity.  Output
    shardings match the update step's state inputs, so the roll hands
    the ring back pre-partitioned."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    inits = tuple(float(_init_value(AggKind(k))) for k in ch_kinds)

    def run(bins, counts, shift):
        idx = jnp.arange(B, dtype=jnp.int32) + shift
        ok = idx < B
        ic = idx.clip(0, B - 1)
        counts = jnp.where(ok[None, :], counts[:, ic], 0)
        outs = [jnp.where(ok[None, :], bins[j][:, ic], jnp.float64(inits[j]))
                for j in range(len(ch_kinds))]
        return jnp.stack(outs), counts

    mesh = _keys_mesh(nk)
    s_bins = NamedSharding(mesh, P(None, "keys", None))
    s2 = NamedSharding(mesh, P("keys", None))
    return jax.jit(run,
                   in_shardings=(s_bins, s2, NamedSharding(mesh, P())),
                   out_shardings=(s_bins, s2))


# ---------------------------------------------------------------------------
# host wrapper: KeyedBinState-compatible API over the mesh
# ---------------------------------------------------------------------------


class MeshKeyedBinState:
    """Drop-in replacement for :class:`KeyedBinState` whose state lives
    sharded across the ``("keys",)`` device mesh.

    The host keeps the key directory (key-hash -> slot, for key-column
    value recovery and exact per-shard cardinality tracking), window
    bookkeeping (base bin, last fired pane), and admission control; the
    device holds keys/bins/counts sharded by key range and does route +
    merge + scatter + fire as jitted SPMD programs.
    """

    GROW_AT = 0.85  # per-shard occupancy that triggers host-side growth

    def __init__(self, aggs: Tuple[AggSpec, ...], slide_micros: int,
                 width_micros: int, capacity: int = 0,
                 n_shards: Optional[int] = None):
        import jax

        assert jax.config.jax_enable_x64, (
            "MeshKeyedBinState requires jax_enable_x64: u64 key hashes "
            "travel through jit and would truncate to uint32")
        if capacity <= 0:
            from ..config import config

            capacity = config().state_capacity
        assert width_micros % slide_micros == 0
        self.aggs = aggs
        self.kinds = tuple(a.kind.value for a in aggs)
        self._ch_kinds, self._valid_ch = build_channels(aggs)
        self._valid_of = {v: k for k, v in self._valid_ch.items()}
        self.slide = slide_micros
        self.W = width_micros // slide_micros
        self.B = _bucket(2 * self.W + 4, floor=8)
        self.nk = n_shards or mesh_key_shards()
        self.C = _bucket(max(capacity // self.nk, 64))  # per-shard slots
        self.mesh = _keys_mesh(self.nk)
        # key-hash bits to skip when routing (set_route_shift): subtask
        # key ranges consume the TOP bits, so a parallel operator's mesh
        # must route on the bits below them or every row funnels to the
        # few shards covering this subtask's top-bit slice
        self.route_shift = 0

        # host key directory (same layout as KeyedBinState for _emit)
        self.key_sorted = np.zeros(0, dtype=np.uint64)
        self.slot_of_sorted = np.zeros(0, dtype=np.int64)
        self.next_slot = 0
        self.slot_to_key = np.zeros(64, dtype=np.uint64)
        from ..native import NativeDir

        self._ndir = NativeDir.create(self.C)
        self.shard_counts = np.zeros(self.nk, dtype=np.int64)

        # window bookkeeping (absolute bins; device works base-relative)
        self.base_bin: Optional[int] = None
        self.min_bin: Optional[int] = None
        self.max_bin: Optional[int] = None
        self.last_fired_pane: Optional[int] = None
        self.late_rows = 0
        # mirror of KeyedBinState.total_rows: bounds any cell/pane count
        # sum, driving i32 -> i64 plane promotion before a wrap is possible
        self.total_rows = 0
        # merge-input mode (factor windows): see
        # KeyedBinState.set_merge_inputs — channels read per-pane partial
        # columns, the counts plane accumulates the row-mass column
        self._merge_cols: Optional[Dict[int, str]] = None
        self._rows_col: Optional[str] = None

        self._alloc_device()

    # -- device state ------------------------------------------------------

    def _alloc_device(self) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        CT = self.nk * self.C
        put = functools.partial(jax.device_put)
        self.d_keys = put(jnp.full((CT,), EMPTY, jnp.uint64),
                          NamedSharding(self.mesh, P("keys")))
        bins = _init_filled(self._ch_kinds, (CT, self.B))
        self.d_bins = put(jnp.asarray(bins),
                          NamedSharding(self.mesh, P(None, "keys", None)))
        self.d_counts = put(jnp.zeros((CT, self.B), jnp.int32),
                            NamedSharding(self.mesh, P("keys", None)))
        self.d_of = put(jnp.zeros((self.nk, 2), jnp.int32),
                        NamedSharding(self.mesh, P("keys", None)))

    def device_bytes(self) -> int:
        """Resident device footprint of the sharded planes (metadata
        only — ``.nbytes`` off the handles, no transfer); feeds the
        per-job device-memory ledger (obs/latency.py)."""
        return (int(self.d_keys.nbytes) + int(self.d_bins.nbytes)
                + int(self.d_counts.nbytes) + int(self.d_of.nbytes))

    def set_route_shift(self, shift: int) -> None:
        """Skip the top ``shift`` key-hash bits when routing rows to
        shards (host directory AND device route step stay in lockstep).
        Set by BinAggOperator before any row lands when the operator
        runs at parallelism > 1: subtask ranges split the top bits, so
        without the shift every subtask's keys collapse onto the
        ~nk/parallelism shards covering its range — the mesh silently
        degenerates to one device per subtask."""
        assert self.next_slot == 0 and self.total_rows == 0, \
            "route shift must be set before any key is admitted"
        assert 0 <= shift <= 32
        self.route_shift = int(shift)

    def _shard_of(self, kh: np.ndarray) -> np.ndarray:
        if self.nk == 1:
            return np.zeros(len(kh), dtype=np.int64)
        lg = int(np.log2(self.nk))
        if self.route_shift:
            kh = kh << np.uint64(self.route_shift)
        return (kh >> np.uint64(64 - lg)).astype(np.int64)

    # -- host key directory ------------------------------------------------

    def _lookup_or_insert(self, kh: np.ndarray) -> np.ndarray:
        kh = np.where(kh == EMPTY, EMPTY - np.uint64(1), kh)  # sentinel

        def ensure(total, new_keys):
            if total > len(self.slot_to_key):
                grown = np.zeros(_bucket(total, floor=64), np.uint64)
                grown[:self.next_slot] = self.slot_to_key[:self.next_slot]
                self.slot_to_key = grown
            np.add.at(self.shard_counts, self._shard_of(new_keys), 1)
            # grow BEFORE any shard can overflow: exact host-side counts
            while self.shard_counts.max() > self.GROW_AT * self.C:
                self._grow_capacity()

        return directory_insert(self, kh, ensure)

    def _grow_capacity(self) -> None:
        """Double per-shard capacity: host re-layout, sharded re-upload."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        import jax

        keys = np.asarray(jax.device_get(self.d_keys)).reshape(self.nk,
                                                               self.C)
        bins = np.asarray(jax.device_get(self.d_bins)).reshape(
            len(self._ch_kinds), self.nk, self.C, self.B)
        counts = np.asarray(jax.device_get(self.d_counts)).reshape(
            self.nk, self.C, self.B)
        C2 = self.C * 2
        keys2 = np.full((self.nk, C2), EMPTY, np.uint64)
        keys2[:, :self.C] = keys  # EMPTY pads sort AFTER real keys
        bins2 = _init_filled(self._ch_kinds, (self.nk, C2, self.B))
        bins2[:, :, :self.C] = bins
        counts2 = np.zeros((self.nk, C2, self.B), counts.dtype)
        counts2[:, :self.C] = counts
        self.C = C2
        self.d_keys = jax.device_put(
            jnp.asarray(keys2.reshape(-1)),
            NamedSharding(self.mesh, P("keys")))
        self.d_bins = jax.device_put(
            jnp.asarray(bins2.reshape(len(self._ch_kinds), -1, self.B)),
            NamedSharding(self.mesh, P(None, "keys", None)))
        self.d_counts = jax.device_put(
            jnp.asarray(counts2.reshape(-1, self.B)),
            NamedSharding(self.mesh, P("keys", None)))

    def _grow_ring(self, needed: int) -> None:
        """Data ran ahead of the watermark beyond the bin ring: widen B."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        B2 = self.B
        while B2 < needed:
            B2 <<= 1
        bins = np.asarray(jax.device_get(self.d_bins))
        counts = np.asarray(jax.device_get(self.d_counts))
        CT = bins.shape[1]
        bins2 = _init_filled(self._ch_kinds, (CT, B2))
        bins2[:, :, :self.B] = bins
        counts2 = np.zeros((CT, B2), counts.dtype)
        counts2[:, :self.B] = counts
        self.B = B2
        self.d_bins = jax.device_put(
            jnp.asarray(bins2), NamedSharding(self.mesh,
                                              P(None, "keys", None)))
        self.d_counts = jax.device_put(
            jnp.asarray(counts2), NamedSharding(self.mesh, P("keys", None)))

    def _rebase(self, new_base: int) -> None:
        """Out-of-order rows landed below the ring base while their panes
        are still unfired: shift the linear columns right (host re-layout,
        rare) so column 0 becomes ``new_base``."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        off = self.base_bin - new_base
        B2 = _bucket(off + self.B, floor=8)
        bins = np.asarray(jax.device_get(self.d_bins))
        counts = np.asarray(jax.device_get(self.d_counts))
        CT = bins.shape[1]
        bins2 = _init_filled(self._ch_kinds, (CT, B2))
        bins2[:, :, off:off + self.B] = bins
        counts2 = np.zeros((CT, B2), counts.dtype)
        counts2[:, off:off + self.B] = counts
        self.B = B2
        self.base_bin = new_base
        self.d_bins = jax.device_put(
            jnp.asarray(bins2), NamedSharding(self.mesh,
                                              P(None, "keys", None)))
        self.d_counts = jax.device_put(
            jnp.asarray(counts2), NamedSharding(self.mesh, P("keys", None)))

    # -- update ------------------------------------------------------------

    def set_merge_inputs(self, channel_cols: Dict[int, str],
                         rows_col: str) -> None:
        """Arm merge-input mode (factor windows) — same contract as
        :meth:`KeyedBinState.set_merge_inputs`; every channel (visible
        and hidden validity) must have a mapped partial column because
        the mesh ships all channels through the route step."""
        assert self.next_slot == 0 and self.total_rows == 0, \
            "merge inputs must be set before any key is admitted"
        for j in range(len(self._ch_kinds)):
            assert j in channel_cols, f"no merge column for channel {j}"
        self._merge_cols = dict(channel_cols)
        self._rows_col = rows_col

    def update(self, key_hash: np.ndarray, timestamps: np.ndarray,
               agg_inputs: Dict[str, np.ndarray]) -> None:
        n = len(key_hash)
        if n == 0:
            return
        from ..obs import perf as _perf

        # factor-window cost evidence (see KeyedBinState.update); the
        # DISPATCH counter increments next to the actual scatter below,
        # so all-late batches that never dispatch are not counted
        _perf.count("pane_update_rows", n)
        kh = np.where(key_hash == EMPTY, EMPTY - np.uint64(1),
                      key_hash.astype(np.uint64))
        self._lookup_or_insert(kh)  # idempotent; ensures capacity

        abs_bin = (timestamps // self.slide).astype(np.int64)
        # a row in bin b feeds panes b..b+W-1; it is late (dropped) ONLY
        # when all those panes already fired — same threshold as the
        # single-device KeyedBinState (NOT the first batch's minimum:
        # out-of-order rows before any fire are always live)
        if self.last_fired_pane is not None:
            thr = self.last_fired_pane - self.W + 2
            live = abs_bin >= thr
        else:
            live = np.ones(n, dtype=bool)
        self.late_rows += int((~live).sum())
        if not live.any():
            return
        if self._merge_cols is not None:
            from ..formats import coerce_float

            w_rows = coerce_float(agg_inputs[self._rows_col], np.float64)
            w_rows = np.where(np.isnan(w_rows), 0.0, w_rows)
            self.total_rows += int(np.ceil(w_rows[live].sum()))
        else:
            w_rows = None
            self.total_rows += int(live.sum())
        if self.total_rows >= KeyedBinState._i32_promote:
            import jax.numpy as _jnp

            if self.d_counts.dtype == _jnp.int32:
                # promote BEFORE the crossing batch lands (same policy as
                # KeyedBinState.update; kernels retrace on the new dtype)
                self.d_counts = self.d_counts.astype(_jnp.int64)
        lo = int(abs_bin[live].min())
        hi = int(abs_bin[live].max())
        self.min_bin = lo if self.min_bin is None else min(self.min_bin, lo)
        self.max_bin = hi if self.max_bin is None else max(self.max_bin, hi)
        if self.base_bin is None:
            self.base_bin = lo
        elif lo < self.base_bin:
            # live rows BELOW the ring base (out-of-order arrivals before
            # their panes fired): rebase the linear ring downward
            self._rebase(lo)
        if hi - self.base_bin >= self.B:
            self._grow_ring(hi - self.base_bin + 1)
        rel = (abs_bin - self.base_bin).astype(np.int32)

        if self._merge_cols is not None:
            # merge-input mode: channels read already-aggregated partial
            # columns (NaN masked to the channel identity); the row mass
            # rides as one extra additive channel so duplicate cells sum
            # their true masses instead of counting pane arrivals
            from ..formats import coerce_float

            vals = np.zeros((len(self._ch_kinds), n), dtype=np.float64)
            for j, kind in enumerate(self._ch_kinds):
                raw = coerce_float(agg_inputs[self._merge_cols[j]],
                                   np.float64)
                ident = np.float64(_init_value(AggKind(kind)))
                vals[j] = np.where(np.isnan(raw), ident, raw)
            if not live.all():
                idx = live.nonzero()[0]
                kh, rel, vals = kh[idx], rel[idx], vals[:, idx]
                w_rows = w_rows[idx]
            kh_c, rel_c, _arrivals, red = preaggregate(
                kh, rel, self._ch_kinds + ("sum",),
                np.concatenate([vals, w_rows[None]]))
            rowcnt = red[-1]
            vals_c = red[:-1]
        else:
            vals = _channel_rows(self.aggs, self._ch_kinds, self._valid_of,
                                 agg_inputs, n)
            # two-phase, local half: reduce rows per (key, bin) on the host
            # BEFORE routing (TumblingLocalAggregator analog) — shrinks both
            # the all_to_all payload and the per-shard scatter
            if not live.all():
                idx = live.nonzero()[0]
                kh, rel, vals = kh[idx], rel[idx], vals[:, idx]
            kh_c, rel_c, rowcnt, vals_c = preaggregate(
                kh, rel, self._ch_kinds, vals)
        m = len(kh_c)
        # pad to nk * N (N power-of-two cells per mesh slice); each slice
        # holds <= N cells so route buckets cannot overflow
        N = _bucket(-(-m // self.nk), floor=_MIN_ROWS)
        total = self.nk * N
        kh_p = np.full(total, EMPTY, np.uint64)
        kh_p[:m] = kh_c
        rel_p = np.zeros(total, np.int32)
        rel_p[:m] = rel_c
        ok_p = np.zeros(total, bool)
        ok_p[:m] = True
        vals_p = np.zeros((len(self._ch_kinds) + 1, total), np.float64)
        vals_p[0, :m] = rowcnt
        vals_p[1:, :m] = vals_c

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..obs.perf import timed_device
        from . import shuffle as _shuffle

        shard1 = NamedSharding(self.mesh, P("keys"))
        # resharding invariant: state arrays must still carry the exact
        # shardings the previous step's out_shardings pinned — a
        # mismatch here is counted (and healed), never silently absorbed
        s_bins = NamedSharding(self.mesh, P(None, "keys", None))
        s2 = NamedSharding(self.mesh, P("keys", None))
        d_keys = _shuffle.ensure_sharded(self.d_keys, shard1)
        d_bins = _shuffle.ensure_sharded(self.d_bins, s_bins)
        d_counts = _shuffle.ensure_sharded(self.d_counts, s2)
        d_of = _shuffle.ensure_sharded(self.d_of, s2)
        step = _update_step(self._ch_kinds, self.nk, self.C, self.B, N,
                            self.route_shift)
        _perf.count("pane_update_dispatches")
        if self.nk > 1:
            # the route half of this step IS the keyed shuffle: one
            # all_to_all over ICI instead of a host exchange
            from ..obs import perf as _perf

            _perf.count(_shuffle.COLLECTIVES)
            _perf.count(_shuffle.COLLECTIVE_ROWS, m)
        self.d_keys, self.d_bins, self.d_counts, self.d_of = timed_device(
            step, d_keys, d_bins, d_counts, d_of,
            jax.device_put(jnp.asarray(kh_p), shard1),
            jax.device_put(jnp.asarray(rel_p), shard1),
            jax.device_put(jnp.asarray(vals_p),
                           NamedSharding(self.mesh, P(None, "keys"))),
            jax.device_put(jnp.asarray(ok_p), shard1))

    # -- pane emission -----------------------------------------------------

    def overflow_counters(self) -> Tuple[int, int]:
        """(route_dropped, keys_dropped) — both stay 0 under the host's
        admission control; exposed for metrics and tests."""
        import jax

        of = np.asarray(jax.device_get(self.d_of))
        return int(of[:, 0].sum()), int(of[:, 1].sum())

    def _read_fired(self, first_rel: int, wm_rel: int):
        """Run the fire step over relative panes [first_rel, wm_rel] and
        materialize (outs, cnts, mask, keys) on host — the shared read
        half of :meth:`fire_panes` and :meth:`drain_deltas` (transfer
        only the fired range; prefetch so the readbacks overlap into
        ~one round-trip)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..obs.perf import timed_device
        from . import shuffle as _shuffle

        d_keys = _shuffle.ensure_sharded(
            self.d_keys, NamedSharding(self.mesh, P("keys")))
        d_bins = _shuffle.ensure_sharded(
            self.d_bins, NamedSharding(self.mesh, P(None, "keys", None)))
        d_counts = _shuffle.ensure_sharded(
            self.d_counts, NamedSharding(self.mesh, P("keys", None)))
        self.d_keys, self.d_bins, self.d_counts = d_keys, d_bins, d_counts
        fire = _fire_step(self._ch_kinds, self.nk, self.C, self.B, self.W)
        outs, cnts, mask = timed_device(
            fire, d_keys, d_bins, d_counts,
            jnp.asarray([first_rel, wm_rel], jnp.int32))
        from ..ops.keyed_bins import _prefetch_host

        k = wm_rel - first_rel + 1
        outs_d = outs[:, :, first_rel:first_rel + k]
        cnts_d = cnts[:, first_rel:first_rel + k]
        mask_d = mask[:, first_rel:first_rel + k]
        _prefetch_host(outs_d, cnts_d, mask_d, self.d_keys)
        return (np.asarray(jax.device_get(outs_d)),
                np.asarray(jax.device_get(cnts_d)),
                np.asarray(jax.device_get(mask_d)),
                np.asarray(jax.device_get(self.d_keys)))

    def _flatten_fired(self, outs, cnts, mask, keys_h, base: int,
                       first_rel: int):
        """Visible aggregate columns from the fired-cell grid — ONE home
        for both emission paths (mirrors KeyedBinState._out_cols) so a
        null/AVG semantics fix cannot apply to fire_panes and silently
        miss drain_deltas."""
        cell_idx, pane_idx = np.nonzero(mask)
        if len(cell_idx) == 0:
            return None
        keys = keys_h[cell_idx]
        # pane_idx is relative to the transferred slice [first_rel, ..]
        window_end = (base + first_rel + pane_idx.astype(np.int64) + 1) \
            * self.slide
        out_cols: Dict[str, np.ndarray] = {}
        for i, a in enumerate(self.aggs):
            col = outs[i][cell_idx, pane_idx]
            if a.kind == AggKind.COUNT:
                col = col.astype(np.int64)
            elif i in self._valid_ch:
                nv = outs[self._valid_ch[i]][cell_idx, pane_idx]
                if a.kind == AggKind.AVG:
                    col = col / np.maximum(nv, 1)
                col = np.where(nv > 0, col, np.nan)
            out_cols[a.output] = col
        return keys, out_cols, window_end, cnts[cell_idx, pane_idx]

    def fire_panes(self, watermark: int, final: bool = False):
        if self.max_bin is None or self.next_slot == 0:
            return None
        if final:
            last_pane = self.max_bin + self.W - 1
        else:
            last_pane = min(int(watermark // self.slide) - 1,
                            self.max_bin + self.W - 1)
        first_pane = (self.last_fired_pane + 1
                      if self.last_fired_pane is not None
                      else (self.min_bin or 0))
        if last_pane < first_pane:
            return None
        base = self.base_bin if self.base_bin is not None else 0
        # rel pane range is always within [0, B+W-2]: last_pane is capped
        # at max_bin + W - 1 and max_bin < base + B
        wm_rel = last_pane - base
        first_rel = first_pane - base
        outs, cnts, mask, keys_h = self._read_fired(first_rel, wm_rel)

        import jax.numpy as jnp

        self.last_fired_pane = last_pane
        # evict: roll the base forward past bins no future pane needs
        new_base = last_pane - self.W + 2
        if new_base > base:
            shift = int(min(new_base - base, self.B))
            roll = _roll_step(self._ch_kinds, self.nk, self.C, self.B)
            self.d_bins, self.d_counts = roll(self.d_bins, self.d_counts,
                                              jnp.int32(shift))
            self.base_bin = base + shift
            if self.min_bin is not None:
                self.min_bin = max(self.min_bin, self.base_bin)

        return self._flatten_fired(outs, cnts, mask, keys_h, base,
                                   first_rel)

    def drain_deltas(self):
        """Checkpoint-barrier drain for FACTOR pane rings (W == 1): same
        contract as :meth:`KeyedBinState.drain_deltas` — read every
        un-fired (key, bin) cell as a pane delta, reset those cells on
        device, leave ``last_fired_pane``/``base_bin`` untouched."""
        assert self.W == 1, "drain_deltas is the factor-pane path (W == 1)"
        if self.max_bin is None or self.next_slot == 0:
            return None
        first_pane = (self.last_fired_pane + 1
                      if self.last_fired_pane is not None
                      else (self.min_bin or 0))
        last_pane = self.max_bin
        if last_pane < first_pane:
            return None
        base = self.base_bin if self.base_bin is not None else 0
        first_rel = max(first_pane - base, 0)
        wm_rel = last_pane - base
        outs, cnts, mask, keys_h = self._read_fired(first_rel, wm_rel)

        import jax.numpy as jnp

        # reset the drained relative bin span on device (base stays put:
        # later rows for these bins re-accumulate and ship as new deltas)
        rs = _reset_span_step(self._ch_kinds, self.nk, self.C, self.B)
        self.d_bins, self.d_counts = rs(
            self.d_bins, self.d_counts,
            jnp.asarray([first_rel, wm_rel], jnp.int32))

        return self._flatten_fired(outs, cnts, mask, keys_h, base,
                                   first_rel)

    # -- checkpoint --------------------------------------------------------

    def snapshot(self) -> Dict[str, np.ndarray]:
        """Canonical topology-independent snapshot (same format as
        KeyedBinState.snapshot): compacted per-key LINEAR bin columns
        (column j = absolute bin lo+j) + host key directory, so restore
        can re-shard onto any mesh OR a single device (rescale by key
        range, parquet.rs:194-218 analog)."""
        import jax

        keys = np.asarray(jax.device_get(self.d_keys))
        bins = np.asarray(jax.device_get(self.d_bins))
        counts = np.asarray(jax.device_get(self.d_counts))
        real = keys != EMPTY
        base = self.base_bin if self.base_bin is not None else -1
        if base >= 0 and self.max_bin is not None:
            lo = max(base, self.min_bin if self.min_bin is not None else base)
            span = self.max_bin - lo + 1
            first = lo - base  # device columns are base-relative
        else:
            lo, span, first = -1, 0, 0
        return {
            "bin_keys": keys[real],
            "bin_vals": bins[:, real][:, :, first:first + span],
            "bin_counts": counts[real][:, first:first + span],
            "ch_init": channel_inits(self._ch_kinds),
            # provenance marker (ignored by restore — the format is
            # topology-independent): lets tests/operators verify a
            # checkpoint was written by an N-shard mesh state
            "mesh_shards": np.array([self.nk], dtype=np.int64),
            "key_sorted": self.key_sorted,
            "slot_of_sorted": self.slot_of_sorted,
            "slot_to_key": self.slot_to_key[:self.next_slot],
            "meta": np.array([
                self.next_slot, lo,  # lo == min_bin (min_bin >= base_bin)
                -1 if self.max_bin is None else self.max_bin,
                -1 if self.last_fired_pane is None else self.last_fired_pane,
            ], dtype=np.int64),
        }

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        meta = arrays["meta"]
        self.next_slot = int(meta[0])
        lo = int(meta[1])
        self.max_bin = None if meta[2] < 0 else int(meta[2])
        self.last_fired_pane = None if meta[3] < 0 else int(meta[3])
        self.min_bin = None if lo < 0 else lo
        # base starts at the oldest stored bin (column 0); update()'s
        # _rebase lowers it on demand if live out-of-order rows arrive
        # below it (eagerly reserving columns down to the late threshold
        # could allocate a huge ring when the watermark lags behind data)
        self.base_bin = lo if lo >= 0 else None
        self.key_sorted = arrays["key_sorted"].astype(np.uint64)
        self.slot_of_sorted = arrays["slot_of_sorted"].astype(np.int64)
        from ..native import NativeDir

        self._ndir = NativeDir.create(max(self.next_slot, 64))
        if self._ndir is not None:
            self._ndir.load(self.key_sorted, self.slot_of_sorted)
        self.slot_to_key = np.zeros(
            _bucket(max(self.next_slot, 1), floor=64), np.uint64)
        self.slot_to_key[:self.next_slot] = \
            arrays["slot_to_key"].astype(np.uint64)[:self.next_slot]

        keys = arrays["bin_keys"].astype(np.uint64)
        bins = np.asarray(arrays["bin_vals"], dtype=np.float64)
        raw_counts = np.asarray(arrays["bin_counts"])
        from ..ops.keyed_bins import restored_count_state

        self.total_rows, cnt_dtype = restored_count_state(
            raw_counts, KeyedBinState._i32_promote)
        counts = raw_counts.astype(cnt_dtype)
        span = bins.shape[-1]
        self.B = _bucket(max(span, 2 * self.W + 4), floor=8)
        if span < self.B:  # pad linear columns out to the ring width
            bins_p = _init_filled(self._ch_kinds, bins.shape[1:-1] + (self.B,))
            bins_p[..., :span] = bins
            bins = bins_p
            counts_p = np.zeros(counts.shape[:-1] + (self.B,), cnt_dtype)
            counts_p[..., :span] = counts
            counts = counts_p
        # admission control counts come from the HOST directory (a strict
        # superset of device-resident keys — late-only keys included), so
        # growth still triggers before any shard can overflow
        self.shard_counts = np.bincount(
            self._shard_of(self.key_sorted), minlength=self.nk)
        # re-shard: place each key into its owner shard's sorted table
        shard = self._shard_of(keys)
        while self.shard_counts.max() > self.GROW_AT * self.C:
            self.C *= 2
        keys2 = np.full((self.nk, self.C), EMPTY, np.uint64)
        bins2 = _init_filled(self._ch_kinds, (self.nk, self.C, self.B))
        counts2 = np.zeros((self.nk, self.C, self.B), counts.dtype)
        for s in range(self.nk):
            sel = shard == s
            ks = keys[sel]
            order = np.argsort(ks)
            m = len(ks)
            keys2[s, :m] = ks[order]
            bins2[:, s, :m] = bins[:, sel][:, order]
            counts2[s, :m] = counts[sel][order]
        self.d_keys = jax.device_put(
            jnp.asarray(keys2.reshape(-1)),
            NamedSharding(self.mesh, P("keys")))
        self.d_bins = jax.device_put(
            jnp.asarray(bins2.reshape(len(self._ch_kinds), -1, self.B)),
            NamedSharding(self.mesh, P(None, "keys", None)))
        self.d_counts = jax.device_put(
            jnp.asarray(counts2.reshape(-1, self.B)),
            NamedSharding(self.mesh, P("keys", None)))
        self.d_of = jax.device_put(
            jnp.zeros((self.nk, 2), jnp.int32),
            NamedSharding(self.mesh, P("keys", None)))


def place_session_partition(p: int):
    """Mesh device owning session-state partition ``p``.  Session runs
    spread over the same ``("keys",)`` axis the window state shards on
    (round-robin, the join-ring placement policy): hot partitions of a
    sessionized job never funnel through one chip while a mesh windowed
    aggregate holds the others.  None when the mesh is off — staged
    planes then live on the default device."""
    from .shuffle import partition_device

    return partition_device(p)


def make_bin_state(aggs: Tuple[AggSpec, ...], slide_micros: int,
                   width_micros: int, capacity: int = 0):
    """State factory for BinAggOperator: mesh-sharded when more than one
    device is available (ARROYO_MESH=auto), single-device otherwise.

    Long-window/short-slide shapes (W = width/slide >= ARROYO_RING_MIN_W,
    e.g. HOP(1s, 300s)) shard the BIN dimension instead of the key
    dimension: KeyedBinState's ring-pane emission (ops/keyed_bins.py
    _emit_ring + parallel/ring_panes.py) replaces the [C, k, W] gather
    that dominates memory at large W — SURVEY §5's sequence-parallel
    discipline, selected automatically."""
    import os

    import jax

    nk = mesh_key_shards()
    W = width_micros // max(slide_micros, 1)
    ring_min = int(os.environ.get("ARROYO_RING_MIN_W", 64))
    ring_shape = (W >= ring_min
                  and os.environ.get("ARROYO_RING", "auto") != "off")
    # the mesh path ships uint64 key hashes through jit: without x64 JAX
    # would truncate them to uint32 (silently wrong merges/routes), so
    # fall back to the x32-safe single-device kernels
    if nk > 1 and jax.config.jax_enable_x64 and not ring_shape:
        return MeshKeyedBinState(aggs, slide_micros, width_micros,
                                 capacity=capacity, n_shards=nk)
    from ..ops.keyed_bins import KeyedBinState

    return KeyedBinState(aggs, slide_micros, width_micros,
                         capacity=capacity)
