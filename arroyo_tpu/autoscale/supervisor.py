"""JobAutoscaler: the live per-job control loop.

Thin I/O shell around BacklogDrainPolicy: every ``interval_secs`` it
reads the controller's heartbeat-aggregated rollups for the job, runs
one policy evaluation, records the decision in the ledger and the
prometheus counters, and — when the policy recommends and nothing vetoes
— actuates through the existing ``controller.rescale_job`` with
per-operator overrides.  All decision logic lives in the policy so the
deterministic simulator exercises exactly the code that runs here.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

from ..config import config
from ..obs import metrics as m
from .ledger import DecisionLedger
from .policy import (
    HOLD,
    SCALE_UP,
    VETO,
    VETO_ACTUATION_FAILED,
    BacklogDrainPolicy,
    Decision,
    EvalInput,
    PolicyConfig,
)

logger = logging.getLogger(__name__)


def upstream_map(program) -> Dict[str, List[str]]:
    """operator_id -> producer operator_ids, from the logical DAG."""
    return {op: sorted(program.graph.predecessors(op))
            for op in program.graph.nodes}


class JobAutoscaler:
    """One control loop per job.  Created for every job the controller
    accepts (so the decision ledger and REST surface always exist); the
    evaluation task only runs while ``enabled``."""

    def __init__(self, controller, job_id: str,
                 policy: Optional[BacklogDrainPolicy] = None,
                 enabled: bool = False):
        self.controller = controller
        self.job_id = job_id
        self.policy = policy or BacklogDrainPolicy(
            PolicyConfig(interval_secs=config().autoscale_interval_secs))
        self.ledger = DecisionLedger()
        self.enabled = enabled
        self._task: Optional[asyncio.Task] = None
        self._rescaling = False

    # -- lifecycle ---------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = enabled
        if enabled:
            self.start()
        else:
            self.stop()

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())

    def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    def status(self) -> Dict[str, Any]:
        """REST/console payload: config, counters, the decision ring."""
        job = self.controller.jobs.get(self.job_id)
        return {
            "job_id": self.job_id,
            "enabled": self.enabled,
            "global_enabled": config().autoscale_enabled,
            "running": self.running,
            "policy": self.policy.cfg.to_json(),
            "evaluations": self.ledger.evaluations,
            "actuations": self.ledger.actuations,
            "vetoes": self.ledger.vetoes,
            "parallelism": ({n.operator_id: n.parallelism
                             for n in job.program.nodes()}
                            if job is not None else {}),
            # recent tail only — each entry carries a per-operator
            # inputs digest, and the console polls this every second;
            # actuations ride in their own list so a busy loop's holds
            # can never push them out of view
            "decisions": self.ledger.to_json(limit=128),
            "actuated": self.ledger.actuated_json(),
        }

    # -- the loop ----------------------------------------------------------

    async def _loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.policy.cfg.interval_secs)
                job = self.controller.jobs.get(self.job_id)
                if job is None or job.fsm.state.terminal:
                    return
                if not self.enabled:
                    return  # disabled mid-sleep; set_enabled restarts
                try:
                    await self.evaluate_once(job)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # the autoscaler must never take the controller down
                    logger.exception("autoscaler evaluation for %s failed",
                                     self.job_id)
        except asyncio.CancelledError:
            raise

    async def evaluate_once(self, job) -> Decision:
        """One evaluation tick: read rollups, decide, maybe actuate."""
        from ..controller.state_machine import JobState

        if self._rescaling or job.fsm.state != JobState.RUNNING:
            # mid-rescale / not running: nothing to measure — skip the
            # tick entirely instead of flooding the ledger with holds
            return Decision(t=time.monotonic(), action=HOLD,
                            reason="not_running")
        rollups = self.controller.job_rollup(self.job_id)
        decision = self.policy.evaluate(EvalInput(
            now=time.monotonic(),
            rollups=rollups,
            parallelism={n.operator_id: n.parallelism
                         for n in job.program.nodes()},
            upstream=upstream_map(job.program),
            # plan-pinned operators (StreamNode.max_parallelism, e.g. a
            # global merge stage) are hard ceilings: recommending past
            # them would checkpoint-stop the whole job for a no-op
            hard_max={n.operator_id: n.max_parallelism
                      for n in job.program.nodes()
                      if n.max_parallelism is not None},
            # latency signal (obs/latency.py): the controller's SLO
            # evaluator keeps the burn-rate ring fresh every supervise
            # tick — 0.0 when no SLO is configured (or on job doubles
            # built without one)
            slo_burn=(job.slo_eval.current_burn_rate
                      if getattr(job, "slo_eval", None) is not None
                      else 0.0)))
        self.ledger.append(decision)
        m.autoscaler_counter(m.AUTOSCALER_DECISIONS, self.job_id,
                             decision.action).inc()
        if decision.action == VETO:
            m.autoscaler_counter(m.AUTOSCALER_VETOES, self.job_id,
                                 decision.reason).inc()
        if decision.overrides:
            await self._actuate(decision)
        return decision

    async def _actuate(self, decision: Decision) -> None:
        # shielded: cancelling the loop (disable toggle, controller
        # shutdown racing a tick) must not abort a rescale in flight —
        # the FSM is between checkpoint-stop and restart there, and an
        # abort would strand the job in RESCALING with no workers
        await asyncio.shield(self._do_rescale(decision))

    async def _do_rescale(self, decision: Decision) -> None:
        direction = "up" if decision.action == SCALE_UP else "down"
        self._rescaling = True
        try:
            await self.controller.rescale_job(self.job_id,
                                              dict(decision.overrides))
        except Exception as e:
            # record the failure in the SAME ledger entry so the REST
            # surface shows "recommended but failed", not a silent hold.
            # The cooldown stamped at recommendation time intentionally
            # stands: the failed attempt still checkpoint-stopped the
            # job (controller.rescale_job recovers it), and retrying a
            # failing rescale every interval would hammer a job that is
            # already struggling
            decision.error = f"{type(e).__name__}: {e}"
            self.ledger.vetoes += 1
            m.autoscaler_counter(m.AUTOSCALER_VETOES, self.job_id,
                                 VETO_ACTUATION_FAILED).inc()
            logger.warning("autoscaler rescale of %s failed: %s",
                           self.job_id, e)
            return
        finally:
            self._rescaling = False
        self.ledger.record_actuated(decision)
        m.autoscaler_counter(m.AUTOSCALER_ACTUATIONS, self.job_id,
                             direction).inc()
        for op, p in decision.overrides.items():
            m.autoscaler_parallelism_gauge(self.job_id, op).set(p)
        logger.info("autoscaler rescaled %s: %s %s -> %s", self.job_id,
                    decision.operator_id, decision.from_parallelism,
                    decision.to_parallelism)
