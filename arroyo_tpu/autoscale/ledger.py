"""Decision ledger: a bounded ring of every autoscaler evaluation.

The ledger is the autoscaler's flight recorder — inputs digest,
recommendation, and the action taken or the veto that blocked it, for
every evaluation — served at ``GET /v1/jobs/{id}/autoscaler`` and
rendered by the console.  Bounded so a long-running job cannot grow it
without limit (same rationale as the trace-span ring in obs/tracing.py).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from .policy import Decision

DEFAULT_CAP = 512


class DecisionLedger:
    def __init__(self, cap: int = DEFAULT_CAP):
        self._ring: deque = deque(maxlen=cap)
        # actuations are rare and the interesting part of the record:
        # keep them separately so a busy loop's holds can never push
        # them out of the REST payload
        self._actuated: deque = deque(maxlen=64)
        self.evaluations = 0
        self.actuations = 0
        self.vetoes = 0

    def append(self, decision: Decision) -> None:
        self._ring.append(decision)
        self.evaluations += 1
        if decision.action == "veto":
            self.vetoes += 1

    def record_actuated(self, decision: Decision) -> None:
        decision.actuated = True
        self.actuations += 1
        self._actuated.append(decision)

    def actuated_json(self) -> List[Dict[str, Any]]:
        return [d.to_json() for d in self._actuated]

    def last(self) -> Optional[Decision]:
        return self._ring[-1] if self._ring else None

    def decisions(self) -> List[Decision]:
        return list(self._ring)

    def to_json(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        items = list(self._ring)
        if limit is not None:
            items = items[-limit:]
        return [d.to_json() for d in items]
