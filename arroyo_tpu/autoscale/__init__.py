"""Closed-loop autoscaler: rollup-driven elastic rescaling.

The control loop (supervisor.JobAutoscaler) periodically reads a job's
per-operator flight-recorder rollups from the controller, runs them
through a pluggable policy (policy.BacklogDrainPolicy — backlog-drain
parallelism model with hysteresis, per-direction cooldowns, per-operator
bounds and a global slot budget), records every evaluation in a bounded
decision ledger (ledger.DecisionLedger, served at
``GET /v1/jobs/{id}/autoscaler``), and actuates via the controller's
existing checkpoint-stop / key-range-reshard / restart rescale path.

``ARROYO_AUTOSCALE=0`` disables the subsystem globally; sim.py is the
deterministic simulator the tests and the smoke gate drive.
"""

from .ledger import DecisionLedger
from .policy import BacklogDrainPolicy, Decision, EvalInput, PolicyConfig
from .supervisor import JobAutoscaler, upstream_map

__all__ = [
    "BacklogDrainPolicy",
    "Decision",
    "DecisionLedger",
    "EvalInput",
    "JobAutoscaler",
    "PolicyConfig",
    "upstream_map",
]
