"""Deterministic policy simulator: replay synthetic rollup traces
through BacklogDrainPolicy with a fake clock.

No workers, no asyncio, no wall time — a tiny fluid model of a streaming
DAG produces exactly the rollup dicts ``controller.job_rollup`` serves
(backpressure from downstream utilization, watermark lag from
accumulated backlog, records/s from processed flow), and the policy is
evaluated against them in a closed loop: when the simulator applies a
recommendation, capacity changes and the signals respond on the next
tick.  Convergence and anti-flapping are therefore assertable in
milliseconds of test time (see tests/test_autoscale.py), and
``tools/smoke.sh`` runs a ramp trace through it as the CI gate.

Load traces: `ramp`, `spike`, `drain`, `square_wave`, `constant` — each
returns offered records/s as a function of sim time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .ledger import DecisionLedger
from .policy import BacklogDrainPolicy, Decision, EvalInput

LoadFn = Callable[[float], float]


# -- load traces -------------------------------------------------------------


def constant(rate: float) -> LoadFn:
    return lambda t: rate


def ramp(start: float, end: float, over_secs: float) -> LoadFn:
    """Linear ramp from start to end over ``over_secs``, then flat."""
    def f(t: float) -> float:
        if t >= over_secs:
            return end
        return start + (end - start) * (t / over_secs)
    return f


def spike(base: float, peak: float, at: float, width: float) -> LoadFn:
    """Flat base load with a rectangular burst [at, at+width)."""
    return lambda t: peak if at <= t < at + width else base

def drain(high: float, low: float, until: float) -> LoadFn:
    """High load until ``until``, then a drop to ``low`` — the
    scale-down-after-drain scenario."""
    return lambda t: high if t < until else low


def square_wave(low: float, high: float, period: float) -> LoadFn:
    """50% duty square wave — the anti-flapping scenario."""
    return lambda t: high if (t % period) < period / 2 else low


# -- fluid DAG model ---------------------------------------------------------


@dataclass
class SimOperator:
    op_id: str
    capacity_per_subtask: float       # records/s one subtask can process
    parallelism: int = 1
    backlog: float = 0.0              # queued records not yet processed


class SimCluster:
    """Fluid-flow model of a linear-or-DAG pipeline.

    ``upstream`` maps operator -> producers (same shape the live
    supervisor derives from the logical DAG); sources are operators with
    no producers and receive the offered load."""

    def __init__(self, ops: List[SimOperator],
                 upstream: Optional[Dict[str, List[str]]] = None):
        self.ops = {o.op_id: o for o in ops}
        self.order = [o.op_id for o in ops]  # topological
        self.upstream = upstream if upstream is not None else {
            self.order[i]: ([self.order[i - 1]] if i else [])
            for i in range(len(self.order))}
        self.downstream: Dict[str, List[str]] = {o: [] for o in self.order}
        for op, ups in self.upstream.items():
            for u in ups:
                self.downstream[u].append(op)
        self._input: Dict[str, float] = {o: 0.0 for o in self.order}
        self._processed: Dict[str, float] = dict(self._input)

    @property
    def parallelism(self) -> Dict[str, int]:
        return {op_id: o.parallelism for op_id, o in self.ops.items()}

    def apply(self, overrides: Dict[str, int]) -> None:
        for op_id, p in overrides.items():
            self.ops[op_id].parallelism = max(1, int(p))

    def advance(self, offered: float, dt: float) -> None:
        """One fluid step: flow the offered load through the DAG,
        accumulating backlog wherever input exceeds capacity."""
        for op_id in self.order:
            o = self.ops[op_id]
            ups = self.upstream[op_id]
            inp = (offered if not ups
                   else sum(self._processed[u] for u in ups))
            cap = o.capacity_per_subtask * o.parallelism
            processed = min(inp, cap)
            if inp > cap:
                o.backlog += (inp - cap) * dt
            elif o.backlog > 0:
                drained = min(o.backlog, (cap - inp) * dt)
                o.backlog -= drained
                processed = min(cap, inp + drained / max(dt, 1e-9))
            self._input[op_id] = inp
            self._processed[op_id] = processed

    def _util(self, op_id: str) -> float:
        o = self.ops[op_id]
        cap = o.capacity_per_subtask * o.parallelism
        return self._input[op_id] / max(cap, 1e-9)

    def _throttled_util(self, op_id: str) -> float:
        """Utilization after upstream throttling: a producer blocked by
        ONE overloaded consumer slows its sends to ALL consumers, so the
        fast siblings starve.  This is what separates the bottleneck
        (still saturated) from the starving sibling (idle, waiting)."""
        o = self.ops[op_id]
        ups = self.upstream[op_id]
        if not ups:
            return self._util(op_id)
        inp = 0.0
        for u in ups:
            throttle = min((1.0 / max(self._util(d), 1.0)
                            for d in self.downstream[u]), default=1.0)
            inp += self._processed[u] * throttle
        cap = o.capacity_per_subtask * o.parallelism
        return inp / max(cap, 1e-9)

    def rollups(self, age_secs: float = 0.0) -> List[Dict[str, Any]]:
        """The controller.job_rollup() shape for the current instant."""
        out = []
        for op_id in self.order:
            o = self.ops[op_id]
            # tx-queue backpressure: my queues fill when a downstream
            # operator runs past its capacity
            bp = max((min(max(2.0 * (self._util(d) - 1.0), 0.0), 1.0)
                      for d in self.downstream[op_id]), default=0.0)
            lag = o.backlog / max(self._input[op_id], 1e-9)
            # queue wait: an operator whose throttled input runs far
            # under its capacity sits waiting on its input queue — but
            # only while an upstream is actually being throttled (an
            # idle pipeline waits too; that carries no signal and the
            # policy only uses this to discount upstream backpressure)
            starving = (self._throttled_util(op_id) < 0.3
                        and any(self._util(d) > 1.0
                                for u in self.upstream[op_id]
                                for d in self.downstream[u]))
            out.append({
                "operator_id": op_id, "workers": 1,
                "backpressure": round(bp, 4),
                "watermark_lag": round(lag, 4),
                "queue_wait": 1.0 if starving else 0.0,
                "records_per_sec": round(self._processed[op_id], 2),
                "age_secs": age_secs,
            })
        return out


# -- the simulator -----------------------------------------------------------


@dataclass
class SimResult:
    decisions: List[Decision] = field(default_factory=list)
    # (t, total parallelism, bottleneck lag) samples per tick
    timeline: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def actuations(self) -> List[Decision]:
        return [d for d in self.decisions if d.overrides and d.actuated]

    def direction_changes(self) -> int:
        dirs = [d.action for d in self.actuations]
        return sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)


class PolicySimulator:
    """Closed loop: cluster -> rollups -> policy -> (apply) -> cluster.

    ``age_fn(t)`` lets tests inject stale snapshots (returns the rollup
    age at sim time t); default is a live scrape (age 0)."""

    def __init__(self, policy: BacklogDrainPolicy, cluster: SimCluster,
                 age_fn: Optional[Callable[[float], float]] = None):
        self.policy = policy
        self.cluster = cluster
        self.age_fn = age_fn or (lambda t: 0.0)
        self.ledger = DecisionLedger()
        self.t = 0.0

    def step(self, load: LoadFn) -> Decision:
        dt = self.policy.cfg.interval_secs
        self.cluster.advance(load(self.t), dt)
        self.t += dt
        decision = self.policy.evaluate(EvalInput(
            now=self.t,
            rollups=self.cluster.rollups(age_secs=self.age_fn(self.t)),
            parallelism=self.cluster.parallelism,
            upstream=self.cluster.upstream))
        self.ledger.append(decision)
        if decision.overrides:
            # in sim, actuation always succeeds and is instantaneous
            self.cluster.apply(decision.overrides)
            self.ledger.record_actuated(decision)
        return decision

    def run(self, load: LoadFn, steps: int) -> SimResult:
        res = SimResult()
        for _ in range(steps):
            d = self.step(load)
            res.decisions.append(d)
            res.timeline.append({
                "t": round(self.t, 2),
                "parallelism": dict(self.cluster.parallelism),
                "max_lag": round(max(o.backlog / max(self.cluster._input[i],
                                                     1e-9)
                                     for i, o in self.cluster.ops.items()),
                                 3),
                "action": d.action,
            })
        return res


def replay(policy: BacklogDrainPolicy,
           trace: List[List[Dict[str, Any]]],
           parallelism: Dict[str, int],
           upstream: Dict[str, List[str]]) -> List[Decision]:
    """Open-loop replay of a raw rollup trace (one rollup list per
    evaluation) — for feeding recorded production rollups back through a
    candidate policy.  Parallelism follows the policy's own overrides."""
    par = dict(parallelism)
    out = []
    t = 0.0
    for rollups in trace:
        t += policy.cfg.interval_secs
        d = policy.evaluate(EvalInput(now=t, rollups=rollups,
                                      parallelism=dict(par),
                                      upstream=upstream))
        if d.overrides:
            par.update(d.overrides)
        out.append(d)
    return out
