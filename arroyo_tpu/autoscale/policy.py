"""Backlog-drain autoscaling policy.

The policy is a pure decision function over the controller's per-operator
job rollups (``controller.job_rollup``): every evaluation receives the
current rollup list, the DAG's parallelism map and upstream topology, and
an externally supplied clock — it never reads wall time itself, which is
what makes the deterministic simulator (``autoscale/sim.py``) possible.

Model (PanJoin-style adaptive provisioning, arxiv 1811.05065):

* An operator's *pressure* is the worse of (a) the backpressure its
  upstream operators report on the queues feeding it (their tx queues
  full == this operator can't keep up) and (b) its watermark-lag score —
  lag mapped linearly from ``lag_warn_secs`` (0) to ``lag_high_secs``
  (1), counted toward scale-up only while the lag trend is not falling.
* Scale-up: the single worst operator whose pressure has stayed at or
  above ``high_water`` for ``up_sustain`` consecutive evaluations — the
  bottleneck, never the whole DAG.  Required parallelism comes from the
  backlog-drain estimate ``p * (1 + bp) * (1 + lag/target_drain_secs)``
  (offered/processed ratio approximated by the backpressure ratio, plus
  catch-up headroom to drain the observed lag within the target), capped
  at ``max_step_factor`` growth per action and the per-operator/global
  bounds.
* Scale-down: only when every operator is calm (pressure at or below
  ``low_water`` for ``down_sustain`` evaluations, none above
  ``high_water``), the backlog has drained (lag <= ``drain_lag_secs``),
  and the down cooldown has expired; one subtask step at a time, most
  over-provisioned operator first.
* Hysteresis is the [low_water, high_water] band where nothing happens;
  per-direction cooldowns after any actuation stop flapping on load
  square waves.
* Any recommendation is vetoed (and recorded) when the rollup is stale —
  older than one evaluation interval — or when the global worker-slot
  budget would be exceeded.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# decision.action values
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
HOLD = "hold"
VETO = "veto"

# veto reasons (ledger + prometheus label values)
VETO_STALE = "stale_rollup"
VETO_COOLDOWN = "cooldown"
VETO_BUDGET = "slot_budget"
VETO_ACTUATION_FAILED = "actuation_failed"


@dataclass
class PolicyConfig:
    """Knobs for BacklogDrainPolicy (all JSON-serializable; the REST
    ``PUT .../autoscaler`` endpoint merges partial updates into this)."""

    interval_secs: float = 15.0      # evaluation cadence AND staleness bar
    high_water: float = 0.7          # pressure >= this -> bottleneck
    low_water: float = 0.2           # pressure <= this -> calm
    up_sustain: int = 2              # consecutive hot evals before up
    down_sustain: int = 4            # consecutive calm evals before down
    up_cooldown_secs: float = 60.0   # min gap after any action before up
    down_cooldown_secs: float = 300.0
    lag_warn_secs: float = 10.0      # watermark lag mapping to pressure 0
    lag_high_secs: float = 60.0      # ... and to pressure 1
    # starvation discriminator: an upstream's backpressure is one scalar
    # across all its out-edges, so under fan-out it would indict every
    # consumer — but a consumer whose avg queue wait exceeds this is
    # starving for input (the bottleneck is a sibling), not slow itself
    starve_wait_secs: float = 0.5
    drain_lag_secs: float = 5.0      # down only when lag drained below
    target_drain_secs: float = 60.0  # catch-up horizon in the drain model
    max_step_factor: float = 2.0     # at most double per scale-up
    min_parallelism: int = 1
    max_parallelism: int = 16        # default per-operator ceiling
    slot_budget: Optional[int] = None  # global sum-of-parallelism cap
    # per-operator {"min": int, "max": int} overrides; an operator whose
    # max equals its current parallelism is pinned
    per_op: Dict[str, Dict[str, int]] = field(default_factory=dict)

    def bounds(self, op_id: str) -> Tuple[int, int]:
        o = self.per_op.get(op_id, {})
        return (int(o.get("min", self.min_parallelism)),
                int(o.get("max", self.max_parallelism)))

    def to_json(self) -> Dict[str, Any]:
        return asdict(self)

    def merged(self, updates: Dict[str, Any]) -> "PolicyConfig":
        """New config with ``updates`` applied; unknown keys raise and
        values are coerced to the knob's type — a mistyped REST update
        must fail the PUT, not poison every later evaluation."""
        cur = self.to_json()
        for k, v in updates.items():
            if k not in cur:
                raise KeyError(f"unknown policy knob {k!r}")
            if k in ("up_sustain", "down_sustain", "min_parallelism",
                     "max_parallelism"):
                v = int(v)
            elif k == "slot_budget":
                v = None if v is None else int(v)
            elif k == "per_op":
                if not isinstance(v, dict) or not all(
                        isinstance(b, dict)
                        and set(b) <= {"min", "max"} and b
                        for b in v.values()):
                    raise ValueError(
                        "per_op must be {op_id: {'min':int,'max':int}}")
                v = {op: {kk: int(vv) for kk, vv in b.items()}
                     for op, b in v.items()}
            else:
                v = float(v)
                if not math.isfinite(v):
                    raise ValueError(f"{k} must be finite")
            cur[k] = v
        out = PolicyConfig(**cur)
        out._check_ranges()
        return out

    def _check_ranges(self) -> None:
        """Reject configs that would break the loop itself — a zero
        interval busy-spins the controller, an inverted hysteresis band
        or step factor quietly disables one direction forever."""
        if self.interval_secs <= 0:
            raise ValueError("interval_secs must be > 0")
        if not 0 <= self.low_water <= self.high_water <= 1.0:
            # pressure is clamped to [0,1]: a band above 1 would quietly
            # disable scale-up AND the never-shrink-under-load guard
            raise ValueError("need 0 <= low_water <= high_water <= 1")
        if self.up_sustain < 1 or self.down_sustain < 1:
            raise ValueError("sustain counts must be >= 1")
        if self.up_cooldown_secs < 0 or self.down_cooldown_secs < 0:
            raise ValueError("cooldowns must be >= 0")
        if not 0 <= self.lag_warn_secs < self.lag_high_secs:
            raise ValueError("need 0 <= lag_warn_secs < lag_high_secs")
        if self.drain_lag_secs < 0 or self.starve_wait_secs < 0:
            raise ValueError("drain_lag/starve_wait must be >= 0")
        if self.target_drain_secs <= 0:
            raise ValueError("target_drain_secs must be > 0")
        if self.max_step_factor <= 1:
            raise ValueError("max_step_factor must be > 1")
        if self.min_parallelism < 1 \
                or self.max_parallelism < self.min_parallelism:
            raise ValueError("need 1 <= min_parallelism <= max_parallelism")
        if self.slot_budget is not None and self.slot_budget < 1:
            raise ValueError("slot_budget must be >= 1")
        for op, b in self.per_op.items():
            lo, hi = self.bounds(op)
            if not 1 <= lo <= hi:
                raise ValueError(f"per_op[{op!r}]: need 1 <= min <= max")


@dataclass
class EvalInput:
    """One evaluation's inputs — everything the policy may look at."""

    now: float                          # injected clock (monotonic-like)
    rollups: List[Dict[str, Any]]       # controller.job_rollup() shape
    parallelism: Dict[str, int]         # operator_id -> current subtasks
    upstream: Dict[str, List[str]]      # operator_id -> producers
    # plan-level StreamNode.max_parallelism pins (only pinned ops
    # present): rescale_job would silently clamp past these, so a
    # recommendation beyond them is a disruptive full-job no-op
    hard_max: Dict[str, int] = field(default_factory=dict)
    # latency SLO burn rate (obs/latency.py SloEvaluator, 0..1): the
    # fraction of recent evaluations out of budget.  Folded into sink
    # pressure so a latency-violating pipeline scales up even when
    # throughput signals (backpressure, watermark lag) look calm.
    slo_burn: float = 0.0


@dataclass
class Decision:
    """One ledger entry: the inputs digest, the recommendation, and the
    action taken or the veto that blocked it."""

    t: float
    action: str                          # scale_up|scale_down|hold|veto
    reason: str = ""                     # trigger or veto reason
    operator_id: Optional[str] = None
    from_parallelism: Optional[int] = None
    to_parallelism: Optional[int] = None
    overrides: Optional[Dict[str, int]] = None  # set when actionable
    inputs: Dict[str, Dict[str, float]] = field(default_factory=dict)
    rollup_age_secs: Optional[float] = None
    actuated: bool = False
    error: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return {k: v for k, v in asdict(self).items() if v not in
                (None, {}, "")} | {"t": round(self.t, 3),
                                   "action": self.action}


class BacklogDrainPolicy:
    """Stateful wrapper around the pure pressure/step math: keeps the
    per-operator sustain counters, the previous lag sample (for the
    trend check) and the last-actuation timestamps between evaluations."""

    def __init__(self, cfg: Optional[PolicyConfig] = None):
        self.cfg = cfg or PolicyConfig()
        self._hot_streak: Dict[str, int] = {}
        self._calm_streak: Dict[str, int] = {}
        self._prev_lag: Dict[str, float] = {}
        self._last_action_t: Optional[float] = None
        self._last_action: Optional[str] = None

    # -- signal extraction -------------------------------------------------

    @staticmethod
    def _lag_of(roll: Dict[str, Any]) -> float:
        lag = roll.get("watermark_lag")
        if lag is None:
            lag = roll.get("event_time_lag", 0.0)
        return float(lag or 0.0)

    def _lag_score(self, lag: float) -> float:
        cfg = self.cfg
        span = max(cfg.lag_high_secs - cfg.lag_warn_secs, 1e-9)
        return min(max((lag - cfg.lag_warn_secs) / span, 0.0), 1.0)

    def signals(self, inp: EvalInput) -> Dict[str, Dict[str, float]]:
        """Per-operator {pressure, bp_in, lag, rate, parallelism} — the
        inputs digest the ledger records for every evaluation."""
        by_op = {r.get("operator_id"): r for r in inp.rollups}
        out: Dict[str, Dict[str, float]] = {}
        for op, p in inp.parallelism.items():
            known = op in by_op
            roll = by_op.get(op, {})
            bp_in = max((float(by_op.get(u, {}).get("backpressure") or 0.0)
                         for u in inp.upstream.get(op, [])), default=0.0)
            # upstream backpressure is one scalar across all the
            # upstream's out-edges, and watermark lag propagates to
            # every branch behind a stalled shared upstream: a consumer
            # that spends its time WAITING for input is starving behind
            # a slow sibling, not the bottleneck — NEITHER shared
            # signal may indict it for scale-up (its calm_pressure
            # keeps the lag, conservatively blocking scale-down too)
            qw = float(roll.get("queue_wait") or 0.0)
            starving = qw > self.cfg.starve_wait_secs
            if starving:
                bp_in = 0.0
            lag = self._lag_of(roll)
            score = self._lag_score(lag)
            rising = lag >= self._prev_lag.get(op, 0.0) - 0.5
            # SLO burn lands as pressure on the operators that REPORT
            # e2e latency (the sinks): the end of the critical path is
            # where the whole chain's latency debt is observable, and
            # pressuring it walks the scale-up back through its
            # upstreams on later ticks if the sink wasn't the cause
            slo_score = (min(max(inp.slo_burn, 0.0), 1.0)
                         if "e2e_latency.p99_ms" in roll else 0.0)
            out[op] = {
                "pressure": (0.0 if starving
                             else max(bp_in, slo_score,
                                      score if rising else 0.0)),
                # full (trend-free) pressure gates scale-down: a falling
                # but still-large lag must keep the operator hot — and a
                # burning SLO blocks scale-down outright
                "calm_pressure": max(bp_in, score, slo_score),
                # absent from the rollup != calm: a heartbeat-dead
                # worker's hot operator simply vanishes from job_rollup
                # while livelier siblings keep the rollup fresh —
                # unknown ops must never qualify for scale-down
                "known": 1.0 if known else 0.0,
                "bp_in": bp_in,
                "lag": lag,
                "queue_wait": qw,
                "rate": float(roll.get("records_per_sec") or 0.0),
                "parallelism": p,
            }
        return out

    # -- evaluation --------------------------------------------------------

    def evaluate(self, inp: EvalInput) -> Decision:
        cfg = self.cfg
        if not inp.rollups:
            return Decision(t=inp.now, action=HOLD, reason="no_rollup")
        sig = self.signals(inp)
        for op, s in sig.items():
            self._prev_lag[op] = s["lag"]
            if s["pressure"] >= cfg.high_water:
                self._hot_streak[op] = self._hot_streak.get(op, 0) + 1
            else:
                self._hot_streak[op] = 0
            if s["calm_pressure"] <= cfg.low_water and s["known"]:
                self._calm_streak[op] = self._calm_streak.get(op, 0) + 1
            else:
                self._calm_streak[op] = 0

        ages = [r.get("age_secs") for r in inp.rollups]
        age = max((a for a in ages if a is not None), default=None)
        stale = age is None or age > cfg.interval_secs
        base = dict(t=inp.now, inputs=sig, rollup_age_secs=age)

        up = self._scale_up_candidate(inp, sig)
        if up is not None:
            return self._gate(up, stale, base)
        down = self._scale_down_candidate(inp, sig)
        if down is not None:
            return self._gate(down, stale, base)
        return Decision(action=HOLD, reason="steady", **base)

    def _gate(self, d: Decision, stale: bool,
              base: Dict[str, Any]) -> Decision:
        """Apply the veto gates common to both directions, in order:
        stale inputs first (an actuation on old data is never safe),
        then the per-direction cooldown."""
        cfg = self.cfg
        for k, v in base.items():
            setattr(d, k, v)
        if stale:
            d.action, d.reason = VETO, VETO_STALE
            d.overrides = None
            return d
        if d.action == VETO:
            # already vetoed by the candidate itself (slot budget): no
            # cooldown applies and NOTHING actuated — recording an
            # action time here would let a phantom action block real
            # scale-ups/downs for a full cooldown
            return d
        cooldown = (cfg.up_cooldown_secs if d.action == SCALE_UP
                    else cfg.down_cooldown_secs)
        if (self._last_action_t is not None
                and d.t - self._last_action_t < cooldown):
            d.action, d.reason = VETO, VETO_COOLDOWN
            d.overrides = None
            return d
        self._last_action_t = d.t
        self._last_action = d.action
        return d

    def _scale_up_candidate(self, inp: EvalInput,
                            sig: Dict[str, Dict[str, float]]
                            ) -> Optional[Decision]:
        cfg = self.cfg
        hot = [(s["pressure"], op) for op, s in sig.items()
               if self._hot_streak.get(op, 0) >= cfg.up_sustain]
        budget_hit = None
        total = sum(inp.parallelism.values())
        # worst first; op id tie-break keeps the choice deterministic
        for pressure, op in sorted(hot, key=lambda x: (-x[0], x[1])):
            p = inp.parallelism[op]
            lo, hi = cfg.bounds(op)
            hi = min(hi, inp.hard_max.get(op, hi))
            if p >= hi:
                continue  # pinned or already at its ceiling
            s = sig[op]
            growth = min((1.0 + s["bp_in"])
                         * (1.0 + min(s["lag"], cfg.lag_high_secs)
                            / max(cfg.target_drain_secs, 1e-9)),
                         cfg.max_step_factor)
            desired = max(p + 1, math.ceil(p * growth))
            desired = min(desired, hi)
            if cfg.slot_budget is not None:
                desired = min(desired, cfg.slot_budget - (total - p))
                if desired <= p:
                    budget_hit = op
                    continue
            return Decision(
                t=inp.now, action=SCALE_UP,
                reason=f"pressure {pressure:.2f} >= {cfg.high_water} "
                       f"for {self._hot_streak[op]} evals",
                operator_id=op, from_parallelism=p, to_parallelism=desired,
                overrides={op: desired})
        if budget_hit is not None:
            return Decision(
                t=inp.now, action=VETO, reason=VETO_BUDGET,
                operator_id=budget_hit,
                from_parallelism=inp.parallelism[budget_hit])
        return None

    def _scale_down_candidate(self, inp: EvalInput,
                              sig: Dict[str, Dict[str, float]]
                              ) -> Optional[Decision]:
        cfg = self.cfg
        if any(s["calm_pressure"] >= cfg.high_water for s in sig.values()):
            return None  # something is still hot; never shrink under load
        if any(not s["known"] for s in sig.values()):
            # partial rollup (a worker stopped reporting): the invisible
            # operator may be the hot one — never shrink ANY operator
            # while the job is partially blind
            return None
        calm = []
        for op, s in sig.items():
            p = inp.parallelism[op]
            lo, _hi = cfg.bounds(op)
            if (p > lo
                    and self._calm_streak.get(op, 0) >= cfg.down_sustain
                    and s["lag"] <= cfg.drain_lag_secs):
                calm.append((s["calm_pressure"], op))
        # most over-provisioned (least pressure) first, one step at a time
        for pressure, op in sorted(calm, key=lambda x: (x[0], x[1])):
            p = inp.parallelism[op]
            lo, _hi = cfg.bounds(op)
            desired = max(lo, p - 1)
            if desired >= p:
                continue
            return Decision(
                t=inp.now, action=SCALE_DOWN,
                reason=f"pressure {pressure:.2f} <= {cfg.low_water} "
                       f"for {self._calm_streak[op]} evals, lag drained",
                operator_id=op, from_parallelism=p, to_parallelism=desired,
                overrides={op: desired})
        return None
