"""arroyo_tpu — a TPU-native distributed stream processing framework.

SQL-defined stateful pipelines with event-time windows, watermarks,
stream-stream joins, exactly-once checkpointing and a controller state
machine (the capability set of the Arroyo reference at /root/reference),
re-designed for TPU: columnar batches, jit-compiled operator kernels, keyed
window state in HBM, shuffles as XLA collectives over a device mesh."""

__version__ = "0.1.0"

# 64-bit integers are load-bearing in a streaming engine: event-time
# micros and Nexmark ids exceed int32, and with x64 disabled JAX silently
# canonicalizes int64 jit inputs to int32 (wraparound corruption, not an
# error).  Enable x64 up front; device kernels pin f32/i32 explicitly so
# MXU-path compute stays 32-bit (weak-type promotion preserves them).
# An embedding host that needs x32 semantics for its own JAX code can set
# JAX_ENABLE_X64=0 explicitly — we honor it and the engine's host paths
# keep 64-bit values in numpy, at reduced in-jit range.
import os as _os

if _os.environ.get("JAX_ENABLE_X64", "").lower() not in ("0", "false"):
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

from .types import (  # noqa: F401
    Batch,
    CheckpointBarrier,
    Message,
    TaskInfo,
    Watermark,
    range_for_server,
    server_for_hash,
)
from .graph.logical import (  # noqa: F401
    AggKind,
    AggSpec,
    Program,
    SessionWindow,
    SlidingWindow,
    Stream,
    TumblingWindow,
)
