"""arroyo_tpu — a TPU-native distributed stream processing framework.

SQL-defined stateful pipelines with event-time windows, watermarks,
stream-stream joins, exactly-once checkpointing and a controller state
machine (the capability set of the Arroyo reference at /root/reference),
re-designed for TPU: columnar batches, jit-compiled operator kernels, keyed
window state in HBM, shuffles as XLA collectives over a device mesh."""

__version__ = "0.1.0"

from .types import (  # noqa: F401
    Batch,
    CheckpointBarrier,
    Message,
    TaskInfo,
    Watermark,
    range_for_server,
    server_for_hash,
)
from .graph.logical import (  # noqa: F401
    AggKind,
    AggSpec,
    Program,
    SessionWindow,
    SlidingWindow,
    Stream,
    TumblingWindow,
)
