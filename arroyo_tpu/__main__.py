"""``python -m arroyo_tpu`` — single entry point for every role,
mirroring the reference's one-binary UX (docker/entrypoint role
selector):

  python -m arroyo_tpu run query.sql     # execute SQL locally, print rows
  python -m arroyo_tpu api               # REST API + controller
  python -m arroyo_tpu controller        # standalone controller
  python -m arroyo_tpu worker            # worker (CONTROLLER_ADDR, JOB_ID)
  python -m arroyo_tpu node              # node daemon
"""

from __future__ import annotations

import sys


def _run(path_or_dash: str, checkpoint_url: str | None) -> None:
    import json

    from .connectors.memory import sink_output
    from .engine.engine import LocalRunner
    from .formats import batch_to_rows
    from .sql import plan_sql

    sql = (sys.stdin.read() if path_or_dash == "-"
           else open(path_or_dash).read())
    prog = plan_sql(sql)
    runner = (LocalRunner(prog, checkpoint_url=checkpoint_url)
              if checkpoint_url else LocalRunner(prog))
    runner.run()
    # bare SELECTs land in the "results" memory sink — print them the
    # way `arroyo run` streams results to stdout
    for batch in sink_output("results"):
        for row in batch_to_rows(batch):
            print(json.dumps(row, default=str))


def main(argv: list[str]) -> int:
    role = argv[0] if argv else "api"
    if role == "run":
        usage = "usage: python -m arroyo_tpu run <query.sql | -> " \
                "[--checkpoint-url URL]"
        ckpt = None
        args = argv[1:]
        if "--checkpoint-url" in args:
            i = args.index("--checkpoint-url")
            if i + 1 >= len(args):
                print(usage, file=sys.stderr)
                return 2
            ckpt = args[i + 1]
            del args[i:i + 2]
        if len(args) != 1:
            print(usage, file=sys.stderr)
            return 2
        _run(args[0], ckpt)
        return 0
    if role == "api":
        from .api.rest import main as api_main

        api_main()
        return 0
    if role == "controller":
        from .controller.controller import main as ctrl_main

        ctrl_main()
        return 0
    if role == "worker":
        from .worker.server import main as worker_main

        worker_main()
        return 0
    if role == "node":
        from .node.daemon import main as node_main

        node_main()
        return 0
    print(f"unknown role {role!r}; choose from run/api/controller/"
          "worker/node", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
