"""StateStore — the typed state facade operators use
(``StateStore<S: BackingStore>``, /root/reference/arroyo-state/src/lib.rs:162-343).

Tables are registered by :class:`TableDescriptor`; the store owns live table
objects plus the backing store, and drives checkpoint (snapshot all tables at
a barrier) and restore (rebuild caches from the backing store filtered by the
task's key range)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..types import SubtaskCheckpointMetadata, TaskInfo
from .backend import BackingStore, InMemoryBackend, ParquetBackend, TableSnapshot
from .tables import (
    TABLE_CLASSES,
    BatchBuffer,
    DeviceTable,
    GlobalKeyedState,
    KeyTimeMultiMap,
    KeyedState,
    TableDescriptor,
    TableType,
    TimeKeyMap,
    WriteBehavior,
)


class StateStore:
    def __init__(self, task_info: TaskInfo, backend: BackingStore,
                 restore_epoch: Optional[int] = None):
        self.task_info = task_info
        self.backend = backend
        self.restore_epoch = restore_epoch
        # arroyosan runtime sanitizer (analysis/sanitizer.py), installed
        # by the engine when ARROYO_SANITIZE is armed: checkpoint() then
        # verifies no table mutates between snapshot and persistence
        self.sanitizer: Optional[Any] = None
        self.descriptors: Dict[str, TableDescriptor] = {}
        self.tables: Dict[str, Any] = {}
        self._restored: Optional[Dict[str, TableSnapshot]] = None
        self._pending_deletes: Dict[str, List[Any]] = {}

    # -- constructors ------------------------------------------------------

    @staticmethod
    def new_in_memory(task_info: TaskInfo,
                      restore_epoch: Optional[int] = None) -> "StateStore":
        return StateStore(task_info, InMemoryBackend(), restore_epoch)

    @staticmethod
    def from_checkpoint_url(task_info: TaskInfo, url: str,
                            restore_epoch: Optional[int] = None) -> "StateStore":
        return StateStore(task_info, ParquetBackend.for_url(url), restore_epoch)

    # -- registration ------------------------------------------------------

    def register(self, descriptor: TableDescriptor) -> Any:
        name = descriptor.name
        if name in self.tables:
            return self.tables[name]
        self.descriptors[name] = descriptor
        if descriptor.table_type == TableType.DEVICE:
            raise ValueError("register device tables via register_device()")
        table = TABLE_CLASSES[descriptor.table_type]()
        self.tables[name] = table
        self._maybe_restore(name, table)
        return table

    def register_device(self, descriptor: TableDescriptor,
                        device_table: DeviceTable) -> Optional[Dict[str, Any]]:
        """Register device-resident state; returns restored arrays (if any)
        for the operator to stage back into HBM."""
        self.descriptors[descriptor.name] = descriptor
        self.tables[descriptor.name] = device_table
        snap = self._restored_snapshot(descriptor.name)
        if snap is not None and snap.arrays:
            device_table.restore(snap.arrays)
            return snap.arrays
        return None

    # typed getters mirroring the reference's get_*_state API
    def get_global_keyed_state(self, name: str, desc: str = "") -> GlobalKeyedState:
        return self.register(TableDescriptor(name, TableType.GLOBAL, desc))

    def get_time_key_map(self, name: str, desc: str = "",
                         retention_micros: int = 0) -> TimeKeyMap:
        return self.register(TableDescriptor(name, TableType.TIME_KEY_MAP, desc,
                                             retention_micros))

    def get_key_time_multi_map(self, name: str, desc: str = "",
                               retention_micros: int = 0) -> KeyTimeMultiMap:
        return self.register(TableDescriptor(name, TableType.KEY_TIME_MULTI_MAP,
                                             desc, retention_micros))

    def get_keyed_state(self, name: str, desc: str = "") -> KeyedState:
        return self.register(TableDescriptor(name, TableType.KEYED, desc))

    def get_batch_buffer(self, name: str, desc: str = "",
                         retention_micros: int = 0) -> BatchBuffer:
        return self.register(TableDescriptor(name, TableType.BATCH_BUFFER, desc,
                                             retention_micros))

    def get_join_buffer(self, name: str, desc: str = "",
                        retention_micros: int = 0,
                        force_partitioned: bool = False) -> BatchBuffer:
        """Join-side buffer: partition-adaptive sorted-run state
        (state/join_state.py) unless ARROYO_JOIN_STATE=legacy.  Both
        layouts checkpoint as the same BATCH_BUFFER table form, so
        epochs restore across layout changes (and across rescale —
        restore filters the snapshot batch by key range).
        ``force_partitioned`` is for operators whose probe path requires
        sorted runs (the multi-way join)."""
        from .join_state import (
            PartitionedJoinBuffer,
            partitioned_join_enabled,
        )

        want_partitioned = force_partitioned or partitioned_join_enabled()
        existing = self.tables.get(name)
        if existing is not None:
            if want_partitioned and type(existing) is BatchBuffer:
                # Operator.open() pre-registered (and possibly restored
                # into) a flat buffer before on_start could choose the
                # layout: upgrade in place, carrying the restored rows
                table = PartitionedJoinBuffer()
                table.restore_batch(existing.snapshot_batch())
                self.tables[name] = table
                return table
            return existing
        descriptor = TableDescriptor(name, TableType.BATCH_BUFFER, desc,
                                     retention_micros)
        self.descriptors[name] = descriptor
        table = (PartitionedJoinBuffer() if want_partitioned
                 else BatchBuffer())
        self.tables[name] = table
        self._maybe_restore(name, table)
        return table

    def get_session_state(self, name: str, desc: str = "") -> KeyedState:
        """Session-window state: partition-adaptive sorted interval runs
        (state/session_state.py) unless ARROYO_SESSION_STATE=legacy.
        Both layouts checkpoint as the same KEYED ``[(time, key,
        sessions)]`` entries, so epochs restore across layout changes
        and rescale's key-range entry filter applies unchanged."""
        from .session_state import SessionRunState, session_state_enabled

        if not session_state_enabled():
            return self.get_keyed_state(name, desc)
        existing = self.tables.get(name)
        if existing is not None:
            if type(existing) is KeyedState:
                # Operator.open() pre-registered (and possibly restored
                # into) the dict layout before on_start could choose:
                # upgrade in place, carrying the restored entries
                table = SessionRunState()
                table.restore(existing.snapshot())
                self.tables[name] = table
                return table
            return existing
        descriptor = TableDescriptor(name, TableType.KEYED, desc)
        self.descriptors[name] = descriptor
        table = SessionRunState()
        self.tables[name] = table
        self._maybe_restore(name, table)
        return table

    def note_delete(self, table: str, key: Any) -> None:
        """Record a key tombstone for the next checkpoint (DataOperation::DeleteKey)."""
        self._pending_deletes.setdefault(table, []).append(key)

    # -- restore -----------------------------------------------------------

    def _restored_snapshot(self, name: str) -> Optional[TableSnapshot]:
        if self.restore_epoch is None:
            return None
        snaps = self.backend.restore_subtask(self.task_info, self.restore_epoch,
                                             [self.descriptors[name]])
        return snaps.get(name)

    def _maybe_restore(self, name: str, table: Any) -> None:
        snap = self._restored_snapshot(name)
        if snap is None:
            return
        if isinstance(table, BatchBuffer):
            if snap.batch is not None:
                table.restore_batch(snap.batch)
        elif snap.entries:
            table.restore(snap.entries)

    def restore_watermark(self) -> Optional[int]:
        if self.restore_epoch is None:
            return None
        return self.backend.restore_watermark(self.task_info, self.restore_epoch)

    def _update_size_gauges(self, snaps: Dict[str, "TableSnapshot"]) -> None:
        """Per-table key-count gauges, refreshed at each barrier — the
        reference's arroyo_worker_table_size_keys with (operator_id,
        task_id, table_char) labels (arroyo-state/src/metrics.rs)."""
        try:
            from ..obs.metrics import table_size_gauge
        except Exception:  # metrics optional in embedded contexts
            return
        for name, table in self.tables.items():
            try:
                if isinstance(table, DeviceTable):
                    # key count from the canonical snapshot just taken
                    # (meta[0] = occupied key slots)
                    arrays = (snaps.get(name).arrays
                              if snaps.get(name) else None) or {}
                    meta = arrays.get("meta")
                    size = int(meta[0]) if meta is not None else None
                elif hasattr(table, "n_keys"):  # KEY count, not entries
                    size = table.n_keys()
                elif hasattr(table, "__len__"):
                    size = len(table)
                else:
                    size = None
            except (TypeError, IndexError):
                size = None
            if size is not None:
                table_size_gauge(self.task_info, name).set(size)

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self, epoch: int,
                   watermark: Optional[int]) -> SubtaskCheckpointMetadata:
        """Snapshot every registered table and persist (lib.rs:345-347 path).
        Device tables call jax.device_get via their snapshot fn, giving a
        device-consistent snapshot at the barrier."""
        snaps: Dict[str, TableSnapshot] = {}
        for name, table in self.tables.items():
            desc = self.descriptors[name]
            if isinstance(table, DeviceTable):
                snaps[name] = TableSnapshot(desc, arrays=table.snapshot())
            elif isinstance(table, BatchBuffer):
                snaps[name] = TableSnapshot(desc, batch=table.snapshot_batch())
            else:
                snaps[name] = TableSnapshot(
                    desc, entries=table.snapshot(),
                    deletes=self._pending_deletes.get(name))
        self._pending_deletes.clear()
        self._update_size_gauges(snaps)
        san = self.sanitizer
        fp = (san.checkpoint_begin(self.task_info.task_id, self.tables)
              if san is not None else None)
        meta = self.backend.write_subtask_checkpoint(
            self.task_info, epoch, snaps, watermark)
        if san is not None:
            # the epoch on disk must reflect exactly the snapshot taken
            # above: any table mutated while persisting is a torn epoch
            san.checkpoint_end(self.task_info.task_id, self.tables, fp)
        # Tables with CommitWrites behavior surface their snapshot to the
        # controller so it can drive the second commit phase
        # (arroyo-controller/src/job_controller/checkpointer.rs:83-110).
        committing = {
            name: {k: v for _ts, k, v in (snap.entries or [])}
            for name, snap in snaps.items()
            if self.descriptors[name].write_behavior == WriteBehavior.COMMIT_WRITES
            and snap.entries
        }
        if committing:
            meta.committing_data = committing
        return meta
