"""Partition-adaptive session-window state (the join-state machinery
generalized; PanJoin's tiered-residency stance, PAPERS.md).

The legacy session path kept each key's ``[(start, end), ...]`` session
list in a :class:`~arroyo_tpu.state.tables.KeyedState` dict and merged
arriving intervals with a Python loop per key (plus a ``sessions.sort()``
per event on the per-event fallback) — the config5 hot loop.  This
module keeps ALL keys' live sessions as hash-partitioned,
**incrementally sorted interval runs**:

* one flat ``(key_hash, start, end)`` run per partition, sorted by
  ``(key, start)`` — partitions route on the LOW hash bits
  (``kh & (P-1)``), orthogonal to the subtask key ranges on the HIGH
  bits, so rescale never re-partitions (the ``state/join_state.py``
  contract);
* an arriving batch's candidate intervals merge in **one vectorized
  interval-union dispatch for all keys at once**
  (:func:`arroyo_tpu.ops.session.union_sorted_intervals`): only the
  touched keys' resident rows join the scan, untouched rows splice back
  positionally — never a full re-sort of resident state;
* the max-session-size clamp keeps the per-key path authoritative: any
  key whose unclamped union span exceeds the clamp is returned to the
  caller, which re-runs the legacy merge for exactly that key — the
  device/host split is counted (``session_device_merge_rows`` /
  ``session_host_merge_rows``), never assumed;
* watermark fires are a **mask-compress**: ``expire()`` splits each
  partition's run at ``end <= watermark`` in O(rows) vector ops instead
  of iterating the key dict;
* **hot partitions** (EWMA row frequency with hysteresis, the join-state
  policy) keep ``(start, end)`` planes staged on a mesh device
  (``parallel/mesh_window.place_session_partition``), so accelerator
  backends run the union scan against resident planes; cold partitions
  stay host numpy.

Checkpoint contract: :class:`SessionRunState` duck-types
:class:`~arroyo_tpu.state.tables.KeyedState` — ``snapshot()`` emits the
same ``[(time, key, sessions)]`` entries and ``restore()`` accepts
them, so the table keeps ``TableType.KEYED`` form on disk: epochs
written by either layout restore into the other, and rescale's
key-range entry filtering (state/backend.py) applies unchanged.

Knobs (see docs/operations.md):
  ARROYO_SESSION_STATE=device|legacy    state layout (default device)
  ARROYO_SESSION_PARTITIONS=16          partitions (power of two)
  ARROYO_SESSION_HOT_PARTITIONS=4       device-staged partition budget
  ARROYO_SESSION_HOT_MIN_ROWS=512       EWMA rows to qualify as hot
  ARROYO_SESSION_DEVICE=auto|on|off     union scan as a device kernel
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import perf

_SESSION_UIDS = itertools.count()


def session_state_enabled() -> bool:
    return os.environ.get("ARROYO_SESSION_STATE", "device") != "legacy"


def session_partitions() -> int:
    p = int(os.environ.get("ARROYO_SESSION_PARTITIONS", 16))
    b = 1
    while b * 2 <= max(p, 1):
        b *= 2
    return b


def _hot_budget() -> int:
    return int(os.environ.get("ARROYO_SESSION_HOT_PARTITIONS", 4))


def _hot_min_rows() -> float:
    return float(os.environ.get("ARROYO_SESSION_HOT_MIN_ROWS", 512))


def _count_merge(dev_rows: int, host_rows: int) -> None:
    """Account merged interval rows to the device/host split (perf
    counters + prometheus mirrors) — the vectorized-merge share is a
    measured number, not an assumption."""
    from ..obs.metrics import session_merge_counter

    if dev_rows:
        perf.count("session_device_merge_rows", dev_rows)
        session_merge_counter("device").inc(dev_rows)
    if host_rows:
        perf.count("session_host_merge_rows", host_rows)
        session_merge_counter("host").inc(host_rows)


class _SessionPartition:
    """One hash partition: a flat session-interval run sorted by
    ``(key, start)`` plus per-row last-update times (the KEYED snapshot
    ``t`` column)."""

    __slots__ = ("kh", "st", "en", "tm", "touches", "dev", "dev_device")

    def __init__(self) -> None:
        self.kh = np.empty(0, dtype=np.uint64)
        self.st = np.empty(0, dtype=np.int64)
        self.en = np.empty(0, dtype=np.int64)
        self.tm = np.empty(0, dtype=np.int64)
        self.touches = 0.0  # EWMA of rows handled per merge
        # staged (start, end) device planes for hot partitions; host
        # arrays stay the checkpoint/fallback mirror
        self.dev: Optional[Any] = None
        self.dev_device: Optional[Any] = None

    @property
    def n(self) -> int:
        return len(self.kh)

    def set_rows(self, kh: np.ndarray, st: np.ndarray, en: np.ndarray,
                 tm: np.ndarray) -> None:
        self.kh, self.st, self.en, self.tm = kh, st, en, tm
        if self.dev is not None:
            self.stage()

    def key_slice(self, kh: int) -> slice:
        k = np.uint64(kh)
        lo = int(np.searchsorted(self.kh, k, side="left"))
        hi = int(np.searchsorted(self.kh, k, side="right"))
        return slice(lo, hi)

    def touched_mask(self, keys_sorted: np.ndarray) -> np.ndarray:
        """Row mask of resident rows whose key is in ``keys_sorted`` —
        one flag-array cumsum over the per-key searchsorted ranges, no
        per-key loop."""
        n = self.n
        if n == 0 or len(keys_sorted) == 0:
            return np.zeros(n, dtype=bool)
        lo = np.searchsorted(self.kh, keys_sorted, side="left")
        hi = np.searchsorted(self.kh, keys_sorted, side="right")
        f = np.zeros(n + 1, dtype=np.int64)
        np.add.at(f, lo, 1)
        np.add.at(f, hi, -1)
        return np.cumsum(f[:-1]) > 0

    def splice(self, keep: np.ndarray, bkh: np.ndarray, bst: np.ndarray,
               ben: np.ndarray, btm: np.ndarray) -> None:
        """Replace this run with (kept resident rows) ∪ (replacement
        rows ``b*``, sorted by (key, start), keys disjoint from the kept
        rows' keys) — one positional merge, no comparison sort of
        resident state."""
        akh = self.kh[keep]
        ast_ = self.st[keep]
        aen = self.en[keep]
        atm = self.tm[keep]
        na, nb = len(akh), len(bkh)
        if nb == 0:
            self.set_rows(akh, ast_, aen, atm)
            return
        # all rows of one key live on one side, so a key-level
        # searchsorted places every replacement row correctly
        ins = np.searchsorted(akh, bkh, side="left")
        bpos = ins + np.arange(nb, dtype=np.int64)
        total = na + nb
        okh = np.empty(total, dtype=np.uint64)
        ost = np.empty(total, dtype=np.int64)
        oen = np.empty(total, dtype=np.int64)
        otm = np.empty(total, dtype=np.int64)
        kmask = np.ones(total, dtype=bool)
        kmask[bpos] = False
        okh[bpos], ost[bpos], oen[bpos], otm[bpos] = bkh, bst, ben, btm
        okh[kmask], ost[kmask], oen[kmask], otm[kmask] = (akh, ast_, aen,
                                                          atm)
        self.set_rows(okh, ost, oen, otm)

    # -- device residency --------------------------------------------------

    def stage(self, device: Any = None) -> None:
        """Stage the ``(start, end)`` interval planes onto this
        partition's mesh device (idempotent; restaged after every
        splice while hot so the planes always mirror the run)."""
        import jax
        import jax.numpy as jnp

        if device is not None:
            self.dev_device = device
        st = jnp.asarray(self.st)
        en = jnp.asarray(self.en)
        if self.dev_device is not None:
            st = jax.device_put(st, self.dev_device)
            en = jax.device_put(en, self.dev_device)
        self.dev = (st, en)
        perf.count("session_state_stages")

    def unstage(self) -> None:
        if self.dev is not None:
            self.dev = None
            perf.count("session_state_unstages")


class SessionRunState:
    """Device-capable session-window state (module docstring).  Duck-
    types :class:`~arroyo_tpu.state.tables.KeyedState` — the per-key
    API (``get``/``insert``/``remove``/``items``) keeps the legacy
    clamp path and checkpoint interchange working against the same
    object that serves the vectorized batch merge."""

    def __init__(self, n_partitions: Optional[int] = None,
                 max_span: Optional[int] = None):
        from ..engine.operators_window import MAX_SESSION_SIZE_MICROS

        self.P = n_partitions or session_partitions()
        self.parts = [_SessionPartition() for _ in range(self.P)]
        self.max_span = (MAX_SESSION_SIZE_MICROS if max_span is None
                         else max_span)
        self._uid = next(_SESSION_UIDS)
        self._merges = 0

    # -- routing -----------------------------------------------------------

    def _route(self, kh: np.ndarray) -> np.ndarray:
        return (kh & np.uint64(self.P - 1)).astype(np.int64)

    def _part_of(self, kh: int) -> _SessionPartition:
        return self.parts[int(kh) & (self.P - 1)]

    # -- vectorized batch merge --------------------------------------------

    def merge_intervals(self, ikh: np.ndarray, ist: np.ndarray,
                        ien: np.ndarray, itm: np.ndarray) -> np.ndarray:
        """Merge a batch's candidate session intervals (sorted by
        ``(key, start)``, gap already applied to ends) into the resident
        runs — ONE union dispatch across every touched key.  Returns the
        keys whose merged span would cross the max-session clamp; their
        resident rows are left UNTOUCHED for the caller's authoritative
        per-key re-merge."""
        m = len(ikh)
        if m == 0:
            return np.zeros(0, dtype=np.uint64)
        from ..ops.session import session_device_enabled, union_sorted_intervals

        dest = self._route(ikh)
        touched_parts = np.unique(dest).tolist()
        dkeys = np.unique(ikh)
        # 1. pull the touched keys' resident rows out of each partition
        pulled: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]] = {}
        keeps: Dict[int, np.ndarray] = {}
        for p in touched_parts:
            part = self.parts[p]
            tm_mask = part.touched_mask(dkeys)
            keeps[p] = ~tm_mask
            pulled[p] = (part.kh[tm_mask], part.st[tm_mask],
                         part.en[tm_mask], part.tm[tm_mask])
        # 2. one global (key, start) sort of touched-resident + delta
        #    rows, then ONE vectorized union for ALL keys at once
        ckh = np.concatenate([pulled[p][0] for p in touched_parts] + [ikh])
        cst = np.concatenate([pulled[p][1] for p in touched_parts] + [ist])
        cen = np.concatenate([pulled[p][2] for p in touched_parts] + [ien])
        ctm = np.concatenate([pulled[p][3] for p in touched_parts] + [itm])
        order = np.lexsort((cst, ckh))
        ckh, cst, cen, ctm = ckh[order], cst[order], cen[order], ctm[order]
        dev = session_device_enabled()
        m_kh, m_st, m_en, _sid, sess_first = union_sorted_intervals(
            ckh, cst, cen, device=dev)
        m_tm = np.maximum.reduceat(ctm, sess_first)
        self._merges += 1
        perf.count("session_merge_dispatches")
        if dev:
            perf.count("session_merge_device_dispatches")
        # 3. clamp detection: an unclamped union span over the max is
        #    EXACTLY the condition under which the legacy per-key merge
        #    would have clamped (ops/session.py module docstring) —
        #    those keys fall back wholesale, state untouched
        over = (m_en - m_st) > self.max_span
        if over.any():
            flagged = np.unique(m_kh[over])
            ok_rows = ~np.isin(m_kh, flagged)
            m_kh, m_st, m_en, m_tm = (m_kh[ok_rows], m_st[ok_rows],
                                      m_en[ok_rows], m_tm[ok_rows])
            flag_mask = np.isin(ikh, flagged)
            host_rows = int(flag_mask.sum())
        else:
            flagged = np.zeros(0, dtype=np.uint64)
            host_rows = 0
        _count_merge(m - host_rows, 0)  # caller counts fallback rows
        # 4. splice merged runs back per partition; flagged keys keep
        #    their resident rows (restored from the pulled copies)
        mdest = self._route(m_kh)
        for p in touched_parts:
            part = self.parts[p]
            sel = mdest == p
            bkh, bst, ben, btm = (m_kh[sel], m_st[sel], m_en[sel],
                                  m_tm[sel])
            if len(flagged):
                # resident rows of flagged keys re-enter untouched;
                # their keys are disjoint from the merged keys so the
                # combined replacement stays (key, start)-sortable
                rkh, rst, ren, rtm = pulled[p]
                fm = np.isin(rkh, flagged)
                if fm.any():
                    bkh = np.concatenate([bkh, rkh[fm]])
                    bst = np.concatenate([bst, rst[fm]])
                    ben = np.concatenate([ben, ren[fm]])
                    btm = np.concatenate([btm, rtm[fm]])
                    o = np.lexsort((bst, bkh))
                    bkh, bst, ben, btm = bkh[o], bst[o], ben[o], btm[o]
            part.splice(keeps[p], bkh, bst, ben, btm)
            part.touches = 0.9 * part.touches + 0.1 * int(sel.sum()) * 10
        self._rebalance_hot()
        if self._merges % 16 == 1:
            reg = perf.get_note("session_state_registry")
            if not isinstance(reg, dict):
                reg = {}
                perf.note("session_state_registry", reg)
            reg[self._uid] = self.stats()
        return flagged

    def _rebalance_hot(self) -> None:
        """Join-state hot-set policy: top-``budget`` partitions by EWMA
        row frequency keep device-staged interval planes, with decay and
        2-slot hysteresis so borderline partitions don't flap."""
        from ..ops.session import session_device_enabled

        if not session_device_enabled():
            for part in self.parts:
                part.unstage()
            return
        budget = _hot_budget()
        floor = _hot_min_rows()
        for part in self.parts:
            part.touches *= 0.98
        ranked = sorted(range(self.P),
                        key=lambda p: (-self.parts[p].touches, p))
        hot = {p for p in ranked[:budget]
               if self.parts[p].touches >= floor}
        grace = set(ranked[: budget + 2])
        from ..parallel.mesh_window import place_session_partition

        for p, part in enumerate(self.parts):
            if p in hot and part.dev is None:
                part.stage(device=place_session_partition(p))
            elif part.dev is not None and p not in hot and (
                    part.touches < floor / 2 or p not in grace):
                part.unstage()

    # -- watermark fires ---------------------------------------------------

    def expire(self, watermark: int
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[int]]:
        """Mask-compress every session with ``end <= watermark`` out of
        the runs.  Returns ``(keys, starts, ends)`` of the fired
        sessions plus the fully-expired keys (for ``note_delete``
        tombstones).  Remaining rows of partially fired keys take
        ``watermark`` as their update time — the legacy
        ``windows.insert(watermark, kh, remain)`` contract."""
        fk: List[np.ndarray] = []
        fs: List[np.ndarray] = []
        fe: List[np.ndarray] = []
        removed: List[int] = []
        for part in self.parts:
            if part.n == 0:
                continue
            fired = part.en <= watermark
            if not fired.any():
                continue
            fk.append(part.kh[fired])
            fs.append(part.st[fired])
            fe.append(part.en[fired])
            kept = ~fired
            kkh = part.kh[kept]
            gone = np.setdiff1d(part.kh[fired], kkh)
            removed.extend(int(k) for k in gone.tolist())
            ktm = part.tm[kept]
            if len(kkh):
                # keys that fired some sessions but keep others
                partial = np.isin(kkh, np.unique(part.kh[fired]))
                ktm = np.where(partial, np.int64(watermark), ktm)
            part.set_rows(kkh, part.st[kept], part.en[kept], ktm)
        if not fk:
            z = np.zeros(0, dtype=np.int64)
            return np.zeros(0, dtype=np.uint64), z, z.copy(), removed
        return (np.concatenate(fk), np.concatenate(fs),
                np.concatenate(fe), removed)

    def min_end(self) -> Optional[int]:
        ends = [int(part.en.min()) for part in self.parts if part.n]
        return min(ends) if ends else None

    def min_live_start(self) -> Optional[int]:
        starts = [int(part.st.min()) for part in self.parts if part.n]
        return min(starts) if starts else None

    # -- KeyedState duck interface (per-key fallback + checkpoints) --------

    def insert(self, time: int, key: Any, value: Sequence[Tuple[int, int]]
               ) -> None:
        part = self._part_of(key)
        sl = part.key_slice(key)
        keep = np.ones(part.n, dtype=bool)
        keep[sl] = False
        rows = sorted((int(s), int(e)) for s, e in value)
        nb = len(rows)
        bkh = np.full(nb, np.uint64(key), dtype=np.uint64)
        bst = np.fromiter((s for s, _ in rows), dtype=np.int64, count=nb)
        ben = np.fromiter((e for _, e in rows), dtype=np.int64, count=nb)
        btm = np.full(nb, int(time), dtype=np.int64)
        part.splice(keep, bkh, bst, ben, btm)

    def get(self, key: Any) -> Optional[List[Tuple[int, int]]]:
        part = self._part_of(key)
        sl = part.key_slice(key)
        if sl.start == sl.stop:
            return None
        return list(zip(part.st[sl].tolist(), part.en[sl].tolist()))

    def get_time(self, key: Any) -> Optional[int]:
        part = self._part_of(key)
        sl = part.key_slice(key)
        if sl.start == sl.stop:
            return None
        return int(part.tm[sl].max())

    def remove(self, key: Any) -> None:
        part = self._part_of(key)
        sl = part.key_slice(key)
        if sl.start == sl.stop:
            return
        keep = np.ones(part.n, dtype=bool)
        keep[sl] = False
        z = np.zeros(0, dtype=np.int64)
        part.splice(keep, np.zeros(0, dtype=np.uint64), z, z.copy(),
                    z.copy())

    def items(self) -> Iterator[Tuple[int, List[Tuple[int, int]]]]:
        for part in self.parts:
            n = part.n
            if n == 0:
                continue
            bounds = np.nonzero(np.concatenate(
                [[True], part.kh[1:] != part.kh[:-1]]))[0]
            bounds = np.append(bounds, n)
            for i in range(len(bounds) - 1):
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                yield (int(part.kh[lo]),
                       list(zip(part.st[lo:hi].tolist(),
                                part.en[lo:hi].tolist())))

    def snapshot(self) -> List[Tuple[int, Any, Any]]:
        """The KEYED table entry form — ``[(time, key, sessions)]`` —
        so epochs interchange with the legacy KeyedState layout in both
        directions (and rescale's key-range filter applies per key)."""
        out: List[Tuple[int, Any, Any]] = []
        for kh, sessions in self.items():
            out.append((self.get_time(kh) or 0, kh, sessions))
        return out

    def restore(self, entries: Sequence[Tuple[int, Any, Any]]) -> None:
        """Bulk-load KEYED entries (either layout wrote them) into
        sorted runs: one lexsort per partition, not one splice per
        key."""
        rows_kh: List[int] = []
        rows_st: List[int] = []
        rows_en: List[int] = []
        rows_tm: List[int] = []
        latest: Dict[int, Tuple[int, Any]] = {}
        for t, k, v in entries:
            latest[int(k)] = (int(t), v)  # last write wins (restore order)
        for k, (t, v) in latest.items():
            for s, e in v:
                rows_kh.append(k)
                rows_st.append(int(s))
                rows_en.append(int(e))
                rows_tm.append(t)
        kh = np.array(rows_kh, dtype=np.uint64)
        st = np.array(rows_st, dtype=np.int64)
        en = np.array(rows_en, dtype=np.int64)
        tm = np.array(rows_tm, dtype=np.int64)
        dest = self._route(kh) if len(kh) else np.zeros(0, dtype=np.int64)
        for p in range(self.P):
            sel = dest == p
            pkh, pst, pen, ptm = kh[sel], st[sel], en[sel], tm[sel]
            o = np.lexsort((pst, pkh))
            self.parts[p].set_rows(pkh[o], pst[o], pen[o], ptm[o])

    def n_keys(self) -> int:
        total = 0
        for part in self.parts:
            if part.n:
                total += 1 + int((part.kh[1:] != part.kh[:-1]).sum())
        return total

    def __len__(self) -> int:
        return self.n_keys()  # KeyedState len() counts keys

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Session-state shape for bench/ops: resident rows (live
        session intervals), keys, hot (device-staged) partitions, and
        host-resident bytes while staging is active — bench's
        ``state_bounded`` gate holds ``rows`` against the session-churn
        horizon."""
        rows = sum(part.n for part in self.parts)
        hot = sum(1 for part in self.parts if part.dev is not None)
        host_bytes = sum(part.kh.nbytes + part.st.nbytes + part.en.nbytes
                         + part.tm.nbytes
                         for part in self.parts if part.dev is None)
        dev_set = {str(part.dev_device) for part in self.parts
                   if part.dev is not None and part.dev_device is not None}
        return {"partitions": self.P, "rows": rows, "keys": self.n_keys(),
                "hot_partitions": hot, "spill_bytes": host_bytes,
                "staged_devices": len(dev_set),
                "merge_dispatches": self._merges}


def aggregate_session_registry(reg: Optional[Dict[Any, Dict[str, Any]]]
                               ) -> Dict[str, Any]:
    """Fold the per-state stats registry into one shape summary for the
    bench counters block."""
    entries = list((reg or {}).values())
    if not entries:
        return {}
    out = {"partitions": max(e.get("partitions", 0) for e in entries),
           "states": len(entries)}
    for k in ("rows", "keys", "hot_partitions", "spill_bytes",
              "merge_dispatches"):
        out[k] = int(sum(e.get(k, 0) for e in entries))
    out["staged_devices"] = int(max(e.get("staged_devices", 0)
                                    for e in entries))
    return out
