"""State layer: typed tables, checkpoint backends, and the
partition-adaptive join state.

Import surface:

* :class:`~arroyo_tpu.state.store.StateStore` — the operator facade
* table classes — :mod:`arroyo_tpu.state.tables`
* :class:`~arroyo_tpu.state.join_state.PartitionedJoinBuffer` — join
  sides' incrementally sorted, hot/cold-partitioned state (lazy:
  ``join_state`` pulls in the obs layer, which must not load while
  ``engine.operator`` is still importing ``state.tables``)
* :class:`~arroyo_tpu.state.session_state.SessionRunState` — session
  operators' partitioned interval runs (lazy for the same reason)
"""

from .tables import (  # noqa: F401
    BatchBuffer,
    DeviceTable,
    GlobalKeyedState,
    KeyTimeMultiMap,
    KeyedState,
    TableDescriptor,
    TableType,
    TimeKeyMap,
)

_LAZY = {
    "StateStore": ("arroyo_tpu.state.store", "StateStore"),
    "PartitionedJoinBuffer": ("arroyo_tpu.state.join_state",
                              "PartitionedJoinBuffer"),
    "make_join_buffer": ("arroyo_tpu.state.join_state",
                         "make_join_buffer"),
    "join_partitions": ("arroyo_tpu.state.join_state", "join_partitions"),
    "partitioned_join_enabled": ("arroyo_tpu.state.join_state",
                                 "partitioned_join_enabled"),
    "SessionRunState": ("arroyo_tpu.state.session_state",
                        "SessionRunState"),
    "session_state_enabled": ("arroyo_tpu.state.session_state",
                              "session_state_enabled"),
    "aggregate_session_registry": ("arroyo_tpu.state.session_state",
                                   "aggregate_session_registry"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib

    return getattr(importlib.import_module(entry[0]), entry[1])
