"""Checkpoint backing stores — the analog of the reference's ``BackingStore``
trait and ``ParquetBackend`` (/root/reference/arroyo-state/src/lib.rs:81-160,
parquet.rs).

The parquet layout mirrors the reference so checkpoints are tool-compatible:
files at ``{job}/checkpoints/checkpoint-{epoch:07}/operator-{id}/
table-{name}-{subtask:03}.parquet`` (parquet.rs:63-83) with columns
``{key_hash: uint64, timestamp: int64, key: binary, value: binary,
operation: int8}`` (RecordBatchBuilder, parquet.rs:1008-1119), zstd-compressed.
Restore filters files by task key-range overlap (parquet.rs:194-218) so
rescaling re-partitions state by key range exactly as the reference does.
"""

from __future__ import annotations

import io
import json
import pickle
import time as _time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..types import (
    Batch,
    SubtaskCheckpointMetadata,
    TableCheckpointMetadata,
    TaskInfo,
    U64_MAX,
    ranges_overlap,
)
from ..utils.storage import StorageProvider
from .tables import TableDescriptor, TableType

# DataOperation log semantics (arroyo-state/src/lib.rs:62-79)
OP_INSERT = 0
OP_DELETE_KEY = 1


def _record_table_checkpoint(task: TaskInfo, table: str, seconds: float,
                             nbytes: int) -> None:
    """Per-table checkpoint cost: gauges + a flight-recorder span (best
    effort — persistence must never fail on a metrics problem)."""
    try:
        from ..obs import tracing
        from ..obs.metrics import checkpoint_table_gauge

        checkpoint_table_gauge(task, table, "seconds").set(seconds)
        checkpoint_table_gauge(task, table, "bytes").set(nbytes)
        end = tracing.now_us()
        tracing.record_span(
            "checkpoint.table", "checkpoint", end - seconds * 1e6,
            seconds * 1e6, tid=task.task_id,
            args={"table": table, "bytes": nbytes})
    except Exception:
        pass


def key_hash_of(key: Any) -> int:
    """u64 hash for range partitioning of checkpointed keys.  Integer keys are
    assumed to already be key-space hashes (our keyed operators key by the
    u64 key_hash); other keys get a stable hash of their pickled bytes."""
    if isinstance(key, (int, np.integer)):
        return int(np.uint64(int(key) & 0xFFFF_FFFF_FFFF_FFFF))
    import zlib

    data = pickle.dumps(key, protocol=4)
    h = (zlib.crc32(data) << 32) | zlib.crc32(data[::-1])
    return h & 0xFFFF_FFFF_FFFF_FFFF


@dataclass
class TableSnapshot:
    """One table's data at a barrier: exactly one of the three forms."""

    descriptor: TableDescriptor
    entries: Optional[List[Tuple[int, Any, Any]]] = None  # (time, key, value)
    batch: Optional[Batch] = None  # BatchBuffer contents
    arrays: Optional[Dict[str, np.ndarray]] = None  # DeviceTable contents
    deletes: Optional[List[Any]] = None  # tombstoned keys


class BackingStore:
    """Storage interface for checkpoints (BackingStore trait,
    arroyo-state/src/lib.rs:81-160, reduced to the batched model)."""

    def write_subtask_checkpoint(
        self, task: TaskInfo, epoch: int, tables: Dict[str, TableSnapshot],
        watermark: Optional[int],
    ) -> SubtaskCheckpointMetadata:
        raise NotImplementedError

    def restore_subtask(
        self, task: TaskInfo, epoch: int,
        tables: Sequence[TableDescriptor],
    ) -> Dict[str, TableSnapshot]:
        """Restore the given tables; non-GLOBAL tables are filtered to the
        restoring task's key range, GLOBAL tables are merged across all
        subtasks unfiltered (global_keyed_map.rs semantics)."""
        raise NotImplementedError

    def restore_watermark(self, task: TaskInfo, epoch: int) -> Optional[int]:
        raise NotImplementedError

    def cleanup_before(self, job_id: str, min_epoch: int) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------


def _serialize_rows(
    snapshot: TableSnapshot,
) -> Tuple[np.ndarray, np.ndarray, List[bytes], List[bytes], np.ndarray]:
    """Flatten a TableSnapshot into the reference's 5-column checkpoint rows."""
    key_hashes: List[int] = []
    timestamps: List[int] = []
    keys: List[bytes] = []
    values: List[bytes] = []
    ops: List[int] = []

    if snapshot.entries is not None:
        for t, k, v in snapshot.entries:
            key_hashes.append(key_hash_of(k))
            timestamps.append(int(t))
            keys.append(pickle.dumps(k, protocol=4))
            values.append(pickle.dumps(v, protocol=4))
            ops.append(OP_INSERT)
    if snapshot.deletes:
        # a tombstone whose key was re-inserted before this checkpoint is
        # superseded by the live entry; writing both into one epoch file
        # would make order-blind readers (compaction) drop the live row
        live_keys = set(keys)
        for k in snapshot.deletes:
            kb = pickle.dumps(k, protocol=4)
            if kb in live_keys:
                continue
            key_hashes.append(key_hash_of(k))
            timestamps.append(0)
            keys.append(kb)
            values.append(b"")
            ops.append(OP_DELETE_KEY)
    if snapshot.batch is not None and len(snapshot.batch):
        buf = io.BytesIO()
        _write_arrow_ipc(snapshot.batch, buf)
        key_hashes.append(0)
        timestamps.append(int(snapshot.batch.timestamp.min()))
        keys.append(b"__batch__")
        values.append(buf.getvalue())
        ops.append(OP_INSERT)
    if snapshot.arrays is not None:
        for name, arr in snapshot.arrays.items():
            buf = io.BytesIO()
            np.save(buf, np.asarray(arr), allow_pickle=True)
            key_hashes.append(0)
            timestamps.append(0)
            keys.append(b"__array__" + name.encode())
            values.append(buf.getvalue())
            ops.append(OP_INSERT)

    return (
        np.asarray(key_hashes, dtype=np.uint64),
        np.asarray(timestamps, dtype=np.int64),
        keys,
        values,
        np.asarray(ops, dtype=np.int8),
    )


def _write_arrow_ipc(batch: Batch, buf: io.BytesIO) -> None:
    import pyarrow as pa

    table = batch.to_arrow()
    # carry key metadata so restore rebuilds key_hash
    meta = {b"key_cols": ",".join(batch.key_cols).encode()}
    table = table.replace_schema_metadata(meta)
    with pa.ipc.new_stream(buf, table.schema) as w:
        w.write_table(table)


def _read_arrow_ipc(data: bytes) -> Batch:
    import pyarrow as pa

    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        table = r.read_all()
    batch = Batch.from_arrow(table)
    meta = table.schema.metadata or {}
    key_cols = meta.get(b"key_cols", b"").decode()
    if key_cols:
        batch = batch.with_key(key_cols.split(","))
    return batch


def _deserialize_rows(
    key_hashes: np.ndarray, timestamps: np.ndarray, keys: List[bytes],
    values: List[bytes], ops: np.ndarray, descriptor: TableDescriptor,
    key_range: Tuple[int, int],
) -> TableSnapshot:
    entries: List[Tuple[int, Any, Any]] = []
    deleted: set = set()
    batch: Optional[Batch] = None
    arrays: Dict[str, np.ndarray] = {}
    range_filter = descriptor.table_type not in (TableType.GLOBAL,)

    for kh, t, k, v, op in zip(key_hashes, timestamps, keys, values, ops):
        if k == b"__batch__":
            b = _read_arrow_ipc(v)
            if range_filter and b.key_hash is not None:
                lo, hi = key_range
                mask = (b.key_hash >= np.uint64(lo)) & (b.key_hash <= np.uint64(hi))
                b = b.select(mask)
            batch = b if batch is None else Batch.concat([batch, b])
            continue
        if k.startswith(b"__array__"):
            buf = io.BytesIO(v)
            arrays[k[len(b"__array__"):].decode()] = np.load(buf, allow_pickle=True)
            continue
        if range_filter and not (key_range[0] <= int(kh) <= key_range[1]):
            continue
        key = pickle.loads(k)
        if op == OP_DELETE_KEY:
            deleted.add(k)
            entries = [(et, ek, ev) for (et, ek, ev) in entries
                       if pickle.dumps(ek, protocol=4) != k]
        else:
            entries.append((int(t), key, pickle.loads(v)))

    return TableSnapshot(
        descriptor,
        entries=entries or None,
        batch=batch,
        arrays=arrays or None,
    )


# ---------------------------------------------------------------------------


class ParquetBackend(BackingStore):
    """Parquet checkpoint persistence (parquet.rs:52-61, 891-1135)."""

    def __init__(self, storage: StorageProvider):
        self.storage = storage

    @staticmethod
    def for_url(url: str) -> "ParquetBackend":
        return ParquetBackend(StorageProvider.for_url(url))

    # -- paths (parquet.rs:63-83 layout) ----------------------------------

    @staticmethod
    def checkpoint_dir(job_id: str, epoch: int) -> str:
        return f"{job_id}/checkpoints/checkpoint-{epoch:07d}"

    @classmethod
    def operator_dir(cls, job_id: str, epoch: int, operator_id: str) -> str:
        return f"{cls.checkpoint_dir(job_id, epoch)}/operator-{operator_id}"

    @classmethod
    def table_file(cls, job_id: str, epoch: int, operator_id: str, table: str,
                   subtask: int) -> str:
        safe = table if table.isalnum() else f"t{ord(table[0]):02x}"
        return (f"{cls.operator_dir(job_id, epoch, operator_id)}/"
                f"table-{safe}-{subtask:03d}.parquet")

    @classmethod
    def metadata_file(cls, job_id: str, epoch: int, operator_id: str,
                      subtask: int) -> str:
        return (f"{cls.operator_dir(job_id, epoch, operator_id)}/"
                f"metadata-{subtask:03d}.json")

    # -- write -------------------------------------------------------------

    def write_subtask_checkpoint(
        self, task: TaskInfo, epoch: int, tables: Dict[str, TableSnapshot],
        watermark: Optional[int],
    ) -> SubtaskCheckpointMetadata:
        import pyarrow as pa
        import pyarrow.parquet as pq

        start = _time.time_ns() // 1_000
        meta = SubtaskCheckpointMetadata(
            epoch=epoch, operator_id=task.operator_id,
            subtask_index=task.task_index, start_time=start, finish_time=0,
            bytes=0, watermark=watermark,
        )
        for name, snap in tables.items():
            t_table = _time.perf_counter()
            kh, ts, keys, values, ops = _serialize_rows(snap)
            if len(kh) == 0:
                continue
            table = pa.table({
                "key_hash": pa.array(kh, type=pa.uint64()),
                "timestamp": pa.array(ts, type=pa.int64()),
                "key": pa.array(keys, type=pa.binary()),
                "value": pa.array(values, type=pa.binary()),
                "operation": pa.array(ops, type=pa.int8()),
            })
            buf = io.BytesIO()
            pq.write_table(table, buf, compression="zstd")
            data = buf.getvalue()
            path = self.table_file(task.job_id, epoch, task.operator_id, name,
                                   task.task_index)
            self.storage.put(path, data)
            meta.bytes += len(data)
            meta.tables[name] = TableCheckpointMetadata(
                table=name, files=(path,),
                min_key_hash=int(kh.min()) if len(kh) else 0,
                max_key_hash=int(kh.max()) if len(kh) else int(U64_MAX),
            )
            _record_table_checkpoint(
                task, name, _time.perf_counter() - t_table, len(data))
        meta.finish_time = _time.time_ns() // 1_000
        self.storage.put(
            self.metadata_file(task.job_id, epoch, task.operator_id, task.task_index),
            json.dumps({
                "epoch": epoch, "operator_id": task.operator_id,
                "subtask_index": task.task_index,
                "watermark": watermark, "bytes": meta.bytes,
                "tables": {n: list(t.files) for n, t in meta.tables.items()},
            }).encode(),
        )
        return meta

    @classmethod
    def compacted_file(cls, job_id: str, epoch: int, operator_id: str,
                       safe_table: str, partition: int) -> str:
        return (f"{cls.operator_dir(job_id, epoch, operator_id)}/"
                f"compacted-{safe_table}-p{partition:03d}.parquet")

    @classmethod
    def compaction_marker(cls, job_id: str, epoch: int,
                          operator_id: str) -> str:
        return f"{cls.operator_dir(job_id, epoch, operator_id)}/compaction.json"

    # -- compaction (parquet.rs:451-560) -----------------------------------

    def compact_operator(self, job_id: str, operator_id: str, epoch: int,
                         n_partitions: int = 1) -> Dict[str, List[str]]:
        """Merge an operator's per-subtask gen-0 checkpoint files into
        ``n_partitions`` key-range-partitioned gen-1 files, applying delete
        tombstones (``compact_operator``, parquet.rs:509-560).

        Returns ``{"to_load": [new files], "to_drop": [replaced files]}``;
        the marker makes restore prefer the compacted generation, and the
        replaced gen-0 files are deleted afterwards.
        """
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ..types import server_for_hash_array

        op_dir = self.operator_dir(job_id, epoch, operator_id)
        marker_path = self.compaction_marker(job_id, epoch, operator_id)
        if self.storage.exists(marker_path):
            # already compacted (retry / double invocation): the marker is
            # the committed swap, so never rebuild — but a crash between
            # marker write and gen-0 deletion may have left the replaced
            # files behind; finish that cleanup here
            marker = json.loads(self.storage.get(marker_path))
            dropped = []
            for info in marker["tables"].values():
                for f in info.get("replaced", []):
                    if self.storage.exists(f):
                        self.storage.delete_if_present(f)
                        dropped.append(f)
            return {"to_load": [f for info in marker["tables"].values()
                               for f in info["files"]],
                    "to_drop": dropped}
        by_table: Dict[str, List[str]] = {}
        for f in self.storage.list(op_dir):
            base = f.rsplit("/", 1)[-1]
            if base.startswith("table-") and base.endswith(".parquet"):
                safe = base[len("table-"):].rsplit("-", 1)[0]
                by_table.setdefault(safe, []).append(f)

        to_load: List[str] = []
        to_drop: List[str] = []
        marker: Dict[str, Any] = {"tables": {}, "n_partitions": n_partitions}
        for safe, files in sorted(by_table.items()):
            cols: Dict[str, list] = {"key_hash": [], "timestamp": [],
                                     "key": [], "value": [], "operation": []}
            for f in sorted(files):
                t = pq.read_table(io.BytesIO(self.storage.get(f)))
                cols["key_hash"].append(t.column("key_hash").to_numpy())
                cols["timestamp"].append(t.column("timestamp").to_numpy())
                cols["key"].extend(t.column("key").to_pylist())
                cols["value"].extend(t.column("value").to_pylist())
                cols["operation"].append(t.column("operation").to_numpy())
            kh = np.concatenate(cols["key_hash"]) if cols["key_hash"] else np.array([], np.uint64)
            ts = np.concatenate(cols["timestamp"]) if cols["timestamp"] else np.array([], np.int64)
            ops = np.concatenate(cols["operation"]) if cols["operation"] else np.array([], np.int8)
            keys, values = cols["key"], cols["value"]
            # Apply tombstones: a DeleteKey removes every insert of that key
            # within the (self-contained) epoch; the tombstone itself is then
            # dropped from the compacted generation.
            deleted = {k for k, op in zip(keys, ops) if op == OP_DELETE_KEY}
            live = [i for i in range(len(keys))
                    if ops[i] != OP_DELETE_KEY and keys[i] not in deleted]
            part_of = server_for_hash_array(kh, n_partitions) if len(kh) else kh
            new_files = []
            for p in range(n_partitions):
                idx = [i for i in live if int(part_of[i]) == p]
                if not idx:
                    continue
                table = pa.table({
                    "key_hash": pa.array(kh[idx], type=pa.uint64()),
                    "timestamp": pa.array(ts[idx], type=pa.int64()),
                    "key": pa.array([keys[i] for i in idx], type=pa.binary()),
                    "value": pa.array([values[i] for i in idx], type=pa.binary()),
                    "operation": pa.array(ops[idx], type=pa.int8()),
                })
                buf = io.BytesIO()
                pq.write_table(table, buf, compression="zstd")
                path = self.compacted_file(job_id, epoch, operator_id, safe, p)
                self.storage.put(path, buf.getvalue())
                new_files.append(path)
            marker["tables"][safe] = {"files": new_files, "replaced": files}
            to_load.extend(new_files)
            to_drop.extend(files)
        # The marker commits the swap: restore prefers the compacted
        # generation from this point, so dropping gen-0 files is safe.
        self.storage.put(self.compaction_marker(job_id, epoch, operator_id),
                         json.dumps(marker).encode())
        for f in to_drop:
            self.storage.delete_if_present(f)
        return {"to_load": to_load, "to_drop": to_drop}

    # -- restore -----------------------------------------------------------

    def restore_subtask(
        self, task: TaskInfo, epoch: int,
        tables: Sequence[TableDescriptor],
    ) -> Dict[str, TableSnapshot]:
        import pyarrow.parquet as pq

        out: Dict[str, TableSnapshot] = {}
        op_dir = self.operator_dir(task.job_id, epoch, task.operator_id)
        # Restore reads *every* subtask's files for this operator and filters
        # by the restoring task's key range (parquet.rs:194-218): this is what
        # makes rescale-by-key-range work.
        files = self.storage.list(op_dir)
        compacted: Dict[str, List[str]] = {}
        marker_path = self.compaction_marker(task.job_id, epoch,
                                             task.operator_id)
        if self.storage.exists(marker_path):
            marker = json.loads(self.storage.get(marker_path))
            compacted = {safe: info["files"]
                         for safe, info in marker["tables"].items()}
        for desc in tables:
            name = desc.name
            safe = name if name.isalnum() else f"t{ord(name[0]):02x}"
            prefix = f"table-{safe}-"
            if safe in compacted:
                # compacted generation supersedes gen-0 subtask files
                table_files = list(compacted[safe])
            else:
                table_files = [
                    f for f in files
                    if f.rsplit("/", 1)[-1].startswith(prefix)
                    and f.endswith(".parquet")]
            snaps: List[TableSnapshot] = []
            for f in table_files:
                if not self.storage.exists(f):
                    # a file named by the compaction marker must exist;
                    # restoring without it would silently lose its key range
                    raise FileNotFoundError(
                        f"checkpoint file listed in compaction marker is "
                        f"missing: {f}")
                data = self.storage.get(f)
                table = pq.read_table(io.BytesIO(data))
                snaps.append(_deserialize_rows(
                    table.column("key_hash").to_numpy(),
                    table.column("timestamp").to_numpy(),
                    table.column("key").to_pylist(),
                    table.column("value").to_pylist(),
                    table.column("operation").to_numpy(),
                    desc,
                    task.key_range,
                ))
            if snaps:
                merged = snaps[0]
                for s in snaps[1:]:
                    if s.entries:
                        merged.entries = (merged.entries or []) + s.entries
                    if s.batch is not None:
                        merged.batch = (s.batch if merged.batch is None
                                        else Batch.concat([merged.batch, s.batch]))
                    if s.arrays:
                        from ..ops.keyed_bins import merge_canonical_snapshots

                        merged.arrays = merge_canonical_snapshots(
                            merged.arrays or {}, s.arrays)
                out[name] = merged
        return out

    def restore_watermark(self, task: TaskInfo, epoch: int) -> Optional[int]:
        path = self.metadata_file(task.job_id, epoch, task.operator_id,
                                  task.task_index)
        if not self.storage.exists(path):
            return None
        meta = json.loads(self.storage.get(path))
        return meta.get("watermark")

    def cleanup_before(self, job_id: str, min_epoch: int) -> None:
        """Epoch cleanup (parquet.rs:245-301): drop checkpoint dirs < min_epoch."""
        prefix = f"{job_id}/checkpoints/"
        seen = set()
        for f in self.storage.list(prefix):
            rest = f[len(prefix):]
            part = rest.split("/", 1)[0]
            if part.startswith("checkpoint-"):
                seen.add(part)
        for part in seen:
            try:
                ep = int(part.split("-")[1])
            except (IndexError, ValueError):
                continue
            if ep < min_epoch:
                self.storage.delete_prefix(prefix + part)


class InMemoryBackend(BackingStore):
    """Test backend: keeps snapshots in a process-global dict."""

    _store: Dict[Tuple[str, int, str, int], Tuple[Dict[str, TableSnapshot], Optional[int]]] = {}

    def write_subtask_checkpoint(self, task, epoch, tables, watermark):
        import copy

        self._store[(task.job_id, epoch, task.operator_id, task.task_index)] = (
            copy.deepcopy(tables), watermark)
        return SubtaskCheckpointMetadata(
            epoch=epoch, operator_id=task.operator_id,
            subtask_index=task.task_index,
            start_time=0, finish_time=0, bytes=0, watermark=watermark)

    def restore_subtask(self, task, epoch, table_descs):
        """Mirrors ParquetBackend semantics: merge all subtasks' snapshots and
        filter non-global tables by the restoring task's key range."""
        import copy

        lo, hi = task.key_range
        out: Dict[str, TableSnapshot] = {}
        for (job, ep, op, _idx), (tables, _wm) in sorted(self._store.items()):
            if job != task.job_id or ep != epoch or op != task.operator_id:
                continue
            for desc in table_descs:
                name = desc.name
                if name not in tables:
                    continue
                snap = copy.deepcopy(tables[name])
                range_filter = snap.descriptor.table_type != TableType.GLOBAL
                if range_filter and snap.entries:
                    snap.entries = [
                        (t, k, v) for (t, k, v) in snap.entries
                        if lo <= key_hash_of(k) <= hi]
                if range_filter and snap.batch is not None and snap.batch.key_hash is not None:
                    mask = ((snap.batch.key_hash >= np.uint64(lo))
                            & (snap.batch.key_hash <= np.uint64(hi)))
                    snap.batch = snap.batch.select(mask)
                if name not in out:
                    out[name] = snap
                else:
                    acc = out[name]
                    if snap.entries:
                        acc.entries = (acc.entries or []) + snap.entries
                    if snap.batch is not None:
                        acc.batch = (snap.batch if acc.batch is None
                                     else Batch.concat([acc.batch, snap.batch]))
                    if snap.arrays:
                        from ..ops.keyed_bins import merge_canonical_snapshots

                        acc.arrays = merge_canonical_snapshots(
                            acc.arrays or {}, snap.arrays)
        return out

    def restore_watermark(self, task, epoch):
        entry = self._store.get((task.job_id, epoch, task.operator_id, task.task_index))
        return entry[1] if entry else None

    def cleanup_before(self, job_id, min_epoch):
        for k in [k for k in self._store if k[0] == job_id and k[1] < min_epoch]:
            del self._store[k]
