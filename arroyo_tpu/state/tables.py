"""State table abstractions — the four table types of the reference's
``arroyo-state`` crate (/root/reference/arroyo-state/src/tables/):

* :class:`TimeKeyMap`       — time -> key -> value          (time_key_map.rs:8-241)
* :class:`KeyTimeMultiMap`  — key -> time -> [values]       (key_time_multi_map.rs)
* :class:`GlobalKeyedState` — kv visible to all subtasks    (global_keyed_map.rs)
* :class:`KeyedState`       — kv with timestamp             (keyed_map.rs)

plus :class:`BatchBuffer`, the batched/columnar hot-path analog of
KeyTimeMultiMap used by window/join operators: whole batches are appended and
consolidated lazily, and queries/evictions are vectorized numpy ops instead of
per-record map lookups.  Device-resident operator state (bins, hash slots)
registers as a :class:`DeviceTable` exposing snapshot()/restore() of arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..types import Batch


class TableType(Enum):
    """TableDescriptor.table_type (rpc.proto:246-283)."""

    GLOBAL = "global"
    TIME_KEY_MAP = "time_key_map"
    KEY_TIME_MULTI_MAP = "key_time_multi_map"
    KEYED = "keyed"
    BATCH_BUFFER = "batch_buffer"
    DEVICE = "device"


class WriteBehavior(Enum):
    DEFAULT = "default"
    COMMIT_WRITES = "commit_writes"  # two-phase-commit sink tables


@dataclass
class TableDescriptor:
    name: str
    table_type: TableType
    description: str = ""
    retention_micros: int = 0
    write_behavior: WriteBehavior = WriteBehavior.DEFAULT


def global_table(name: str, description: str = "") -> TableDescriptor:
    return TableDescriptor(name, TableType.GLOBAL, description)


def timer_table() -> TableDescriptor:
    # The reference reserves table name '[' for timers (arroyo-worker/src/lib.rs:152).
    return TableDescriptor("[", TableType.TIME_KEY_MAP, "timers")


# ---------------------------------------------------------------------------


class TimeKeyMap:
    """time -> key -> value with watermark-driven flush/evict
    (time_key_map.rs:8-241).  Tracks a buffered vs persisted split so that
    checkpoints only write new data."""

    def __init__(self) -> None:
        self._data: Dict[int, Dict[Any, Any]] = {}
        self._dirty: List[Tuple[int, Any]] = []

    def insert(self, time: int, key: Any, value: Any) -> None:
        self._data.setdefault(int(time), {})[key] = value
        self._dirty.append((int(time), key))

    def get(self, time: int, key: Any) -> Any:
        return self._data.get(int(time), {}).get(key)

    def get_all_for_time(self, time: int) -> Dict[Any, Any]:
        return self._data.get(int(time), {})

    def get_min_time(self) -> Optional[int]:
        return min(self._data) if self._data else None

    def all_times(self) -> List[int]:
        return sorted(self._data)

    def evict_for_timestamp(self, time: int) -> Dict[Any, Any]:
        """Remove and return the entries at exactly ``time``."""
        return self._data.pop(int(time), {})

    def evict_before(self, time: int) -> None:
        for t in [t for t in self._data if t < time]:
            del self._data[t]

    def drain_dirty(self) -> List[Tuple[int, Any, Any]]:
        out = []
        seen = set()
        for t, k in self._dirty:
            if (t, k) in seen:
                continue
            seen.add((t, k))
            if t in self._data and k in self._data[t]:
                out.append((t, k, self._data[t][k]))
        self._dirty.clear()
        return out

    def snapshot(self) -> List[Tuple[int, Any, Any]]:
        return [(t, k, v) for t, kv in self._data.items() for k, v in kv.items()]

    def restore(self, entries: Iterable[Tuple[int, Any, Any]]) -> None:
        for t, k, v in entries:
            self._data.setdefault(int(t), {})[k] = v

    def __len__(self) -> int:
        return sum(len(kv) for kv in self._data.values())


class KeyTimeMultiMap:
    """key -> time -> [values] with range queries and range clears
    (key_time_multi_map.rs)."""

    def __init__(self) -> None:
        self._data: Dict[Any, Dict[int, List[Any]]] = {}

    def insert(self, time: int, key: Any, value: Any) -> None:
        self._data.setdefault(key, {}).setdefault(int(time), []).append(value)

    def get_time_range(self, key: Any, start: int, end: int) -> List[Any]:
        """Values for ``key`` with start <= time < end, time-ordered."""
        by_time = self._data.get(key)
        if not by_time:
            return []
        out: List[Any] = []
        for t in sorted(by_time):
            if start <= t < end:
                out.extend(by_time[t])
        return out

    def clear_time_range(self, key: Any, start: int, end: int) -> None:
        by_time = self._data.get(key)
        if not by_time:
            return
        for t in [t for t in by_time if start <= t < end]:
            del by_time[t]
        if not by_time:
            del self._data[key]

    def expire_entries_before(self, time: int) -> None:
        for key in list(self._data):
            by_time = self._data[key]
            for t in [t for t in by_time if t < time]:
                del by_time[t]
            if not by_time:
                del self._data[key]

    def keys(self) -> List[Any]:
        return list(self._data)

    def snapshot(self) -> List[Tuple[int, Any, Any]]:
        return [
            (t, k, v)
            for k, by_time in self._data.items()
            for t, vs in by_time.items()
            for v in vs
        ]

    def restore(self, entries: Iterable[Tuple[int, Any, Any]]) -> None:
        for t, k, v in entries:
            self.insert(t, k, v)

    def __len__(self) -> int:
        return sum(len(vs) for bt in self._data.values() for vs in bt.values())

    def n_keys(self) -> int:
        """Distinct-key count (the size the reference's table gauge
        reports, key_time_multi_map.rs)."""
        return len({k for bt in self._data.values() for k in bt})


class GlobalKeyedState:
    """kv state visible across all subtasks — used for source offsets
    (global_keyed_map.rs).

    Entries carry a strictly monotonic per-key INSERT VERSION (persisted
    through the checkpoint row's timestamp column) and restore is
    newest-version-wins.  Global tables merge across every subtask's
    checkpoint files unfiltered, and a restored subtask re-persists the
    OTHER subtasks' entries it merely read — so its next checkpoint
    contains STALE COPIES of its peers' keys.  Un-versioned restore
    resolved such collisions by file order: after a second
    checkpoint-restore cycle a source could resume from a peer's stale
    offset and replay thousands of delivered events (observed as
    duplicated window mass at parallelism 2; regression-pinned by
    tests/test_state.py + the factor-window interchange test).  A
    restored entry keeps its original version, so staleness can never
    launder through re-snapshotting."""

    def __init__(self) -> None:
        self._data: Dict[Any, Any] = {}
        self._version: Dict[Any, int] = {}

    def insert(self, key: Any, value: Any) -> None:
        from ..types import now_micros

        v = now_micros()
        prev = self._version.get(key, -1)
        # max(wall, prev + 1): restore always precedes any insert and
        # merges EVERY peer's files, so ``prev`` already holds the
        # highest version any worker ever recorded for this key — a new
        # owner with a lagging clock (cross-worker skew) still bumps
        # strictly past the restored copy instead of losing to it
        self._version[key] = v if v > prev else prev + 1
        self._data[key] = value

    def get(self, key: Any) -> Any:
        return self._data.get(key)

    def get_all(self) -> Dict[Any, Any]:
        return dict(self._data)

    def remove(self, key: Any) -> None:
        self._data.pop(key, None)
        self._version.pop(key, None)

    def clear(self) -> None:
        self._data.clear()
        self._version.clear()

    def snapshot(self) -> List[Tuple[int, Any, Any]]:
        return [(self._version.get(k, 0), k, v)
                for k, v in self._data.items()]

    def restore(self, entries: Iterable[Tuple[int, Any, Any]]) -> None:
        for t, k, v in entries:
            # >= so identical stale copies (same version, same value)
            # and legacy un-versioned (t=0) checkpoints still restore
            if int(t) >= self._version.get(k, -1):
                self._version[k] = int(t)
                self._data[k] = v

    def __len__(self) -> int:
        return len(self._data)


class KeyedState:
    """kv with timestamp (keyed_map.rs); deletes produce tombstones so that
    compaction/restore preserves removal.

    Interchange contract: ``snapshot()`` / ``restore()`` speak
    ``[(time, key, value)]`` entry lists — the canonical KEYED table
    form every backend persists and filters by key range on rescale.
    Alternate layouts serving the same table (the session-run state in
    state/session_state.py) MUST round-trip this exact form so epochs
    written under one layout restore under the other."""

    def __init__(self) -> None:
        self._data: Dict[Any, Tuple[int, Any]] = {}

    def insert(self, time: int, key: Any, value: Any) -> None:
        self._data[key] = (int(time), value)

    def get(self, key: Any) -> Any:
        entry = self._data.get(key)
        return entry[1] if entry is not None else None

    def get_time(self, key: Any) -> Optional[int]:
        entry = self._data.get(key)
        return entry[0] if entry is not None else None

    def remove(self, key: Any) -> None:
        self._data.pop(key, None)

    def items(self) -> List[Tuple[Any, Any]]:
        return [(k, v) for k, (_, v) in self._data.items()]

    def snapshot(self) -> List[Tuple[int, Any, Any]]:
        return [(t, k, v) for k, (t, v) in self._data.items()]

    def restore(self, entries: Iterable[Tuple[int, Any, Any]]) -> None:
        for t, k, v in entries:
            self._data[k] = (int(t), v)

    def __len__(self) -> int:
        return len(self._data)

    def n_keys(self) -> int:
        return len(self._data)  # table-size gauges count KEYS


# ---------------------------------------------------------------------------


class BatchBuffer:
    """Columnar buffered rows for window/join operators: the hot-path
    KeyTimeMultiMap.  Batches are appended O(1) and consolidated lazily; range
    query and eviction are vectorized over the merged batch."""

    def __init__(self) -> None:
        self._pending: List[Batch] = []
        self._merged: Optional[Batch] = None
        self._keys: Optional[set] = None  # lazy incremental key-hash set

    def append(self, batch: Batch) -> None:
        if len(batch):
            self._pending.append(batch)
            if self._keys is not None and batch.key_hash is not None:
                self._keys.update(batch.key_hash.tolist())

    def _consolidate(self) -> Optional[Batch]:
        if self._pending:
            parts = ([self._merged] if self._merged is not None else []) + self._pending
            self._merged = Batch.concat(parts)
            self._pending.clear()
        return self._merged

    def query_range(self, start: int, end: int) -> Optional[Batch]:
        """Rows with start <= timestamp < end."""
        m = self._consolidate()
        if m is None or len(m) == 0:
            return None
        mask = (m.timestamp >= start) & (m.timestamp < end)
        if not mask.any():
            return None
        return m.select(mask)

    def evict_before(self, time: int) -> None:
        m = self._consolidate()
        if m is None:
            return
        mask = m.timestamp >= time
        if not mask.all():
            self._merged = m.select(mask)
            self._keys = None  # rows left: rebuild membership lazily

    def all(self) -> Optional[Batch]:
        return self._consolidate()

    def contains_keys(self, key_hashes: np.ndarray) -> np.ndarray:
        """Per-element membership of ``key_hashes`` among buffered rows'
        key hashes — incremental (set updated on append, rebuilt only
        after an eviction actually dropped rows), so outer-join
        first-match checks cost O(batch), not O(buffer) per batch."""
        if self._keys is None:
            m = self._consolidate()
            self._keys = (set(m.key_hash.tolist())
                          if m is not None and m.key_hash is not None
                          else set())
        s = self._keys
        return np.fromiter((int(k) in s for k in key_hashes.tolist()),
                           dtype=bool, count=len(key_hashes))

    def remove_keys(self, key_hashes: np.ndarray) -> None:
        """Drop buffered rows whose key_hash is in ``key_hashes`` (used by
        the semi-join: matched-and-emitted left rows leave the buffer)."""
        m = self._consolidate()
        if m is None or len(m) == 0 or m.key_hash is None:
            return
        keep = ~np.isin(m.key_hash, key_hashes)
        if not keep.all():
            self._merged = m.select(keep)
            self._keys = None

    def __len__(self) -> int:
        m = self._consolidate()
        return len(m) if m is not None else 0

    # checkpoint interface: the batch itself is the snapshot
    def snapshot_batch(self) -> Optional[Batch]:
        return self._consolidate()

    def restore_batch(self, batch: Optional[Batch]) -> None:
        self._merged = batch
        self._pending.clear()
        self._keys = None


class DeviceTable:
    """Operator-owned device-resident state (HBM arrays) that participates in
    checkpoints: the operator provides snapshot() -> dict[str, np.ndarray] and
    restore(dict).  The barrier path calls jax.device_get through snapshot so
    device state is serialized consistently with host queue positions."""

    def __init__(self, snapshot_fn: Callable[[], Dict[str, np.ndarray]],
                 restore_fn: Callable[[Dict[str, np.ndarray]], None]):
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn

    def snapshot(self) -> Dict[str, np.ndarray]:
        return self.snapshot_fn()

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        self.restore_fn(arrays)


TABLE_CLASSES = {
    TableType.GLOBAL: GlobalKeyedState,
    TableType.TIME_KEY_MAP: TimeKeyMap,
    TableType.KEY_TIME_MULTI_MAP: KeyTimeMultiMap,
    TableType.KEYED: KeyedState,
    TableType.BATCH_BUFFER: BatchBuffer,
}
