"""Partition-adaptive join state (PanJoin-style; PAPERS.md).

The legacy join path kept each side in a flat :class:`BatchBuffer` and
re-sorted BOTH sides' full key arrays on every probe or window fire
(``ops/join.join_pairs`` argsorts ``lk``/``rk`` each call), and every
TTL eviction re-materialized the surviving rows with a full copy.  Under
long-TTL skewed streams both costs grow with *state*, not with the
arriving batch.

This module replaces that with hash-partitioned, incrementally sorted
state:

* each side's rows hash-partition by the low bits of ``key_hash`` (the
  subtask key ranges split on the HIGH bits, so partitioning stays
  orthogonal to rescale);
* each partition maintains its rows as an **incrementally maintained
  sorted run**: an arriving delta is sorted alone (O(m log m)) and
  merged against the resident run with one vectorized positional merge
  (O(n+m) moves, no comparisons beyond a searchsorted) — never a full
  re-sort of resident state;
* TTL eviction is a **valid-range advance**: ``evict_before`` just
  raises the partition's ``valid_from`` bound; dead rows are filtered
  out of probe results by timestamp and physically compacted only when
  they outnumber live rows (amortized O(1) per row);
* **hot partitions** (by observed row frequency, EWMA with hysteresis)
  keep their sorted key run device-resident in a preallocated
  power-of-two ring, maintained by a single scatter-merge kernel
  dispatch per append and probed on device (``ops/join.py``); cold
  partitions stay host numpy ("spill").  Promotion/demotion depends
  only on the observed data sequence, so it is deterministic;
* hot rings are **fully device-resident** (PR 15): keys store as
  native-i32 split-hash planes (top-32 sort key + low-32 collision
  verify — no emulated-u64 argsort on TPU) and, with
  ``ARROYO_JOIN_PAYLOAD_DEVICE`` on (default auto), the partition's
  payload columns ride co-located device planes in the same layout,
  maintained by the SAME scatter-merge dispatch.  Probes then emit
  matches through ONE fused expand+verify+gather dispatch instead of a
  host fancy-index per match (``join_device_gather_rows`` vs
  ``join_host_gather_rows`` count the split).  Object (string) columns
  cannot ride the device: the first string column observed flips the
  buffer's STICKY host-gather fallback (rings stay keys-only, the
  emission layout never flips mid-stream).

Checkpoint contract: :class:`PartitionedJoinBuffer` subclasses
:class:`BatchBuffer` and keeps its ``snapshot_batch``/``restore_batch``
interface, so checkpoints serialize the same Arrow batch form the
legacy buffer wrote, restores filter by key range for rescale exactly
as before, and the two state layouts are checkpoint-compatible in both
directions.

Knobs (see docs/operations.md):
  ARROYO_JOIN_STATE=partitioned|legacy   state layout (default partitioned)
  ARROYO_JOIN_PARTITIONS=16              partitions per side (power of two)
  ARROYO_JOIN_HOT_PARTITIONS=4           device-resident partition budget
  ARROYO_JOIN_HOT_MIN_ROWS=4096          EWMA rows to qualify as hot
  ARROYO_JOIN_PAYLOAD_DEVICE=auto|off    payload planes on hot rings
"""

from __future__ import annotations

import itertools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import perf, profiler
from ..types import Batch
from .tables import BatchBuffer

_NEG_INF = np.iinfo(np.int64).min

# dtype kinds the payload planes can transport (ops/join.payload_plan);
# anything else — object/str — flips the buffer's sticky host fallback
_PAYLOAD_KINDS = "fiubMm"


def _count_gather(dev_rows: int, host_rows: int) -> None:
    """Account materialized join rows to the device/host gather split
    (perf counters + prometheus mirrors) — the payload-residency
    invariant is a measured number, not an assumption."""
    from ..obs.metrics import join_gather_counter

    if dev_rows:
        perf.count("join_device_gather_rows", dev_rows)
        join_gather_counter("device").inc(dev_rows)
    if host_rows:
        perf.count("join_host_gather_rows", host_rows)
        join_gather_counter("host").inc(host_rows)


def _fill_cols(cols: Dict[str, np.ndarray], n: int, sel: Any,
               pcols: Dict[str, np.ndarray]) -> None:
    """Fill output rows ``sel`` from one partition's gathered columns,
    null-initializing and dtype-promoting so a partition lacking a
    column (late schema drift) can never expose garbage."""
    for c, v in pcols.items():
        if c not in cols:
            if v.dtype == object:
                cols[c] = np.full(n, None, dtype=object)
            elif v.dtype.kind == "f":
                cols[c] = np.full(n, np.nan, dtype=v.dtype)
            else:
                cols[c] = np.zeros(n, dtype=v.dtype)
        tgt = cols[c]
        if tgt.dtype != v.dtype:
            cols[c] = tgt = tgt.astype(
                object if (tgt.dtype == object or v.dtype == object)
                else np.result_type(tgt.dtype, v.dtype))
        tgt[sel] = v


def partitioned_join_enabled() -> bool:
    return os.environ.get("ARROYO_JOIN_STATE", "partitioned") != "legacy"


def join_partitions() -> int:
    p = int(os.environ.get("ARROYO_JOIN_PARTITIONS", 16))
    # clamp to a power of two so routing is a mask
    b = 1
    while b * 2 <= max(p, 1):
        b *= 2
    return b


def _hot_budget() -> int:
    return int(os.environ.get("ARROYO_JOIN_HOT_PARTITIONS", 4))


def _hot_min_rows() -> float:
    return float(os.environ.get("ARROYO_JOIN_HOT_MIN_ROWS", 4096))


def _grow(arr: np.ndarray, cap: int) -> np.ndarray:
    out = np.empty(cap, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


class _Partition:
    """One hash partition of one join side: columnar storage in arrival
    order plus an incrementally merged key-sorted run over it."""

    __slots__ = ("cols", "keys", "ts", "n", "cap", "order", "skeys",
                 "sts", "valid_from", "dead", "_evicts_since_scan",
                 "touches", "dev", "dev_device", "payload_on")

    def __init__(self) -> None:
        self.cols: Dict[str, np.ndarray] = {}
        self.keys = np.empty(0, dtype=np.uint64)
        self.ts = np.empty(0, dtype=np.int64)
        self.n = 0
        self.cap = 0
        # sorted run: order[i] = storage position of the i-th smallest key
        # (stable by arrival); skeys/sts mirror keys/ts in sorted order
        self.order = np.empty(0, dtype=np.int64)
        self.skeys = np.empty(0, dtype=np.uint64)
        self.sts = np.empty(0, dtype=np.int64)
        self.valid_from = _NEG_INF
        self.dead = 0  # estimated rows below valid_from
        self._evicts_since_scan = 0
        self.touches = 0.0  # EWMA of rows handled per operation
        # device-resident split-hash ring (ops/join.SplitRing): i32 key
        # planes + optionally the co-located payload stacks
        self.dev: Optional[Any] = None
        # mesh device owning this partition's ring (None = default chip;
        # parallel.shuffle.partition_device spreads hot rings over the
        # ("keys",) mesh so joins stop funneling through one device)
        self.dev_device: Optional[Any] = None
        self.payload_on = False  # buffer policy at last promotion

    # -- storage -----------------------------------------------------------

    def _ensure_cap(self, need: int) -> None:
        if need <= self.cap:
            return
        cap = max(self.cap, 256)
        while cap < need:
            cap *= 2
        self.keys = _grow(self.keys[: self.n], cap)
        self.ts = _grow(self.ts[: self.n], cap)
        for c in list(self.cols):
            self.cols[c] = _grow(self.cols[c][: self.n], cap)
        self.cap = cap

    def _coerce_col(self, name: str, v: np.ndarray) -> np.ndarray:
        """Dtype-promote storage when a later batch widens a column (the
        engine's nullable-int convention can flip int64 -> float64)."""
        cur = self.cols.get(name)
        if cur is None or cur.dtype == v.dtype:
            return v
        if cur.dtype == object or v.dtype == object:
            tgt = np.dtype(object)
        else:
            tgt = np.result_type(cur.dtype, v.dtype)
        if cur.dtype != tgt:
            self.cols[name] = self.cols[name].astype(tgt)
        return v.astype(tgt) if v.dtype != tgt else v

    def append(self, keys: np.ndarray, ts: np.ndarray,
               cols: Dict[str, np.ndarray]) -> None:
        m = len(keys)
        if m == 0:
            return
        n = self.n
        self._ensure_cap(n + m)
        self.keys[n:n + m] = keys
        self.ts[n:n + m] = ts
        for c, v in cols.items():
            if c not in self.cols:
                col = np.empty(self.cap, dtype=v.dtype)
                if n:  # column appeared late: null-fill history
                    if v.dtype == object:
                        col[:n] = None
                    elif v.dtype.kind == "f":
                        col[:n] = np.nan
                    else:
                        col = col.astype(np.float64)
                        col[:n] = np.nan
                self.cols[c] = col
            v = self._coerce_col(c, v)
            self.cols[c][n:n + m] = v
        for c in self.cols:
            if c not in cols:  # missing column: null-fill the delta
                cur = self.cols[c]
                if cur.dtype == object:
                    cur[n:n + m] = None
                else:
                    if cur.dtype.kind != "f":
                        self.cols[c] = cur = cur.astype(np.float64)
                    cur[n:n + m] = np.nan

        # incremental sorted-run maintenance: sort ONLY the delta, then
        # positionally merge against the resident run (one searchsorted
        # + two scatters — the tentpole replacement for re-sorting both
        # sides per probe)
        dorder = np.argsort(keys, kind="stable")
        dkeys = keys[dorder]
        ins = np.searchsorted(self.skeys[:n], dkeys, side="right")
        dpos = ins + np.arange(m, dtype=np.int64)
        total = n + m
        new_order = np.empty(total, dtype=np.int64)
        new_skeys = np.empty(total, dtype=np.uint64)
        new_sts = np.empty(total, dtype=np.int64)
        keep = np.ones(total, dtype=bool)
        keep[dpos] = False
        new_order[dpos] = n + dorder
        new_skeys[dpos] = dkeys
        new_sts[dpos] = ts[dorder]
        new_order[keep] = self.order[:n]
        new_skeys[keep] = self.skeys[:n]
        new_sts[keep] = self.sts[:n]
        self.order, self.skeys, self.sts = new_order, new_skeys, new_sts
        self.n = total
        perf.count("join_state_merges")
        self.touches = 0.9 * self.touches + 0.1 * m * 10  # EWMA over ops
        if self.dev is not None:
            dts = ts[dorder]
            dcols = ({c: self.cols[c][n:n + m][dorder]
                      for c in self.cols}
                     if self.dev.plan is not None else None)
            self._device_merge(dkeys, dpos, keep, dts, dcols)

    # -- device residency --------------------------------------------------

    def _device_merge(self, dkeys: np.ndarray, dpos: np.ndarray,
                      keep: np.ndarray, dts: np.ndarray,
                      dcols: Optional[Dict[str, np.ndarray]]) -> None:
        from ..ops import join as dj

        ring = self.dev
        if self.n > ring.cap:
            # ring overflow: regrow to the next power-of-two ring — the
            # restage keeps key AND payload placement in lockstep
            perf.count("join_state_ring_regrows")
            self.promote()
            return
        if self.payload_on:
            # payload plan drift (a column appeared, widened, or went
            # string): restage so the planes always mirror storage.  A
            # string schema keeps a KEYS-ONLY ring without restaging
            # every merge (payload_plan stays None for it).
            want = {c: v.dtype for c, v in self.cols.items()}
            want_plan = dj.payload_plan(want)
            if want_plan is not None and (
                    ring.plan is None or ring.plan_schema() != want):
                self.promote()
                return
            if want_plan is None and ring.plan is not None:
                self.promote()
                return
        elif ring.plan is not None:
            self.promote()  # payload switched off: drop the planes
            return
        res_pos = np.nonzero(keep)[0].astype(np.int64)
        merged = dj.merge_ring(ring, res_pos, dkeys, dpos,
                               delta_ts=dts, delta_cols=dcols)
        if merged is None:  # delta hit the top-32 sentinel: exactness
            self.demote()   # over speed — the host mirror takes over
            return
        self.dev = merged
        perf.count("join_state_device_merges")

    def promote(self, device: Any = None,
                payload: Optional[bool] = None) -> None:
        """Stage this partition's sorted keys — plus, when the buffer's
        payload policy is on, its payload columns in the same sorted-run
        order — into preallocated power-of-two device planes
        (idempotent; also used to regrow and to re-plan after schema
        drift — restages keep the mesh device the first promotion
        pinned)."""
        from ..ops import join as dj

        if device is not None:
            self.dev_device = device
        if payload is not None:
            self.payload_on = payload
        n = self.n
        cols = None
        if self.payload_on:
            order = self.order[:n]
            cols = {c: v[:n][order] for c, v in self.cols.items()}
        ring = dj.stage_ring(self.skeys[:n], device=self.dev_device,
                             sorted_ts=self.sts[:n], sorted_cols=cols)
        if ring is None:
            # a key's top-32 bits collide with the ring sentinel
            # (~2^-32/row): this partition stays host — exactness first
            self.dev = None
            return
        self.dev = ring
        perf.count("join_state_promotions")

    def demote(self) -> None:
        if self.dev is not None:
            self.dev = None
            perf.count("join_state_demotions")

    # -- TTL ---------------------------------------------------------------

    def evict_before(self, t: int) -> None:
        """Valid-range advance: no data movement here.  The dead-row
        rescan (an O(n) timestamp compare) is throttled to every 8th
        advance, so per-watermark work stays amortized O(1)/row even
        when watermarks arrive per batch; compaction runs only when
        dead rows outnumber live ones."""
        if t <= self.valid_from or self.n == 0:
            return
        self.valid_from = t
        self._evicts_since_scan += 1
        if self.n >= 1024 and self._evicts_since_scan >= 8:
            self._evicts_since_scan = 0
            self.dead = int((self.sts[: self.n] < t).sum())
            if self.dead * 2 > self.n:
                self._compact()

    def _compact(self) -> None:
        live = self.ts[: self.n] >= self.valid_from
        for c in list(self.cols):
            self.cols[c] = self.cols[c][: self.n][live].copy()
        self.keys = self.keys[: self.n][live].copy()
        self.ts = self.ts[: self.n][live].copy()
        self.n = int(live.sum())
        self.cap = self.n
        # rebuild the sorted run from the compacted storage: positions
        # shifted by the cumulative dead count before them
        shift = np.cumsum(~live) if len(live) else np.zeros(0, np.int64)
        old_order = self.order[: len(live)]
        okeep = live[old_order]
        kept = old_order[okeep]
        self.order = (kept - shift[kept]).astype(np.int64)
        self.skeys = self.skeys[: len(live)][okeep].copy()
        self.sts = self.sts[: len(live)][okeep].copy()
        self.dead = 0
        perf.count("join_state_compactions")
        if self.dev is not None:
            self.promote()  # restage the compacted run

    # -- queries -----------------------------------------------------------

    def live_mask_sorted(self, start: Optional[int] = None,
                         end: Optional[int] = None) -> np.ndarray:
        sts = self.sts[: self.n]
        m = sts >= (self.valid_from if start is None
                    else max(self.valid_from, start))
        if end is not None:
            m &= sts < end
        return m

    def live_count(self) -> int:
        if self.n == 0:
            return 0
        if self.valid_from == _NEG_INF:
            return self.n
        return int((self.ts[: self.n] >= self.valid_from).sum())

    def probe(self, qkeys_sorted: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Match ranges of sorted query keys against the resident run.
        Returns (qidx, spos): for every (query row, live matching state
        row) pair, the index into ``qkeys_sorted`` and the SORTED-RUN
        position of the match (``gather`` maps to storage, or straight
        into the device payload planes)."""
        n = self.n
        if n == 0 or len(qkeys_sorted) == 0:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        self.touches = 0.9 * self.touches + 0.1 * len(qkeys_sorted) * 10
        if self.dev is not None:
            from ..ops import join as dj

            hit = dj.probe_ring(self.dev, qkeys_sorted, n)
            total = int(hit.counts.sum())
            if total == 0:
                z = np.zeros(0, dtype=np.int64)
                return z, z
            qidx, sidx = dj.expand_hit(self.dev, hit, total)
            # full-key collision verify on the host mirror: device
            # candidates are top-32-equal ranges; the rare
            # i32-equal-but-u64-distinct rows die here
            ok = self.skeys[sidx] == qkeys_sorted[qidx]
            if not ok.all():
                qidx, sidx = qidx[ok], sidx[ok]
        else:
            skeys = self.skeys[:n]
            start = np.searchsorted(skeys, qkeys_sorted, side="left")
            end = np.searchsorted(skeys, qkeys_sorted, side="right")
            counts = end - start
            if not counts.any():
                z = np.zeros(0, dtype=np.int64)
                return z, z
            from ..ops.join import expand_counts

            qidx, offs = expand_counts(counts)
            sidx = np.repeat(start, counts) + offs  # sorted-run positions
        if self.valid_from != _NEG_INF and len(sidx):
            alive = self.sts[sidx] >= self.valid_from
            qidx, sidx = qidx[alive], sidx[alive]
        return qidx, sidx

    def probe_rows(self, qkeys_sorted: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray,
                              Optional[Dict[str, np.ndarray]],
                              Optional[np.ndarray]]:
        """Fused probe + payload materialization: like :meth:`probe`
        but, when this partition's payload planes are resident, the
        candidate expansion, the full-key collision verify AND the
        payload gather run as ONE device dispatch
        (``ops/join.expand_gather``) — no host fancy-index per match.
        Returns (qidx, spos, cols, ts); cols/ts are None when the
        caller must host-gather (cold partition or keys-only ring)."""
        ring = self.dev
        if ring is None or ring.plan is None:
            qidx, spos = self.probe(qkeys_sorted)
            return qidx, spos, None, None
        n = self.n
        if n == 0 or len(qkeys_sorted) == 0:
            z = np.zeros(0, dtype=np.int64)
            return z, z, None, None
        self.touches = 0.9 * self.touches + 0.1 * len(qkeys_sorted) * 10
        from ..ops import join as dj

        hit = dj.probe_ring(ring, qkeys_sorted, n)
        total = int(hit.counts.sum())
        if total == 0:
            z = np.zeros(0, dtype=np.int64)
            return z, z, None, None
        qidx, sidx, valid, gf, gi = dj.expand_gather(ring, hit, total)
        keep = valid
        if self.valid_from != _NEG_INF:
            keep = keep & (gi[0] >= self.valid_from)
        if not keep.all():
            qidx, sidx = qidx[keep], sidx[keep]
            gf, gi = gf[:, keep], gi[:, keep]
        ts, cols = dj.unpack_payload(ring, gf, gi)
        return qidx, sidx, cols, ts

    def range_view(self, start: Optional[int], end: Optional[int]
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """(keys_sorted, sorted_run_positions) of live rows with
        start <= ts < end — mask-compress of the sorted run, which stays
        key-sorted, so fires never re-sort."""
        if self.n == 0:
            return (np.zeros(0, dtype=np.uint64),
                    np.zeros(0, dtype=np.int64))
        m = self.live_mask_sorted(start, end)
        return self.skeys[: self.n][m], np.nonzero(m)[0]


class PartitionedJoinBuffer(BatchBuffer):
    """Drop-in BatchBuffer replacement for join sides: partition-adaptive
    incrementally sorted state (module docstring).  The checkpoint
    interface (``snapshot_batch``/``restore_batch``) is inherited
    behavior-compatibly, so epochs written by either layout restore into
    the other."""

    def __init__(self, n_partitions: Optional[int] = None):
        super().__init__()
        self.P = n_partitions or join_partitions()
        self.parts = [_Partition() for _ in range(self.P)]
        self.key_cols: Tuple[str, ...] = ()
        self._schema: Dict[str, np.dtype] = {}
        self._appends = 0
        self._uid = next(_BUF_UIDS)
        # STICKY string fallback: the first object/string column flips
        # payload residency off for this buffer's whole life — the
        # emission layout (and the edge's sharding spec) never flips
        # mid-stream (shardcheck's sticky-route contract)
        self._payload_sticky_host = False

    # -- routing -----------------------------------------------------------

    def _route(self, kh: np.ndarray) -> np.ndarray:
        return (kh & np.uint64(self.P - 1)).astype(np.int64)

    def _device_active(self) -> bool:
        from ..ops.join import device_join_enabled

        return device_join_enabled(1 << 30)  # state-resident: size-free

    def _payload_active(self) -> bool:
        from ..ops.join import payload_device_enabled

        return payload_device_enabled() and not self._payload_sticky_host

    def append(self, batch: Batch) -> None:
        if not len(batch):
            return
        assert batch.key_hash is not None, "join state requires keyed rows"
        if batch.key_cols:
            self.key_cols = batch.key_cols
        self._schema = {c: v.dtype for c, v in batch.columns.items()}
        if not self._payload_sticky_host and any(
                dt.kind not in _PAYLOAD_KINDS
                for dt in self._schema.values()):
            self._payload_sticky_host = True
        dest = self._route(batch.key_hash)
        order = np.argsort(dest, kind="stable")
        bounds = np.searchsorted(dest[order], np.arange(self.P + 1))
        device_on = self._device_active()
        for p in range(self.P):
            lo, hi = bounds[p], bounds[p + 1]
            if lo == hi:
                continue
            rows = order[lo:hi]
            self.parts[p].append(
                batch.key_hash[rows], batch.timestamp[rows],
                {c: v[rows] for c, v in batch.columns.items()})
        if device_on:
            self._rebalance_hot()
        elif any(pt.dev is not None for pt in self.parts):
            for pt in self.parts:
                pt.demote()
        self._appends += 1
        if self._appends % 16 == 1:  # throttled flight-recorder note:
            # one registry entry per buffer (a query has >= 2 side
            # buffers; a single last-writer-wins note would misattribute
            # the state shape) — bench clears and aggregates the registry
            reg = perf.get_note("join_state_registry")
            if not isinstance(reg, dict):
                reg = {}
                perf.note("join_state_registry", reg)
            reg[self._uid] = self.stats()

    def _rebalance_hot(self) -> None:
        """Deterministic hot-set maintenance: the top-``budget``
        partitions by EWMA row frequency hold device rings, with 2x
        hysteresis so borderline partitions don't flap.  Every
        partition's EWMA decays here too — a formerly hot partition
        that stops seeing rows must cool below the demotion floor, or
        its score would freeze and resident rings could exceed the
        budget forever after a skew shift."""
        budget = _hot_budget()
        floor = _hot_min_rows()
        for part in self.parts:
            part.touches *= 0.98
        ranked = sorted(range(self.P),
                        key=lambda p: (-self.parts[p].touches, p))
        hot = {p for p in ranked[:budget]
               if self.parts[p].touches >= floor}
        # rank-based demotion with 2-slot hysteresis: a resident ring
        # demotes when it cools below floor/2 OR falls out of the top
        # budget+2 ranking — resident rings are hard-capped near the
        # budget even when ALL partitions keep moderate traffic (an
        # absolute floor alone would let rings accumulate to P)
        grace = set(ranked[: budget + 2])
        from ..parallel.shuffle import partition_device

        payload = self._payload_active()
        for p, part in enumerate(self.parts):
            if p in hot and part.dev is None:
                # sharded device placement over the same ("keys",) mesh
                # axis the window state uses: partition p's ring lives on
                # mesh device p % nk (deterministic — promotion stays a
                # pure function of the observed data sequence); payload
                # planes ride the same device in lockstep
                part.promote(device=partition_device(p), payload=payload)
            elif part.dev is not None and p not in hot and (
                    part.touches < floor / 2 or p not in grace):
                part.demote()

    # -- BatchBuffer interface --------------------------------------------

    def evict_before(self, time: int) -> None:
        for part in self.parts:
            part.evict_before(time)

    def _materialize(self, start: Optional[int] = None,
                     end: Optional[int] = None) -> Optional[Batch]:
        parts: List[Batch] = []
        for part in self.parts:
            n = part.n
            if n == 0:
                continue
            ts = part.ts[:n]
            m = ts >= (part.valid_from if start is None
                       else max(part.valid_from, start))
            if end is not None:
                m &= ts < end
            if not m.any():
                continue
            cols = {c: v[:n][m] for c, v in part.cols.items()}
            parts.append(Batch(ts[m], cols, part.keys[:n][m],
                               self.key_cols))
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else Batch.concat(parts)

    def all(self) -> Optional[Batch]:
        return self._materialize()

    def query_range(self, start: int, end: int) -> Optional[Batch]:
        return self._materialize(start, end)

    def contains_keys(self, key_hashes: np.ndarray) -> np.ndarray:
        out = np.zeros(len(key_hashes), dtype=bool)
        if not len(key_hashes):
            return out
        sorter = np.argsort(key_hashes, kind="stable")
        qidx, _pos = self.probe_positions(key_hashes[sorter],
                                          pre_sorted=True)
        if len(qidx):
            out[sorter[np.unique(qidx)]] = True
        return out

    def remove_keys(self, key_hashes: np.ndarray) -> None:
        for part in self.parts:
            n = part.n
            if n == 0:
                continue
            keep = ~np.isin(part.keys[:n], key_hashes)
            if keep.all():
                continue
            # key removal is rare (semi-join only): compact via mask
            live = keep & (part.ts[:n] >= part.valid_from)
            for c in list(part.cols):
                part.cols[c] = part.cols[c][:n][live].copy()
            part.keys = part.keys[:n][live].copy()
            part.ts = part.ts[:n][live].copy()
            part.n = int(live.sum())
            part.cap = part.n
            part.order = np.argsort(part.keys, kind="stable")
            part.skeys = part.keys[part.order].copy()
            part.sts = part.ts[part.order].copy()
            part.dead = 0
            perf.count("join_state_resorts")
            if part.dev is not None:
                part.promote()

    def __len__(self) -> int:
        return sum(part.live_count() for part in self.parts)

    def snapshot_batch(self) -> Optional[Batch]:
        return self._materialize()

    def restore_batch(self, batch: Optional[Batch]) -> None:
        self.parts = [_Partition() for _ in range(self.P)]
        if batch is not None and len(batch):
            if batch.key_hash is None and batch.key_cols:
                batch = batch.with_key(batch.key_cols)
            self.append(batch)

    # -- join probes -------------------------------------------------------

    def probe_positions(self, qkeys_sorted: np.ndarray, pre_sorted: bool
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """(qidx, (part, pos) encoded) for every live match of the sorted
        query keys; used by contains_keys and rows_with_keys."""
        assert pre_sorted
        dest = self._route(qkeys_sorted)
        qi_parts: List[np.ndarray] = []
        gp_parts: List[np.ndarray] = []
        for p in range(self.P):
            sel = np.nonzero(dest == p)[0]
            if not len(sel):
                continue
            qidx, pos = self.parts[p].probe(qkeys_sorted[sel])
            if len(qidx):
                qi_parts.append(sel[qidx])
                gp_parts.append(p * (1 << 48) + pos)
        if not qi_parts:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        return np.concatenate(qi_parts), np.concatenate(gp_parts)

    def _empty_rows(self) -> Batch:
        cols = {c: np.empty(0, dtype=dt)
                for c, dt in self._schema.items()}
        return Batch(np.zeros(0, dtype=np.int64), cols,
                     np.zeros(0, dtype=np.uint64), self.key_cols)

    def gather(self, gpos: np.ndarray) -> Batch:
        """Materialize rows by encoded (part, sorted-run pos) global
        positions, preserving the given order (pair alignment).  Hot
        partitions with resident payload planes gather ON DEVICE (one
        fused dispatch per partition, ``ops/join.gather_ring``); cold
        partitions host-gather through the sorted-run order mapping —
        the split is counted (``join_device_gather_rows`` /
        ``join_host_gather_rows``) and profiled (``gather`` phase)."""
        n = len(gpos)
        if n == 0:
            return self._empty_rows()
        part_of = (gpos >> 48).astype(np.int64)
        pos = (gpos & ((1 << 48) - 1)).astype(np.int64)
        ts = np.empty(n, dtype=np.int64)
        kh = np.empty(n, dtype=np.uint64)
        cols: Dict[str, np.ndarray] = {}
        dev_rows = host_rows = 0
        prof = profiler.active()
        frame = (prof.begin(perf.active_operator_id() or "join",
                            "gather") if prof is not None else None)
        try:
            for p in np.unique(part_of).tolist():
                part = self.parts[p]
                sel = part_of == p
                spos = pos[sel]
                kh[sel] = part.skeys[spos]
                ring = part.dev
                if ring is not None and ring.plan is not None:
                    from ..ops import join as dj

                    gf, gi = dj.gather_ring(ring, spos)
                    pts, pcols = dj.unpack_payload(ring, gf, gi)
                    ts[sel] = pts
                    dev_rows += len(spos)
                else:
                    ts[sel] = part.sts[spos]
                    rows = part.order[spos]
                    pcols = {c: v[rows] for c, v in part.cols.items()}
                    host_rows += len(spos)
                _fill_cols(cols, n, sel, pcols)
        finally:
            if frame is not None:
                prof.end(frame)
        _count_gather(dev_rows, host_rows)
        return Batch(ts, cols, kh, self.key_cols)

    def probe_batch(self, batch: Batch
                    ) -> Tuple[np.ndarray, Batch, np.ndarray]:
        """Join an arriving batch against this (opposite-side) state
        WITHOUT materializing or re-sorting the state: sort only the
        batch's keys, probe each partition's resident run.  Hot
        partitions with payload planes take the fused
        probe->expand->gather device path (:meth:`_Partition.probe_rows`)
        so matched state rows materialize without a host fancy-index.

        Returns ``(bsel, state_rows, counts)``: matched-pair batch row
        indices, the aligned state rows, and per-batch-row live match
        counts (original batch order) for outer-join unmatched masks."""
        kh = batch.key_hash
        nq = len(kh)
        sorter = np.argsort(kh, kind="stable")
        qk = kh[sorter]
        dest = self._route(qk)
        counts = np.zeros(nq, dtype=np.int64)
        qi_parts: List[np.ndarray] = []
        blocks: List[Tuple[_Partition, np.ndarray,
                           Optional[Dict[str, np.ndarray]],
                           Optional[np.ndarray]]] = []
        total = 0
        for p in range(self.P):
            sel = np.nonzero(dest == p)[0]
            if not len(sel) or self.parts[p].n == 0:
                continue
            qidx, spos, dcols, dts = self.parts[p].probe_rows(qk[sel])
            if not len(qidx):
                continue
            qi_parts.append(sel[qidx])
            blocks.append((self.parts[p], spos, dcols, dts))
            total += len(qidx)
        if not total:
            return np.zeros(0, dtype=np.int64), self._empty_rows(), counts
        bsel = sorter[np.concatenate(qi_parts)]
        np.add.at(counts, bsel, 1)
        return bsel, self._assemble_blocks(blocks, total), counts

    def _assemble_blocks(self, blocks, total: int) -> Batch:
        """One output batch from per-partition probe results, device- or
        host-gathered per block (same null-init/promotion rules as
        :meth:`gather`)."""
        ts = np.empty(total, dtype=np.int64)
        kh = np.empty(total, dtype=np.uint64)
        cols: Dict[str, np.ndarray] = {}
        dev_rows = host_rows = 0
        at = 0
        prof = profiler.active()
        frame = (prof.begin(perf.active_operator_id() or "join",
                            "gather") if prof is not None else None)
        try:
            for part, spos, dcols, dts in blocks:
                m = len(spos)
                sel = slice(at, at + m)
                kh[sel] = part.skeys[spos]
                if dcols is not None:
                    ts[sel] = dts
                    pcols = dcols
                    dev_rows += m
                else:
                    ts[sel] = part.sts[spos]
                    rows = part.order[spos]
                    pcols = {c: v[rows] for c, v in part.cols.items()}
                    host_rows += m
                _fill_cols(cols, total, sel, pcols)
                at += m
        finally:
            if frame is not None:
                prof.end(frame)
        _count_gather(dev_rows, host_rows)
        return Batch(ts, cols, kh, self.key_cols)

    def rows_with_keys(self, keys: np.ndarray) -> Batch:
        """Live rows whose key is in ``keys`` (each row once)."""
        ks = np.sort(np.asarray(keys, dtype=np.uint64))
        _qidx, gpos = self.probe_positions(ks, pre_sorted=True)
        return self.gather(gpos)

    def range_join(self, other: "PartitionedJoinBuffer", start: int,
                   end: int) -> Tuple[np.ndarray, np.ndarray,
                                      np.ndarray, np.ndarray]:
        """Equi-join both sides' rows with ts in [start, end): per
        partition, mask-compress each sorted run (stays key-sorted — no
        sort) and merge-probe the two.  Returns (l_gpos, r_gpos — aligned
        pair positions; l_unmatched_gpos, r_unmatched_gpos)."""
        lg: List[np.ndarray] = []
        rg: List[np.ndarray] = []
        lu: List[np.ndarray] = []
        ru: List[np.ndarray] = []
        for p in range(self.P):
            lk, lpos = self.parts[p].range_view(start, end)
            rk, rpos = other.parts[p].range_view(start, end)
            enc_l = p * (1 << 48) + lpos
            enc_r = p * (1 << 48) + rpos
            if len(lk) == 0 or len(rk) == 0:
                if len(lk):
                    lu.append(enc_l)
                if len(rk):
                    ru.append(enc_r)
                continue
            s = np.searchsorted(rk, lk, side="left")
            e = np.searchsorted(rk, lk, side="right")
            counts = e - s
            if counts.any():
                from ..ops.join import expand_counts

                lidx, offs = expand_counts(counts)
                ridx = np.repeat(s, counts) + offs
                lg.append(enc_l[lidx])
                rg.append(enc_r[ridx])
                rmatched = np.zeros(len(rk), dtype=bool)
                rmatched[ridx] = True
                if not rmatched.all():
                    ru.append(enc_r[~rmatched])
            else:
                ru.append(enc_r)
            lun = counts == 0
            if lun.any():
                lu.append(enc_l[lun])
        z = np.zeros(0, dtype=np.int64)
        cat = lambda xs: np.concatenate(xs) if xs else z  # noqa: E731
        return cat(lg), cat(rg), cat(lu), cat(ru)

    def stats(self) -> Dict[str, Any]:
        """Join-state shape for bench/ops: hot partitions, spill bytes
        (host-resident bytes while the device path is active), rows.
        ``rows`` uses the maintained resident/dead estimates — stats run
        on the append hot path and must not rescan timestamps."""
        hot = sum(1 for part in self.parts if part.dev is not None)
        host_bytes = 0
        for part in self.parts:
            if part.dev is not None:
                continue
            n = part.n
            host_bytes += int(sum(v[:n].nbytes if v.dtype != object
                                  else n * 8 for v in part.cols.values())
                              + part.keys[:n].nbytes + part.ts[:n].nbytes)
        rows = sum(max(part.n - part.dead, 0) for part in self.parts)
        # mesh spread of resident rings: >1 means hot partitions are NOT
        # funneling through one device (the q7/q8 sharded-placement win)
        ring_devs = {str(part.dev_device) for part in self.parts
                     if part.dev is not None
                     and part.dev_device is not None}
        # payload residency shape: rings carrying co-located payload
        # planes, their device bytes, and total ring capacity — bench's
        # state_bounded check holds these against the TTL horizon so a
        # regrow leak is a failed gate, not a silent OOM
        payload_rings = ring_cap = payload_bytes = 0
        for part in self.parts:
            if part.dev is None:
                continue
            ring_cap += part.dev.cap
            if part.dev.plan is not None:
                payload_rings += 1
                payload_bytes += part.dev.payload_bytes()
        return {"partitions": self.P, "hot_partitions": hot,
                "spill_bytes": host_bytes, "rows": rows,
                "ring_devices": len(ring_devs),
                "payload_rings": payload_rings,
                "payload_ring_bytes": payload_bytes,
                "ring_cap_rows": ring_cap}


_BUF_UIDS = itertools.count()


def aggregate_stats_registry(reg: Optional[Dict[Any, Dict[str, Any]]]
                             ) -> Dict[str, Any]:
    """Fold the per-buffer stats registry into one shape summary:
    additive fields sum across buffers, ``partitions`` reports the
    per-side setting."""
    entries = list((reg or {}).values())
    if not entries:
        return {}
    out = {"partitions": max(e.get("partitions", 0) for e in entries),
           "buffers": len(entries)}
    for k in ("hot_partitions", "spill_bytes", "rows", "payload_rings",
              "payload_ring_bytes", "ring_cap_rows"):
        out[k] = int(sum(e.get(k, 0) for e in entries))
    # mesh spread is per buffer; the fold reports the widest one
    out["ring_devices"] = int(max(e.get("ring_devices", 0)
                                  for e in entries))
    return out


def make_join_buffer() -> BatchBuffer:
    """The join side buffer for the configured state layout."""
    return (PartitionedJoinBuffer() if partitioned_join_enabled()
            else BatchBuffer())
