"""ctypes bindings for the C++ host runtime library (native/src/host_ops.cpp).

Loads ``libarroyo_host.so`` next to this file, building it from source on
first use when a toolchain is available.  Every binding has a numpy
fallback with identical semantics; ``HAVE_NATIVE`` reports which path is
active and ``ARROYO_NATIVE=0`` forces the fallback.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_SO = os.path.join(os.path.dirname(__file__), "libarroyo_host.so")
_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")

_lib: Optional[ctypes.CDLL] = None


def _build() -> bool:
    """Build the library, safe against concurrent workers: an exclusive
    lockfile serializes builds, and make writes the final .so via the
    compiler in one pass so a loader never sees a half-written file that
    a racing builder produced under the lock."""
    import fcntl

    makefile = os.path.join(_SRC_DIR, "Makefile")
    if not os.path.exists(makefile):
        return False
    lock_path = _SO + ".lock"
    try:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(_SO):  # another process won the race
                return True
            tmp = _SO + f".tmp{os.getpid()}"
            subprocess.run(
                ["make", "-C", _SRC_DIR, f"OUT={tmp}"], check=True,
                capture_output=True, timeout=120)
            os.replace(tmp, _SO)  # atomic publish
            return True
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("native build failed, using numpy fallbacks: %s", e)
        return False


_ABI_VERSION = 2  # must match arroyo_abi_version() in host_ops.cpp


def _abi_ok(lib: ctypes.CDLL) -> bool:
    try:
        fn = lib.arroyo_abi_version
        fn.restype = ctypes.c_int64
        return int(fn()) == _ABI_VERSION
    except (AttributeError, OSError):
        return False  # pre-versioning build: signatures may have changed


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("ARROYO_NATIVE", "1") in ("0", "false", "no"):
        return None
    if not os.path.exists(_SO) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
        if not _abi_ok(lib):
            raise OSError(f"stale ABI (want v{_ABI_VERSION})")
    except OSError as e:  # stale/foreign-arch binary: rebuild once
        logger.warning("reloading native lib after load failure: %s", e)
        try:
            os.unlink(_SO)
        except OSError:
            # read-only install: can't replace the corrupt library
            return None
        if not _build():
            return None
        try:
            # dlopen caches by pathname, so re-CDLL of _SO would return
            # the stale mapping we just detected — load the rebuilt
            # library through a unique temp copy instead (unlinked after
            # dlopen; the mapping survives on Linux)
            import shutil
            import tempfile

            fd, tmp = tempfile.mkstemp(
                suffix=".so", dir=os.path.dirname(_SO))
            os.close(fd)
            shutil.copy2(_SO, tmp)
            try:
                lib = ctypes.CDLL(tmp)
            finally:
                os.unlink(tmp)
            if not _abi_ok(lib):
                logger.warning("native lib ABI mismatch after rebuild; "
                               "numpy fallbacks")
                return None
        except OSError as e2:
            logger.warning("native lib unusable, numpy fallbacks: %s", e2)
            return None

    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")

    lib.arroyo_hash_u64.argtypes = [u64p, u64p, ctypes.c_int64]
    lib.arroyo_hash_combine.argtypes = [u64p, u64p, ctypes.c_int64]
    lib.arroyo_partition_route.argtypes = [
        u64p, ctypes.c_int64, ctypes.c_int32, i32p, i64p, i64p]
    lib.arroyo_assign_bins.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, i32p, u8p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.arroyo_assign_bins.restype = ctypes.c_int64
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.arroyo_dir_new.argtypes = [ctypes.c_int64]
    lib.arroyo_dir_new.restype = ctypes.c_void_p
    lib.arroyo_dir_free.argtypes = [ctypes.c_void_p]
    lib.arroyo_dir_load.argtypes = [ctypes.c_void_p, u64p, i64p,
                                    ctypes.c_int64]
    lib.arroyo_dir_insert.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64,
                                      ctypes.c_int64, i64p, u64p]
    lib.arroyo_dir_insert.restype = ctypes.c_int64
    lib.arroyo_dir_lookup.argtypes = [ctypes.c_void_p, u64p, ctypes.c_int64,
                                      i64p]
    lib.arroyo_agg_cells.argtypes = [
        i64p, i32p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        f64p, u8p, ctypes.c_int32, i64p, i32p, f64p, f64p]
    lib.arroyo_agg_cells.restype = ctypes.c_int64
    return lib


_lib = _load()
HAVE_NATIVE = _lib is not None


def hash_u64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer; bit-identical to types.hash_u64."""
    xs = np.ascontiguousarray(x, dtype=np.uint64)
    if _lib is None:
        from ..types import _py_hash_u64

        return _py_hash_u64(xs)
    out = np.empty_like(xs)
    _lib.arroyo_hash_u64(xs, out, len(xs))
    return out


def hash_combine(acc: np.ndarray, h: np.ndarray) -> np.ndarray:
    """acc = splitmix64(acc * 31 + h), elementwise; mutates a copy."""
    a = np.ascontiguousarray(acc, dtype=np.uint64).copy()
    hs = np.ascontiguousarray(h, dtype=np.uint64)
    if _lib is None:
        from ..types import _py_hash_u64

        with np.errstate(over="ignore"):
            return _py_hash_u64(a * np.uint64(31) + hs)
    _lib.arroyo_hash_combine(a, hs, len(a))
    return a


def partition_route(key_hash: np.ndarray, n_parts: int
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(dest[n] i32, order[n] i64 stable by dest, bounds[n_parts+1] i64).

    ``order[bounds[p]:bounds[p+1]]`` are the row indices destined for
    shard ``p`` — one O(n) pass in native code vs argsort in numpy.
    """
    kh = np.ascontiguousarray(key_hash, dtype=np.uint64)
    n = len(kh)
    if _lib is None:
        from ..types import server_for_hash_array

        dest = server_for_hash_array(kh, n_parts).astype(np.int32)
        order = np.argsort(dest, kind="stable").astype(np.int64)
        bounds = np.searchsorted(
            dest[order], np.arange(n_parts + 1)).astype(np.int64)
        return dest, order, bounds
    dest = np.empty(n, dtype=np.int32)
    order = np.empty(n, dtype=np.int64)
    bounds = np.empty(n_parts + 1, dtype=np.int64)
    _lib.arroyo_partition_route(kh, n, n_parts, dest, order, bounds)
    return dest, order, bounds


def assign_bins(ts: np.ndarray, slide: int, ring: int,
                threshold: Optional[int]
                ) -> Tuple[np.ndarray, np.ndarray, int, Optional[int],
                           Optional[int]]:
    """Window-bin assignment + liveness: (bins i32, live bool, n_live,
    abs_min, abs_max) where abs_* cover live rows only."""
    t = np.ascontiguousarray(ts, dtype=np.int64)
    n = len(t)
    thr = -(2**63) if threshold is None else int(threshold)
    if _lib is None:
        abs_bins = t // slide
        live = abs_bins >= thr
        bins = (abs_bins % ring).astype(np.int32)
        n_live = int(live.sum())
        if n_live:
            lo = int(abs_bins[live].min())
            hi = int(abs_bins[live].max())
        else:
            lo = hi = None
        return bins, live, n_live, lo, hi
    bins = np.empty(n, dtype=np.int32)
    live = np.empty(n, dtype=np.uint8)
    lo = ctypes.c_int64()
    hi = ctypes.c_int64()
    n_live = _lib.arroyo_assign_bins(t, n, slide, ring, thr, bins, live,
                                     ctypes.byref(lo), ctypes.byref(hi))
    if n_live == 0:
        return bins, live.astype(bool), 0, None, None
    return bins, live.astype(bool), int(n_live), lo.value, hi.value

class NativeDir:
    """Persistent open-addressing key directory (key hash -> slot) backed
    by the C++ table; ``None``-like when the native lib is unavailable —
    callers must check :data:`HAVE_NATIVE` or use ``NativeDir.create()``."""

    __slots__ = ("_h",)

    @classmethod
    def create(cls, cap_hint: int = 1024) -> Optional["NativeDir"]:
        return cls(cap_hint) if _lib is not None else None

    def __init__(self, cap_hint: int = 1024):
        self._h = _lib.arroyo_dir_new(int(cap_hint))

    def __del__(self):
        if _lib is not None and getattr(self, "_h", None):
            _lib.arroyo_dir_free(self._h)
            self._h = None

    def load(self, keys: np.ndarray, slots: np.ndarray) -> None:
        """Bulk-load explicit (key, slot) pairs (checkpoint restore)."""
        k = np.ascontiguousarray(keys, dtype=np.uint64)
        s = np.ascontiguousarray(slots, dtype=np.int64)
        _lib.arroyo_dir_load(self._h, k, s, len(k))

    def insert(self, kh: np.ndarray, next_slot: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Lookup-or-insert: returns (slots[n], new_keys) where unknown
        keys got sequential slots from ``next_slot`` in first-seen order."""
        k = np.ascontiguousarray(kh, dtype=np.uint64)
        n = len(k)
        slots = np.empty(n, dtype=np.int64)
        new_keys = np.empty(n, dtype=np.uint64)
        n_new = _lib.arroyo_dir_insert(self._h, k, n, int(next_slot),
                                       slots, new_keys)
        return slots, new_keys[:n_new]

    def lookup(self, kh: np.ndarray) -> np.ndarray:
        """Slots for known keys, -1 for unknown."""
        k = np.ascontiguousarray(kh, dtype=np.uint64)
        out = np.empty(len(k), dtype=np.int64)
        _lib.arroyo_dir_lookup(self._h, k, len(k), out)
        return out


def agg_cells(slots: np.ndarray, bins: np.ndarray,
              live: Optional[np.ndarray], ring: int,
              vals: np.ndarray, ch_kinds: Tuple[str, ...]
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(slot, bin)-cell pre-aggregation in one native hash pass: returns
    (cell_slots, cell_bins, cell_rowcounts f64, cell_vals [n_ch, n_cells])
    — the lexsort+reduceat ``preaggregate`` path's fast twin.  ``live``
    filters rows; returns cells in first-appearance order.  Accumulation
    is f64 (exact int sums to 2^53 — the numeric-fidelity policy)."""
    assert _lib is not None
    s = np.ascontiguousarray(slots, dtype=np.int64)
    b = np.ascontiguousarray(bins, dtype=np.int32)
    n = len(s)
    v = np.ascontiguousarray(vals, dtype=np.float64)
    kinds = np.array([1 if k == "min" else 2 if k == "max" else 0
                      for k in ch_kinds], dtype=np.uint8)
    n_ch = len(ch_kinds)
    out_slot = np.empty(n, dtype=np.int64)
    out_bin = np.empty(n, dtype=np.int32)
    out_cnt = np.empty(n, dtype=np.float64)
    out_vals = np.empty((n_ch, n), dtype=np.float64)
    lv = (None if live is None
          else np.ascontiguousarray(live, dtype=np.uint8))
    lp = lv.ctypes.data_as(ctypes.c_void_p) if lv is not None else None
    m = _lib.arroyo_agg_cells(s, b, lp, n, int(ring), v, kinds, n_ch,
                              out_slot, out_bin, out_cnt, out_vals)
    return out_slot[:m], out_bin[:m], out_cnt[:m], out_vals[:, :m]
