"""Keyed binned aggregation state in device memory — the engine's core
windowing kernel (SURVEY.md "Core TPU kernel #2").

This is the TPU re-design of the reference's two-phase sliding aggregator
(/root/reference/arroyo-worker/src/operators/aggregating_window.rs:14-258):
the reference keeps per-(key, bin) pre-aggregates in a TimeKeyMap and, on
watermark advance, adds/retracts bins from an in-memory per-key view.  Here:

* the **key directory** lives on host: a sorted uint64 array of known key
  hashes with a parallel slot array (lookups are one vectorized
  ``np.searchsorted`` per batch; inserts are a vectorized merge);
* the **bin ring** lives in HBM: ``values[n_aggs, C, B]`` device arrays — C
  key slots x B time bins of ``slide`` width each, scatter-reduced per batch
  by one jitted kernel;
* **pane emission** on watermark advance is one device kernel over all
  pending panes at once: for sums/counts a bins-x-pane-mask **matmul**
  (``[C,B] @ [B,k]`` — MXU work), for min/max a gathered window reduce;
* eviction is O(1): expired ring slots are zeroed on device.

Capacity doubles when the key directory fills; shapes are powers of two so
recompiles are O(log keys).
"""

from __future__ import annotations

import functools
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.logical import AggKind, AggSpec

# f64 extremes (the accumulation channels are float64, see ACC_DTYPE):
# f32 extremes here would clip MIN/MAX values beyond +/-3.4e38.  The
# Pallas path never sees these — it handles additive channels only.
NEG_INF = float(jnp.finfo(jnp.float64).min)
POS_INF = float(jnp.finfo(jnp.float64).max)

# Numeric-fidelity policy (VERDICT r2 #5; the reference aggregates in exact
# i64/f64, aggregating_window.rs): all XLA-path accumulation channels are
# float64 — int64 SUM/COUNT stay exact to 2^53, MIN/MAX preserve full int64
# comparisons below that, AVG divides exactly-summed numerators.  The Pallas
# MXU path keeps its bf16 hi/lo compensated scatter per batch but lands the
# deltas in this f64 state, so only within-batch rounding (~2^-16 relative)
# remains.  MIN/MAX null identities are f64 extremes (NEG_INF/POS_INF
# above) so values beyond +/-3.4e38 never clip.
ACC_DTYPE = np.float64


def _init_value(kind: AggKind) -> float:
    if kind == AggKind.MIN:
        return POS_INF
    if kind == AggKind.MAX:
        return NEG_INF
    return 0.0


@functools.lru_cache(maxsize=256)
def _update_kernel(kinds: Tuple[str, ...], C: int, B: int, n: int,
                   dup: Tuple[int, ...] = ()):
    dup_set = frozenset(dup)

    @jax.jit
    def run(values, counts, idx, packed):
        # TWO packed inputs (two host->device transfers — a tunneled TPU
        # pays per-transfer latency, so indices don't ride as f64):
        # idx i32[2, n] rows are [slots, bins]; packed f64[k+1, n] rows
        # are [rowcount, channel values...] per pre-aggregated (key, bin)
        # cell.  rowcount 0 marks padding.  Channels in ``dup`` (COUNT(*))
        # accumulate exactly the rowcount, so their input never rides the
        # transfer — the kernel reconstructs it from packed[0].
        slots = idx[0]
        bins = idx[1]
        rowcnt = packed[0]
        valid = rowcnt > 0.5
        vals = packed[1:]
        s = jnp.where(valid, slots, C)  # trash row
        b = jnp.where(valid, bins, 0)
        counts = counts.at[s.clip(0, C - 1), b].add(
            jnp.where(valid & (s < C), rowcnt, 0.0).astype(counts.dtype))
        outs = []
        r = 0
        for i, kind in enumerate(kinds):
            v = values[i]
            if i in dup_set:
                x = rowcnt
            else:
                x = vals[r]
                r += 1
            ok = valid & (s < C)
            si = s.clip(0, C - 1)
            if kind in ("sum", "avg", "count"):
                v = v.at[si, b].add(jnp.where(ok, x, 0.0))
            elif kind == "min":
                v = v.at[si, b].min(jnp.where(ok, x, POS_INF))
            elif kind == "max":
                v = v.at[si, b].max(jnp.where(ok, x, NEG_INF))
            else:
                raise ValueError(kind)
            outs.append(v)
        return jnp.stack(outs), counts

    return run


def _pane_reduce(kind: str, g, bin_ok):
    """Reduce one channel's gathered [..., k, W] window bins to [..., k]
    pane aggregates (shared by the dense and compacted emit kernels so the
    two paths cannot diverge)."""
    if kind in ("sum", "avg", "count"):
        return jnp.sum(jnp.where(bin_ok[None], g, 0.0), axis=-1)
    if kind == "min":
        return jnp.min(jnp.where(bin_ok[None], g, POS_INF), axis=-1)
    if kind == "max":
        return jnp.max(jnp.where(bin_ok[None], g, NEG_INF), axis=-1)
    raise ValueError(kind)


@functools.lru_cache(maxsize=256)
def _emit_kernel(kinds: Tuple[str, ...], C: int, B: int, W: int, k: int,
                 keep: Optional[Tuple[int, ...]] = None,
                 cnt16: bool = False):
    """Compute per-key aggregates for k panes.  ``ring[k, W]`` (int32) and
    ``bin_ok[k, W]`` are computed on host from the absolute (int64) bin
    indices — keeping 64-bit bin arithmetic out of jit, where x64-disabled
    JAX would truncate it.  ``keep`` selects the channels that ride the
    device->host transfer (COUNT(*) channels are dropped — their pane
    output is exactly the counts plane, which transfers as integers
    anyway).  ``cnt16`` downcasts the count grid to u16 for the transfer —
    the caller proves pane sums fit (host-tracked bound), halving the
    dominant readback."""
    if keep is None:
        keep = tuple(range(len(kinds)))

    @jax.jit
    def run(values, counts, ring, bin_ok):
        # counts per key per pane: gather [C, k, W] then sum
        cnt_g = counts[:, ring]  # [C, k, W]
        cnt = jnp.sum(jnp.where(bin_ok[None], cnt_g, 0), axis=-1)  # [C, k]
        if cnt16:
            cnt = cnt.astype(jnp.uint16)

        outs = []
        for i in keep:
            # (avg division happens on host from the validity-count
            # channel — NOT from cnt, which counts null rows too)
            outs.append(_pane_reduce(kinds[i], values[i][:, ring], bin_ok))
        return (jnp.stack(outs) if outs else jnp.zeros((0, C, k))), cnt

    return run


@functools.lru_cache(maxsize=256)
def _argmax_nnz_kernel(C: int, B: int, W: int, k: int, minmax: str):
    """Phase 1 of argmax emission: pane counts + per-pane extremum stay
    device-resident; only the candidate total crosses (4 bytes).  The
    candidate mask is (cnt == pane extremum) & (cnt > 0) — every global
    argmax row is a local candidate, so this is a sound pre-filter for
    the downstream WindowArgmax stage."""

    @jax.jit
    def run(counts, ring, bin_ok):
        cnt_g = counts[:, ring]  # [C, k, W]
        cnt = jnp.sum(jnp.where(bin_ok[None], cnt_g, 0), axis=-1)  # [C, k]
        if minmax == "max":
            ext = jnp.max(cnt, axis=0)  # counts are >= 0: empty cells lose
        else:
            big = jnp.iinfo(cnt.dtype).max
            ext = jnp.min(jnp.where(cnt > 0, cnt, big), axis=0)
        sel = (cnt == ext[None, :]) & (cnt > 0)
        return cnt, sel, jnp.sum(sel)

    return run


@functools.lru_cache(maxsize=256)
def _argmax_gather_kernel(C: int, B: int, W: int, k: int, npad: int):
    """Phase 2: gather ONLY the candidate cells' (key, pane, count)."""

    @jax.jit
    def run(cnt, sel):
        flat = sel.reshape(-1)
        idx = jnp.nonzero(flat, size=npad, fill_value=C * k)[0]
        ok = idx < C * k
        safe = jnp.where(ok, idx, 0)
        idx2 = jnp.stack([(safe // k).astype(jnp.int32),
                          (safe % k).astype(jnp.int32)])
        cnt_c = jnp.where(ok, cnt.reshape(-1)[safe], 0)
        return idx2, cnt_c

    return run


@functools.lru_cache(maxsize=256)
def _emit_count_kernel(C: int, B: int, W: int, k: int):
    """Phase 1 of compacted emission: pane counts stay device-resident;
    only the live-cell total crosses (4 bytes instead of the [C, k]
    grid — the scalar sizes phase 2's static-shape compaction)."""

    @jax.jit
    def run(counts, ring, bin_ok):
        cnt_g = counts[:, ring]  # [C, k, W]
        cnt = jnp.sum(jnp.where(bin_ok[None], cnt_g, 0), axis=-1)  # [C, k]
        return cnt, jnp.sum(cnt > 0)

    return run


@functools.lru_cache(maxsize=256)
def _emit_compact_kernel(kinds: Tuple[str, ...], C: int, B: int, W: int,
                         k: int, keep: Tuple[int, ...], npad: int):
    """Phase 2: gather ONLY live (key, pane) cells.  The dense pane grid
    is C*k cells of which a fire typically touches a few percent (keys
    active inside one window span vs every key ever seen) — compacting on
    device shrinks the tunnel readback by that ratio and replaces the
    host-side np.nonzero scan."""

    @jax.jit
    def run(values, cnt, ring, bin_ok):
        flat = cnt.reshape(-1)  # [C * k]
        idx = jnp.nonzero(flat > 0, size=npad, fill_value=C * k)[0]
        ok = idx < C * k
        safe = jnp.where(ok, idx, 0)
        key_idx = (safe // k).astype(jnp.int32)
        pane_idx = (safe % k).astype(jnp.int32)
        cnt_c = jnp.where(ok, flat[safe], 0)
        outs = []
        for i in keep:
            r = _pane_reduce(kinds[i], values[i][:, ring], bin_ok)
            outs.append(r.reshape(-1)[safe])
        idx2 = jnp.stack([key_idx, pane_idx])
        return idx2, cnt_c, (jnp.stack(outs) if outs else
                             jnp.zeros((0, npad), jnp.float64))

    return run


@functools.lru_cache(maxsize=64)
def _linearize_kernel(kinds: Tuple[str, ...], C: int, B: int, L: int):
    """Materialize the LINEAR bin span [C, L] from the modular ring —
    one gather; bins outside the live range read as each channel's
    aggregation identity.  Feeds the ring-pane emission path."""

    @jax.jit
    def run(values, counts, ring_idx, ok):
        outs = []
        for i, kind in enumerate(kinds):
            g = values[i][:, ring_idx]  # [C, L]
            outs.append(jnp.where(ok[None, :], g,
                                  _init_value(AggKind(kind))))
        cg = jnp.where(ok[None, :], counts[:, ring_idx], 0)
        return (jnp.stack(outs) if outs else
                jnp.zeros((0, C, L), jnp.float64)), cg

    return run


@functools.lru_cache(maxsize=256)
def _evict_kernel(kinds: Tuple[str, ...], C: int, B: int):
    @jax.jit
    def run(values, counts, ring_slots, slot_valid):
        # zero expired ring columns
        mask = jnp.zeros((B,), dtype=bool).at[
            jnp.where(slot_valid, ring_slots, 0)].max(slot_valid)
        counts = jnp.where(mask[None, :], 0, counts)
        outs = []
        for i, kind in enumerate(kinds):
            init = _init_value(AggKind(kind))
            outs.append(jnp.where(mask[None, :], init, values[i]))
        return jnp.stack(outs), counts

    return run


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def restored_count_state(raw_counts: np.ndarray, promote_at: int
                         ) -> Tuple[int, np.dtype]:
    """(total restored rows, counts-plane dtype) for a snapshot restore —
    the single policy both KeyedBinState and MeshKeyedBinState apply: the
    plane dtype must cover pane SUMS (bounded by total mass), so restored
    mass at or beyond the promotion threshold restores straight into i64
    (fire_panes may run before any update(), where promotion normally
    triggers)."""
    total = int(raw_counts.sum())
    return total, (np.int64 if total >= promote_at else np.int32)


def _prefetch_host(*arrays) -> None:
    """Start device->host copies for every array before any blocking
    ``np.asarray``: on a tunneled TPU each readback pays a fixed ~70 ms
    round-trip, so N sequential materializations cost N round-trips while
    prefetched ones overlap into ~one."""
    for a in arrays:
        start = getattr(a, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # pragma: no cover - non-committed arrays
                pass


# -- shared channel + directory semantics (single-device AND mesh state) -----
#
# The null-skipping accumulation rules and the host key directory are THE
# shared semantics between KeyedBinState and parallel/mesh_window's
# MeshKeyedBinState; they live here once so a fix cannot apply to one
# implementation and silently miss the other.


def build_channels(aggs: Tuple[AggSpec, ...]
                   ) -> Tuple[Tuple[str, ...], Dict[int, int]]:
    """(kernel channel kinds, visible-agg -> hidden-validity-channel map).

    One accumulation channel per visible agg (AVG accumulates as a sum),
    plus a hidden additive validity-count channel per column-reading agg
    so null (NaN) rows neither poison SUM/MIN/MAX nor inflate AVG's
    divisor (reference nulls-skipping semantics, aggregating_window.rs)."""
    ch_kinds: List[str] = []
    for a in aggs:
        ch_kinds.append("sum" if a.kind == AggKind.AVG else a.kind.value)
    valid_ch: Dict[int, int] = {}
    for i, a in enumerate(aggs):
        if a.column is not None and a.kind != AggKind.COUNT:
            valid_ch[i] = len(ch_kinds)
            ch_kinds.append("sum")
    return tuple(ch_kinds), valid_ch


def channel_input(aggs: Tuple[AggSpec, ...], ch_kinds: Tuple[str, ...],
                  valid_of: Dict[int, int], j: int,
                  agg_inputs: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """Per-row contribution of channel ``j`` with nulls (NaN) masked to the
    channel's identity so they are skipped, not aggregated.
    ``valid_of`` maps hidden channel index -> source visible agg index."""
    from ..formats import coerce_float

    src = valid_of.get(j)
    if src is not None:  # hidden validity count for agg `src`
        raw = coerce_float(agg_inputs[aggs[src].column], ACC_DTYPE)
        return (~np.isnan(raw)).astype(ACC_DTYPE)
    a = aggs[j]
    if a.column is None:
        return np.ones(n, dtype=ACC_DTYPE)
    raw = coerce_float(agg_inputs[a.column], ACC_DTYPE)
    ok = ~np.isnan(raw)
    if a.kind == AggKind.COUNT:  # COUNT(col) counts non-null rows
        return ok.astype(ACC_DTYPE)
    ident = _init_value(AggKind(ch_kinds[j]))
    return np.where(ok, raw, ACC_DTYPE(ident)).astype(ACC_DTYPE)


def channel_inits(ch_kinds: Tuple[str, ...]) -> np.ndarray:
    """Per-channel aggregation identity values ([n_ch]), carried
    inside canonical snapshots so topology-level merges can pad
    uncovered bin spans with the right identity (+inf for MIN, -inf for
    MAX) instead of 0 — a 0-pad makes a post-rescale MIN/MAX window
    wrongly emit 0 for bins one parent never held."""
    return np.array([_init_value(AggKind(k)) for k in ch_kinds],  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
                    dtype=ACC_DTYPE)


def preaggregate(kh: np.ndarray, bins: np.ndarray,
                 ch_kinds: Tuple[str, ...], vals: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Two-phase aggregation, local half: reduce rows with the same
    (key, bin) on the host BEFORE device dispatch (the reference's
    TumblingLocalAggregator, plan_graph.rs:71-83 / optimizations.rs:241-291
    — pre-aggregate without shuffle, then the global phase merges bins).

    Every channel kind is reducible (sum/count add, min/max reduce), so
    this is lossless; under hot-key skew it collapses a 64k-row batch to
    a few thousand (key, bin) cells — less scatter work AND a smaller
    host->device transfer.

    Returns (unique key hashes, bins, per-cell row counts, reduced
    channel values [n_ch, n_cells]); inputs must be live rows only.
    """
    order = np.lexsort((bins, kh))
    kh_s, bin_s = kh[order], bins[order]
    is_first = np.ones(len(kh_s), dtype=bool)
    is_first[1:] = (kh_s[1:] != kh_s[:-1]) | (bin_s[1:] != bin_s[:-1])
    starts = is_first.nonzero()[0]
    vals_s = vals[:, order]
    out = np.empty((len(ch_kinds), len(starts)), dtype=ACC_DTYPE)
    for j, kind in enumerate(ch_kinds):
        if kind == "min":
            out[j] = np.minimum.reduceat(vals_s[j], starts)
        elif kind == "max":
            out[j] = np.maximum.reduceat(vals_s[j], starts)
        else:  # sum / count channels are additive
            out[j] = np.add.reduceat(vals_s[j], starts)
    rowcnt = np.diff(np.append(starts, len(kh_s))).astype(ACC_DTYPE)
    return kh_s[starts], bin_s[starts], rowcnt, out


def update_coalescing_enabled() -> bool:
    """``ARROYO_UPDATE_COALESCE=0`` dispatches every batch's scatter
    immediately (the pre-deferral behavior, bit-for-bit).  Read per
    call so tests can toggle without rebuilding state."""
    return os.environ.get("ARROYO_UPDATE_COALESCE", "1") not in (
        "0", "off", "false")


def _flush_cell_bound() -> int:
    """Pending-cell count above which buffered updates flush even
    without a reader (bounds host memory and scatter size)."""
    return int(os.environ.get("ARROYO_UPDATE_FLUSH_CELLS", 65536))


def _merge_cells(slots: np.ndarray, bins: np.ndarray, rowcnt: np.ndarray,
                 vals: np.ndarray, ch_kinds: Tuple[str, ...]
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Reduce duplicate (slot, bin) cells across buffered batch runs —
    the cross-batch half of :func:`preaggregate`: value channels reduce
    by their kind, row counts add.  Keeps the flushed scatter no larger
    than the live cell set."""
    order = np.lexsort((bins, slots))
    s, b = slots[order], bins[order]
    is_first = np.ones(len(s), dtype=bool)
    is_first[1:] = (s[1:] != s[:-1]) | (b[1:] != b[:-1])
    starts = is_first.nonzero()[0]
    if len(starts) == len(s):
        return slots, bins, rowcnt, vals  # already unique
    v = vals[:, order]
    out = np.empty((vals.shape[0], len(starts)), dtype=ACC_DTYPE)
    for j, kind in enumerate(ch_kinds):
        if kind == "min":
            out[j] = np.minimum.reduceat(v[j], starts)
        elif kind == "max":
            out[j] = np.maximum.reduceat(v[j], starts)
        else:  # sum / count channels are additive
            out[j] = np.add.reduceat(v[j], starts)
    rc = np.add.reduceat(rowcnt[order], starts)
    return s[starts], b[starts], rc, out


def directory_insert(state, kh: np.ndarray, ensure_capacity) -> np.ndarray:
    """Vectorized key-hash -> slot lookup over the host directory attrs
    (``key_sorted``, ``slot_of_sorted``, ``next_slot``, ``slot_to_key``),
    inserting unknown keys.  ``ensure_capacity(total_slots, new_keys)`` is
    the growth hook (device-array growth for KeyedBinState, shard-count
    accounting + device growth for the mesh state).

    Fast path: when the state carries a native C++ hash directory
    (``state._ndir``), the per-row lookup is one O(n) linear-probe pass;
    the sorted arrays are still maintained (from the much smaller new-key
    set) because checkpointing and emission-time lookups read them."""
    ndir = getattr(state, "_ndir", None)
    if ndir is not None:
        slots, new_keys = ndir.insert(kh, state.next_slot)
        _append_new_keys(state, new_keys, ensure_capacity)
        return slots
    uniq = np.unique(kh)
    pos = np.searchsorted(state.key_sorted, uniq)
    pos_c = np.minimum(pos, max(len(state.key_sorted) - 1, 0))
    known = (len(state.key_sorted) > 0) & (
        state.key_sorted[pos_c] == uniq if len(state.key_sorted) else
        np.zeros(len(uniq), dtype=bool))
    new_keys = uniq[~known] if len(state.key_sorted) else uniq
    _append_new_keys(state, new_keys, ensure_capacity)
    idx = np.searchsorted(state.key_sorted, kh)
    return state.slot_of_sorted[idx]


def _append_new_keys(state, new_keys: np.ndarray, ensure_capacity) -> None:
    """Register new keys: sequential slots from ``next_slot`` (the order
    the native dir already assigned), slot_to_key update, and sorted-array
    merge.  Shared by the native and numpy directory paths so the
    checkpointable arrays stay bit-identical between builds."""
    if not len(new_keys):
        return
    n_new = len(new_keys)
    ensure_capacity(state.next_slot + n_new, new_keys)
    new_slots = np.arange(state.next_slot, state.next_slot + n_new)
    state.slot_to_key[new_slots] = new_keys
    state.next_slot += n_new
    merged = np.concatenate([state.key_sorted, new_keys])
    merged_slots = np.concatenate([state.slot_of_sorted, new_slots])
    order = np.argsort(merged, kind="stable")
    state.key_sorted = merged[order]
    state.slot_of_sorted = merged_slots[order]


class KeyedBinState:
    """Sharded keyed bin-ring aggregation state for one subtask."""

    # rows after which the i32 counts plane could wrap (class attr so
    # tests can exercise the promotion without 2^31 rows)
    _i32_promote = 2**31 - 1

    def __init__(self, aggs: Tuple[AggSpec, ...], slide_micros: int,
                 width_micros: int, capacity: int = 0):
        if capacity <= 0:
            # pre-size from config: capacity growth doubles the arrays and
            # recompiles the kernels, so starting near the expected key
            # cardinality avoids O(log C) recompile stalls mid-stream
            from ..config import config

            capacity = config().state_capacity
        assert width_micros % slide_micros == 0, (
            "window width must be a multiple of slide")
        self.aggs = aggs
        self.kinds = tuple(a.kind.value for a in aggs)
        self._ch_kinds, self._valid_ch = build_channels(aggs)
        self._valid_of = {v: k for k, v in self._valid_ch.items()}
        # COUNT(*) channels accumulate exactly the per-cell row count that
        # the i32 counts plane already holds — they never ride a tunnel
        # transfer: updates reconstruct them on device from the rowcount
        # row, emission reads them from the counts output (state still
        # carries them so canonical snapshots stay topology-portable)
        self._dup_ch = tuple(i for i, a in enumerate(aggs)
                             if a.kind == AggKind.COUNT and a.column is None)
        dup_set = frozenset(self._dup_ch)
        self._xfer_ch = tuple(j for j in range(len(self._ch_kinds))
                              if j not in dup_set)
        self._xfer_pos = {j: r for r, j in enumerate(self._xfer_ch)}
        self.slide = slide_micros
        self.W = width_micros // slide_micros  # bins per window
        # ring must hold all open bins: W for the widest window plus headroom
        # for out-of-order arrivals ahead of the watermark
        self.B = _bucket(2 * self.W + 4, floor=8)
        self.C = _bucket(capacity)

        self.key_sorted = np.zeros(0, dtype=np.uint64)  # sorted known hashes
        self.slot_of_sorted = np.zeros(0, dtype=np.int64)
        self.next_slot = 0
        self.slot_to_key = np.zeros(self.C, dtype=np.uint64)
        from ..native import NativeDir

        self._ndir = NativeDir.create(self.C)

        self.values = jnp.zeros((len(self._ch_kinds), self.C, self.B),
                                dtype=jnp.float64)
        for j, kind in enumerate(self._ch_kinds):
            iv = _init_value(AggKind(kind))
            if iv != 0.0:
                self.values = self.values.at[j].set(iv)
        self.counts = jnp.zeros((self.C, self.B), dtype=jnp.int32)

        self.min_bin: Optional[int] = None  # oldest retained absolute bin
        self.max_bin: Optional[int] = None
        self.last_fired_pane: Optional[int] = None
        # rows ever accumulated into the counts plane: any cell or pane
        # sum is bounded by it, so while it stays below 2^31 the i32 plane
        # (and the COUNT(*) outputs read from it) cannot wrap — once it
        # could, update() promotes the plane to i64 (one recompile)
        self.total_rows = 0
        # observed live-cell fraction of the last fire's pane grid (None
        # until a fire happens); drives the compact-emission prediction
        self._fire_density: Optional[float] = None
        # set via set_argmax_local: emission keeps only local per-pane
        # argmax candidates (planner-proven sole consumer settles the
        # global answer); only COUNT(*) values qualify (see planner)
        self._argmax_local: Optional[str] = None  # 'max' | 'min'
        # per-ABSOLUTE-bin upper bound on any (key, bin) cell count (each
        # touched bin accrues the batch's largest pre-aggregated cell;
        # evicted bins drop out).  The max sliding-window sum over W bins
        # bounds any pane sum, proving when the emit count grid can ride
        # the tunnel as u16 instead of i32 — per-bin (vs one monotone
        # scalar) keeps the proof live on long-running streams
        self._bin_bound: Dict[int, int] = {}
        # update coalescing (ARROYO_UPDATE_COALESCE): per-batch
        # pre-aggregated cell runs buffer HERE and flush to the device in
        # one merged scatter when a reader needs the planes (pane fire,
        # snapshot, ring relayout) or the buffer crosses
        # ARROYO_UPDATE_FLUSH_CELLS — one dispatch + one h2d transfer
        # amortizes across many batches (the dominant per-batch device
        # cost once the ingest spine killed the expression dispatches)
        self._pending: List[Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray]] = []
        self._pending_cells = 0
        # merge-input mode (factor windows, graph/factor_windows.py):
        # channel j reads an ALREADY-AGGREGATED per-pane partial column
        # instead of deriving its contribution from raw rows, and the
        # counts plane accumulates the per-pane row-mass column — the
        # derived-window ring is then bit-compatible with the ring the
        # unfactored member would have built from the same rows
        self._merge_cols: Optional[Dict[int, str]] = None
        self._rows_col: Optional[str] = None

    # -- key directory -----------------------------------------------------

    def _lookup_or_insert(self, kh: np.ndarray) -> np.ndarray:
        """Vectorized key hash -> slot id, inserting unknown keys."""
        def ensure(total, _new_keys):
            if total > self.C:
                self._grow(total)

        return directory_insert(self, kh, ensure)

    def _grow(self, needed: int) -> None:
        newC = self.C
        while newC < needed:
            newC <<= 1
        pad = newC - self.C
        self.values = jnp.concatenate([
            self.values,
            jnp.stack([jnp.full((pad, self.B),
                                _init_value(AggKind(kind)), jnp.float64)
                       for kind in self._ch_kinds]) if self._ch_kinds else
            jnp.zeros((0, pad, self.B), jnp.float64)], axis=1)
        self.counts = jnp.concatenate(
            [self.counts, jnp.zeros((pad, self.B), self.counts.dtype)],
            axis=0)
        self.slot_to_key = np.concatenate(
            [self.slot_to_key, np.zeros(pad, dtype=np.uint64)])
        self.C = newC

    # -- update ------------------------------------------------------------

    def set_merge_inputs(self, channel_cols: Dict[int, str],
                         rows_col: str) -> None:
        """Arm merge-input mode (must run before any row lands): channel
        ``j`` reads ``channel_cols[j]`` — a per-(key, pane) partial of
        its own kind (NaN = pane had no contributing rows, masked to the
        channel identity) — and the per-cell row count accumulates
        ``rows_col`` so COUNT(*) dup channels and the u16-proof bounds
        stay exact row masses, not pane-arrival counts."""
        assert self.next_slot == 0 and self.total_rows == 0, \
            "merge inputs must be set before any key is admitted"
        for j in self._xfer_ch:
            assert j in channel_cols, f"no merge column for channel {j}"
        self._merge_cols = dict(channel_cols)
        self._rows_col = rows_col

    def update(self, key_hash: np.ndarray, timestamps: np.ndarray,
               agg_inputs: Dict[str, np.ndarray]) -> None:
        n = len(key_hash)
        if n == 0:
            return
        # the factor-window cost claim, made measurable: rows entering
        # pane-update state per event is ~K unfactored (every ring sees
        # every event) vs ~1 + O(panes) factored (derived rings see only
        # fired pane cells) — the correlated_windows bench reads these
        from ..obs import perf

        perf.count("pane_update_rows", n)
        if self._merge_cols is not None:
            self._update_merged(key_hash, timestamps, agg_inputs)
            return
        admitted = self._admit_bins(timestamps)
        if admitted is None:
            return
        bins_mod, live, n_live, lo, hi = admitted
        self._note_mass(int(n_live))

        slots = self._lookup_or_insert(key_hash)

        # two-phase, local half: reduce rows per (slot, bin) on the host
        # before any device work (TumblingLocalAggregator analog) — under
        # hot-key skew this collapses the batch by orders of magnitude
        # COUNT(*) channels are reconstructed from the rowcount on device;
        # only the remaining channels are materialized, pre-aggregated, and
        # shipped (for a bare COUNT(*) query the f64 pack shrinks to the
        # rowcount row alone — half the h2d bytes per batch)
        xfer = self._xfer_ch
        xfer_kinds = tuple(self._ch_kinds[j] for j in xfer)
        vals = np.empty((len(xfer), n), dtype=ACC_DTYPE)
        for r, j in enumerate(xfer):
            vals[r] = self._channel_input(j, agg_inputs, n)
        from ..native import HAVE_NATIVE, agg_cells

        if HAVE_NATIVE:
            # one O(n) native hash pass (liveness filter folded in)
            slots_c, bins_c, rowcnt, vals_c = agg_cells(
                slots, bins_mod, None if live.all() else live,
                self.B, vals, xfer_kinds)
        else:
            if not live.all():
                idx = live.nonzero()[0]
                slots, bins_mod, vals = \
                    slots[idx], bins_mod[idx], vals[:, idx]
            slots_c, bins_c, rowcnt, vals_c = preaggregate(
                slots, bins_mod, xfer_kinds, vals)
        self._enqueue_cells(slots_c, bins_c, rowcnt, vals_c, lo, hi)

    def _admit_bins(self, timestamps: np.ndarray
                    ) -> Optional[Tuple[np.ndarray, np.ndarray, int,
                                        int, int]]:
        """Shared update prologue (raw AND merge-input paths): a row in
        bin b feeds panes b..b+W-1 and is late (dropped) only when all
        those panes already fired — the reference's drop-behind-watermark
        semantics.  Bin assignment + liveness + min/max run as one
        native pass; returns (bins_mod, live, n_live, lo, hi), or None
        when nothing is live."""
        from ..native import assign_bins

        threshold = (self.last_fired_pane - self.W + 2
                     if self.last_fired_pane is not None else None)
        bins_mod, live, n_live, lo, hi = assign_bins(
            timestamps, self.slide, self.B, threshold)
        if n_live == 0:
            return None
        lo_new = lo if self.min_bin is None else min(self.min_bin, lo)
        hi_new = hi if self.max_bin is None else max(self.max_bin, hi)
        # ring capacity check BEFORE extending min/max: _grow_ring copies
        # the ring span [min_bin, max_bin] into the wider ring, so the
        # bounds must still describe what the OLD ring actually holds —
        # growing after extending them replicated old slots into the
        # about-to-be-written range (ghost duplicates under far-apart
        # sources, e.g. two impulse splits with staggered time bases)
        if hi_new - lo_new >= self.B:
            self._grow_ring(hi_new - lo_new + 1)
            bins_mod = ((timestamps // self.slide) % self.B).astype(np.int32)
        self.min_bin = lo_new
        self.max_bin = hi_new
        return bins_mod, live, n_live, lo, hi

    def _note_mass(self, mass: int) -> None:
        """Count accumulated row mass; the next accumulation could wrap
        an i32 cell or pane sum once the total crosses the promotion
        threshold, so promote BEFORE it lands (kernels retrace on the
        new dtype).  Shared by both update paths."""
        self.total_rows += mass
        if (self.total_rows >= self._i32_promote
                and self.counts.dtype == jnp.int32):
            self.counts = self.counts.astype(jnp.int64)

    def _enqueue_cells(self, slots_c: np.ndarray, bins_c: np.ndarray,
                       rowcnt: np.ndarray, vals_c: np.ndarray,
                       lo: int, hi: int) -> None:
        """Shared update tail: u16-proof bin bounds, then either buffer
        the pre-aggregated cell run (update coalescing — one merged
        scatter carries many batches; the planes are only read at pane
        fires / snapshots, and every reader flushes) or dispatch now."""
        m = len(slots_c)
        if m:
            # coarse but sound: every bin this batch touched could have
            # grown by at most the batch's largest cell mass
            bmax = int(np.ceil(rowcnt.max()))
            for b in range(lo, hi + 1):
                self._bin_bound[b] = self._bin_bound.get(b, 0) + bmax
        if update_coalescing_enabled():
            self._pending.append((slots_c, bins_c, rowcnt, vals_c))
            self._pending_cells += m
            if self._pending_cells >= _flush_cell_bound():
                self.flush_updates()
            return
        self._dispatch_cells(slots_c, bins_c, rowcnt, vals_c)

    def _update_merged(self, key_hash: np.ndarray, timestamps: np.ndarray,
                       agg_inputs: Dict[str, np.ndarray]) -> None:
        """Merge-input update (derived windows): inputs are fired factor
        panes, one row per (key, pane) — channel values come straight
        from the mapped partial columns (their kinds reduce partial →
        partial losslessly) and the per-cell rowcount is the SUM of the
        pane row-mass column, so the resulting ring is the one the
        unfactored member would hold after the same raw rows."""
        n = len(key_hash)
        from ..formats import coerce_float

        admitted = self._admit_bins(timestamps)
        if admitted is None:
            return
        bins_mod, live, _n_live, lo, hi = admitted
        w = coerce_float(agg_inputs[self._rows_col], ACC_DTYPE)
        w = np.where(np.isnan(w), 0.0, w)
        self._note_mass(int(np.ceil(w[live].sum())))

        slots = self._lookup_or_insert(key_hash)

        xfer = self._xfer_ch
        xfer_kinds = tuple(self._ch_kinds[j] for j in xfer)
        vals = np.empty((len(xfer), n), dtype=ACC_DTYPE)
        for r, j in enumerate(xfer):
            raw = coerce_float(agg_inputs[self._merge_cols[j]], ACC_DTYPE)
            ident = ACC_DTYPE(_init_value(AggKind(self._ch_kinds[j])))
            vals[r] = np.where(np.isnan(raw), ident, raw)
        if not live.all():
            idx = live.nonzero()[0]
            slots, bins_mod = slots[idx], bins_mod[idx]
            vals, w = vals[:, idx], w[idx]
        # the row mass rides the cell reduction as one extra additive
        # channel so duplicate (slot, bin) cells sum their masses —
        # preaggregate's own rowcnt would count PANE ARRIVALS, which
        # COUNT(*) outputs and the u16 proof must never see
        ext_kinds = xfer_kinds + ("sum",)
        slots_c, bins_c, _arrivals, red = preaggregate(
            slots, bins_mod, ext_kinds, np.concatenate([vals, w[None]]))
        rowcnt = red[-1]
        vals_c = red[:-1]
        self._enqueue_cells(slots_c, bins_c, rowcnt, vals_c, lo, hi)

    def flush_updates(self) -> None:
        """Apply every buffered pre-aggregated cell run to the device
        planes in ONE scatter dispatch.  Called by every plane reader
        (fire_panes, snapshot, ring relayout) and when the buffer
        crosses the cell bound, so deferral is invisible to emission,
        checkpoint and rescale semantics."""
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        self._pending_cells = 0
        if len(pend) == 1:
            slots_c, bins_c, rowcnt, vals_c = pend[0]
        else:
            xfer_kinds = tuple(self._ch_kinds[j] for j in self._xfer_ch)
            slots_c, bins_c, rowcnt, vals_c = _merge_cells(
                np.concatenate([p[0] for p in pend]),
                np.concatenate([p[1] for p in pend]),
                np.concatenate([p[2] for p in pend]),
                np.concatenate([p[3] for p in pend], axis=1), xfer_kinds)
        self._dispatch_cells(slots_c, bins_c, rowcnt, vals_c)

    def _dispatch_cells(self, slots_c: np.ndarray, bins_c: np.ndarray,
                        rowcnt: np.ndarray, vals_c: np.ndarray) -> None:
        from ..obs import perf

        perf.count("pane_update_dispatches")
        # additive aggregates route through the Pallas MXU scatter (one-hot
        # matmul) instead of XLA's serial scatter; min/max stay on XLA
        if self._use_pallas():
            self._update_pallas(slots_c, bins_c, rowcnt, vals_c)
            return

        m = len(slots_c)
        npad = _bucket(m, floor=256)
        idx = np.zeros((2, npad), dtype=np.int32)
        idx[0, :m] = slots_c
        idx[1, :m] = bins_c
        packed = np.zeros((len(self._xfer_ch) + 1, npad), dtype=ACC_DTYPE)
        packed[0, :m] = rowcnt
        packed[1:, :m] = vals_c

        from ..obs.perf import timed_device

        kernel = _update_kernel(self._ch_kinds, self.C, self.B, npad,
                                self._dup_ch)
        self.values, self.counts = timed_device(
            kernel, self.values, self.counts, jnp.asarray(idx),
            jnp.asarray(packed))

    def _channel_input(self, j: int, agg_inputs: Dict[str, np.ndarray],
                       n: int) -> np.ndarray:
        return channel_input(self.aggs, self._ch_kinds, self._valid_of, j,
                             agg_inputs, n)

    def _use_pallas(self) -> bool:
        from .pallas_kernels import LANES, pallas_enabled

        if not pallas_enabled():
            return False
        if not all(k in ("sum", "avg", "count") for k in self._ch_kinds):
            return False
        if self.counts.dtype != jnp.int32:
            return False  # promoted i64 plane: the Pallas kernel is f32-pair
        # packed width P = 2 channels (hi/lo) x (channels + count) x B lanes;
        # the kernel holds [CHUNK, P] + [TILE_C, P] f32 blocks in VMEM, so
        # wide rings (long window / short slide) must fall back to XLA
        P = 2 * (len(self._ch_kinds) + 1) * self.B
        return ((P + LANES - 1) // LANES) * LANES <= 1024

    def _update_pallas(self, slots_c: np.ndarray, bins_c: np.ndarray,
                       rowcnt: np.ndarray, vals_c: np.ndarray) -> None:
        from .pallas_kernels import (active_capacity, pad_batch,
                                     update_bin_state)

        # pre-aggregated cells: counts channel carries the per-cell row
        # count (the kernel sums weight channels, so this is exact).
        # vals_c holds transferred channels only — COUNT(*) rows are the
        # rowcount itself
        if self._dup_ch:
            full = np.empty((len(self._ch_kinds), len(rowcnt)),
                            dtype=ACC_DTYPE)
            for r, j in enumerate(self._xfer_ch):
                full[j] = vals_c[r]
            for j in self._dup_ch:
                full[j] = rowcnt
            vals_c = full
        weights = np.concatenate([rowcnt[None], vals_c], axis=0)
        s, b, w = pad_batch(slots_c.astype(np.int32), bins_c, weights)
        c_act = active_capacity(self.next_slot, self.C)
        self.values, self.counts = update_bin_state(
            self.values, self.counts, s, b, w, c_act, self.B)

    def _grow_ring(self, needed: int) -> None:
        """Rare: data spans more bins than the ring; re-layout host-side."""
        # buffered cell runs carry ring indices mod the OLD B — they must
        # land before the ring re-layout redefines the modulus
        self.flush_updates()
        newB = self.B
        while newB < needed:
            newB <<= 1
        vals = np.asarray(self.values)  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
        cnts = np.asarray(self.counts)  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
        new_vals = np.zeros((len(self._ch_kinds), self.C, newB),
                            dtype=ACC_DTYPE)
        for j, kind in enumerate(self._ch_kinds):
            new_vals[j] = _init_value(AggKind(kind))
        new_cnts = np.zeros((self.C, newB), dtype=cnts.dtype)
        if self.min_bin is not None and self.max_bin is not None:
            for ab in range(self.min_bin, self.max_bin + 1):
                new_vals[:, :, ab % newB] = vals[:, :, ab % self.B]
                new_cnts[:, ab % newB] = cnts[:, ab % self.B]
        self.values = jnp.asarray(new_vals)
        self.counts = jnp.asarray(new_cnts)
        self.B = newB

    # -- pane emission ------------------------------------------------------

    def _use_ring(self) -> bool:
        """Select bin-dimension ring-parallel emission (SURVEY §5
        sequence-parallel discipline) for long windows: the [C, k, W]
        pane gather materializes W copies of the state, while the ring
        path does one linear gather plus a cumulative sweep with
        ``ppermute`` halos — worthwhile once W is large (long window /
        short slide) and there is a mesh to shard bins over."""
        import os

        mode = os.environ.get("ARROYO_RING", "auto")
        if mode == "off":
            return False
        if mode == "on":
            return True
        w_min = int(os.environ.get("ARROYO_RING_MIN_W", 64))
        return self.W >= w_min and len(jax.devices()) > 1

    def _pane_bound(self, first_pane: int, last_pane: int) -> int:
        """Largest provable pane sum over the firing range: max sliding
        W-sum of the per-bin cell bounds.  Sound by construction — every
        pane's true count is at most the sum of its bins' bounds."""
        W = self.W
        span = last_pane - first_pane + 1
        if span + W > 100_000:  # degenerate range: don't scan, stay i32
            return 1 << 40
        lo_b = first_pane - W + 1
        n = last_pane - lo_b + 1
        arr = np.fromiter((self._bin_bound.get(b, 0)
                           for b in range(lo_b, last_pane + 1)),
                          dtype=np.int64, count=n)
        c = np.concatenate([[0], np.cumsum(arr)])
        sums = c[W:] - c[:-W]  # sums[i] covers bins [first_pane+i-W+1, ..]
        return int(sums.max()) if len(sums) else 0

    def set_argmax_local(self, agg_out: str, minmax: str) -> None:
        """Enable candidate-only emission for the given COUNT(*) agg
        (the value IS the counts plane — enforced here, not just by the
        planner: a non-count target would silently rank by row counts)."""
        target = next((i for i, a in enumerate(self.aggs)
                       if a.output == agg_out), None)
        assert target is not None and target in self._dup_ch, (
            f"argmax_local target {agg_out!r} is not a bare COUNT(*) "
            f"aggregate of this state")
        assert minmax in ("max", "min"), minmax
        self._argmax_local = minmax

    def _emit_argmax(self, ring: np.ndarray, bin_ok: np.ndarray, kpad: int
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]:
        """Candidate-only emission: (key_idx, pane_idx, counts, empty
        channel block) for cells at their pane's count extremum — on a
        tunneled TPU this is the ~1000x transfer cut (ties-per-pane
        instead of every (key, pane) cell)."""
        from ..obs.perf import timed_device

        ring_j = jnp.asarray(ring)
        ok_j = jnp.asarray(bin_ok)
        nk = _argmax_nnz_kernel(self.C, self.B, self.W, kpad,
                                self._argmax_local)
        cnt_dev, sel_dev, nnz_dev = timed_device(
            nk, self.counts, ring_j, ok_j)
        nnz = int(nnz_dev)  # the only blocking scalar readback
        if nnz == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int64),
                    np.zeros((len(self._xfer_ch), 0)))
        npad = _bucket(nnz, floor=8)
        gk = _argmax_gather_kernel(self.C, self.B, self.W, kpad, npad)
        idx2_d, cnt_d = timed_device(gk, cnt_dev, sel_dev)
        _prefetch_host(idx2_d, cnt_d)
        idx2 = np.asarray(idx2_d)  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
        return (idx2[0, :nnz].astype(np.int64),
                idx2[1, :nnz].astype(np.int64),
                np.asarray(cnt_d)[:nnz],  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
                np.zeros((len(self._xfer_ch), nnz)))

    def _use_compact_emit(self, c_slice: int, k: int) -> bool:
        """Two-phase compacted emission: worth one extra (4-byte) scalar
        round-trip only when fires are SPARSE (keys active inside one
        window span vs every key ever seen).  ``auto`` predicts from the
        last observed fire density — nexmark q5 measures density 1.0
        (every auction bids in every window), where compaction is
        strictly worse; long-window/churning-key shapes measure a few
        percent, where it wins by that ratio."""
        import os

        mode = os.environ.get("ARROYO_EMIT_COMPACT", "auto")
        if mode == "off":
            return False
        if mode == "on":
            return True
        if self._fire_density is None:
            return False  # no evidence yet: dense is the safe default
        itemsize = self.counts.dtype.itemsize
        row_bytes = 8 + itemsize + 8 * len(self._xfer_ch)  # idx2+cnt+chans
        compact_bytes = self._fire_density * self.next_slot * k * row_bytes
        dense_bytes = (8 * len(self._xfer_ch) + itemsize) * c_slice * k
        # margin stands in for the extra scalar round-trip + gather pass
        margin = int(os.environ.get("ARROYO_EMIT_COMPACT_MARGIN",
                                    256 * 1024))
        return compact_bytes + margin < dense_bytes

    def _emit_compact(self, ring: np.ndarray, bin_ok: np.ndarray, kpad: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
        """(key_idx, pane_idx, counts, channel values [n_xfer, m]) for the
        live cells only, compacted on device (row-major order — identical
        to the dense path's np.nonzero order)."""
        from ..obs.perf import timed_device

        ring_j = jnp.asarray(ring)
        ok_j = jnp.asarray(bin_ok)
        ck = _emit_count_kernel(self.C, self.B, self.W, kpad)
        cnt_dev, nnz_dev = timed_device(ck, self.counts, ring_j, ok_j)
        nnz = int(nnz_dev)  # the only blocking readback: one scalar
        if nnz == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int64),
                    np.zeros((len(self._xfer_ch), 0)))
        npad = _bucket(nnz, floor=256)
        gk = _emit_compact_kernel(self._ch_kinds, self.C, self.B, self.W,
                                  kpad, self._xfer_ch, npad)
        idx2_d, cnt_d, ch_d = timed_device(gk, self.values, cnt_dev,
                                           ring_j, ok_j)
        _prefetch_host(idx2_d, cnt_d, ch_d)
        idx2 = np.asarray(idx2_d)  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
        return (idx2[0, :nnz].astype(np.int64),
                idx2[1, :nnz].astype(np.int64),
                np.asarray(cnt_d)[:nnz], np.asarray(ch_d)[:, :nnz])  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design

    def _ring_shards(self) -> int:
        nk = 1
        while nk * 2 <= len(jax.devices()):
            nk *= 2
        return nk

    def _emit_ring(self, pane_ends: np.ndarray, k: int):
        """Pane aggregates for the contiguous ``pane_ends`` range via the
        bin-sharded ring kernel (parallel/ring_panes.py): linearize the
        span once, then one trailing-W sweep per channel."""
        from ..obs.perf import timed_device
        from ..parallel.ring_panes import _ring_step_2d

        nk = self._ring_shards()
        a_lo = self.min_bin if self.min_bin is not None else 0
        a_hi = int(pane_ends[-1])
        L0 = a_hi - a_lo + 1
        L = max(-(-L0 // nk) * nk, nk)
        padl = L - L0
        abs_bins = np.arange(a_lo - padl, a_hi + 1, dtype=np.int64)
        ok = (abs_bins >= a_lo) & (abs_bins <= self.max_bin)
        ring_idx = (abs_bins % self.B).astype(np.int32)
        lin = _linearize_kernel(self._ch_kinds, self.C, self.B, L)
        g, cg = timed_device(lin, self.values, self.counts,
                             jnp.asarray(ring_idx), jnp.asarray(ok))
        # dispatch every channel sweep, then materialize: the transfers
        # overlap instead of each paying its own tunnel round-trip.
        # Channel set matches _emit_kernel's ``keep`` (COUNT(*) channels
        # come from the count sweep, which rides as i32)
        devs = []
        for i in self._xfer_ch:
            fn, sharding = _ring_step_2d(self._ch_kinds[i], nk, self.C,
                                         L // nk, self.W)
            out = timed_device(fn, jax.device_put(g[i], sharding))
            devs.append(out[:, -k:])  # slice on device: transfer k panes
        fn, sharding = _ring_step_2d("count", nk, self.C, L // nk, self.W)
        cdev = timed_device(fn, jax.device_put(cg.astype(jnp.float64),
                                               sharding))[:, -k:]
        _prefetch_host(*devs, cdev)
        outs = [np.asarray(d) for d in devs]  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
        # match the plane dtype: a promoted i64 plane can hold pane sums
        # beyond i32 (the sweep itself is exact in f64 to 2^53)
        cnt_np = (np.int64 if self.counts.dtype == jnp.int64 else np.int32)
        cnts = np.asarray(cdev).astype(cnt_np)  # arroyolint: disable=host-sync -- intentional canonical-snapshot/ring-relayout readback: rescale merges and ring growth operate on host copies by design
        return (np.stack(outs) if outs else
                np.zeros((0, self.C, k))), cnts

    def fire_panes(self, watermark: int, final: bool = False
                   ) -> Optional[Tuple[np.ndarray, Dict[str, np.ndarray],
                                       np.ndarray, np.ndarray]]:
        """Emit all panes whose window end <= watermark.

        Pane with absolute end-bin e covers bins (e-W, e]; its window end time
        is (e+1)*slide.  Returns (keys, {agg_output: values}, window_end,
        counts) flattened over (pane, key-with-data), or None.
        """
        if self.max_bin is None or self.next_slot == 0:
            return None
        if final:
            # flush every window containing data: the last data bin feeds
            # panes up to max_bin + W - 1
            last_pane = self.max_bin + self.W - 1
        else:
            last_pane = min(int(watermark // self.slide) - 1, self.max_bin)
        first_pane = (self.last_fired_pane + 1
                      if self.last_fired_pane is not None
                      else (self.min_bin or 0))
        if last_pane < first_pane:
            return None
        # panes will actually fire: buffered batch updates must be in the
        # planes first (the early returns above keep no-op watermark
        # advances from forcing a flush per batch)
        self.flush_updates()
        pane_ends = np.arange(first_pane, last_pane + 1, dtype=np.int64)
        k = len(pane_ends)
        kpad = _bucket(k, floor=1)
        # host-side 64-bit bin arithmetic -> small int32 ring indices for jit
        offs = np.arange(self.W, dtype=np.int64) - (self.W - 1)
        abs_bins = pane_ends[:, None] + offs[None, :]  # [k, W] int64
        ring = np.zeros((kpad, self.W), dtype=np.int32)
        ring[:k] = (abs_bins % self.B).astype(np.int32)
        bin_ok = np.zeros((kpad, self.W), dtype=bool)
        # only bins in [min_bin, max_bin] are live in the ring; anything
        # outside is either evicted/dropped or never written (and its ring
        # slot may alias a live bin)
        lo = self.min_bin if self.min_bin is not None else 0
        bin_ok[:k] = (abs_bins >= lo) & (abs_bins <= self.max_bin)

        from ..obs.perf import timed_device

        # transfer only the occupied key rows, not all C slots.  2048-row
        # granularity: finer than pow2 buckets (pow2 wastes up to 50% of a
        # remote-tunnel transfer) while bounding the compile-variant count;
        # the persistent compile cache amortizes each variant to one compile
        c_slice = self._c_slice()
        compact = None
        use_ring = self._use_ring()
        if use_ring:
            outs, cnts = self._emit_ring(pane_ends, k)
        elif self._argmax_local is not None and not self._xfer_ch:
            # candidate-only emission: every output column derives from
            # the counts plane (bare COUNT(*) aggs), so nothing else
            # needs to ride the transfer; with f64 channels present the
            # normal paths run and the downstream argmax stage filters
            compact = self._emit_argmax(ring, bin_ok, kpad)
        elif self._use_compact_emit(c_slice, k):
            compact = self._emit_compact(ring, bin_ok, kpad)
        else:
            # pane sums provably fit u16 -> halve the dominant transfer
            cnt16 = (self.counts.dtype == jnp.int32
                     and self._pane_bound(first_pane, last_pane) < 65_000)
            outs, cnts = self._read_dense(ring, bin_ok, kpad, k, self.W,
                                          cnt16)

        self.last_fired_pane = last_pane
        # evict bins that no future pane needs: abs bins <= last_pane - W + 1
        new_min = last_pane - self.W + 2
        if self.min_bin is not None and new_min > self.min_bin:
            expired = np.arange(self.min_bin, min(new_min, self.max_bin + 1))
            if len(expired):
                epad = _bucket(len(expired), floor=8)
                ring = np.zeros(epad, dtype=np.int32)
                ring[:len(expired)] = expired % self.B
                ev = np.zeros(epad, dtype=bool)
                ev[:len(expired)] = True
                ek = _evict_kernel(self._ch_kinds, self.C, self.B)
                self.values, self.counts = ek(self.values, self.counts,
                                              jnp.asarray(ring), jnp.asarray(ev))
            self.min_bin = new_min
            # evicted bins leave the u16 proof, keeping it live on
            # long-running streams (the bound would otherwise only grow)
            self._bin_bound = {b: v for b, v in self._bin_bound.items()
                               if b >= new_min}

        # flatten (key, pane) pairs with data
        if compact is not None:
            key_idx, pane_idx, cnt_sel, ch_sel = compact
        else:
            key_idx, pane_idx, cnt_sel, ch_sel = self._flatten_dense(
                outs, cnts, k)
        self._fire_density = len(key_idx) / max(self.next_slot * k, 1)
        if len(key_idx) == 0:
            return None
        keys = self.slot_to_key[key_idx]
        window_end = (pane_ends[pane_idx] + 1) * self.slide
        return keys, self._out_cols(cnt_sel, ch_sel), window_end, cnt_sel

    def _c_slice(self) -> int:
        """Occupied-key transfer granularity (2048-row steps above the
        pow2 floor: finer than pow2 buckets — which waste up to 50% of a
        remote-tunnel transfer — while bounding compile variants)."""
        if self.next_slot <= 2048:
            return min(_bucket(max(self.next_slot, 1), floor=256), self.C)
        return min(-(-self.next_slot // 2048) * 2048, self.C)

    def _read_dense(self, ring: np.ndarray, bin_ok: np.ndarray, kpad: int,
                    k: int, W: int, cnt16: bool
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Dense emit-kernel read: dispatch, device-slice to occupied
        keys AND real panes (k, not the pow2-padded kpad — a 5-pane
        fire in an 8-pane kernel grid would ship 37% dead bytes), then
        overlap the round-trips.  ONE home for fire_panes and
        drain_deltas so a transfer/slicing fix cannot diverge."""
        from ..obs.perf import timed_device

        c_slice = self._c_slice()
        kernel = _emit_kernel(self._ch_kinds, self.C, self.B, W, kpad,
                              self._xfer_ch, cnt16)
        outs, cnts = timed_device(kernel, self.values, self.counts,
                                  jnp.asarray(ring), jnp.asarray(bin_ok))
        outs_d = outs[:, :c_slice, :k]  # [n_xfer, c_slice, k]
        cnts_d = cnts[:c_slice, :k]  # [c_slice, k]
        _prefetch_host(outs_d, cnts_d)
        outs = np.asarray(outs_d)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        cnts = np.asarray(cnts_d)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        return outs, cnts

    def _flatten_dense(self, outs: np.ndarray, cnts: np.ndarray, k: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray]:
        """(key_idx, pane_idx, counts, channel values) for the live
        cells of a dense read — shared fire/drain flatten."""
        C_used = self.next_slot
        cnts_u = cnts[:C_used, :k]
        key_idx, pane_idx = np.nonzero(cnts_u)
        cnt_sel = cnts_u[key_idx, pane_idx]
        ch_sel = outs[:, :C_used, :k][:, key_idx, pane_idx]
        return key_idx, pane_idx, cnt_sel, ch_sel

    def _out_cols(self, cnt_sel: np.ndarray, ch_sel: np.ndarray
                  ) -> Dict[str, np.ndarray]:
        """Visible aggregate columns from flattened fired cells (shared
        by fire_panes and drain_deltas so the two emission paths cannot
        diverge)."""
        out_cols: Dict[str, np.ndarray] = {}
        dup_set = frozenset(self._dup_ch)
        for i, a in enumerate(self.aggs):
            if i in dup_set:
                # COUNT(*): the counts plane IS the aggregate (integer
                # counts, no f64 channel ever crossed the tunnel)
                out_cols[a.output] = cnt_sel.astype(np.int64)
                continue
            col = ch_sel[self._xfer_pos[i]]
            if a.kind == AggKind.COUNT:
                col = col.astype(np.int64)
            elif i in self._valid_ch:
                # nulls-skipping semantics from the validity-count channel:
                # AVG divides by non-null rows; an all-null pane is NULL
                nv = ch_sel[self._xfer_pos[self._valid_ch[i]]]
                if a.kind == AggKind.AVG:
                    col = col / np.maximum(nv, 1)
                col = np.where(nv > 0, col, np.nan)
            out_cols[a.output] = col
        return out_cols

    def drain_deltas(self) -> Optional[Tuple[np.ndarray,
                                             Dict[str, np.ndarray],
                                             np.ndarray, np.ndarray]]:
        """Checkpoint-barrier drain for FACTOR pane rings (W == 1): read
        every un-fired (key, bin) cell as a pane DELTA and reset those
        cells to their channel identities — WITHOUT advancing
        ``last_fired_pane``/``min_bin``, so rows arriving after the
        drain re-accumulate in the same bins and ship as a later delta.
        Derived-window rings merge deltas losslessly (their channels
        reduce partial-into-partial), so the factor's own snapshot holds
        no un-shipped mass and factored checkpoints restore into
        unfactored plans epoch for epoch.  Same return shape as
        ``fire_panes``; None when nothing is pending."""
        assert self.W == 1, "drain_deltas is the factor-pane path (W == 1)"
        if self.max_bin is None or self.next_slot == 0:
            return None
        self.flush_updates()
        first_pane = (self.last_fired_pane + 1
                      if self.last_fired_pane is not None
                      else (self.min_bin or 0))
        last_pane = self.max_bin
        if last_pane < first_pane:
            return None
        pane_ends = np.arange(first_pane, last_pane + 1, dtype=np.int64)
        k = len(pane_ends)
        kpad = _bucket(k, floor=1)
        ring = np.zeros((kpad, 1), dtype=np.int32)
        ring[:k, 0] = (pane_ends % self.B).astype(np.int32)
        bin_ok = np.zeros((kpad, 1), dtype=bool)
        lo = self.min_bin if self.min_bin is not None else 0
        bin_ok[:k, 0] = (pane_ends >= lo) & (pane_ends <= self.max_bin)

        outs, cnts = self._read_dense(ring, bin_ok, kpad, k, 1, False)

        # reset the drained bins to identity; bookkeeping stays put
        drained = pane_ends[bin_ok[:k, 0]]
        if len(drained):
            epad = _bucket(len(drained), floor=8)
            rslots = np.zeros(epad, dtype=np.int32)
            rslots[:len(drained)] = (drained % self.B).astype(np.int32)
            ev = np.zeros(epad, dtype=bool)
            ev[:len(drained)] = True
            ek = _evict_kernel(self._ch_kinds, self.C, self.B)
            self.values, self.counts = ek(self.values, self.counts,
                                          jnp.asarray(rslots),
                                          jnp.asarray(ev))
            # drained cells are 0 again: their bounds restart from zero
            for b in drained.tolist():
                self._bin_bound.pop(int(b), None)

        key_idx, pane_idx, cnt_sel, ch_sel = self._flatten_dense(
            outs, cnts, k)
        if len(key_idx) == 0:
            return None
        keys = self.slot_to_key[key_idx]
        window_end = (pane_ends[pane_idx] + 1) * self.slide
        return keys, self._out_cols(cnt_sel, ch_sel), window_end, cnt_sel

    # -- checkpoint ---------------------------------------------------------
    #
    # Snapshots use the CANONICAL topology-independent bin-state format
    # shared with MeshKeyedBinState (parallel/mesh_window.py): compact
    # per-key LINEAR bin columns (column j = absolute bin lo+j) plus the
    # host key directory, so a checkpoint taken single-device restores
    # onto any mesh and vice versa (restore-time re-partitioning,
    # parquet.rs:194-218 analog).

    def device_bytes(self) -> int:
        """Resident device footprint of the bin planes (metadata-only:
        reads ``.nbytes`` off the array handles, no transfer) — feeds
        the per-job device-memory ledger (obs/latency.py)."""
        return int(self.values.nbytes) + int(self.counts.nbytes)

    def snapshot(self) -> Dict[str, np.ndarray]:
        self.flush_updates()  # buffered cells belong to this epoch
        n = self.next_slot
        _prefetch_host(self.values, self.counts)
        values = np.asarray(jax.device_get(self.values))
        counts = np.asarray(jax.device_get(self.counts))
        if self.min_bin is not None and self.max_bin is not None:
            lo = self.min_bin
            cols = (np.arange(lo, self.max_bin + 1) % self.B)
        else:
            lo = -1
            cols = np.zeros(0, dtype=np.int64)
        return {
            "bin_keys": self.slot_to_key[:n],
            "bin_vals": values[:, :n][:, :, cols],
            "bin_counts": counts[:n][:, cols],
            "ch_init": channel_inits(self._ch_kinds),
            "mesh_shards": np.array([1], dtype=np.int64),
            "key_sorted": self.key_sorted,
            "slot_of_sorted": self.slot_of_sorted,
            "slot_to_key": self.slot_to_key[:n],
            "meta": np.array([
                n, lo,  # lo == min_bin: first linear column's absolute bin
                -1 if self.max_bin is None else self.max_bin,
                -1 if self.last_fired_pane is None else self.last_fired_pane,
            ], dtype=np.int64),
        }

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        # buffered updates from a pre-restore life are void
        self._pending = []
        self._pending_cells = 0
        meta = arrays["meta"]
        self.next_slot = int(meta[0])
        lo = int(meta[1])
        self.max_bin = None if meta[2] < 0 else int(meta[2])
        self.last_fired_pane = None if meta[3] < 0 else int(meta[3])
        self.min_bin = None if lo < 0 else lo
        self.key_sorted = arrays["key_sorted"].astype(np.uint64)
        self.slot_of_sorted = arrays["slot_of_sorted"].astype(np.int64)
        from ..native import NativeDir

        self._ndir = NativeDir.create(max(self.next_slot, 8))
        if self._ndir is not None:
            self._ndir.load(self.key_sorted, self.slot_of_sorted)
        self.C = _bucket(max(self.next_slot, 8))
        self.slot_to_key = np.zeros(self.C, dtype=np.uint64)
        self.slot_to_key[:self.next_slot] = \
            arrays["slot_to_key"].astype(np.uint64)[:self.next_slot]

        bin_keys = arrays["bin_keys"].astype(np.uint64)
        bin_vals = np.asarray(arrays["bin_vals"], dtype=ACC_DTYPE)
        raw_counts = np.asarray(arrays["bin_counts"])
        self.total_rows, cnt_dtype = restored_count_state(
            raw_counts, self._i32_promote)
        bin_counts = raw_counts.astype(cnt_dtype)
        # the u16-downcast proof must survive restore: seed each restored
        # bin's bound from its largest restored cell so cnt16 never
        # "proves" a vacuous empty bound over non-empty state (review r4:
        # pane counts wrapped modulo 65536 after any checkpoint restore)
        self._bin_bound = {}
        if raw_counts.size and lo >= 0:
            col_max = raw_counts.max(axis=0)
            for j, bnd in enumerate(col_max.tolist()):
                if bnd > 0:
                    self._bin_bound[lo + j] = int(bnd)
        span = bin_vals.shape[-1]
        self.B = _bucket(max(span, 2 * self.W + 4), floor=8)
        values = np.zeros((len(self._ch_kinds), self.C, self.B), ACC_DTYPE)
        for j, k in enumerate(self._ch_kinds):
            values[j] = _init_value(AggKind(k))
        counts = np.zeros((self.C, self.B), cnt_dtype)
        if len(bin_keys) and span and lo >= 0:
            # bin rows land at their DIRECTORY slot (restores from a mesh
            # snapshot may order rows differently than this host's slots)
            idx = np.searchsorted(self.key_sorted, bin_keys)
            slots = self.slot_of_sorted[idx]
            cols = (np.arange(lo, lo + span) % self.B)
            values[:, slots[:, None], cols[None, :]] = bin_vals
            counts[slots[:, None], cols[None, :]] = bin_counts
        self.values = jnp.asarray(values)
        self.counts = jnp.asarray(counts)


def filter_canonical_snapshot(arrays: Dict[str, np.ndarray],
                              key_range: Tuple[int, int]
                              ) -> Dict[str, np.ndarray]:
    """Restrict a canonical bin-state snapshot (snapshot()/restore()
    format, incl. the operator's kv_* key-column arrays) to the keys a
    subtask OWNS under its key range.

    Restore-time re-partitioning (parquet.rs:194-218 analog): on a
    rescale every new subtask reads the full device-table snapshot, and
    without this filter each would hold (and re-fire panes for) every
    key — duplicate output.  Entry/batch tables are range-filtered in the
    backend; the canonical array format is filtered here where its slot
    relationships are understood."""
    lo, hi = np.uint64(key_range[0]), np.uint64(key_range[1])
    slot_to_key = arrays["slot_to_key"].astype(np.uint64)
    n_old = len(slot_to_key)
    own_slot = (slot_to_key >= lo) & (slot_to_key <= hi)
    if own_slot.all():
        return arrays  # 1:1 restore: nothing to drop
    old_slots = own_slot.nonzero()[0]  # kept keys, old slot order
    kept_keys = slot_to_key[old_slots]

    out = dict(arrays)
    out["slot_to_key"] = kept_keys
    order = np.argsort(kept_keys, kind="stable")
    out["key_sorted"] = kept_keys[order]
    # new slots are positions in old-slot order
    out["slot_of_sorted"] = np.arange(len(kept_keys), dtype=np.int64)[order]

    bin_keys = arrays["bin_keys"].astype(np.uint64)
    own_row = (bin_keys >= lo) & (bin_keys <= hi)
    out["bin_keys"] = bin_keys[own_row]
    out["bin_vals"] = arrays["bin_vals"][:, own_row]
    out["bin_counts"] = arrays["bin_counts"][own_row]

    meta = arrays["meta"].copy()
    meta[0] = len(kept_keys)
    out["meta"] = meta

    # operator key-column values are indexed by OLD slot: gather into the
    # new slot order
    for name, arr in arrays.items():
        if name.startswith("kv_") and name != "kv_size":
            if len(arr) < n_old:
                # the snapshot invariant is kv rows == occupied slots; a
                # short array silently mis-aligned would emit WRONG key
                # columns — fail loudly instead
                raise ValueError(
                    f"canonical snapshot kv array {name!r} has {len(arr)} "
                    f"rows for {n_old} slots")
            out[name] = arr[old_slots]
    if "kv_size" in arrays:
        out["kv_size"] = np.array([len(kept_keys)])
    return out


def merge_canonical_snapshots(a: Dict[str, np.ndarray],
                              b: Dict[str, np.ndarray]
                              ) -> Dict[str, np.ndarray]:
    """Merge two canonical bin-state snapshots from DIFFERENT parent
    subtasks (disjoint key ranges) into one, for restore-time
    re-partitioning (a rescale N->M reads every parent overlapping the
    new range; parquet.rs:194-218).  A naive dict merge would keep only
    one parent's arrays — silent state loss."""
    if not a:
        return b
    if not b:
        return a
    am, bm = a["meta"], b["meta"]
    if am[0] == 0:
        return b
    if bm[0] == 0:
        return a

    # unified linear-column span over absolute bins [lo, hi]
    spans = []
    for arrs, m in ((a, am), (b, bm)):
        lo = int(m[1])
        span = arrs["bin_vals"].shape[-1]
        spans.append((lo, span))
    los = [lo for lo, s in spans if lo >= 0]
    his = [lo + s - 1 for lo, s in spans if lo >= 0]
    lo_u = min(los) if los else -1
    hi_u = max(his) if his else -1
    width = (hi_u - lo_u + 1) if lo_u >= 0 else 0

    n_ch = a["bin_vals"].shape[0]
    # per-channel aggregation identities: bins one parent never held must
    # pad to +inf/-inf for MIN/MAX channels, not 0 (a 0-pad would make a
    # merged window emit min/max == 0 for keys spanning the gap)
    ch_init = None
    for arrs in (a, b):
        if "ch_init" in arrs:
            ch_init = np.asarray(arrs["ch_init"], dtype=ACC_DTYPE)
            break
    if ch_init is None or len(ch_init) != n_ch:
        import logging

        logging.getLogger(__name__).warning(
            "merging bin-state snapshots without ch_init (pre-upgrade "
            "checkpoint): MIN/MAX channels pad uncovered bins with 0")
        ch_init = np.zeros(n_ch, dtype=ACC_DTYPE)
    parts_keys, parts_vals, parts_counts = [], [], []
    kv_parts: Dict[str, List[np.ndarray]] = {}
    slot_parts: List[np.ndarray] = []
    for arrs, (lo, span) in ((a, spans[0]), (b, spans[1])):
        keys = arrs["bin_keys"].astype(np.uint64)
        vals = np.asarray(arrs["bin_vals"], dtype=ACC_DTYPE)
        counts = np.asarray(arrs["bin_counts"])
        if width and len(keys):
            pv = np.broadcast_to(ch_init[:, None, None],
                                 (n_ch, len(keys), width)).copy()
            pc = np.zeros((len(keys), width), counts.dtype)
            if lo >= 0 and span:
                off = lo - lo_u
                pv[:, :, off:off + span] = vals
                pc[:, off:off + span] = counts
            vals, counts = pv, pc
        parts_keys.append(keys)
        parts_vals.append(vals)
        parts_counts.append(counts)
        slot_parts.append(arrs["slot_to_key"].astype(np.uint64))
        for k, v in arrs.items():
            if k.startswith("kv_") and k != "kv_size":
                kv_parts.setdefault(k, []).append(
                    v[:int(arrs["meta"][0])] if len(v) >= int(arrs["meta"][0])
                    else v)

    out: Dict[str, np.ndarray] = {}
    out["bin_keys"] = np.concatenate(parts_keys)
    out["bin_vals"] = (np.concatenate(parts_vals, axis=1) if width else
                       a["bin_vals"][:, :0])
    out["bin_counts"] = (np.concatenate(parts_counts, axis=0) if width else
                         a["bin_counts"][:0])
    slot_to_key = np.concatenate(slot_parts)
    out["slot_to_key"] = slot_to_key
    order = np.argsort(slot_to_key, kind="stable")
    out["key_sorted"] = slot_to_key[order]
    out["slot_of_sorted"] = np.arange(len(slot_to_key), dtype=np.int64)[order]
    for k, vs in kv_parts.items():
        out[k] = np.concatenate(vs) if len(vs) > 1 else vs[0]
    out["kv_size"] = np.array([len(slot_to_key)])
    out["ch_init"] = ch_init
    # panes fired under the SAME aligned barrier: parents agree; max is
    # the safe choice if they ever differ (never re-fire an emitted pane)
    out["meta"] = np.array([
        len(slot_to_key), lo_u,
        max(int(am[2]), int(bm[2])),
        max(int(am[3]), int(bm[3])),
    ], dtype=np.int64)
    return out
