"""Columnar expression execution on device.

Replaces the reference's SQL-expression-to-Rust-source pipeline
(arroyo-sql/src/expressions.rs -> ExpressionOperator bodies,
arroyo-datastream/src/lib.rs:1430-1505): expressions here are jnp-traceable
functions over a dict of columns, jit-compiled once per (schema, size-bucket).

XLA constraints shape the design:
* batches vary in length -> pad rows up to power-of-two buckets so each
  expression compiles O(log max_batch) times, not per batch;
* string/object columns can't live on device -> they bypass the jitted fn and
  are re-attached (or pre-hashed) on the host;
* predicates return a device bool mask; selection happens host-side where the
  batch lives.
"""

from __future__ import annotations

import functools
from contextlib import nullcontext
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import weakref

from ..types import Batch

_MIN_BUCKET = 256


def bucket_size(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def _is_device_dtype(dt: np.dtype) -> bool:
    return dt != np.dtype(object) and (
        np.issubdtype(dt, np.number) or np.issubdtype(dt, np.bool_))


def _expr_device():
    """Placement for jitted expressions: ``ARROYO_EXPR_DEVICE=cpu`` pins
    elementwise expression kernels to the host CPU backend while keyed
    window state stays on the accelerator.  Elementwise projections are
    HBM-bandwidth-bound, not MXU work — when the accelerator sits behind
    a high-latency tunnel, shipping every batch across it for a map/
    filter costs far more than the compute saves."""
    import os

    if os.environ.get("ARROYO_EXPR_DEVICE", "").lower() == "cpu":
        try:
            return jax.devices("cpu")[0]
        except RuntimeError:
            return None
    return None


def _host_eval_device():
    """CPU device for eager host-side expression evaluation (the chain
    ingest spine); None when the CPU platform is unavailable — callers
    must then keep the jitted path."""
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def _looks_stringy(v: np.ndarray) -> bool:
    """First non-None value (of a prefix) is a str: the column would stay
    on the host path rather than coerce to a device dtype."""
    for x in v[:64]:
        if x is not None:
            return isinstance(x, str)
    return False


class CompiledExpr:
    """A ColumnExpr jitted over padded numeric columns.

    ``fn(cols)`` may return a dict of columns (record exprs) or a single
    array (predicates).  ``__timestamp`` is always available as a column.
    ``valid`` (bool[n]) marks real rows in the padded batch; expressions never
    see it but predicate results are AND-ed with it.
    """

    # jitted-executable cache shared process-wide, keyed by the underlying
    # expression fn (weakly — closures die with their program) and the
    # batch schema: rebuilding the physical graph from the same logical
    # program (engine restarts, bench warm runs) reuses compiled kernels
    _JIT_CACHE = weakref.WeakKeyDictionary()

    def __init__(self, name: str, fn: Callable[[Dict[str, Any]], Any]):
        self.name = name
        self.fn = fn
        # columns the fn actually reads (attached by the SQL planner from
        # the compile-time AST; None = unknown, coerce everything)
        self.used_cols = getattr(fn, "used_cols", None)
        try:
            self._jitted = CompiledExpr._JIT_CACHE.setdefault(fn, {})
        except TypeError:  # non-weakref-able callable: private cache
            self._jitted = {}

    def _get_jitted(self, schema_key: Tuple) -> Callable:
        f = self._jitted.get(schema_key)
        if f is None:
            fn = self.fn

            @jax.jit
            def run(num_cols: Dict[str, jnp.ndarray]):
                return fn(dict(num_cols))

            dev = _expr_device()
            if dev is not None:
                jitted = run

                def run_on(num_cols, _j=jitted, _d=dev):
                    return _j({k: jax.device_put(v, _d)
                               for k, v in num_cols.items()})

                f = run_on
            else:
                f = run
            self._jitted[schema_key] = f
        return f

    def _split_cols(self, batch: Batch
                    ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
        """(numeric env, host passthrough cols) for this expression over
        one batch — the single definition of which columns enter the fn
        and which bypass it, shared by the jitted path and the host
        (ingest-spine) path so the two produce identical layouts."""
        num_cols: Dict[str, np.ndarray] = {"__timestamp": batch.timestamp}
        host_cols: Dict[str, np.ndarray] = {}
        used = self.used_cols
        for k, v in batch.columns.items():
            if used is not None and k not in used:
                # untouched by the expression: skip coercion/padding.
                # STRING-like object columns stay visible for host
                # passthrough (where they land today); nullable-numeric
                # object columns would have been coerced-then-dropped by
                # the projection, so drop them here too.
                if v.dtype == object and _looks_stringy(v):
                    host_cols[k] = v
                continue
            if v.dtype == object:
                # nullable scalar columns (bool/int with Nones) become a
                # typed column + __mask_ validity so they can enter jit
                from ..formats import coerce_object_col

                vals, mask = coerce_object_col(v)
                if vals.dtype != object:
                    num_cols[k] = vals
                    if mask is not None:
                        num_cols["__mask_" + k] = mask
                    continue
                host_cols[k] = v
            elif _is_device_dtype(v.dtype):
                num_cols[k] = v
            else:
                host_cols[k] = v
        return num_cols, host_cols

    def eval_host(self, batch: Batch) -> Any:
        """Evaluate the expression eagerly on the HOST — no padding, no
        jit, no accelerator dispatch.  The fn's jnp ops run op-by-op
        pinned to the CPU backend, so on an accelerator box the batch
        never crosses the transfer boundary.  Used by the chain ingest
        spine (engine/chained.py), where the batch is host-resident on
        both sides of the expression and a per-batch kernel dispatch is
        pure envelope.  Returns the same ``(out, n, host_cols)``
        contract as ``__call__``."""
        n = len(batch)
        num_cols, host_cols = self._split_cols(batch)
        dev = _host_eval_device()
        ctx = jax.default_device(dev) if dev is not None else nullcontext()
        with ctx:
            out = self.fn(dict(num_cols))
        return out, n, host_cols

    def __call__(self, batch: Batch) -> Any:
        n = len(batch)
        padded = bucket_size(n)
        num_cols, host_cols = self._split_cols(batch)

        padded_cols = {
            k: np.concatenate([v, np.zeros(padded - n, dtype=v.dtype)])
            if padded > n else v
            for k, v in num_cols.items()
        }
        schema_key = tuple(sorted((k, str(v.dtype), padded)
                                  for k, v in padded_cols.items())
                           ) + (_expr_device() is not None,)
        from ..obs.perf import timed_device

        out = timed_device(self._get_jitted(schema_key), padded_cols)
        return out, n, host_cols


def eval_record_expr(expr: CompiledExpr, batch: Batch,
                     host: bool = False) -> Batch:
    """Record expression: fn(cols) -> dict of output columns.
    ``host=True`` evaluates eagerly on the CPU backend (ingest spine) —
    identical output layout, no padding/jit/dispatch."""
    out, n, host_cols = expr.eval_host(batch) if host else expr(batch)
    assert isinstance(out, dict), f"record expr {expr.name} must return a dict"
    cols: Dict[str, np.ndarray] = {}
    ts = batch.timestamp
    for k, v in out.items():
        if k == "__timestamp":
            ts = np.asarray(v)[:n]  # arroyolint: disable=host-sync -- record-expr output must materialize as host numpy batch columns
            continue
        arr = np.asarray(v)  # arroyolint: disable=host-sync -- record-expr output must materialize as host numpy batch columns
        cols[k] = arr[:n] if arr.ndim >= 1 and arr.shape[0] >= n else arr
    # host (string) columns referenced in output pass through by name
    for k, v in host_cols.items():
        if k not in cols:
            cols[k] = v
    return Batch(ts, cols, batch.key_hash, batch.key_cols,
                 lat_stamp=batch.lat_stamp)


def eval_predicate(expr: CompiledExpr, batch: Batch,
                   host: bool = False) -> np.ndarray:
    out, n, _ = expr.eval_host(batch) if host else expr(batch)
    mask = np.asarray(out)  # arroyolint: disable=host-sync -- predicate mask materializes on host where batch.select runs
    assert mask.dtype == np.bool_ or np.issubdtype(mask.dtype, np.bool_), (
        f"predicate {expr.name} must return bool")
    if mask.ndim == 0:
        # constant predicate (e.g. a now()-only comparison): broadcast
        # to the batch — Batch.select(scalar_bool) would otherwise
        # numpy-index every column into a dimension-lifted (1, n) shape
        # that crashes the next operator's padding.  (Mirrored in
        # planner._host_filter for the host path — the two sites cannot
        # share code because this one receives post-trace output while
        # that one runs eagerly inside the UDF.)
        return np.full(len(batch), bool(mask))
    return mask[:n]


def eval_host_expr(fn: Callable[[Dict[str, np.ndarray]], Any], batch: Batch
                   ) -> Batch:
    """Host-side (non-jitted) record expression over raw numpy columns —
    the UDF escape hatch (the reference runs UDFs in wasmtime,
    operators/mod.rs:347-494; ours run as plain Python over the batch).

    When expressions are pinned to host (the tunnel regime), any jnp
    call the function makes internally must ALSO stay off the
    accelerator: an uncommitted jnp op lands on the default backend, and
    converting its result back is a ~70 ms tunnel readback per column
    (measured: 33 s of a 47 s config5 run before this guard)."""
    dev = _expr_device()
    ctx = jax.default_device(dev) if dev is not None else nullcontext()
    with ctx:
        cols = {"__timestamp": batch.timestamp, **batch.columns}
        out = fn(cols)
        assert isinstance(out, dict)
        ts = np.asarray(out.pop("__timestamp", batch.timestamp))  # arroyolint: disable=host-sync -- host UDF path: outputs are host numpy by contract
        return Batch(ts, {k: np.asarray(v) for k, v in out.items()},  # arroyolint: disable=host-sync -- host UDF path: outputs are host numpy by contract
                     batch.key_hash, batch.key_cols)
