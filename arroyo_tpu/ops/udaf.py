"""UDAF partial decomposition: compile numeric user aggregates into the
bin-agg channel model (Flare's stance, PAPERS.md — compile the slow
path into the native execution model instead of interpreting it).

A registered UDAF is an opaque ``fn(values) -> scalar`` the engine can
only call per segment on host — the config5 slow path.  But most numeric
UDAFs people register ARE one of a small algebra over mergeable partials
(sum / non-null count / min / max / sum-of-squares).  This module
**probes** a UDAF against that algebra with deterministic numeric test
vectors: when ``fn`` agrees with a candidate formula on every probe, it
compiles to a :class:`UdafPlan` — channel kinds for the existing
segment/bin kernels plus a vectorized ``combine`` over the per-segment
partials — and the per-segment host loop never runs.  The verdict is
**sticky** per function object (probed once per process), and object or
string columns always take the counted host fallback regardless of the
plan (the channels are f64).

Probing is behavioral, not syntactic, so ``np.sum``, ``lambda v:
v.mean()``, a Rust-backed mean — anything extensionally equal on the
probes — all compile.  A UDAF that matches no candidate (``np.median``
has its own exact vectorized path in ops/segment.py; percentiles are
order statistics, not mergeable partials) stays on the host loop and is
counted there (``udaf_host_rows``).  General ``jax.vmap`` tracing of
opaque fns is deliberately NOT attempted: a traced fn would see PADDED
segment rows, and pad-insensitivity of an arbitrary aggregate is
undecidable — the probe algebra is the subset where correctness is
checkable.

``ARROYO_UDAF_CHANNELS=off`` disables compilation (every UDAF on the
host loop — the A/B axis the bench sessions family sweeps).
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import numpy as np

# channel vocabulary: "nnz" is the per-segment non-null count (always
# present — it masks all-null segments to NaN, the SQL NULL contract);
# "sumsq" rides the kernels as a sum channel over squared inputs
CHANNEL_KINDS = ("sum", "nnz", "min", "max", "sumsq")


def udaf_channels_enabled() -> bool:
    return os.environ.get("ARROYO_UDAF_CHANNELS", "on").lower() not in (
        "off", "0", "false", "no")


@dataclass(frozen=True)
class UdafPlan:
    """A UDAF compiled onto mergeable partial channels.

    ``name`` identifies the algebra member (the planner's AST rewrite
    keys off it); ``channels`` are the partial kinds the segment/bin
    kernels must produce; ``combine`` folds the per-segment partial
    arrays into the output column (all-null masking is the caller's
    job, uniformly ``nnz == 0 -> NaN``)."""

    name: str
    channels: Tuple[str, ...]
    combine: Callable[[Dict[str, np.ndarray]], np.ndarray]


def _c_sum(d):
    return d["sum"]


def _c_count(d):
    return d["nnz"]


def _c_mean(d):
    with np.errstate(all="ignore"):
        return d["sum"] / d["nnz"]


def _c_min(d):
    return d["min"]


def _c_max(d):
    return d["max"]


def _c_ptp(d):
    return d["max"] - d["min"]


def _var_pop(d):
    with np.errstate(all="ignore"):
        n = d["nnz"]
        # E[x^2] - E[x]^2 in the single-pass mergeable form; tiny
        # negative residue from cancellation clips to zero
        return np.maximum((d["sumsq"] - d["sum"] * d["sum"] / n) / n, 0.0)


def _var_samp(d):
    with np.errstate(all="ignore"):
        n = d["nnz"]
        return np.maximum(
            (d["sumsq"] - d["sum"] * d["sum"] / n) / (n - 1), 0.0)


def _std_pop(d):
    return np.sqrt(_var_pop(d))


def _std_samp(d):
    return np.sqrt(_var_samp(d))


# (name, channels, reference implementation, combine) — probe order;
# first behavioral match wins.  References are the ground truth the
# probes compare fn against; combines are what production then runs.
_CANDIDATES: Tuple[Tuple[str, Tuple[str, ...], Callable, Callable], ...] = (
    ("count", ("nnz",), lambda p: float(len(p)), _c_count),
    ("sum", ("sum", "nnz"), np.sum, _c_sum),
    ("mean", ("sum", "nnz"), np.mean, _c_mean),
    ("min", ("min", "nnz"), np.min, _c_min),
    ("max", ("max", "nnz"), np.max, _c_max),
    ("ptp", ("min", "max", "nnz"), lambda p: np.max(p) - np.min(p), _c_ptp),
    ("var_pop", ("sum", "sumsq", "nnz"), lambda p: np.var(p), _var_pop),
    ("var_samp", ("sum", "sumsq", "nnz"),
     lambda p: np.var(p, ddof=1), _var_samp),
    ("std_pop", ("sum", "sumsq", "nnz"), lambda p: np.std(p), _std_pop),
    ("std_samp", ("sum", "sumsq", "nnz"),
     lambda p: np.std(p, ddof=1), _std_samp),
)

# Probe vectors (dyadic rationals — exact in binary, so algebraically
# equal formulas agree to the last ulp).  The multiset [3.5, -1.25, 7,
# 0.5, 2, 2] separates median (= 2) from mean (= 2.2916..); [2.5] and
# [1..5] separate sum/count/mean; the constant vector catches aggregates
# that ignore their input.
_PROBES = (
    np.array([2.5]),
    np.array([1.0, 2.0, 3.0, 4.0, 5.0]),
    np.array([3.5, -1.25, 7.0, 0.5, 2.0, 2.0]),
    np.array([4.0, 4.0, 4.0, 4.0]),
    np.array([0.8125, -3.75, 12.5, 0.0, 5.25, -0.5, 2.125]),
)

_RTOL = 1e-9
_ATOL = 1e-12

# sticky verdict per function OBJECT: probed once per process, then the
# segment path branches on a dict hit (the fallback is sticky too — a
# fn that failed probing never re-probes)
_verdicts: Dict[Callable, Optional[UdafPlan]] = {}


def _scalar(x) -> Optional[float]:
    try:
        arr = np.asarray(x, dtype=np.float64)  # arroyolint: disable=host-sync -- probe-time scalar coercion of fn's return; probing runs once per fn on host test vectors
    except (TypeError, ValueError):
        return None
    if arr.shape not in ((), (1,)):
        return None
    return float(arr.reshape(()))


def _matches(fn: Callable, ref: Callable) -> bool:
    for p in _PROBES:
        try:
            with warnings.catch_warnings(), np.errstate(all="ignore"):
                warnings.simplefilter("ignore")
                got = _scalar(fn(p.copy()))
                want = _scalar(ref(p))
        except Exception:
            return False
        if got is None or want is None:
            return False
        if np.isnan(want) and np.isnan(got):
            continue
        if not np.isclose(got, want, rtol=_RTOL, atol=_ATOL):
            return False
    return True


def udaf_plan(fn: Callable) -> Optional[UdafPlan]:
    """The channel plan for ``fn``, or None (host loop).  Probes at most
    once per function object; ``None`` verdicts are sticky.  The knob is
    honored on every call (not just at probe time), so an A/B sweep can
    flip ARROYO_UDAF_CHANNELS mid-process without stale cached plans."""
    if not udaf_channels_enabled():
        return None
    if fn in _verdicts:
        return _verdicts[fn]
    plan: Optional[UdafPlan] = None
    for name, channels, ref, combine in _CANDIDATES:
        if _matches(fn, ref):
            plan = UdafPlan(name, channels, combine)
            break
    _verdicts[fn] = plan
    return plan


def channel_rows(kind: str, raw: np.ndarray, ok: np.ndarray
                 ) -> Tuple[str, np.ndarray]:
    """Per-row kernel input for one plan channel: (kernel kind, rows).
    Nulls feed each kind its identity, so the partials are exact over
    the non-null subset — the same rows the host loop would see."""
    from .segment import NEG_INF, POS_INF

    if kind == "nnz":
        return "sum", ok.astype(np.float64)
    if kind == "sum":
        return "sum", np.where(ok, raw, 0.0)
    if kind == "sumsq":
        return "sum", np.where(ok, raw * raw, 0.0)
    if kind == "min":
        return "min", np.where(ok, raw, POS_INF)
    return "max", np.where(ok, raw, NEG_INF)
