"""Pallas TPU kernels for the hot scatter-reduce in windowed aggregation.

The reference's sliding-window aggregator updates per-(key, bin) accumulators
one record at a time (/root/reference/arroyo-worker/src/operators/
aggregating_window.rs:114-160, map.insert per element).  The XLA translation
of that is ``values.at[slots, bins].add(x)`` — a scatter, which TPUs execute
serially.  This module reformulates the additive scatter as a **one-hot
matmul on the MXU**:

    delta[c, p] = sum_i onehot_slots[i, c] * packed[i, p]

where ``packed`` carries, along the lane axis, one column group per
aggregation channel: ``packed[i, g*B + b] = (bin_i == b) * w_g,i``.  The
Pallas kernel materializes the [CHUNK, TILE_C] slot one-hot in VMEM on the
fly (it never touches HBM) and contracts it against the packed block with a
single DEFAULT-precision matmul.  Two tricks keep that both exact and fast:

* the slot one-hot is 0/1 — exact in bf16, so no HIGHEST-precision passes;
* each weighted channel is split into bf16 hi + lo column groups
  (w = hi + lo), recovering ~f32 accuracy at 2 exact-product columns
  instead of 6 multi-pass matmul passes.

The grid covers only **active** key tiles (slots actually in use), not the
full capacity, and the batch-chunk axis is innermost so each [TILE_C, P]
accumulator stays VMEM-resident and is written to HBM exactly once.

Used for sum/count/avg channels (min/max stay on the XLA scatter path —
they are not additive and are rare in the hot queries).  On non-TPU
backends the kernel runs in interpret mode so tests exercise the same code.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:
    _enable_x64 = jax.enable_x64  # jax >= 0.5 top-level export
except AttributeError:
    from jax.experimental import enable_x64 as _enable_x64

try:  # pallas ships with jax, but guard for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

LANES = 128  # TPU lane width
CHUNK = 1024  # batch rows per grid step
TILE_C = 512  # key slots per grid tile


def pallas_enabled() -> bool:
    """Pallas update path is opt-in (ARROYO_PALLAS=1) on every backend:
    on real TPU v5 hardware the XLA scatter update measured 1.17 ms per
    16k-cell step against the engine's 8192x16 resident state while this
    kernel measured 52-76 ms at the identical shape across three
    sessions (BENCH_TPU_KERNELS_r04.json) — the one-hot MXU scatter
    does not pay off at bin-ring widths, so defaulting it on would
    silently cost the q5 hot loop ~44x."""
    env = os.environ.get("ARROYO_PALLAS")
    if env is not None:
        return env not in ("0", "false", "no") and HAVE_PALLAS
    return False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _scatter_kernel(tile_c: int, P: int):
    def kernel(slots_ref, packed_ref, out_ref):
        t = pl.program_id(0)
        c = pl.program_id(1)

        @pl.when(c == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        slots = slots_ref[:]  # i32 [CHUNK, 1] (global slot ids; -1 invalid)
        base = t * tile_c
        c_iota = jax.lax.broadcasted_iota(jnp.int32, (CHUNK, tile_c), 1)
        onehot_s = jnp.where(c_iota + base == slots, 1.0, 0.0)
        # [tile_c, CHUNK] @ [CHUNK, P], single MXU pass: both operands are
        # explicitly bf16 and every packed entry is bf16-representable (the
        # hi/lo split happens on host), so the cast loses nothing and the
        # products accumulate exactly in f32
        out_ref[:] += jax.lax.dot_general(
            onehot_s.astype(jnp.bfloat16),
            packed_ref[:].astype(jnp.bfloat16),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    return kernel


@functools.lru_cache(maxsize=256)
def _scatter_call(C_act: int, P: int, n_chunks: int, interpret: bool):
    tile_c = min(C_act, TILE_C)
    assert C_act % tile_c == 0
    grid = (C_act // tile_c, n_chunks)

    return pl.pallas_call(
        _scatter_kernel(tile_c, P),
        out_shape=jax.ShapeDtypeStruct((C_act, P), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((CHUNK, 1), lambda t, c: (c, 0)),
            pl.BlockSpec((CHUNK, P), lambda t, c: (c, 0)),
        ],
        out_specs=pl.BlockSpec((tile_c, P), lambda t, c: (t, 0)),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _scatter_multi(k2: int, B: int, C_act: int, n_chunks: int,
                   interpret: bool):
    """k2 bf16-exact weight channels -> [k2, C_act, B] via one matmul."""
    P = ((k2 * B + LANES - 1) // LANES) * LANES
    call = _scatter_call(C_act, P, n_chunks, interpret)
    n = n_chunks * CHUNK

    @jax.jit
    def run(slots, bins, weights):
        # packed[i, g*B + b] = (bin_i == b) * w_g,i ; every entry is
        # bf16-representable because the hi/lo split happened on host
        onehot_b = jnp.where(
            bins[:, None] == jnp.arange(B, dtype=jnp.int32)[None, :],
            1.0, 0.0)  # [n, B]
        groups = [onehot_b * weights[g][:, None] for g in range(k2)]
        packed = jnp.concatenate(groups, axis=1)
        packed = jnp.pad(packed, ((0, 0), (0, P - k2 * B)))
        out = call(slots.reshape(n, 1), packed)  # [C_act, P]
        return jnp.stack(
            [out[:, g * B:(g + 1) * B] for g in range(k2)])

    return run


def _split_hi_lo(weights: np.ndarray) -> np.ndarray:
    """[k, n] f32 -> [2k, n] f32 with every entry bf16-representable."""
    import ml_dtypes

    hi = weights.astype(ml_dtypes.bfloat16).astype(np.float32)
    lo = (weights - hi).astype(ml_dtypes.bfloat16).astype(np.float32)
    return np.concatenate([hi, lo], axis=0)


def scatter_add_channels(slots: np.ndarray, bins: np.ndarray,
                         weights: np.ndarray, C_act: int, B: int
                         ) -> jnp.ndarray:
    """Batched scatter-add of ``k`` weight channels into [k, C_act, B].

    ``slots`` must be in [0, C_act) for real rows and -1 (or any
    out-of-range value) for padding; ``C_act`` must be a power of two
    (multiple of TILE_C when larger).
    """
    k, n = weights.shape
    assert n % CHUNK == 0 and len(slots) == n
    w2 = _split_hi_lo(np.asarray(weights, np.float32))  # arroyolint: disable=host-sync -- kernel input packing reads host arrays; no device round-trip on this path
    run = _scatter_multi(2 * k, B, C_act, n // CHUNK, _interpret())
    # every operand is 32-bit; trace under x32 — Mosaic's TPU lowering
    # rejects the 64-bit index types that global x64 mode introduces
    with _enable_x64(False):
        out = run(jnp.asarray(slots, jnp.int32),
                  jnp.asarray(bins, jnp.int32),
                  jnp.asarray(w2))  # [2k, C_act, B]
    return out[:k] + out[k:]


@functools.lru_cache(maxsize=256)
def _update_delta_call(k: int, B: int, C_act: int, n_chunks: int,
                       interpret: bool):
    """One x32 dispatch producing the per-batch [k, C_act, B] deltas from
    the packed pallas scatter (hi + lo recombined).

    Channel 0 is the count channel; channels 1..k map to values[0..k-1].
    """
    run = _scatter_multi(2 * k, B, C_act, n_chunks, interpret)

    @jax.jit
    def apply(packed):
        # ONE packed f32 input (one transfer): [slots, bins, w2 hi/lo...]
        slots = packed[0].astype(jnp.int32)
        bins = packed[1].astype(jnp.int32)
        out = run(slots, bins, packed[2:])
        return out[:k] + out[k:]

    return apply


@functools.lru_cache(maxsize=64)
def _apply_delta_call(k: int, C_act: int):
    @jax.jit
    def apply(values, counts, deltas):
        counts = counts.at[:C_act].add(deltas[0].astype(counts.dtype))
        if k > 1:
            values = values.at[:, :C_act].add(
                deltas[1:].astype(values.dtype))
        return values, counts

    return apply


def update_bin_state(values: jnp.ndarray, counts: jnp.ndarray,
                     slots: np.ndarray, bins: np.ndarray,
                     weights: np.ndarray, C_act: int, B: int):
    """Fused state update; returns (values, counts). weights[0] is the
    count channel, weights[1:] the aggregate channels.

    Two dispatches: the pallas scatter runs under x32 (Mosaic's TPU
    lowering rejects 64-bit index types), while the state add runs under
    the session's x64 so the f64 accumulator state is NOT silently
    downcast (the numeric-fidelity policy in keyed_bins.ACC_DTYPE)."""
    k, n = weights.shape
    assert n % CHUNK == 0
    # slot ids ride an f32 row: exact only below 2^24 (same guard as the
    # XLA packing in keyed_bins.update)
    assert C_act <= 1 << 24, "key capacity exceeds f32-exact packing"
    w2 = _split_hi_lo(np.asarray(weights, np.float32))  # arroyolint: disable=host-sync -- kernel input packing reads host arrays; no device round-trip on this path
    packed = np.empty((2 + w2.shape[0], n), dtype=np.float32)
    packed[0] = slots  # small ints: exact in f32
    packed[1] = bins
    packed[2:] = w2
    delta = _update_delta_call(k, B, C_act, n // CHUNK, _interpret())
    with _enable_x64(False):
        deltas = delta(jnp.asarray(packed))
    return _apply_delta_call(k, C_act)(values, counts, deltas)


def pad_batch(slots: np.ndarray, bins: np.ndarray,
              weights: np.ndarray) -> tuple:
    """Pad 1-D batch arrays up to a CHUNK multiple.

    Padding rows get slot -1 (matches no tile) and weight 0.
    """
    n = len(slots)
    npad = ((n + CHUNK - 1) // CHUNK) * CHUNK
    s = np.full(npad, -1, dtype=np.int32)
    s[:n] = slots
    b = np.zeros(npad, dtype=np.int32)
    b[:n] = bins
    w = np.zeros((weights.shape[0], npad), dtype=np.float32)
    w[:, :n] = weights
    return s, b, w


def active_capacity(used: int, total_c: int) -> int:
    """Smallest pallas-friendly slot count covering ``used`` slots."""
    c = 8
    while c < used:
        c <<= 1
    if c > TILE_C:
        c = ((used + TILE_C - 1) // TILE_C) * TILE_C
    return min(c, total_c)
