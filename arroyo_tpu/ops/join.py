"""Device-side equi-join pair computation (SURVEY "Core TPU kernel #3";
reference: arroyo-worker/src/operators/joins.rs:14-181, per-record Rust
hash-join loops re-designed as batched XLA kernels).

The join's compute — sorting both key columns, probing match ranges,
prefix-summing match counts, and expanding (left, right) index pairs for
the cross product — runs as static-shape jitted kernels on the device.
On the legacy one-shot path (``join_pairs``) the final materialization
(gathering payload columns by the computed indices) stays on host; the
partition-adaptive resident rings below close that last host hop — hot
partitions co-locate their payload columns on device and the probe ->
expand -> gather pipeline emits matched rows without touching the host
mirror (strings keep the host path via the buffer's sticky fallback).

Static shapes: inputs pad to power-of-two buckets (sentinel keys sort to
the end and are excluded by valid-count masking), and the pair output
pads to the bucket of the exact total from the probe's prefix sum — so
each (bucket_l, bucket_r, bucket_m) triple compiles once.

Dispatch discipline: one sort per side, one probe, one expansion = four
device round trips per fired window, independent of fan-out.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.perf import timed_device

# padding key: sorts after every real hash; a real key colliding with it
# (probability ~2^-64 per row) routes the call to the host fallback
SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _bucket(n: int, floor: int = 512) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def _merged_probe() -> bool:
    """True when ``searchsorted`` must be avoided on device: XLA lowers
    it to a sequential per-bit scan that measured ~78 ms for 16k queries
    on a TPU v5 (the u64 argsort itself is fast there, 0.03 ms — the
    sort was never the problem).  The merged-rank probe computes the
    same bounds from one extra stable sort (~0.1 ms).
    ARROYO_JOIN_PROBE=merged|search forces either path on any backend
    so the CPU test mesh can check parity."""
    forced = os.environ.get("ARROYO_JOIN_PROBE")
    if forced == "merged":
        return True
    if forced == "search":
        return False
    return jax.default_backend() == "tpu"


@functools.lru_cache(maxsize=64)
def _sort_kernel(n: int):
    @jax.jit
    def run(keys):
        order = jnp.argsort(keys, stable=True)
        return order, keys[order]

    return run


@functools.lru_cache(maxsize=64)
def _probe_kernel(nl: int, nr: int, merged: bool):
    if not merged:
        @jax.jit
        def run(lk_sorted, rk_sorted, nl_valid, nr_valid):
            start = jnp.searchsorted(rk_sorted, lk_sorted, side="left")
            end = jnp.searchsorted(rk_sorted, lk_sorted, side="right")
            # right padding lives in [nr_valid, nr): clamp both bounds
            start = jnp.minimum(start, nr_valid)
            end = jnp.minimum(end, nr_valid)
            counts = jnp.where(jnp.arange(nl) < nl_valid, end - start, 0)
            cum = jnp.cumsum(counts)
            return start, counts, cum

        return run

    @jax.jit
    def run(lk_sorted, rk_sorted, nl_valid, nr_valid):
        # merged-rank probe: for every (already sorted) left key, how
        # many right keys are < / <= it falls out of its position in a
        # stably sorted concatenation.  With the right side placed
        # first, equal right keys sort before a left key, so
        # pos - own_rank = #(right <= key); left-first gives
        # #(right < key).
        iota = jnp.arange(nl, dtype=jnp.int32)
        pos = jnp.arange(nl + nr, dtype=jnp.int32)
        o_lf = jnp.argsort(jnp.concatenate([lk_sorted, rk_sorted]),
                           stable=True)
        inv_lf = jnp.zeros(nl + nr, jnp.int32).at[o_lf].set(pos)
        start = inv_lf[:nl] - iota
        o_rf = jnp.argsort(jnp.concatenate([rk_sorted, lk_sorted]),
                           stable=True)
        inv_rf = jnp.zeros(nl + nr, jnp.int32).at[o_rf].set(pos)
        end = inv_rf[nr:] - iota
        nr_valid = jnp.asarray(nr_valid, jnp.int32)
        start = jnp.minimum(start, nr_valid)
        end = jnp.minimum(end, nr_valid)
        counts = jnp.where(iota < jnp.asarray(nl_valid, jnp.int32),
                           end - start, 0)
        cum = jnp.cumsum(counts)
        return start, counts, cum

    return run


@functools.lru_cache(maxsize=64)
def _expand_kernel(nl: int, m: int):
    @jax.jit
    def run(start, cum):
        # pair j belongs to the left row whose cumulative-count interval
        # contains j (cum[i-1] <= j < cum[i]), i.e.
        # lidx[j] = #{i: cum[i] <= j}: scatter each interval end into a
        # histogram and inclusive-prefix-sum it — searchsorted computes
        # the same thing but lowers to a sequential scan on TPU
        # (measured 78 ms for 16k pairs vs ~0.1 ms for this form)
        dt = cum.dtype
        mark = jnp.zeros(m + 1, dt).at[cum].add(1, mode="drop")
        lidx = jnp.cumsum(mark[:m]).clip(0, nl - 1)
        before = jnp.where(lidx > 0, cum[lidx - 1], 0)
        ridx = start[lidx] + (jnp.arange(m, dtype=dt) - before)
        return lidx, ridx

    return run


def expand_counts(counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten per-group match counts into (group_idx, within_offset)
    pairs — the one home of the repeat/cumsum expansion idiom shared by
    the host join fallback and the partitioned sorted-run probes."""
    total = int(counts.sum())
    gidx = np.repeat(np.arange(len(counts)), counts)
    offs = np.arange(total) - np.repeat(np.cumsum(counts) - counts,
                                        counts)
    return gidx, offs


def _host_pairs(lk_sorted: np.ndarray, rk_sorted: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host fallback: identical contract, numpy end to end."""
    left_start = np.searchsorted(rk_sorted, lk_sorted, side="left")
    left_end = np.searchsorted(rk_sorted, lk_sorted, side="right")
    counts = left_end - left_start
    lidx, offs = expand_counts(counts)
    ridx = np.repeat(left_start, counts) + offs
    return lidx, ridx, counts


def device_join_enabled(n_rows: int) -> bool:
    """auto: device path on a real accelerator for batches big enough to
    amortize dispatch (on the CPU backend the "device" is the same
    core, so kernel dispatch is pure overhead — measured ~9% on q5);
    on: always (tests/fuzz parity); off: host numpy only."""
    mode = os.environ.get("ARROYO_DEVICE_JOIN", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    if jax.default_backend() == "cpu":
        return False
    return n_rows >= int(os.environ.get("ARROYO_DEVICE_JOIN_MIN", 2048))


def payload_device_enabled() -> bool:
    """Should hot-partition rings co-locate payload planes?  ``auto``
    (default) rides along whenever the device-join path is active (a
    ring without its payload pays a host gather per match — the hop
    this layer exists to kill); ``off`` keeps today's keys-only rings;
    ``on`` is the same as auto (the ring itself is still gated by
    ``device_join_enabled``, so forcing payload on a host-only join is
    meaningless).  Strings always stay host via the buffer's sticky
    fallback regardless of this knob."""
    mode = os.environ.get("ARROYO_JOIN_PAYLOAD_DEVICE", "auto").lower()
    if mode in ("off", "0", "false"):
        return False
    return bool(jax.config.jax_enable_x64)


# -- partition-adaptive resident rings (state/join_state.py) -----------------
#
# Hot join-state partitions keep their sorted key run device-resident in a
# preallocated power-of-two ring (sentinel-padded).  Maintenance is ONE
# scatter-merge dispatch per arriving delta (positions computed on the host
# mirror — the delta was already sorted there) and probes run against the
# resident ring without re-uploading state.
#
# SPLIT-HASH LAYOUT (native-i32): within a partition the partition id
# already fixes the LOW hash bits (state/join_state.py routes on
# ``kh & (P-1)``), so the ring does not need 64-bit keys for ordering.
# The host run is sorted by the full u64 hash; its TOP 32 bits are an
# order-consistent prefix of that sort, so the ring stores them as a
# bias-mapped i32 ``hi`` plane (``u32 ^ 0x80000000`` viewed i32 — the
# standard order-preserving unsigned->signed transform) that sorts,
# probes and merges in NATIVE int32 — no emulated-u64 argsort (537 ms /
# 16k rows measured on the tunnel TPU).  The remaining 32 bits live in a
# collision-disambiguation ``lo`` plane (i32 bit-view, equality only):
# probe candidates are hi-equal ranges, and the rare
# i32-equal-but-u64-distinct rows are killed by a full-key verify (on
# device in the fused gather kernel, against the host mirror otherwise).
#
# PAYLOAD PLANES: when payload residency is on, the ring co-locates the
# partition's payload columns in the same power-of-two layout — one f64
# stack (floats) and one i64 stack (ints/uints/bools/timestamps as
# bit-views; slot 0 reserved for the sorted event-time run) — kept in
# key+payload lockstep by the SAME single scatter-merge dispatch per
# delta.  Strings (object dtype) cannot ride the device: the buffer's
# sticky fallback keeps such sides host-gathered (state/join_state.py).

# biased-i32 images of u32 0xFFFFFFFF: the ring's padding values.  A
# real key whose TOP 32 hash bits are all ones would be ambiguous with
# the hi pad, so such partitions refuse staging and stay host
# (probability ~2^-32 per row; parity-pinned by test).
SENT32_HI = np.int32(0x7FFFFFFF)
SENT32_LO = np.int32(-1)
_HI_BIAS = np.uint32(0x80000000)


def split_hi32(keys: np.ndarray) -> np.ndarray:
    """Order-preserving i32 image of the top 32 key-hash bits."""
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    return (hi ^ _HI_BIAS).view(np.int32)


def split_lo32(keys: np.ndarray) -> np.ndarray:
    """i32 bit-view of the low 32 key-hash bits (equality only)."""
    return (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)


def ring_stageable(keys: np.ndarray) -> bool:
    """False when any key's top-32 image would collide with the hi pad
    (the partition then keeps the host probe — exactness over speed)."""
    if not len(keys):
        return True
    return int(keys.max() >> np.uint64(32)) != 0xFFFFFFFF


def _pay_to_i64(v: np.ndarray) -> np.ndarray:
    if v.dtype == np.uint64 or v.dtype.kind in "Mm":
        return v.view(np.int64)  # bit-preserving
    if v.dtype == np.int64:
        return v
    return v.astype(np.int64)


def _pay_from_i64(v: np.ndarray, dtype: np.dtype) -> np.ndarray:
    if dtype == np.uint64 or dtype.kind in "Mm":
        return v.view(dtype)
    if dtype == np.bool_:
        return v != 0
    return v.astype(dtype)


def payload_plan(schema: "dict[str, np.dtype]"
                 ) -> Optional[Tuple[Tuple[str, str, int, Any], ...]]:
    """(name, stack, slot, dtype) transport plan for a partition's
    payload columns, or None when any column cannot ride the device
    (strings/objects -> the sticky host-gather fallback).  i-stack slot
    0 is reserved for the sorted event-time run; floats ride the f64
    stack losslessly (f32 round-trips exactly), everything else
    bit-views or widens into i64."""
    if not jax.config.jax_enable_x64:
        return None  # f64/i64 stacks would truncate
    plan = []
    nf, ni = 0, 1  # i-stack slot 0: timestamps
    for name, dt in schema.items():
        k = dt.kind
        if k == "f":
            plan.append((name, "f", nf, dt))
            nf += 1
        elif k in "iubMm":
            plan.append((name, "i", ni, dt))
            ni += 1
        else:
            return None
    return tuple(plan)


class SplitRing:
    """One hot partition's device residency: split-hash key planes plus
    (optionally) the co-located payload stacks, all in the sorted-run
    order of the host mirror and all padded to one power-of-two ``cap``.
    ``plan`` is None for a keys-only ring (payload residency off or the
    schema holds strings)."""

    __slots__ = ("hi", "lo", "cap", "fstack", "istack", "plan",
                 "nf", "ni", "device")

    def __init__(self, hi, lo, cap, fstack, istack, plan, nf, ni, device):
        self.hi = hi
        self.lo = lo
        self.cap = cap
        self.fstack = fstack
        self.istack = istack
        self.plan = plan
        self.nf = nf
        self.ni = ni
        self.device = device

    def plan_schema(self) -> "dict[str, Any]":
        return {name: dt for name, _s, _i, dt in (self.plan or ())}

    def payload_bytes(self) -> int:
        return self.cap * (8 + 8 * (self.nf + self.ni))


def _plan_dims(plan) -> Tuple[int, int]:
    nf = sum(1 for _n, s, _i, _d in plan if s == "f")
    ni = 1 + sum(1 for _n, s, _i, _d in plan if s == "i")
    return nf, ni


def _pack_stacks(plan, nf, ni, width, n, cols, ts):
    fv = np.zeros((nf, width), np.float64)
    iv = np.zeros((ni, width), np.int64)
    iv[0, :n] = ts
    for name, stack, idx, _dt in plan:
        if stack == "f":
            fv[idx, :n] = cols[name]
        else:
            iv[idx, :n] = _pay_to_i64(cols[name])
    return fv, iv


def stage_ring(sorted_keys: np.ndarray, device: Any = None,
               sorted_ts: Optional[np.ndarray] = None,
               sorted_cols: Optional["dict[str, np.ndarray]"] = None
               ) -> Optional[SplitRing]:
    """Upload a sorted key run (plus payload columns when given, all in
    the same sorted-run order) into a fresh power-of-two sentinel-padded
    device ring.  ``device`` pins the ring to one mesh device
    (state/join_state.py spreads hot partitions over the ``("keys",)``
    mesh via ``parallel.shuffle.partition_device`` so q7/q8-style joins
    stop funneling every ring through chip 0); None keeps the default
    placement.  Later ``merge_ring``/``probe_ring`` dispatches follow
    the committed planes' device automatically.  Returns None when the
    run is not stageable (top-32 sentinel collision)."""
    if not ring_stageable(sorted_keys):
        return None
    n = len(sorted_keys)
    cap = _bucket(max(n, 1))
    hi = np.full(cap, SENT32_HI, np.int32)
    lo = np.full(cap, SENT32_LO, np.int32)
    hi[:n] = split_hi32(sorted_keys)
    lo[:n] = split_lo32(sorted_keys)
    plan = (payload_plan({c: v.dtype for c, v in sorted_cols.items()})
            if sorted_cols is not None else None)
    fstack = istack = None
    nf = ni = 0
    if plan is not None:
        nf, ni = _plan_dims(plan)
        fv, iv = _pack_stacks(plan, nf, ni, cap, n, sorted_cols, sorted_ts)
        fstack = jax.device_put(fv, device)
        istack = jax.device_put(iv, device)
    return SplitRing(jax.device_put(hi, device), jax.device_put(lo, device),
                     cap, fstack, istack, plan, nf, ni, device)


@functools.lru_cache(maxsize=64)
def _merge32_kernel(cap: int, db: int, nf: int, ni: int):
    @jax.jit
    def run(hi, lo, fstack, istack, res_pos, d_hi, d_lo, d_f, d_i,
            delta_pos):
        out_hi = jnp.full(cap, SENT32_HI, jnp.int32)
        out_hi = out_hi.at[res_pos].set(hi, mode="drop")
        out_hi = out_hi.at[delta_pos].set(d_hi, mode="drop")
        out_lo = jnp.full(cap, SENT32_LO, jnp.int32)
        out_lo = out_lo.at[res_pos].set(lo, mode="drop")
        out_lo = out_lo.at[delta_pos].set(d_lo, mode="drop")
        if not ni:
            return out_hi, out_lo
        out_f = jnp.zeros((nf, cap), jnp.float64)
        if nf:
            out_f = out_f.at[:, res_pos].set(fstack, mode="drop")
            out_f = out_f.at[:, delta_pos].set(d_f, mode="drop")
        out_i = jnp.zeros((ni, cap), jnp.int64)
        out_i = out_i.at[:, res_pos].set(istack, mode="drop")
        out_i = out_i.at[:, delta_pos].set(d_i, mode="drop")
        return out_hi, out_lo, out_f, out_i

    return run


def merge_ring(ring: SplitRing, res_pos: np.ndarray,
               delta_sorted: np.ndarray, delta_pos: np.ndarray,
               delta_ts: Optional[np.ndarray] = None,
               delta_cols: Optional["dict[str, np.ndarray]"] = None
               ) -> Optional[SplitRing]:
    """ONE scatter-merge dispatch moving resident entries to ``res_pos``
    and landing the (already sorted) delta — keys AND payload planes in
    lockstep — at ``delta_pos``.  Positions beyond the caller-tracked
    valid length are padded to >= cap and dropped.  Returns None when
    the delta is not stageable (the caller demotes to host)."""
    if not ring_stageable(delta_sorted):
        return None
    cap = ring.cap
    n_res = len(res_pos)
    db = _bucket(max(len(delta_sorted), 1))
    rp = np.full(cap, cap, np.int64)
    rp[:n_res] = res_pos
    d_hi = np.full(db, SENT32_HI, np.int32)
    d_lo = np.full(db, SENT32_LO, np.int32)
    d_hi[: len(delta_sorted)] = split_hi32(delta_sorted)
    d_lo[: len(delta_sorted)] = split_lo32(delta_sorted)
    dp = np.full(db, cap, np.int64)
    dp[: len(delta_pos)] = delta_pos
    if ring.plan is None:
        out_hi, out_lo = timed_device(
            _merge32_kernel(cap, db, 0, 0), ring.hi, ring.lo, 0, 0,
            rp, d_hi, d_lo, 0, 0, dp)
        return SplitRing(out_hi, out_lo, cap, None, None, None, 0, 0,
                         ring.device)
    m = len(delta_sorted)
    d_f, d_i = _pack_stacks(ring.plan, ring.nf, ring.ni, db, m,
                            delta_cols, delta_ts)
    out_hi, out_lo, out_f, out_i = timed_device(
        _merge32_kernel(cap, db, ring.nf, ring.ni), ring.hi, ring.lo,
        ring.fstack, ring.istack, rp, d_hi, d_lo, d_f, d_i, dp)
    return SplitRing(out_hi, out_lo, cap, out_f, out_i, ring.plan,
                     ring.nf, ring.ni, ring.device)


class ProbeHit:
    """One ring probe's device-resident intermediates: candidate match
    ranges by the i32 hi plane (a SUPERSET of true matches — hi-equal,
    full-key-unverified) with ``start``/``cum`` still on device so the
    fused expand+gather dispatch consumes them without a round trip."""

    __slots__ = ("start_d", "cum_d", "counts", "q_hi", "q_lo", "mq", "m")

    def __init__(self, start_d, cum_d, counts, q_hi, q_lo, mq, m):
        self.start_d = start_d
        self.cum_d = cum_d
        self.counts = counts
        self.q_hi = q_hi
        self.q_lo = q_lo
        self.mq = mq
        self.m = m


def probe_ring(ring: SplitRing, qkeys_sorted: np.ndarray,
               n_valid: int) -> ProbeHit:
    """Candidate match ranges of sorted query keys against a resident
    ring — native-i32 compares on the hi plane (the merged-rank variant
    keeps TPU off searchsorted's sequential scan AND off the emulated
    u64 argsort).  Candidates still need the full-key collision verify
    (``expand_hit`` / ``expand_gather``)."""
    m = len(qkeys_sorted)
    mq = _bucket(max(m, 1))
    q_hi = np.full(mq, SENT32_HI, np.int32)
    q_lo = np.full(mq, SENT32_LO, np.int32)
    q_hi[:m] = split_hi32(qkeys_sorted)
    q_lo[:m] = split_lo32(qkeys_sorted)
    start_d, counts_d, cum_d = timed_device(
        _probe_kernel(mq, ring.cap, _merged_probe()), q_hi, ring.hi,
        m, n_valid)
    counts = np.asarray(counts_d)[:m].astype(np.int64)  # arroyolint: disable=host-sync -- intentional probe readback: candidate totals size the static-shape expansion
    return ProbeHit(start_d, cum_d, counts, q_hi, q_lo, mq, m)


def expand_hit(ring: SplitRing, hit: ProbeHit, total: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Keys-only expansion of candidate ranges: (qidx, ring positions),
    UNVERIFIED — the caller must kill i32 collisions against its host
    mirror (``skeys[spos] == qkeys[qidx]``)."""
    mb = _bucket(total)
    lidx_d, ridx_d = timed_device(_expand_kernel(hit.mq, mb),
                                  hit.start_d, hit.cum_d)
    lidx = np.asarray(lidx_d)[:total].astype(np.int64)  # arroyolint: disable=host-sync -- intentional probe readback: match pairs drive host-side verify/gather
    ridx = np.asarray(ridx_d)[:total].astype(np.int64)  # arroyolint: disable=host-sync -- intentional probe readback: match pairs drive host-side verify/gather
    return lidx, ridx


@functools.lru_cache(maxsize=64)
def _expand_gather_kernel(mq: int, cap: int, m: int, nf: int, ni: int):
    """The fused hot-path dispatch: candidate expansion (the histogram
    + prefix-sum form — searchsorted lowers to a sequential scan on
    TPU), full-key collision verify against the lo plane, and the
    payload-plane gather for BOTH stacks, all in one jitted call."""

    @jax.jit
    def run(start, cum, hi, lo, q_hi, q_lo, fstack, istack):
        dt = cum.dtype
        mark = jnp.zeros(m + 1, dt).at[cum].add(1, mode="drop")
        lidx = jnp.cumsum(mark[:m]).clip(0, mq - 1)
        before = jnp.where(lidx > 0, cum[lidx - 1], 0)
        ridx = (start[lidx]
                + (jnp.arange(m, dtype=dt) - before)).clip(0, cap - 1)
        valid = ((hi[ridx] == q_hi[lidx])
                 & (lo[ridx] == q_lo[lidx]))
        gf = (fstack[:, ridx] if nf
              else jnp.zeros((0, m), jnp.float64))
        gi = istack[:, ridx]
        return lidx, ridx, valid, gf, gi

    return run


def expand_gather(ring: SplitRing, hit: ProbeHit, total: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                             np.ndarray, np.ndarray]:
    """probe -> expand -> payload materialization, fused: ONE dispatch
    turns the (still device-resident) candidate ranges into verified
    pair indices plus the gathered payload stacks.  Returns
    (qidx, ring_pos, valid, f_rows, i_rows) sliced to ``total``
    candidates; ``valid`` is the on-device full-key verify (i32-equal-
    but-u64-distinct rows are False)."""
    mb = _bucket(total)
    lidx_d, ridx_d, valid_d, gf_d, gi_d = timed_device(
        _expand_gather_kernel(hit.mq, ring.cap, mb, ring.nf, ring.ni),
        hit.start_d, hit.cum_d, ring.hi, ring.lo, hit.q_hi, hit.q_lo,
        ring.fstack if ring.nf else np.zeros((0, ring.cap), np.float64),
        ring.istack)
    lidx = np.asarray(lidx_d)[:total].astype(np.int64)  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch
    ridx = np.asarray(ridx_d)[:total].astype(np.int64)  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch
    valid = np.asarray(valid_d)[:total]  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch
    gf = np.asarray(gf_d)[:, :total]  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch
    gi = np.asarray(gi_d)[:, :total]  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch
    return lidx, ridx, valid, gf, gi


@functools.lru_cache(maxsize=64)
def _gather32_kernel(cap: int, m: int, nf: int, ni: int):
    @jax.jit
    def run(idx, fstack, istack):
        gf = (fstack[:, idx] if nf
              else jnp.zeros((0, m), jnp.float64))
        gi = istack[:, idx]
        return gf, gi

    return run


def gather_ring(ring: SplitRing, spos: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Fire-path payload gather: materialize payload stacks for the
    given sorted-run positions (already exact — window fires match on
    the host mirror's full keys) in one dispatch.  Returns
    (f_rows, i_rows) sliced to ``len(spos)``."""
    n = len(spos)
    mb = _bucket(max(n, 1))
    idx = np.zeros(mb, np.int64)
    idx[:n] = spos
    gf_d, gi_d = timed_device(
        _gather32_kernel(ring.cap, mb, ring.nf, ring.ni), idx,
        ring.fstack if ring.nf else np.zeros((0, ring.cap), np.float64),
        ring.istack)
    return (np.asarray(gf_d)[:, :n],  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch
            np.asarray(gi_d)[:, :n])  # arroyolint: disable=host-sync -- intentional join-emission readback: gathered payload rows become the output batch


def unpack_payload(ring: SplitRing, gf: np.ndarray, gi: np.ndarray
                   ) -> Tuple[np.ndarray, "dict[str, np.ndarray]"]:
    """(timestamps, columns) from gathered payload stacks, restoring
    each column's exact storage dtype (bit-views for u64/datetimes,
    lossless narrowing for f32/int32/bool)."""
    ts = gi[0].astype(np.int64, copy=False)
    cols = {}
    for name, stack, idx, dt in ring.plan:
        cols[name] = (gf[idx] if dt == np.float64
                      else gf[idx].astype(dt) if stack == "f"
                      else _pay_from_i64(gi[idx], dt))
    return ts, cols


def join_pairs(lk: np.ndarray, rk: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                          np.ndarray, np.ndarray]:
    """(lo, ro, lidx, ridx, counts) for an equi-join of two uint64 key
    arrays: ``lo``/``ro`` sort each side, ``lidx``/``ridx`` index pairs
    into the sorted orders, ``counts`` is per-sorted-left-row match
    count (for outer-join unmatched masks)."""
    from ..obs import perf

    perf.count("join_state_resorts")  # full re-sort of both sides (the
    # legacy path the partitioned sorted runs exist to avoid)
    nl, nr = len(lk), len(rk)
    if not device_join_enabled(nl + nr) or nl == 0 or nr == 0 \
            or (lk == SENTINEL).any() or (rk == SENTINEL).any():
        lo = np.argsort(lk, kind="stable")
        ro = np.argsort(rk, kind="stable")
        lidx, ridx, counts = _host_pairs(lk[lo], rk[ro])
        return lo, ro, lidx, ridx, counts

    nlp, nrp = _bucket(nl), _bucket(nr)
    lk_p = np.full(nlp, SENTINEL, np.uint64)
    lk_p[:nl] = lk
    rk_p = np.full(nrp, SENTINEL, np.uint64)
    rk_p[:nr] = rk
    lo_d, lks_d = timed_device(_sort_kernel(nlp), lk_p)
    ro_d, rks_d = timed_device(_sort_kernel(nrp), rk_p)
    start_d, counts_d, cum_d = timed_device(
        _probe_kernel(nlp, nrp, _merged_probe()), lks_d, rks_d, nl, nr)
    counts = np.asarray(counts_d)[:nl]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    total = int(counts.sum())
    if total:
        m = _bucket(total)
        lidx_d, ridx_d = timed_device(_expand_kernel(nlp, m),
                                      start_d, cum_d)
        lidx = np.asarray(lidx_d)[:total]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
        ridx = np.asarray(ridx_d)[:total]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    else:
        lidx = np.zeros(0, dtype=np.int64)
        ridx = np.zeros(0, dtype=np.int64)
    lo = np.asarray(lo_d)[:nl]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    ro = np.asarray(ro_d)[:nr]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    return lo, ro, lidx, ridx, counts
