"""Device-side equi-join pair computation (SURVEY "Core TPU kernel #3";
reference: arroyo-worker/src/operators/joins.rs:14-181, per-record Rust
hash-join loops re-designed as batched XLA kernels).

The join's compute — sorting both key columns, probing match ranges,
prefix-summing match counts, and expanding (left, right) index pairs for
the cross product — runs as four static-shape jitted kernels on the
device.  Only the final materialization (gathering payload columns by
the computed indices) stays on host, where numpy fancy-indexing is a
memcpy and every dtype (strings, exact int64) survives untouched.

Static shapes: inputs pad to power-of-two buckets (sentinel keys sort to
the end and are excluded by valid-count masking), and the pair output
pads to the bucket of the exact total from the probe's prefix sum — so
each (bucket_l, bucket_r, bucket_m) triple compiles once.

Dispatch discipline: one sort per side, one probe, one expansion = four
device round trips per fired window, independent of fan-out.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.perf import timed_device

# padding key: sorts after every real hash; a real key colliding with it
# (probability ~2^-64 per row) routes the call to the host fallback
SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _bucket(n: int, floor: int = 512) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def _merged_probe() -> bool:
    """True when ``searchsorted`` must be avoided on device: XLA lowers
    it to a sequential per-bit scan that measured ~78 ms for 16k queries
    on a TPU v5 (the u64 argsort itself is fast there, 0.03 ms — the
    sort was never the problem).  The merged-rank probe computes the
    same bounds from one extra stable sort (~0.1 ms).
    ARROYO_JOIN_PROBE=merged|search forces either path on any backend
    so the CPU test mesh can check parity."""
    forced = os.environ.get("ARROYO_JOIN_PROBE")
    if forced == "merged":
        return True
    if forced == "search":
        return False
    return jax.default_backend() == "tpu"


@functools.lru_cache(maxsize=64)
def _sort_kernel(n: int):
    @jax.jit
    def run(keys):
        order = jnp.argsort(keys, stable=True)
        return order, keys[order]

    return run


@functools.lru_cache(maxsize=64)
def _probe_kernel(nl: int, nr: int, merged: bool):
    if not merged:
        @jax.jit
        def run(lk_sorted, rk_sorted, nl_valid, nr_valid):
            start = jnp.searchsorted(rk_sorted, lk_sorted, side="left")
            end = jnp.searchsorted(rk_sorted, lk_sorted, side="right")
            # right padding lives in [nr_valid, nr): clamp both bounds
            start = jnp.minimum(start, nr_valid)
            end = jnp.minimum(end, nr_valid)
            counts = jnp.where(jnp.arange(nl) < nl_valid, end - start, 0)
            cum = jnp.cumsum(counts)
            return start, counts, cum

        return run

    @jax.jit
    def run(lk_sorted, rk_sorted, nl_valid, nr_valid):
        # merged-rank probe: for every (already sorted) left key, how
        # many right keys are < / <= it falls out of its position in a
        # stably sorted concatenation.  With the right side placed
        # first, equal right keys sort before a left key, so
        # pos - own_rank = #(right <= key); left-first gives
        # #(right < key).
        iota = jnp.arange(nl, dtype=jnp.int32)
        pos = jnp.arange(nl + nr, dtype=jnp.int32)
        o_lf = jnp.argsort(jnp.concatenate([lk_sorted, rk_sorted]),
                           stable=True)
        inv_lf = jnp.zeros(nl + nr, jnp.int32).at[o_lf].set(pos)
        start = inv_lf[:nl] - iota
        o_rf = jnp.argsort(jnp.concatenate([rk_sorted, lk_sorted]),
                           stable=True)
        inv_rf = jnp.zeros(nl + nr, jnp.int32).at[o_rf].set(pos)
        end = inv_rf[nr:] - iota
        nr_valid = jnp.asarray(nr_valid, jnp.int32)
        start = jnp.minimum(start, nr_valid)
        end = jnp.minimum(end, nr_valid)
        counts = jnp.where(iota < jnp.asarray(nl_valid, jnp.int32),
                           end - start, 0)
        cum = jnp.cumsum(counts)
        return start, counts, cum

    return run


@functools.lru_cache(maxsize=64)
def _expand_kernel(nl: int, m: int):
    @jax.jit
    def run(start, cum):
        # pair j belongs to the left row whose cumulative-count interval
        # contains j (cum[i-1] <= j < cum[i]), i.e.
        # lidx[j] = #{i: cum[i] <= j}: scatter each interval end into a
        # histogram and inclusive-prefix-sum it — searchsorted computes
        # the same thing but lowers to a sequential scan on TPU
        # (measured 78 ms for 16k pairs vs ~0.1 ms for this form)
        dt = cum.dtype
        mark = jnp.zeros(m + 1, dt).at[cum].add(1, mode="drop")
        lidx = jnp.cumsum(mark[:m]).clip(0, nl - 1)
        before = jnp.where(lidx > 0, cum[lidx - 1], 0)
        ridx = start[lidx] + (jnp.arange(m, dtype=dt) - before)
        return lidx, ridx

    return run


def expand_counts(counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten per-group match counts into (group_idx, within_offset)
    pairs — the one home of the repeat/cumsum expansion idiom shared by
    the host join fallback and the partitioned sorted-run probes."""
    total = int(counts.sum())
    gidx = np.repeat(np.arange(len(counts)), counts)
    offs = np.arange(total) - np.repeat(np.cumsum(counts) - counts,
                                        counts)
    return gidx, offs


def _host_pairs(lk_sorted: np.ndarray, rk_sorted: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host fallback: identical contract, numpy end to end."""
    left_start = np.searchsorted(rk_sorted, lk_sorted, side="left")
    left_end = np.searchsorted(rk_sorted, lk_sorted, side="right")
    counts = left_end - left_start
    lidx, offs = expand_counts(counts)
    ridx = np.repeat(left_start, counts) + offs
    return lidx, ridx, counts


def device_join_enabled(n_rows: int) -> bool:
    """auto: device path on a real accelerator for batches big enough to
    amortize dispatch (on the CPU backend the "device" is the same
    core, so kernel dispatch is pure overhead — measured ~9% on q5);
    on: always (tests/fuzz parity); off: host numpy only."""
    mode = os.environ.get("ARROYO_DEVICE_JOIN", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    if jax.default_backend() == "cpu":
        return False
    return n_rows >= int(os.environ.get("ARROYO_DEVICE_JOIN_MIN", 2048))


# -- partition-adaptive resident rings (state/join_state.py) -----------------
#
# Hot join-state partitions keep their sorted key run device-resident in a
# preallocated power-of-two ring (SENTINEL-padded).  Maintenance is ONE
# scatter-merge dispatch per arriving delta (positions computed on the host
# mirror — the delta was already sorted there) and probes run against the
# resident ring without re-uploading state.


@functools.lru_cache(maxsize=64)
def _merge_ring_kernel(cap: int, db: int):
    @jax.jit
    def run(ring, res_pos, delta, delta_pos):
        out = jnp.full(cap, SENTINEL, jnp.uint64)
        out = out.at[res_pos].set(ring, mode="drop")
        out = out.at[delta_pos].set(delta, mode="drop")
        return out

    return run


def stage_ring(sorted_keys: np.ndarray,
               device: Any = None) -> Tuple[Any, int]:
    """Upload a sorted key run into a fresh power-of-two SENTINEL-padded
    device ring; returns (device array, capacity).  ``device`` pins the
    ring to one mesh device (state/join_state.py spreads hot partitions
    over the ``("keys",)`` mesh via ``parallel.shuffle.partition_device``
    so q7/q8-style joins stop funneling every ring through chip 0);
    None keeps the default placement.  Later ``merge_ring``/``probe_ring``
    dispatches follow the committed ring's device automatically."""
    cap = _bucket(max(len(sorted_keys), 1))
    padded = np.full(cap, SENTINEL, np.uint64)
    padded[: len(sorted_keys)] = sorted_keys
    return jax.device_put(padded, device), cap


def merge_ring(ring: Any, cap: int, res_pos: np.ndarray,
               delta_sorted: np.ndarray, delta_pos: np.ndarray) -> Any:
    """One scatter-merge dispatch: resident entries move to ``res_pos``,
    the (already sorted) delta lands at ``delta_pos``.  Positions beyond
    the caller-tracked valid length are padded to >= cap and dropped."""
    n_res = len(res_pos)
    db = _bucket(max(len(delta_sorted), 1))
    rp = np.full(cap, cap, np.int64)
    rp[:n_res] = res_pos
    dk = np.full(db, SENTINEL, np.uint64)
    dk[: len(delta_sorted)] = delta_sorted
    dp = np.full(db, cap, np.int64)
    dp[: len(delta_pos)] = delta_pos
    return timed_device(_merge_ring_kernel(cap, db), ring, rp, dk, dp)


def probe_ring(ring: Any, cap: int, qkeys_sorted: np.ndarray, n_valid: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """(start, counts) of sorted query keys against a resident ring —
    bit-identical to the host searchsorted probe (parity-tested)."""
    mq = _bucket(max(len(qkeys_sorted), 1))
    qp = np.full(mq, SENTINEL, np.uint64)
    qp[: len(qkeys_sorted)] = qkeys_sorted
    m = len(qkeys_sorted)
    # reuse the pairwise probe kernel (query = left, ring = right); the
    # merged-rank variant keeps TPU off searchsorted's sequential scan
    start_d, counts_d, _cum = timed_device(
        _probe_kernel(mq, cap, _merged_probe()), qp, ring, m, n_valid)
    return (np.asarray(start_d)[:m].astype(np.int64),  # arroyolint: disable=host-sync -- intentional probe readback: match ranges drive host-side pair expansion/gather
            np.asarray(counts_d)[:m].astype(np.int64))  # arroyolint: disable=host-sync -- intentional probe readback: match ranges drive host-side pair expansion/gather


def join_pairs(lk: np.ndarray, rk: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                          np.ndarray, np.ndarray]:
    """(lo, ro, lidx, ridx, counts) for an equi-join of two uint64 key
    arrays: ``lo``/``ro`` sort each side, ``lidx``/``ridx`` index pairs
    into the sorted orders, ``counts`` is per-sorted-left-row match
    count (for outer-join unmatched masks)."""
    from ..obs import perf

    perf.count("join_state_resorts")  # full re-sort of both sides (the
    # legacy path the partitioned sorted runs exist to avoid)
    nl, nr = len(lk), len(rk)
    if not device_join_enabled(nl + nr) or nl == 0 or nr == 0 \
            or (lk == SENTINEL).any() or (rk == SENTINEL).any():
        lo = np.argsort(lk, kind="stable")
        ro = np.argsort(rk, kind="stable")
        lidx, ridx, counts = _host_pairs(lk[lo], rk[ro])
        return lo, ro, lidx, ridx, counts

    nlp, nrp = _bucket(nl), _bucket(nr)
    lk_p = np.full(nlp, SENTINEL, np.uint64)
    lk_p[:nl] = lk
    rk_p = np.full(nrp, SENTINEL, np.uint64)
    rk_p[:nr] = rk
    lo_d, lks_d = timed_device(_sort_kernel(nlp), lk_p)
    ro_d, rks_d = timed_device(_sort_kernel(nrp), rk_p)
    start_d, counts_d, cum_d = timed_device(
        _probe_kernel(nlp, nrp, _merged_probe()), lks_d, rks_d, nl, nr)
    counts = np.asarray(counts_d)[:nl]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    total = int(counts.sum())
    if total:
        m = _bucket(total)
        lidx_d, ridx_d = timed_device(_expand_kernel(nlp, m),
                                      start_d, cum_d)
        lidx = np.asarray(lidx_d)[:total]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
        ridx = np.asarray(ridx_d)[:total]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    else:
        lidx = np.zeros(0, dtype=np.int64)
        ridx = np.zeros(0, dtype=np.int64)
    lo = np.asarray(lo_d)[:nl]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    ro = np.asarray(ro_d)[:nr]  # arroyolint: disable=host-sync -- intentional join-emission readback: matched pairs must land on host to build output batch
    return lo, ro, lidx, ridx, counts
