"""Device segment aggregation: group-by-key over a sorted batch.

The TPU replacement for the reference's per-record aggregator loops
(windows.rs:19-59 built-in vec/count/min/max/sum aggregators): rows are
sorted by key hash, segment ids assigned by run-length, and aggregates
computed with jax.ops.segment_* in one fused XLA program.  Shapes are
bucketed to powers of two so each operator compiles O(log n) kernels.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.logical import AggKind, AggSpec
from .expr import bucket_size

# f64 extremes: the aggregation channels are float64 (numeric-fidelity
# policy, ops/keyed_bins.ACC_DTYPE) so the null identities must not clip
# values beyond the float32 range
NEG_INF = jnp.finfo(jnp.float64).min
POS_INF = jnp.finfo(jnp.float64).max


@functools.lru_cache(maxsize=256)
def _segment_agg_kernel(n_padded: int, n_segments: int, agg_kinds: Tuple[str, ...]):
    """Jitted kernel: (values[k, n], segment_ids[n], valid[n]) ->
    per-segment aggregates [k, n_segments] + counts [n_segments]."""

    @jax.jit
    def run(values: jnp.ndarray, segment_ids: jnp.ndarray, valid: jnp.ndarray):
        # invalid rows go to a trash segment
        sid = jnp.where(valid, segment_ids, n_segments)
        counts = jax.ops.segment_sum(
            jnp.where(valid, 1, 0), sid, num_segments=n_segments + 1)[:n_segments]
        outs = []
        for i, kind in enumerate(agg_kinds):
            v = values[i]
            if kind == "sum":
                r = jax.ops.segment_sum(jnp.where(valid, v, 0.0), sid,
                                        num_segments=n_segments + 1)[:n_segments]
            elif kind == "min":
                r = jax.ops.segment_min(jnp.where(valid, v, POS_INF), sid,
                                        num_segments=n_segments + 1)[:n_segments]
            elif kind == "max":
                r = jax.ops.segment_max(jnp.where(valid, v, NEG_INF), sid,
                                        num_segments=n_segments + 1)[:n_segments]
            elif kind == "count":
                r = counts.astype(jnp.float64)
            else:
                raise ValueError(kind)
            outs.append(r)
        return jnp.stack(outs) if outs else jnp.zeros((0, n_segments)), counts

    return run


def _segment_host() -> bool:
    """True when the per-fire segment reduce should run as numpy
    reduceat instead of the device kernel: expressions are pinned to
    host while an accelerator backend is active — the tunnel regime,
    where every device readback pays ~70 ms fixed latency (BASELINE.md
    round-4).  This reduce runs once per watermark flush and its result
    is consumed on host immediately, so it follows the expressions.
    ARROYO_SEGMENT_HOST forces either path (tests cover the host branch
    from the CPU mesh this way)."""
    import os

    forced = os.environ.get("ARROYO_SEGMENT_HOST")
    if forced is not None:
        return forced.lower() not in ("", "0", "off", "false", "no")
    from .expr import _expr_device

    return _expr_device() is not None and jax.default_backend() != "cpu"


def _segmented_median(v: np.ndarray, kh_sorted: np.ndarray,
                      uniq: np.ndarray, seg_start: np.ndarray
                      ) -> np.ndarray:
    """Per-segment median in three vector ops (the np.median UDAF fast
    path): in-segment value sort via one lexsort, NaNs last, then the
    two middle elements of each segment's non-null prefix."""
    n = len(v)
    if n == 0 or len(seg_start) == 0:
        return np.zeros(0, dtype=np.float64)
    so = np.lexsort((v, kh_sorted))  # NaN sorts after every number
    vs = v[so]
    sizes = np.diff(np.append(seg_start, n))
    nn = sizes - np.add.reduceat(np.isnan(vs).astype(np.int64), seg_start)
    lo_i = seg_start + np.maximum(nn - 1, 0) // 2
    hi_i = seg_start + np.maximum(nn, 1) // 2
    med = 0.5 * (vs[np.minimum(lo_i, n - 1)] + vs[np.minimum(hi_i, n - 1)])
    return np.where(nn > 0, med, np.nan)


def segment_aggregate(
    key_hash: np.ndarray,
    timestamps: np.ndarray,
    agg_inputs: Dict[str, np.ndarray],
    aggs: Tuple[AggSpec, ...],
) -> Tuple[np.ndarray, Dict[str, np.ndarray], np.ndarray, np.ndarray,
           Dict[str, np.ndarray]]:
    """Group rows by key_hash and compute ``aggs``.

    Returns (unique_keys, {output_name: values}, max_ts_per_key,
    row_counts_per_key, {output_name: non_null_counts} for column aggs).
    Nulls (NaN after coercion) are skipped: they feed the aggregate its
    identity, COUNT(col) counts non-null rows only, AVG divides by the
    non-null count, and an all-null segment emits NaN (SQL NULL).  Host
    does the sort (numpy argsort, C speed) — the reduce runs on device.
    """
    n = len(key_hash)
    order = np.argsort(key_hash, kind="stable")
    kh = key_hash[order]
    uniq, seg_start = np.unique(kh, return_index=True)
    seg_ids = np.searchsorted(uniq, kh).astype(np.int32)
    n_seg = len(uniq)

    npad = bucket_size(n)
    spad = bucket_size(n_seg)
    valid = np.zeros(npad, dtype=bool)
    valid[:n] = True
    sid_p = np.zeros(npad, dtype=np.int32)
    sid_p[:n] = seg_ids

    # COUNT(DISTINCT x) is inherently sort-based: host np.unique over
    # (key, value) pairs (not mergeable across bins, hence only available on
    # the buffered window path — matching the reference's two-phase
    # exclusion of non-mergeable aggregates, operators.rs:165-167)
    distinct_results: Dict[str, np.ndarray] = {}
    host_valid_counts: Dict[str, np.ndarray] = {}
    device_aggs = []
    # channel layout accumulators (device_aggs append theirs below;
    # UDAF plans append theirs inside the dispatch loop)
    from ..formats import coerce_float

    kinds: List[str] = []
    rows: List[np.ndarray] = []
    udaf_specs: List[Tuple[AggSpec, "UdafPlan", Dict[str, int]]] = []

    def _host_segments(column: np.ndarray):
        """(values-in-order, per-row validity, per-segment row groups) —
        the shared scaffolding for every host-reduced aggregate (UDAFs,
        string MIN/MAX)."""
        from ..formats import nan_validity

        v = column[order]
        ok = nan_validity(v, None)
        ok_rows = (np.ones(len(v), dtype=bool) if ok is None
                   else np.asarray(ok))  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
        return v, ok_rows, np.split(np.arange(n), seg_start[1:])

    from ..obs import perf as _perf

    for a in aggs:
        if a.kind == AggKind.UDAF:
            from .udaf import channel_rows, udaf_channels_enabled, udaf_plan

            col_raw = np.asarray(agg_inputs[a.column])  # arroyolint: disable=host-sync -- aggregate inputs on this generic path are host numpy columns (device-channel rows never reach it)
            if (a.fn is np.median and col_raw.dtype.kind in "if"  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
                    and udaf_channels_enabled()):
                # vectorized across ALL segments: one in-segment sort,
                # then middle-element picks — NaNs sort last inside each
                # segment, so the non-null count bounds the true middle
                # (order statistics don't decompose into channels; this
                # exact path counts on the vectorized side of the split)
                _perf.count("udaf_channel_rows", n)
                distinct_results[a.output] = _segmented_median(
                    np.asarray(agg_inputs[a.column][order],  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
                               dtype=np.float64), kh, uniq, seg_start)
                continue
            # numeric UDAF expressible over mergeable partials: compile
            # onto channels (ops/udaf.py probe algebra) — object/string
            # columns stay on the counted sticky host fallback
            plan = (udaf_plan(a.fn) if col_raw.dtype.kind in "ifbu"
                    else None)
            if plan is not None:
                raw = coerce_float(col_raw[order], np.float64)
                ok = ~np.isnan(raw)
                chmap: Dict[str, int] = {}
                for ch in plan.channels:
                    kind, rowv = channel_rows(ch, raw, ok)
                    chmap[ch] = len(kinds)
                    kinds.append(kind)
                    rows.append(rowv)
                udaf_specs.append((a, plan, chmap))
                _perf.count("udaf_channel_rows", n)
                continue
            # per-segment host call over non-null values (non-mergeable —
            # only reachable via buffered window paths, like the
            # reference's wasm UDFs, operators/mod.rs:347-494)
            _perf.count("udaf_host_rows", n)
            v, ok_rows, groups = _host_segments(agg_inputs[a.column])
            out = []
            cnt = np.zeros(n_seg, dtype=np.int64)
            for j, g in enumerate(groups):
                gv = v[g[ok_rows[g]]]
                cnt[j] = len(gv)
                out.append(a.fn(gv) if len(gv) else np.nan)
            distinct_results[a.output] = np.asarray(out)  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
            # same valid_counts contract as the compiled-channel path:
            # the knob must not change the result SHAPE, only the route
            host_valid_counts[a.output] = cnt
        elif (a.kind in (AggKind.MIN, AggKind.MAX)
              and np.asarray(agg_inputs[a.column]).dtype == object):  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
            # string MIN/MAX (lexicographic, NULLs skipped): object
            # columns can't ride the f64 device channels — per-segment
            # host reduce, like the reference's accumulator for Utf8
            v, ok_rows, groups = _host_segments(agg_inputs[a.column])
            pick = min if a.kind == AggKind.MIN else max
            outv = []
            for g in groups:
                gv = v[g[ok_rows[g]]]
                outv.append(pick(gv) if len(gv) else None)
            distinct_results[a.output] = np.asarray(outv, dtype=object)  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
        elif a.kind == AggKind.COUNT_DISTINCT:
            from ..formats import nan_validity

            v = agg_inputs[a.column][order]
            # SQL excludes NULLs from COUNT(DISTINCT) — and NaN != NaN
            # would otherwise make every null row its own "distinct"
            # value
            ok = nan_validity(v, None)
            if ok is not None and not np.asarray(ok).all():  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
                keep = np.asarray(ok)  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
                vv0, kv0 = v[keep], kh[keep]
            else:
                vv0, kv0 = v, kh
            m = len(vv0)
            pair_sort = np.lexsort((vv0, kv0))
            kv, vv = kv0[pair_sort], vv0[pair_sort]
            is_new = np.ones(m, dtype=bool)
            is_new[1:] = (kv[1:] != kv[:-1]) | (vv[1:] != vv[:-1])
            per_key = np.zeros(n_seg, dtype=np.int64)
            np.add.at(per_key, np.searchsorted(uniq, kv[is_new]), 1)
            distinct_results[a.output] = per_key
        else:
            device_aggs.append(a)

    # Channel layout: one kernel channel per agg, plus a hidden additive
    # validity-count channel per column-reading agg so nulls are skipped
    # (same scheme as ops/keyed_bins.py)
    specs: List[Tuple[AggSpec, int, Optional[int]]] = []
    for a in device_aggs:
        if a.column is None:  # COUNT(*) — all rows
            specs.append((a, len(kinds), None))
            kinds.append("count")
            rows.append(np.zeros(n, dtype=np.float64))
            continue
        raw = coerce_float(agg_inputs[a.column][order],
                           np.float64)
        ok = ~np.isnan(raw)
        if a.kind == AggKind.COUNT:  # COUNT(col) — non-null rows
            specs.append((a, len(kinds), None))
            kinds.append("sum")
            rows.append(ok.astype(np.float64))
            continue
        ident = np.float64(0.0 if a.kind in (AggKind.SUM, AggKind.AVG)
                           else (POS_INF if a.kind == AggKind.MIN
                                 else NEG_INF))
        specs.append((a, len(kinds), len(kinds) + 1))
        kinds.append("sum" if a.kind == AggKind.AVG else a.kind.value)
        rows.append(np.where(ok, raw, ident).astype(np.float64))
        kinds.append("sum")
        rows.append(ok.astype(np.float64))

    if _segment_host():
        row_counts = np.diff(np.append(seg_start, n))
        outs = np.empty((len(kinds), n_seg), dtype=np.float64)
        for i, kind in enumerate(kinds):
            row = rows[i]
            if kind == "sum":
                outs[i] = np.add.reduceat(row, seg_start)
            elif kind == "min":
                outs[i] = np.minimum.reduceat(row, seg_start)
            elif kind == "max":
                outs[i] = np.maximum.reduceat(row, seg_start)
            else:  # count: rows per segment
                outs[i] = row_counts
        counts = row_counts
    else:
        vals = np.zeros((len(kinds), npad), dtype=np.float64)
        for i, row in enumerate(rows):
            vals[i, :n] = row

        from ..obs.perf import timed_device

        kernel = _segment_agg_kernel(npad, spad, tuple(kinds))
        outs, counts = timed_device(kernel, jnp.asarray(vals),
                                    jnp.asarray(sid_p), jnp.asarray(valid))
        outs = np.asarray(outs)[:, :n_seg]  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
    out_cols = dict(distinct_results)
    valid_counts: Dict[str, np.ndarray] = dict(host_valid_counts)
    for a, plan, chmap in udaf_specs:
        parts = {ch: np.asarray(outs[i], dtype=np.float64)  # arroyolint: disable=host-sync -- outs was pulled above; these are host slices of the already-read kernel result
                 for ch, i in chmap.items()}
        nnz = parts["nnz"]
        with np.errstate(all="ignore"):
            col = plan.combine(parts)
        # all-null segments emit NaN — exactly what the host loop's
        # "empty gv" branch produces
        out_cols[a.output] = np.where(nnz > 0, col, np.nan)
        valid_counts[a.output] = nnz.astype(np.int64)
    for a, ci, vi in specs:
        col = outs[ci]
        if vi is not None:
            nv = outs[vi]
            valid_counts[a.output] = nv.astype(np.int64)
            if a.kind == AggKind.AVG:
                col = col / np.maximum(nv, 1)
            col = np.where(nv > 0, col, np.nan)
        if a.kind == AggKind.COUNT:
            col = col.astype(np.int64)
            valid_counts[a.output] = col
        out_cols[a.output] = col

    # per-key max timestamp (host; used for emitted record timestamps)
    ts_sorted = timestamps[order]
    max_ts = np.maximum.reduceat(ts_sorted, seg_start)
    return (uniq, out_cols, max_ts,
            np.asarray(counts)[:n_seg].astype(np.int64), valid_counts)  # arroyolint: disable=host-sync -- host-segment fallback path: UDAF/string/object columns cannot ride the f64 device channels; these are host numpy arrays
