"""Device segment top-k: the TopN hot path (SURVEY #14/#15).

The reference keeps per-partition sorted retention on the heap
(tumbling_top_n_window.rs, sliding_top_n_aggregating_window.rs); here the
whole (partition, window) top-k is ONE fused device sort: sort rows by
(segment, -value) with a single ``lax.sort``, rank within segment via a
cumulative max over segment starts, and keep rank < K.  Ties preserve
row order (stable sort), matching the host lexsort semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .expr import bucket_size

_PAD_SEG = np.int32(2**31 - 1)  # padding rows sort after all segments


@functools.lru_cache(maxsize=128)
def _topk_kernel(n_pad: int, k: int):
    @jax.jit
    def run(seg, neg_val):
        # seg: i32[n_pad] (padding = _PAD_SEG); neg_val: f64[n_pad]
        idx = jnp.arange(n_pad, dtype=jnp.int32)
        s_seg, _s_val, s_idx = jax.lax.sort(
            (seg, neg_val, idx), num_keys=2, is_stable=True)
        pos = jnp.arange(n_pad, dtype=jnp.int32)
        is_first = jnp.ones(n_pad, bool).at[1:].set(s_seg[1:] != s_seg[:-1])
        run_start = jax.lax.cummax(jnp.where(is_first, pos, 0))
        rank = pos - run_start
        keep = (rank < k) & (s_seg != _PAD_SEG)
        return s_idx, keep

    return run


def segment_top_k(part: np.ndarray, values: np.ndarray, k: int
                  ) -> np.ndarray:
    """Row indices (in original order) of the top ``k`` rows by ``values``
    (descending) within each ``part`` group."""
    n = len(part)
    # segment ids: dense i32 from the (arbitrary-dtype) partition column
    uniq = np.unique(part)
    seg = np.searchsorted(uniq, part).astype(np.int32)
    n_pad = bucket_size(n)
    seg_p = np.full(n_pad, _PAD_SEG, np.int32)
    seg_p[:n] = seg
    val_p = np.zeros(n_pad, np.float64)
    val_p[:n] = -np.asarray(values, dtype=np.float64)  # arroyolint: disable=host-sync -- intentional top-k emission readback: surviving rows must select on host

    from ..obs.perf import timed_device

    s_idx, keep = timed_device(_topk_kernel(n_pad, k),
                               jnp.asarray(seg_p), jnp.asarray(val_p))
    s_idx = np.asarray(s_idx)  # arroyolint: disable=host-sync -- intentional top-k emission readback: surviving rows must select on host
    keep = np.asarray(keep)  # arroyolint: disable=host-sync -- intentional top-k emission readback: surviving rows must select on host
    out = s_idx[keep]
    out.sort()  # restore original row order
    return out
