"""Vectorized session-interval union: ONE merge dispatch per batch.

The legacy :class:`~arroyo_tpu.engine.operators_window.SessionWindowOperator`
gap-merged per-key Python lists — a ``sessions.sort()`` and a linear
scan per key per batch (windows.rs:232-302 semantics) that made config5
the slowest headline workload.  This module computes the SAME union for
ALL keys at once over ``(key_hash, start, end)`` interval rows sorted by
``(key, start)``:

1. a **segmented running max of ends** (Hillis-Steele log-doubling with
   a same-key guard — int64-exact; the classic per-group offset trick
   would overflow int64 with micros timestamps),
2. a *new-session* flag wherever an interval's start exceeds the running
   end of every prior interval of its key (touching intervals merge,
   matching the reference's ``s <= merged[-1][1]``),
3. per-session merged bounds by ``reduceat`` over the flag boundaries.

The max-size clamp is NOT vectorized: a merged span exceeding
``MAX_SESSION_SIZE_MICROS`` is exactly the condition under which the
legacy path would have clamped (the unclamped union span bounds every
intermediate span from above, and equals the legacy span when no clamp
fires), so flagged keys are returned for the caller to re-run through
the authoritative per-key path — bit-for-bit parity by construction.

The same scan compiles as a jitted kernel (``ARROYO_SESSION_DEVICE``)
so accelerator backends keep the merge on device; numpy is the default
on CPU where the dispatch envelope would dominate.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import numpy as np

from .expr import bucket_size

_I64_MIN = np.iinfo(np.int64).min
_I64_MAX = np.iinfo(np.int64).max


def session_device_enabled() -> bool:
    """Should the union scan run as a jitted device kernel?  ``auto``
    keeps it on host for the CPU backend (the scan is memory-bound and
    the dispatch envelope dominates at session-state sizes) and on
    device for accelerators."""
    mode = os.environ.get("ARROYO_SESSION_DEVICE", "auto").lower()
    if mode in ("off", "0", "false", "no"):
        return False
    if mode in ("on", "1", "true", "force"):
        return True
    import jax

    return jax.default_backend() != "cpu"


def _segmented_running_max(en: np.ndarray, newkey: np.ndarray) -> np.ndarray:
    """Inclusive per-key prefix max of ``en`` (keys contiguous, flagged
    by ``newkey``).  Log-doubling: O(n log n) pure vector ops, exact in
    int64."""
    run = en.copy()
    gid = np.cumsum(newkey)
    n = len(run)
    d = 1
    while d < n:
        same = gid[d:] == gid[:-d]
        np.copyto(run[d:], np.maximum(run[d:], run[:-d]), where=same)
        d <<= 1
    return run


@functools.lru_cache(maxsize=64)
def _union_kernel(npad: int):
    """Jitted union scan: (kh, st, en, valid) -> (new_flags, run_en).
    Padded rows carry valid=False and become singleton trash sessions;
    the host compresses them away.  int64 arithmetic relies on the
    package-wide x64 enable (arroyo_tpu/__init__.py)."""
    import jax
    import jax.numpy as jnp

    steps = max(npad - 1, 1).bit_length()

    @jax.jit
    def run(kh: "jnp.ndarray", st: "jnp.ndarray", en: "jnp.ndarray",
            valid: "jnp.ndarray"):
        newkey = jnp.ones(npad, dtype=bool)
        if npad > 1:
            newkey = newkey.at[1:].set((kh[1:] != kh[:-1])
                                       | ~valid[1:] | ~valid[:-1])
        gid = jnp.cumsum(newkey.astype(jnp.int64))
        run_en = en
        for i in range(steps):
            d = 1 << i
            same = gid[d:] == gid[:-d]
            run_en = run_en.at[d:].set(
                jnp.where(same, jnp.maximum(run_en[d:], run_en[:-d]),
                          run_en[d:]))
        new = newkey
        if npad > 1:
            new = new.at[1:].set(newkey[1:] | (st[1:] > run_en[:-1]))
        return new, run_en

    return run


def union_sorted_intervals(
    kh: np.ndarray, st: np.ndarray, en: np.ndarray,
    device: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Union interval rows sorted by ``(key, start)`` into disjoint
    sessions per key (touching intervals merge).

    Returns ``(m_kh, m_st, m_en, sid, sess_first)``: merged session
    keys/bounds (still sorted by ``(key, start)``), the per-input-row
    merged-session ordinal ``sid`` (for folding per-row metadata into
    its session), and the first input row of each session."""
    n = len(kh)
    if n == 0:
        z64 = np.zeros(0, dtype=np.int64)
        return (np.zeros(0, dtype=np.uint64), z64.copy(), z64.copy(),
                z64.copy(), z64.copy())
    if device and n > 1:
        import jax.numpy as jnp

        from ..obs.perf import timed_device

        npad = bucket_size(n)
        khp = np.zeros(npad, dtype=np.uint64)
        stp = np.full(npad, _I64_MAX, dtype=np.int64)
        enp = np.full(npad, _I64_MIN, dtype=np.int64)
        vp = np.zeros(npad, dtype=bool)
        khp[:n], stp[:n], enp[:n], vp[:n] = kh, st, en, True
        new_d, _run = timed_device(_union_kernel(npad), jnp.asarray(khp),
                                   jnp.asarray(stp), jnp.asarray(enp),
                                   jnp.asarray(vp))
        new = np.asarray(new_d)[:n]  # arroyolint: disable=host-sync -- merged-session boundaries must materialize on host to splice the session run (pane-emission-class readback)
    else:
        newkey = np.empty(n, dtype=bool)
        newkey[0] = True
        newkey[1:] = kh[1:] != kh[:-1]
        run_en = _segmented_running_max(en, newkey)
        new = newkey
        new[1:] |= st[1:] > run_en[:-1]
    sess_first = np.nonzero(new)[0]
    sid = np.cumsum(new) - 1
    m_kh = kh[sess_first]
    m_st = st[sess_first]  # sorted by start: first interval owns the min
    m_en = np.maximum.reduceat(en, sess_first)
    return m_kh, m_st, m_en, sid.astype(np.int64), sess_first
