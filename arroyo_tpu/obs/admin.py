"""Per-service admin HTTP server: /status /name /metrics /details
(start_admin_server, arroyo-server-common/src/lib.rs:180-205).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional

from ..api.http import HttpServer, Request, Response, Router
from .metrics import render_metrics


class AdminServer:
    def __init__(self, service: str,
                 details: Optional[Callable[[], Dict[str, Any]]] = None):
        self.service = service
        self.details_fn = details or (lambda: {})
        self.started = time.time()
        # in-flight jax-profiler capture (POST /debug/profile start/stop)
        self._profile_capture: Optional[Dict[str, Any]] = None
        router = Router()

        @router.get("/status")
        async def status(req: Request):
            return {"status": "ok", "service": f"arroyo-{self.service}",
                    "uptime_s": time.time() - self.started}

        @router.get("/name")
        async def name(req: Request):
            return Response(body=f"arroyo-{self.service}".encode(),
                            content_type="text/plain")

        @router.get("/metrics")
        async def metrics(req: Request):
            return Response(body=render_metrics(),
                            content_type="text/plain; version=0.0.4")

        # flight-recorder export: the span ring (task lifecycle, barrier
        # alignment, checkpoint phases, window fires, kernel dispatch,
        # data-plane flushes) as Chrome-trace JSON — open in
        # ui.perfetto.dev.  ?cat=checkpoint filters to one category;
        # ?reset=1 clears the ring after export.
        @router.get("/trace")
        async def trace(req: Request):
            from . import tracing

            out = tracing.chrome_trace(req.query.get("cat") or None)
            if req.query.get("reset"):
                tracing.reset()
            return Response(body=json.dumps(out).encode(),
                            content_type="application/json")

        @router.get("/details")
        async def details(req: Request):
            return {"service": f"arroyo-{self.service}",
                    "pid": os.getpid(),
                    "details": self.details_fn()}

        # arroyosan triage surface: whether the runtime sanitizer is
        # armed, and the tail of its protocol event ring (the same ring
        # a SanitizerError snapshots) — the first stop after a
        # task_failed carrying an arroyosan[...] message
        @router.get("/sanitizer")
        async def sanitizer(req: Request):
            from ..analysis.sanitizer import (recent_events,
                                              sanitize_enabled)

            limit = int(req.query.get("limit") or 64)
            return {
                "enabled": sanitize_enabled(),
                "events": [
                    {"t": round(ts, 6), "kind": kind, "task": task,
                     "detail": detail}
                    for ts, kind, task, detail in recent_events(limit)],
            }

        # latency-observatory export (obs/latency.py): per-sink e2e
        # quantiles, per-edge watermark ages, critical-path stage
        # decomposition and the device-memory ledger — the "p99 is
        # high, where is the time?" first stop.  Empty/disabled until
        # sampling is armed (ARROYO_LATENCY_SAMPLE_N>0 at engine build).
        @router.get("/latency")
        async def latency_snapshot(req: Request):
            from . import latency

            lat = latency.active()
            if lat is None:
                return {"enabled": False}
            snap = lat.snapshot()
            snap["enabled"] = True
            return snap

        # phase-profiler export (obs/profiler.py): the measured phase
        # table as pprof/flamegraph folded stacks (`job;operator;phase
        # micros` lines — feed to flamegraph.pl / speedscope), or the
        # full structured snapshot incl. watchdog stall stacks with
        # ?fmt=json.  Empty/disabled until the profiler is armed
        # (ARROYO_PROFILE=1 at engine build).
        @router.get("/profile/phases")
        async def profile_phases(req: Request):
            from . import profiler

            prof = profiler.active()
            if req.query.get("fmt") == "json":
                if prof is None:
                    return {"enabled": False}
                snap = prof.snapshot()
                snap["enabled"] = True
                # full stall stacks only here (the heartbeat rollup
                # ships just the tails)
                snap["watchdog"]["stall_stacks"] = [
                    dict(s) for s in list(prof.watchdog.stalls)]
                return snap
            body = prof.collapsed_stacks() if prof is not None else ""
            return Response(body=body.encode(),
                            content_type="text/plain")

        # continuous-profiling hooks: the pyroscope analog
        # (arroyo-server-common/src/lib.rs:12-15, try_profile_start) is the
        # jax profiler — a POST captures a Perfetto/XPlane trace of every
        # device kernel + host dispatch.  Two modes:
        #   one-shot: {"seconds": 2}            (trace, sleep, stop)
        #   start/stop: {"action": "start", "max_seconds": 60} then
        #               {"action": "stop"}
        # every start arms a max-duration watchdog, so a forgotten stop
        # can no longer trace forever; the stop response returns the
        # capture directory listing.
        @router.post("/debug/profile")
        async def profile(req: Request):
            import asyncio

            body = req.json() if req.body else {}
            action = body.get("action")
            out_dir = body.get(
                "dir", f"/tmp/arroyo_tpu/profiles/{self.service}")

            def listing(d=None):
                files = []
                for root, _dirs, fs in os.walk(d or out_dir):
                    files += [os.path.join(root, f) for f in fs]
                return sorted(files)

            if action == "stop":
                cap = self._profile_capture
                if cap is None:
                    return {"error": "no capture in progress"}
                self._profile_capture = None
                cap["watchdog"].cancel()
                if not cap["stopped"]:
                    cap["stopped"] = True
                    import jax

                    jax.profiler.stop_trace()
                return {"dir": cap["dir"], "stopped": True,
                        "auto_stopped": cap["auto_stopped"],
                        # list where the capture was WRITTEN (its start
                        # dir), not the stop request's default dir
                        "files": listing(cap["dir"])[-32:],
                        "hint": "open in perfetto.dev or tensorboard"}

            if self._profile_capture is not None:
                return {"error": "capture already in progress",
                        "dir": self._profile_capture["dir"]}
            import jax

            os.makedirs(out_dir, exist_ok=True)
            if action == "start":
                max_secs = min(float(body.get("max_seconds", 60.0)),
                               600.0)
                jax.profiler.start_trace(out_dir)
                cap = {"dir": out_dir, "stopped": False,
                       "auto_stopped": False}

                async def auto_stop():
                    # the forgotten-stop watchdog: bound every capture
                    await asyncio.sleep(max_secs)
                    if self._profile_capture is cap:
                        self._profile_capture = None
                        cap["stopped"] = True
                        cap["auto_stopped"] = True
                        jax.profiler.stop_trace()

                cap["watchdog"] = asyncio.ensure_future(auto_stop())
                self._profile_capture = cap
                return {"dir": out_dir, "started": True,
                        "max_seconds": max_secs}

            # legacy one-shot capture (bounded as before)
            secs = float(body.get("seconds", 2.0))
            jax.profiler.start_trace(out_dir)
            try:
                await asyncio.sleep(min(secs, 60.0))
            finally:
                jax.profiler.stop_trace()
            traces = [f for f in listing()
                      if f.endswith((".trace.json.gz", ".xplane.pb"))]
            return {"dir": out_dir, "seconds": secs,
                    "traces": traces[-4:],
                    "files": listing()[-32:],
                    "hint": "open in perfetto.dev or tensorboard"}

        @router.get("/debug/device")
        async def device(req: Request):
            import jax

            return {"backend": jax.default_backend(),
                    "devices": [str(d) for d in jax.devices()],
                    "live_buffer_bytes": sum(
                        getattr(b, "nbytes", 0)
                        for d in jax.devices()
                        for b in d.live_buffers())
                    if hasattr(jax.devices()[0], "live_buffers") else None}

        self.http = HttpServer(router)
        self.port: Optional[int] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.port = await self.http.start(host, port)
        return self.port

    async def stop(self) -> None:
        await self.http.stop()
