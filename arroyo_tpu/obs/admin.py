"""Per-service admin HTTP server: /status /name /metrics /details
(start_admin_server, arroyo-server-common/src/lib.rs:180-205).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional

from ..api.http import HttpServer, Request, Response, Router
from .metrics import render_metrics


class AdminServer:
    def __init__(self, service: str,
                 details: Optional[Callable[[], Dict[str, Any]]] = None):
        self.service = service
        self.details_fn = details or (lambda: {})
        self.started = time.time()
        router = Router()

        @router.get("/status")
        async def status(req: Request):
            return {"status": "ok", "service": f"arroyo-{self.service}",
                    "uptime_s": time.time() - self.started}

        @router.get("/name")
        async def name(req: Request):
            return Response(body=f"arroyo-{self.service}".encode(),
                            content_type="text/plain")

        @router.get("/metrics")
        async def metrics(req: Request):
            return Response(body=render_metrics(),
                            content_type="text/plain; version=0.0.4")

        @router.get("/details")
        async def details(req: Request):
            return {"service": f"arroyo-{self.service}",
                    "pid": os.getpid(),
                    "details": self.details_fn()}

        self.http = HttpServer(router)
        self.port: Optional[int] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.port = await self.http.start(host, port)
        return self.port

    async def stop(self) -> None:
        await self.http.stop()
