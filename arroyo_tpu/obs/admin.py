"""Per-service admin HTTP server: /status /name /metrics /details
(start_admin_server, arroyo-server-common/src/lib.rs:180-205).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional

from ..api.http import HttpServer, Request, Response, Router
from .metrics import render_metrics


class AdminServer:
    def __init__(self, service: str,
                 details: Optional[Callable[[], Dict[str, Any]]] = None):
        self.service = service
        self.details_fn = details or (lambda: {})
        self.started = time.time()
        router = Router()

        @router.get("/status")
        async def status(req: Request):
            return {"status": "ok", "service": f"arroyo-{self.service}",
                    "uptime_s": time.time() - self.started}

        @router.get("/name")
        async def name(req: Request):
            return Response(body=f"arroyo-{self.service}".encode(),
                            content_type="text/plain")

        @router.get("/metrics")
        async def metrics(req: Request):
            return Response(body=render_metrics(),
                            content_type="text/plain; version=0.0.4")

        # flight-recorder export: the span ring (task lifecycle, barrier
        # alignment, checkpoint phases, window fires, kernel dispatch,
        # data-plane flushes) as Chrome-trace JSON — open in
        # ui.perfetto.dev.  ?cat=checkpoint filters to one category;
        # ?reset=1 clears the ring after export.
        @router.get("/trace")
        async def trace(req: Request):
            from . import tracing

            out = tracing.chrome_trace(req.query.get("cat") or None)
            if req.query.get("reset"):
                tracing.reset()
            return Response(body=json.dumps(out).encode(),
                            content_type="application/json")

        @router.get("/details")
        async def details(req: Request):
            return {"service": f"arroyo-{self.service}",
                    "pid": os.getpid(),
                    "details": self.details_fn()}

        # arroyosan triage surface: whether the runtime sanitizer is
        # armed, and the tail of its protocol event ring (the same ring
        # a SanitizerError snapshots) — the first stop after a
        # task_failed carrying an arroyosan[...] message
        @router.get("/sanitizer")
        async def sanitizer(req: Request):
            from ..analysis.sanitizer import (recent_events,
                                              sanitize_enabled)

            limit = int(req.query.get("limit") or 64)
            return {
                "enabled": sanitize_enabled(),
                "events": [
                    {"t": round(ts, 6), "kind": kind, "task": task,
                     "detail": detail}
                    for ts, kind, task, detail in recent_events(limit)],
            }

        # continuous-profiling hooks: the pyroscope analog
        # (arroyo-server-common/src/lib.rs:12-15, try_profile_start) is the
        # jax profiler — one POST captures a Perfetto/XPlane trace of every
        # device kernel + host dispatch in the window
        @router.post("/debug/profile")
        async def profile(req: Request):
            import asyncio

            import jax

            body = req.json() if req.body else {}
            secs = float(body.get("seconds", 2.0))
            out_dir = body.get(
                "dir", f"/tmp/arroyo_tpu/profiles/{self.service}")
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                await asyncio.sleep(min(secs, 60.0))
            finally:
                jax.profiler.stop_trace()
            traces = []
            for root, _dirs, files in os.walk(out_dir):
                traces += [os.path.join(root, f) for f in files
                           if f.endswith((".trace.json.gz", ".xplane.pb"))]
            return {"dir": out_dir, "seconds": secs,
                    "traces": sorted(traces)[-4:],
                    "hint": "open in perfetto.dev or tensorboard"}

        @router.get("/debug/device")
        async def device(req: Request):
            import jax

            return {"backend": jax.default_backend(),
                    "devices": [str(d) for d in jax.devices()],
                    "live_buffer_bytes": sum(
                        getattr(b, "nbytes", 0)
                        for d in jax.devices()
                        for b in d.live_buffers())
                    if hasattr(jax.devices()[0], "live_buffers") else None}

        self.http = HttpServer(router)
        self.port: Optional[int] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.port = await self.http.start(host, port)
        return self.port

    async def stop(self) -> None:
        await self.http.stop()
