"""Flight-recorder span tracing: a fixed-size ring of completed spans,
exportable as Chrome-trace / Perfetto JSON.

The reference leans on pyroscope for continuous profiling and on tokio
tracing for structured spans; here one lock-guarded ring buffer records
the runtime's interesting intervals — task lifecycle, barrier alignment,
checkpoint phases, window fires, kernel dispatch, data-plane flushes —
at a cost of one ``perf_counter`` pair and a deque append per span.
Always on: the ring bounds memory (``ARROYO_TRACE_CAP`` spans, default
16384) and recording never allocates more than one tuple.

Export (``chrome_trace()``) produces the Chrome Trace Event Format
(``{"traceEvents": [{"ph": "X", ...}]}``) which loads directly in
https://ui.perfetto.dev or ``chrome://tracing``; the admin server's
``/trace`` endpoint serves it.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

_CAP = int(os.environ.get("ARROYO_TRACE_CAP", "16384"))
_lock = threading.Lock()
# (name, cat, start_us, dur_us, pid, tid, args)
_spans: deque = deque(maxlen=_CAP)

# wall-clock anchor for perf_counter timestamps: Chrome-trace ts fields
# are absolute microseconds, perf_counter is an arbitrary monotonic
# origin — one pairing at import maps between them
_WALL_ANCHOR_US = time.time() * 1e6 - time.perf_counter() * 1e6


def now_us() -> float:
    """Monotonic wall-clock microseconds, comparable across spans."""
    return _WALL_ANCHOR_US + time.perf_counter() * 1e6


def set_capacity(n: int) -> None:
    """Resize the ring (tests / long capture sessions); keeps the newest
    spans."""
    global _spans
    with _lock:
        _spans = deque(_spans, maxlen=max(int(n), 1))


def reset() -> None:
    with _lock:
        _spans.clear()


def record_span(name: str, cat: str, start_us: float, dur_us: float,
                pid: str = "worker", tid: str = "",
                args: Optional[Dict[str, Any]] = None) -> None:
    """Append one completed span.  ``start_us`` is absolute microseconds
    (use :func:`now_us`); ``dur_us`` the span length."""
    with _lock:
        _spans.append((name, cat, start_us, dur_us, pid, tid, args))


def instant(name: str, cat: str, pid: str = "worker", tid: str = "",
            args: Optional[Dict[str, Any]] = None) -> None:
    """A zero-duration marker (rendered as an instant event)."""
    record_span(name, cat, now_us(), 0.0, pid, tid, args)


@contextmanager
def span(name: str, cat: str, pid: str = "worker", tid: str = "",
         args: Optional[Dict[str, Any]] = None) -> Iterator[None]:
    """Time a block and record it; exceptions still record the span."""
    t0 = time.perf_counter()
    start = _WALL_ANCHOR_US + t0 * 1e6
    try:
        yield
    finally:
        record_span(name, cat, start,
                    (time.perf_counter() - t0) * 1e6, pid, tid, args)


def ctx_tid(ctx) -> str:
    """Trace track id for an operator context — tolerant of the
    duck-typed test contexts that carry no task_info."""
    ti = getattr(ctx, "task_info", None)
    return getattr(ti, "task_id", "") if ti is not None else ""


def spans(cat: Optional[str] = None) -> List[tuple]:
    """Snapshot of the ring, oldest first (optionally one category)."""
    with _lock:
        out = list(_spans)
    if cat is not None:
        out = [s for s in out if s[1] == cat]
    return out


def chrome_trace(cat: Optional[str] = None) -> Dict[str, Any]:
    """Chrome Trace Event Format JSON dict (Perfetto-loadable)."""
    events: List[Dict[str, Any]] = []
    tids: Dict[tuple, None] = {}
    for name, scat, start, dur, pid, tid, args in spans(cat):
        if dur > 0:
            ev: Dict[str, Any] = {
                "name": name, "cat": scat, "ph": "X",
                "ts": round(start, 1), "dur": round(dur, 1),
                "pid": pid, "tid": tid or scat,
            }
        else:
            # zero-width "X" slices are invisible in Perfetto; instants
            # (watermark.emit markers) render as thread-scoped arrows
            ev = {
                "name": name, "cat": scat, "ph": "i", "s": "t",
                "ts": round(start, 1), "pid": pid, "tid": tid or scat,
            }
        if args:
            ev["args"] = args
        events.append(ev)
        tids.setdefault((pid, ev["tid"]))
    # thread-name metadata keeps Perfetto's track labels readable
    for pid, tid in tids:
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": str(tid)}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
