"""Phase-attributed host/device profiler: account for every microsecond
of the hot path.

ROADMAP item 5 ("kill the host path") needs the host time *decomposed*
before anyone can kill it: the bench's old ``host_time_share`` was a
residual (1 - device/wall) with zero attribution.  This module measures
every batch's journey as named phases at the runtime's existing choke
points:

==================  =========================================================
phase               measured where
==================  =========================================================
``source_decode``   connector decode / generation (nexmark generator on its
                    executor thread, kafka format decode, single_file JSON
                    parse, impulse batch assembly)
``proc``            operator ``process_batch`` host compute, EXCLUSIVE of
                    the nested phases below (per chain member for fused
                    operators)
``dispatch``        host-side kernel dispatch wall time (``perf.timed_device``
                    without blocking — the Python/jax envelope around XLA)
``device_execute``  same site under ``ARROYO_TIMING=1``: dispatch blocked on
                    the result, so the span is true device time
``shuffle_prep``    Collector partition/route/select CPU before fan-out
``coalesce_merge``  input-side batch concat in the coalescer
``watermark``       timer fires + ``handle_watermark`` (window fires live
                    here)
``checkpoint``      state snapshot sync phase at a barrier
``emit_encode``     sink-side encode (single_file JSON lines, ...)
``frame_encode``    data-plane Arrow IPC encode per frame
``frame_decode``    data-plane decode on the receiving worker
``reshard``         device arrays re-placed because their resident
                    sharding mismatched a kernel's explicit in_sharding
                    (parallel/shuffle.ensure_sharded — steady state
                    should show NO such phase at all)
``shuffle_collective``  on-device ``all_to_all`` exchange carrying a
                    co-located SHUFFLE edge (parallel/shuffle.py route
                    dispatch + per-shard readback)
``gather``          join payload materialization per emitted match set
                    (state/join_state.py — device-plane gather dispatch
                    or host fancy-index, whichever path ran; the
                    device/host row split rides the
                    ``join_*_gather_rows`` counters)
==================  =========================================================

plus overlapping **wait** phases (reported separately, never summed into
the work table): ``queue_wait``, ``coalesce_wait``, ``send_wait``
(backpressure enqueue), ``net_flush`` (socket drain).

Accounting model
----------------

Each asyncio task (and each executor thread) owns its own frame stack
(contextvar-held, thread-id-guarded), so ``begin``/``end`` pairs nest
LIFO within one task.  A frame's recorded time is **exclusive**: child
frames — including *wait* frames that span awaits — subtract their full
inclusive span from the parent.  Work phases are only opened around
synchronous blocks (their only interior awaits are wrapped as wait
children), so no other task's work can ever be charged to them: summed
work phases per thread can never exceed that thread's busy wall time.
Executor-side work (source prefetch, offloaded transfers) overlaps the
event loop by design, so a job's summed work phases may exceed wall
time — the bench reports the raw ratio and flags the overlap, exactly
like ``device_time_share`` already does.

Off-path discipline (same as arroyosan): every instrumentation site
holds a local that is ``None`` unless the profiler was armed
(``ARROYO_PROFILE=1`` at engine build, or an explicit :func:`arm`), so
the disabled path is a single ``is not None`` test.

The event-loop **stall watchdog** pairs an on-loop ticker task with a
sampler thread: the ticker heartbeats a timestamp every few ms; when
the thread sees the heartbeat stall past the threshold it captures the
loop thread's live stack (``sys._current_frames()``) — naming the
blocking call *while it blocks*, the runtime cross-check of the
arroyolint ``async-blocking`` static pass.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import weakref
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "Profiler",
    "LoopWatchdog",
    "profile_enabled",
    "active",
    "arm",
    "disarm",
    "ensure_armed",
    "WORK_PHASES",
    "WAIT_PHASES",
]

WORK_PHASES = ("source_decode", "proc", "dispatch", "device_execute",
               "shuffle_prep", "coalesce_merge", "watermark", "checkpoint",
               "emit_encode", "frame_encode", "frame_decode", "reshard",
               "shuffle_collective", "gather", "session_merge")
WAIT_PHASES = ("queue_wait", "coalesce_wait", "send_wait", "net_flush")


def profile_enabled() -> bool:
    """``ARROYO_PROFILE=1`` arms the profiler at engine build (read per
    build, not at import, so tests and bench can toggle per run)."""
    return os.environ.get("ARROYO_PROFILE", "0") not in ("0", "off",
                                                         "false", "")


_ACTIVE: Optional["Profiler"] = None


def active() -> Optional["Profiler"]:
    """The armed profiler, or ``None`` — the instrumentation sites'
    single cheap test."""
    return _ACTIVE


def arm(job_id: str = "") -> "Profiler":
    """Arm the process-wide profiler (idempotent: an already-armed
    profiler is returned unchanged, keeping its buckets)."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = Profiler(job_id)
    return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    prof = _ACTIVE
    _ACTIVE = None
    if prof is not None:
        prof.watchdog.stop()


def ensure_armed(job_id: str = "") -> Optional["Profiler"]:
    """Engine-build hook: arm iff ``ARROYO_PROFILE`` asks for it (or an
    explicit :func:`arm` already did); returns the active profiler or
    ``None``."""
    if _ACTIVE is not None:
        return _ACTIVE
    if profile_enabled():
        return arm(job_id)
    return None


# -- frame stacks ------------------------------------------------------------

# Per-task stacks: a contextvar gives every asyncio task its own box (so
# begin/end pairs nest LIFO even when awaits interleave tasks); the tid
# guard gives executor threads a fresh box when a copied context (e.g.
# perf.run_offloaded) would otherwise share the loop task's live list
# across threads.
class _StackBox:
    __slots__ = ("tid", "frames")

    def __init__(self, tid: int):
        self.tid = tid
        self.frames: List[list] = []


_STACK: ContextVar[Optional[_StackBox]] = ContextVar(
    "arroyo_profiler_stack", default=None)

# frame layout: [op_id, phase, is_wait, t0, child_inclusive_secs]
_OP, _PHASE, _WAIT, _T0, _CHILD = range(5)


class Profiler:
    """Process-wide phase accounting (one job per worker process; the
    embedded multi-job scheduler shares one profiler, documented)."""

    def __init__(self, job_id: str = ""):
        self.job_id = job_id
        self._lock = threading.Lock()
        self._work: Dict[Tuple[str, str], float] = {}
        self._waits: Dict[Tuple[str, str], float] = {}
        self._counts: Dict[Tuple[str, str], int] = {}
        self._t0 = time.perf_counter()
        self.watchdog = LoopWatchdog(job_id=job_id)

    # -- hot-path API ------------------------------------------------------

    def _frames(self) -> List[list]:
        box = _STACK.get()
        tid = threading.get_ident()
        if box is None or box.tid != tid:
            box = _StackBox(tid)
            _STACK.set(box)
        return box.frames

    def begin(self, op_id: str, phase: str, wait: bool = False) -> list:
        """Open a phase frame; returns the token for :meth:`end`.  Work
        frames must not span an await except through nested wait
        children (the site discipline the accounting model rests on)."""
        f = [op_id, phase, wait, time.perf_counter(), 0.0]
        self._frames().append(f)
        return f

    def end(self, f: list) -> None:
        now = time.perf_counter()
        frames = self._frames()
        if frames and frames[-1] is f:
            frames.pop()
        else:
            # defensive: a corrupted interleaving (shouldn't happen with
            # per-task stacks) degrades to attribution blur, never an
            # exception or unbounded stack growth
            try:
                frames.remove(f)
            except ValueError:
                pass
        dt = now - f[_T0]
        excl = dt - f[_CHILD]
        if excl < 0.0:
            excl = 0.0
        if frames:
            frames[-1][_CHILD] += dt
        key = (f[_OP], f[_PHASE])
        with self._lock:
            d = self._waits if f[_WAIT] else self._work
            d[key] = d.get(key, 0.0) + excl
            self._counts[key] = self._counts.get(key, 0) + 1

    def add(self, op_id: str, phase: str, secs: float,
            wait: bool = False, count: int = 1) -> None:
        """Direct accounting for sites that measure their own span and
        cannot nest (executor-thread source generation, the task loop's
        input waits)."""
        key = (op_id, phase)
        with self._lock:
            d = self._waits if wait else self._work
            d[key] = d.get(key, 0.0) + secs
            self._counts[key] = self._counts.get(key, 0) + count

    @contextmanager
    def phase(self, op_id: str, phase: str,
              wait: bool = False) -> Iterator[None]:
        """Context-manager convenience for non-hot paths."""
        f = self.begin(op_id, phase, wait)
        try:
            yield
        finally:
            self.end(f)

    # -- reads -------------------------------------------------------------

    def reset(self) -> None:
        with self._lock:
            self._work.clear()
            self._waits.clear()
            self._counts.clear()
            self._t0 = time.perf_counter()
        self.watchdog.reset()

    def work_snapshot(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._work)

    def wait_snapshot(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._waits)

    def snapshot(self) -> Dict[str, Any]:
        """Full structured snapshot: per-operator work/wait phase maps,
        job-level phase totals, wall since arm/reset, watchdog stats."""
        with self._lock:
            work, waits = dict(self._work), dict(self._waits)
            counts = dict(self._counts)
            wall = time.perf_counter() - self._t0
        ops: Dict[str, Dict[str, Any]] = {}
        phases: Dict[str, float] = {}
        wait_totals: Dict[str, float] = {}
        for (op, ph), secs in work.items():
            ops.setdefault(op, {"phases": {}, "waits": {}})[
                "phases"][ph] = round(secs, 6)
            phases[ph] = phases.get(ph, 0.0) + secs
        for (op, ph), secs in waits.items():
            ops.setdefault(op, {"phases": {}, "waits": {}})[
                "waits"][ph] = round(secs, 6)
            wait_totals[ph] = wait_totals.get(ph, 0.0) + secs
        attributed = sum(phases.values())
        return {
            "job_id": self.job_id,
            "wall_secs": round(wall, 6),
            "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
            "waits": {k: round(v, 6) for k, v in sorted(
                wait_totals.items())},
            "attributed_secs": round(attributed, 6),
            "attributed_share": round(attributed / wall, 4) if wall > 0
            else 0.0,
            "unattributed_share": round(
                max(1.0 - attributed / wall, 0.0), 4) if wall > 0 else 0.0,
            "operators": {op: v for op, v in sorted(ops.items())},
            "counts": {f"{op}/{ph}": n for (op, ph), n in sorted(
                counts.items())},
            "watchdog": self.watchdog.stats(),
        }

    def collapsed_stacks(self) -> str:
        """pprof/flamegraph folded-stack text: one ``job;operator;phase
        <microseconds>`` line per bucket (waits carry a ``(wait)``
        leaf so they are visually separable from summed work)."""
        job = self.job_id or "job"
        lines: List[str] = []
        with self._lock:
            work, waits = dict(self._work), dict(self._waits)
        for (op, ph), secs in sorted(work.items()):
            lines.append(f"{job};{op};{ph} {int(secs * 1e6)}")
        for (op, ph), secs in sorted(waits.items()):
            lines.append(f"{job};{op};{ph} (wait) {int(secs * 1e6)}")
        return "\n".join(lines) + ("\n" if lines else "")


# -- event-loop stall watchdog -----------------------------------------------


class LoopWatchdog:
    """Scheduling-lag sampler + blocking-call catcher.

    The on-loop ticker (:meth:`run`) sleeps ``interval`` and records how
    late the loop woke it — the scheduling lag every other coroutine on
    that loop also experiences.  A daemon sampler thread watches the
    ticker's heartbeat; when it stalls past ``stall_threshold`` the
    thread snapshots the loop thread's current Python stack, so the
    blocking call is named **while it is still blocking** (the runtime
    cross-check of arroyolint's ``async-blocking`` pass).  One stall
    episode records once, however long it lasts.
    """

    def __init__(self, interval: float = 0.02,
                 stall_threshold: Optional[float] = None,
                 job_id: str = ""):
        self.interval = interval
        self.stall_threshold = stall_threshold if stall_threshold is not None \
            else float(os.environ.get("ARROYO_PROFILE_STALL_MS", "250")) / 1e3
        self.job_id = job_id
        self.lags: deque = deque(maxlen=1024)  # recent lag samples (secs)
        self.stalls: deque = deque(maxlen=64)  # {t, lag, stack}
        self.stall_count = 0
        self._last_tick = time.perf_counter()
        self._loop_tid: Optional[int] = None
        self._stop = threading.Event()
        self._sampler_started = False
        self._tickers: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()  # loop -> ticker task
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def ensure_ticker(self) -> None:
        """Idempotently start the ticker task on the running loop (and
        the sampler thread on first use).  Called from Engine.start when
        the profiler is armed; the task dies with its loop."""
        import asyncio

        loop = asyncio.get_running_loop()
        t = self._tickers.get(loop)
        if t is not None and not t.done():
            return
        self._tickers[loop] = asyncio.ensure_future(self.run())

    async def run(self) -> None:
        self._loop_tid = threading.get_ident()
        self._last_tick = time.perf_counter()
        if not self._sampler_started:
            self._sampler_started = True
            self._stop.clear()
            threading.Thread(target=self._sample, name="arroyo-loop-watchdog",
                             daemon=True).start()
        import asyncio

        from .metrics import event_loop_lag_gauge, event_loop_stalls_counter

        gauge_p50 = event_loop_lag_gauge(self.job_id, "p50")
        gauge_p99 = event_loop_lag_gauge(self.job_id, "p99")
        stalls_c = event_loop_stalls_counter(self.job_id)
        reported_stalls = 0
        last_gauge = 0.0
        try:
            while True:
                t0 = time.perf_counter()
                await asyncio.sleep(self.interval)
                now = time.perf_counter()
                self._last_tick = now
                self.lags.append(max(now - t0 - self.interval, 0.0))
                if now - last_gauge >= 1.0:
                    last_gauge = now
                    p50, p99 = self._percentiles()
                    gauge_p50.set(p50)
                    gauge_p99.set(p99)
                    if self.stall_count > reported_stalls:
                        stalls_c.inc(self.stall_count - reported_stalls)
                        reported_stalls = self.stall_count
        finally:
            # the loop is going away: freeze the heartbeat far in the
            # future so the sampler never mistakes shutdown for a stall
            self._last_tick = float("inf")

    def stop(self) -> None:
        self._stop.set()
        self._sampler_started = False

    # -- sampling ----------------------------------------------------------

    def _percentiles(self) -> Tuple[float, float]:
        lags = sorted(self.lags)
        if not lags:
            return 0.0, 0.0
        return (lags[len(lags) // 2],
                lags[min(int(len(lags) * 0.99), len(lags) - 1)])

    def _sample(self) -> None:
        poll = max(self.interval / 2, 0.005)
        while not self._stop.is_set():
            time.sleep(poll)
            last = self._last_tick
            if last == float("inf"):
                continue
            lag = time.perf_counter() - last
            if lag < self.stall_threshold or self._loop_tid is None:
                continue
            frame = sys._current_frames().get(self._loop_tid)
            stack = ("".join(traceback.format_stack(frame, limit=12))
                     if frame is not None else "<no frame>")
            with self._lock:
                self.stall_count += 1
                self.stalls.append({
                    "t": round(time.time(), 3),
                    "lag_secs": round(lag, 4),
                    "stack": stack,
                })
            # one record per stall episode: wait for the loop to tick
            # again before re-arming (bounded so a dead loop can't wedge
            # the sampler thread forever)
            deadline = time.perf_counter() + 60.0
            while (self._last_tick <= last
                   and time.perf_counter() < deadline
                   and not self._stop.is_set()):
                time.sleep(poll)

    # -- reads -------------------------------------------------------------

    def reset(self) -> None:
        with self._lock:
            self.lags.clear()
            self.stalls.clear()
            self.stall_count = 0

    def stats(self) -> Dict[str, Any]:
        p50, p99 = self._percentiles()
        with self._lock:
            stalls = list(self.stalls)
            count = self.stall_count
        return {
            "lag_p50_secs": round(p50, 6),
            "lag_p99_secs": round(p99, 6),
            "stalls": count,
            "stall_threshold_secs": self.stall_threshold,
            "recent_stalls": [
                {"t": s["t"], "lag_secs": s["lag_secs"],
                 # last frames name the blocking call; full stack stays
                 # in-process (admin /profile/phases?fmt=json serves it)
                 "stack_tail": s["stack"].strip().splitlines()[-4:]}
                for s in stalls[-8:]],
        }
