"""Structured logging init (init_logging, arroyo-server-common/src/
lib.rs:49-101): human-readable stdout in dev, logfmt-style JSON lines in
prod (LOG_JSON=true), plus an excepthook that reports panics through the
logger the way the reference installs a tracing panic hook (lib.rs:86-99).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
import traceback
from typing import Optional


class LogfmtJsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, target, message, fields."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname.lower(),
            "target": record.name,
            "message": record.getMessage(),
        }
        for k in ("job_id", "operator_id", "subtask_idx", "worker_id"):
            v = getattr(record, k, None)
            if v is not None:
                out[k] = v
        if record.exc_info:
            out["exception"] = "".join(
                traceback.format_exception(*record.exc_info))
        return json.dumps(out)


def init_logging(service: str, level: Optional[str] = None) -> None:
    level_name = (level or os.environ.get("LOG_LEVEL", "INFO")).upper()
    root = logging.getLogger()
    root.setLevel(getattr(logging, level_name, logging.INFO))
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("LOG_JSON", "").lower() in ("1", "true", "yes"):
        handler.setFormatter(LogfmtJsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            f"%(asctime)s %(levelname)-7s {service} %(name)s: %(message)s"))
    root.addHandler(handler)

    def hook(exc_type, exc, tb):
        logging.getLogger(service).critical(
            "panic: %s", exc, exc_info=(exc_type, exc, tb))
        sys.__excepthook__(exc_type, exc, tb)

    sys.excepthook = hook
