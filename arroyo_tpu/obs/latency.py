"""Record-level end-to-end latency observatory (ROADMAP item 4).

Every observability layer before this one measured *where CPU time
goes* (the PR 1 flight recorder, the PR 7 phase profiler); none
measured *how long a record takes* from source ingestion to sink
emission — so the config5 p99 < 100 ms SLO had no instrument behind
it.  This module is that instrument, three coupled parts:

**1. Latency sampling.**  Sources stamp a deterministic 1-in-N sample
of records with their ingest wall-clock.  The stamp is a *side-channel
batch annotation* (``Batch.lat_stamp``, types.py) rather than a hidden
``__lat_ingest`` column: the coalescer signature, the sanitizer's
per-edge schema check and the data plane's Arrow-schema continuation
fast path all read only ``columns``/``key_cols``/``key_hash``, so
arming sampling mid-stream provably never flips a schema signature
(tests/test_latency.py asserts this with the sanitizer armed).  The
stamp survives:

- operator chaining: the task loop parks the input batch's stamp in a
  per-asyncio-task :data:`ContextVar` (:func:`set_current`) and
  ``Context.collect`` re-attaches it to operator-built batches, so a
  chain tail's emission inherits the head input's stamp without
  per-member plumbing;
- coalescing: ``Batch.concat`` keeps the **oldest** stamp (linger is
  charged to latency, never hidden);
- shuffles: ``Batch.select`` carries it through host partition routes,
  ``DeviceShuffle.route`` threads it onto rebuilt sub-batches, and the
  network data plane ships it as a frame-flag + 8-byte prefix *outside*
  the Arrow payload (network/data_plane.py) so the cached-schema
  continuation path never thrashes;
- window fires: a fired pane inherits the **max** stamp of the sampled
  batches that contributed since the last fire (the freshest sampled
  record still waiting in the pane bounds the watermark hold from
  below), persisted across checkpoint/restore with the pane state;
- joins: an emitted match set inherits the probing batch's stamp via
  the same ContextVar re-attach.

Sinks compute emit-minus-ingest into per-sink
``arroyo_sink_e2e_latency_seconds`` histograms plus rolling p50/p99
gauges.

**2. Watermark lineage.**  ``Context.observe_watermark`` notes the age
of every watermark each operator consumes (per-edge watermark-age
tracking), and :meth:`LatencyObservatory.critical_path` decomposes
where a sampled record's time went — source linger → queue wait →
barrier align → watermark hold → fire → emit — by folding the phase
profiler's work/wait buckets (when armed) with the observatory's own
barrier-align and watermark-hold accumulators.  Exported at admin
``/latency``, folded into heartbeat rollups (``summary_ride_alongs``)
→ ``controller.job_rollup`` → REST ``GET /v1/jobs/{id}/latency`` → the
console latency panel.

**3. SLO engine.**  A per-pipeline declarative :class:`Slo`
(``slo_p99_ms`` / ``slo_staleness_ms``, env or REST) is evaluated by
the controller loop against the rollup quantiles via
:class:`SloEvaluator`: every tick appends a violating/ok sample, the
burn rate is the violating fraction of the trailing
``burn_window_secs`` (:func:`burn_rate` is the pure math, unit-tested
in isolation), violations land in a decision-ledger-style event ring
and the ``arroyo_slo_{violations_total,burn_rate}`` metrics — giving
the autoscaler a latency signal to scale on instead of backlog alone.

Off-path discipline (same as profiler/arroyosan): every hook site
tests ``latency.active() is not None`` — disarmed, the whole
observatory is a single ``None`` check and records nothing.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..types import now_micros

__all__ = [
    "LatencyObservatory",
    "Slo",
    "SloEvaluator",
    "burn_rate",
    "sampling_enabled",
    "active",
    "arm",
    "disarm",
    "ensure_armed",
    "set_current",
    "current",
    "device_state_tables",
    "summary_ride_alongs",
    "CRITICAL_PATH_STAGES",
    "STAMP_COLUMN",
]

# Reserved hidden-column name for the ingest stamp.  The shipped
# mechanism is the side-channel ``Batch.lat_stamp`` (see module doc), so
# this name never appears in a live schema — but shardcheck models it as
# a transportable numeric kind and the formats layer strips it on
# ingest, so a connector surfacing it can never pin an edge to the
# sticky host route or leak it into user-visible output.
STAMP_COLUMN = "__lat_ingest"

# The per-fire critical-path decomposition stages, in record order.
CRITICAL_PATH_STAGES = ("source_linger", "queue_wait", "barrier_align",
                        "watermark_hold", "fire", "emit", "compute")

# How the profiler's phase/wait buckets fold into the stages (the
# observatory's own accumulators cover barrier_align and
# watermark_hold, which the profiler has no phase for).
_STAGE_FOLD = {
    "source_linger": (("source_decode", False), ("coalesce_merge", False),
                      ("coalesce_wait", True)),
    "queue_wait": (("queue_wait", True), ("send_wait", True),
                   ("net_flush", True)),
    "fire": (("watermark", False),),
    "emit": (("emit_encode", False), ("frame_encode", False)),
    "compute": (("proc", False), ("dispatch", False),
                ("device_execute", False), ("shuffle_prep", False),
                ("frame_decode", False), ("reshard", False),
                ("shuffle_collective", False), ("gather", False)),
}


def sampling_enabled() -> bool:
    """``ARROYO_LATENCY_SAMPLE_N > 0`` arms the observatory at engine
    build (read per build, not at import, so tests/bench toggle per
    run)."""
    from ..config import config

    return config().latency_sample_n > 0


_ACTIVE: Optional["LatencyObservatory"] = None


def active() -> Optional["LatencyObservatory"]:
    """The armed observatory, or ``None`` — the hook sites' single
    cheap test."""
    return _ACTIVE


def arm(job_id: str = "", sample_n: Optional[int] = None
        ) -> "LatencyObservatory":
    """Arm the process-wide observatory (idempotent: an already-armed
    observatory is returned unchanged, keeping its rolling windows)."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = LatencyObservatory(job_id, sample_n)
    return _ACTIVE


def disarm() -> None:
    global _ACTIVE
    _ACTIVE = None


def ensure_armed(job_id: str = "") -> Optional["LatencyObservatory"]:
    """Engine-build hook: arm iff the config asks for sampling (or an
    explicit :func:`arm` already did)."""
    if _ACTIVE is not None:
        return _ACTIVE
    if sampling_enabled():
        return arm(job_id)
    return None


# -- current-input stamp (chain / operator-rebuild survival) -----------------

# Each TaskRunner processes one input batch at a time within its own
# asyncio task, so a ContextVar scopes "the stamp of the batch being
# processed right now" correctly even when tasks interleave on the
# loop.  The task loop sets it around process_batch; Context.collect
# re-attaches it to operator-built batches that lost the annotation.
_CUR: ContextVar[Optional[int]] = ContextVar("arroyo_lat_current",
                                             default=None)


def set_current(stamp: Optional[int]) -> None:
    _CUR.set(stamp)


def current() -> Optional[int]:
    return _CUR.get()


def maybe_stamp(src_key: str, batch) -> None:
    """Source-boundary stamping for connectors that emit ``Batch``
    objects directly (bypassing ``SourceBatcher``): stamps the batch
    carrying the next 1-in-N sampled record with its ingest wall-clock.
    Never overwrites a stamp the caller set (tests / replays)."""
    lat = _ACTIVE
    if (lat is None or batch is None or len(batch) == 0
            or batch.lat_stamp is not None):
        return
    stamp = lat.source_stamp(src_key, len(batch))
    if stamp is not None:
        batch.lat_stamp = stamp


# -- the observatory ---------------------------------------------------------


class LatencyObservatory:
    """Process-wide record-latency accounting (one job per worker
    process; the embedded multi-job scheduler shares one, documented
    like the profiler)."""

    def __init__(self, job_id: str = "", sample_n: Optional[int] = None):
        from ..config import config

        self.job_id = job_id
        n = sample_n if sample_n is not None else config().latency_sample_n
        self.sample_n = max(int(n), 1)
        self._lock = threading.Lock()
        # deterministic 1-in-N sampling: per-source-subtask row counters
        self._seen: Dict[str, int] = {}
        self._stamps: Dict[str, int] = {}
        # per-sink rolling latency windows (seconds)
        self._sinks: Dict[str, Deque[float]] = {}
        self._sink_counts: Dict[str, int] = {}
        self._sink_last: Dict[str, float] = {}
        # per-consumer watermark ages: op_id -> (age_secs, wm_micros)
        self._wm_age: Dict[str, Tuple[float, int]] = {}
        # own critical-path accumulators (stages the profiler lacks)
        self._stages: Dict[str, float] = {}
        self._stage_counts: Dict[str, int] = {}

    # -- sampling (source side) --------------------------------------------

    def source_stamp(self, src_key: str, n_rows: int) -> Optional[int]:
        """Deterministic 1-in-N sampling: returns the ingest wall-clock
        (micros) iff this batch contains the next sampled record — i.e.
        the source's cumulative row count crosses a multiple of N —
        else ``None``.  Counting rows (not batches) keeps the sampled
        rate independent of batch size."""
        if n_rows <= 0:
            return None
        n = self.sample_n
        with self._lock:
            prev = self._seen.get(src_key, 0)
            cur = prev + int(n_rows)
            self._seen[src_key] = cur
            if prev // n == cur // n:
                return None
            self._stamps[src_key] = self._stamps.get(src_key, 0) + 1
        return now_micros()

    # -- sink side ----------------------------------------------------------

    def observe_sink(self, task_info, stamp_micros: int,
                     emit_micros: Optional[int] = None) -> float:
        """Record one emit-minus-ingest sample at a sink: feeds the
        per-sink histogram and refreshes the rolling p50/p99 gauges.
        Returns the latency in seconds."""
        from . import metrics as _m

        emit = now_micros() if emit_micros is None else emit_micros
        secs = max(int(emit) - int(stamp_micros), 0) / 1e6
        op = task_info.operator_id
        _m.sink_latency_histogram(task_info).observe(secs)
        with self._lock:
            dq = self._sinks.get(op)
            if dq is None:
                dq = self._sinks[op] = deque(maxlen=2048)
            dq.append(secs)
            self._sink_counts[op] = self._sink_counts.get(op, 0) + 1
            self._sink_last[op] = secs
            p50, p99 = _quantiles(dq)
        _m.sink_latency_quantile_gauge(task_info, "p50").set(p50)
        _m.sink_latency_quantile_gauge(task_info, "p99").set(p99)
        return secs

    # -- watermark lineage --------------------------------------------------

    def note_edge_watermark(self, op_id: str, wm_micros: int) -> None:
        """Per-edge watermark-age tracking: how stale the watermark an
        operator just consumed was at consumption time.  A sink whose
        age keeps growing is downstream of the held stage."""
        age = max(now_micros() - int(wm_micros), 0) / 1e6
        with self._lock:
            self._wm_age[op_id] = (age, int(wm_micros))

    def note_stage(self, stage: str, secs: float) -> None:
        """Accumulate an observatory-owned critical-path stage (the
        profiler has no phase for barrier alignment or watermark
        hold)."""
        with self._lock:
            self._stages[stage] = self._stages.get(stage, 0.0) + secs
            self._stage_counts[stage] = self._stage_counts.get(stage, 0) + 1

    def critical_path(self) -> Dict[str, Any]:
        """Per-fire critical-path decomposition: fold the profiler's
        phase/wait totals (when armed) with the observatory's own
        barrier-align / watermark-hold accumulators into the record's
        journey stages, and name the dominant one."""
        stages = {s: 0.0 for s in CRITICAL_PATH_STAGES}
        with self._lock:
            for stage, secs in self._stages.items():
                if stage in stages:
                    stages[stage] += secs
        from . import profiler as _profiler

        prof = _profiler.active()
        if prof is not None:
            work: Dict[str, float] = {}
            waits: Dict[str, float] = {}
            for (_op, ph), secs in prof.work_snapshot().items():
                work[ph] = work.get(ph, 0.0) + secs
            for (_op, ph), secs in prof.wait_snapshot().items():
                waits[ph] = waits.get(ph, 0.0) + secs
            for stage, parts in _STAGE_FOLD.items():
                for phase, is_wait in parts:
                    stages[stage] += (waits if is_wait else work).get(
                        phase, 0.0)
        total = sum(stages.values())
        dominant = max(stages, key=stages.get) if total > 0 else ""
        return {
            "stages": {k: round(v, 6) for k, v in stages.items()},
            "total_secs": round(total, 6),
            "dominant": dominant,
            "dominant_share": round(stages[dominant] / total, 4)
            if total > 0 else 0.0,
        }

    # -- reads --------------------------------------------------------------

    def sink_quantiles(self) -> Dict[str, Dict[str, float]]:
        """Per-sink rolling-window stats: p50/p99/last (ms) + count."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for op, dq in self._sinks.items():
                p50, p99 = _quantiles(dq)
                out[op] = {
                    "p50_ms": round(p50 * 1e3, 3),
                    "p99_ms": round(p99 * 1e3, 3),
                    "last_ms": round(self._sink_last.get(op, 0.0) * 1e3, 3),
                    "count": float(self._sink_counts.get(op, 0)),
                }
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Full structured snapshot for admin ``/latency``."""
        with self._lock:
            seen = dict(self._seen)
            stamps = dict(self._stamps)
            wm = {op: {"age_ms": round(age * 1e3, 3), "watermark": t}
                  for op, (age, t) in self._wm_age.items()}
        return {
            "job_id": self.job_id,
            "sample_n": self.sample_n,
            "records_seen": sum(seen.values()),
            "records_sampled": sum(stamps.values()),
            "sources": {k: {"seen": seen[k], "sampled": stamps.get(k, 0)}
                        for k in sorted(seen)},
            "sinks": self.sink_quantiles(),
            "watermarks": wm,
            "critical_path": self.critical_path(),
            "device_state_bytes": device_state_tables(),
        }


def _quantiles(samples: Sequence[float]) -> Tuple[float, float]:
    xs = sorted(samples)
    if not xs:
        return 0.0, 0.0
    return (xs[len(xs) // 2], xs[min(int(len(xs) * 0.99), len(xs) - 1)])


# -- SLO engine --------------------------------------------------------------


@dataclass
class Slo:
    """Per-pipeline declarative latency SLO.  A dimension set to 0 is
    unset; :meth:`configured` is False when both are."""

    p99_ms: float = 0.0
    staleness_ms: float = 0.0
    burn_window_secs: float = 60.0

    @staticmethod
    def from_config() -> "Slo":
        from ..config import config

        c = config()
        return Slo(p99_ms=float(c.slo_p99_ms),
                   staleness_ms=float(c.slo_staleness_ms),
                   burn_window_secs=float(c.slo_burn_window_secs) or 60.0)

    def configured(self) -> bool:
        return self.p99_ms > 0 or self.staleness_ms > 0

    def to_json(self) -> Dict[str, float]:
        return {"p99_ms": self.p99_ms, "staleness_ms": self.staleness_ms,
                "burn_window_secs": self.burn_window_secs}


def burn_rate(samples: Sequence[Tuple[float, bool]], now: float,
              window_secs: float) -> float:
    """The pure burn math: the violating fraction of SLO evaluations in
    the trailing window — 0.0 is a healthy pipeline, 1.0 burns the
    whole error budget every tick.  Samples outside the window are
    ignored; an empty window reads 0.0 (no evidence is not a
    violation)."""
    recent = [bool(v) for t, v in samples if now - t <= window_secs]
    if not recent:
        return 0.0
    return sum(recent) / len(recent)


class SloEvaluator:
    """Controller-side SLO burn-rate evaluation for one job, in the
    decision-ledger style (autoscale/ledger.py): a bounded sample ring,
    a bounded violation-event ring, and counters — ``to_json`` is the
    REST verdict."""

    def __init__(self, job_id: str, slo: Slo):
        self.job_id = job_id
        self.slo = slo
        self._samples: Deque[Tuple[float, bool]] = deque(maxlen=4096)
        self._events: Deque[Dict[str, Any]] = deque(maxlen=256)
        self.violations_total = 0
        self.evaluations_total = 0
        self._last: Dict[str, Any] = {}

    def evaluate(self, p99_ms: Optional[float],
                 staleness_ms: Optional[float],
                 now: Optional[float] = None) -> Dict[str, Any]:
        """One controller-loop tick: judge the rollup quantiles against
        the SLO, update the burn rate, and record a violation event +
        metrics when a dimension is out of budget.  ``None`` measured
        values (no samples yet) never violate."""
        now = time.time() if now is None else now
        s = self.slo
        violated: Dict[str, Dict[str, float]] = {}
        if s.p99_ms > 0 and p99_ms is not None and p99_ms > s.p99_ms:
            violated["p99"] = {"measured_ms": round(p99_ms, 3),
                               "target_ms": s.p99_ms}
        if (s.staleness_ms > 0 and staleness_ms is not None
                and staleness_ms > s.staleness_ms):
            violated["staleness"] = {"measured_ms": round(staleness_ms, 3),
                                     "target_ms": s.staleness_ms}
        violating = bool(violated)
        self.evaluations_total += 1
        self._samples.append((now, violating))
        rate = burn_rate(self._samples, now, s.burn_window_secs)
        from . import metrics as _m

        _m.slo_burn_rate_gauge(self.job_id).set(rate)
        if violating:
            self.violations_total += 1
            _m.slo_violations_counter(self.job_id).inc()
            self._events.append({"t": round(now, 3), "dims": violated,
                                 "burn_rate": round(rate, 4)})
        self._last = {
            "configured": s.configured(),
            "violating": violating,
            "burn_rate": round(rate, 4),
            "violated_dims": violated,
            "p99_ms": round(p99_ms, 3) if p99_ms is not None else None,
            "staleness_ms": round(staleness_ms, 3)
            if staleness_ms is not None else None,
            "t": round(now, 3),
        }
        return self._last

    @property
    def current_burn_rate(self) -> float:
        return float(self._last.get("burn_rate", 0.0))

    def to_json(self, limit: int = 16) -> Dict[str, Any]:
        return {
            "slo": self.slo.to_json(),
            "configured": self.slo.configured(),
            "last": dict(self._last),
            "violations_total": self.violations_total,
            "evaluations_total": self.evaluations_total,
            "recent_violations": list(self._events)[-limit:],
        }


# -- device-memory ledger (ROADMAP-1 groundwork) -----------------------------


def device_state_tables() -> Dict[str, int]:
    """Sweep the existing per-subsystem ``stats()`` surfaces into one
    table -> bytes map: join payload rings + ring key/ts slots + host
    spill (state/join_state.py registry), window pane planes
    (``pane_state_registry``, noted by BinAggOperator), and the device
    shuffle's packed column stacks.  This is the data source the
    co-scheduled-job memory accounting (per-tenant isolation) will
    budget against."""
    from . import perf

    out: Dict[str, int] = {}
    try:
        from ..state.join_state import aggregate_stats_registry

        js = aggregate_stats_registry(perf.get_note("join_state_registry"))
    except Exception:
        js = {}
    if js:
        out["join_payload_rings"] = int(js.get("payload_ring_bytes", 0))
        # keys-only ring slots: u64 key + i64 timestamp per capacity row
        out["join_ring_keys"] = int(js.get("ring_cap_rows", 0)) * 16
        out["join_spill_host"] = int(js.get("spill_bytes", 0))
    panes = perf.get_note("pane_state_registry")
    if isinstance(panes, dict) and panes:
        out["panes"] = int(sum(int(v) for v in panes.values()))
    stacks = perf.get_note("shuffle_stack_bytes")
    if stacks:
        out["shuffle_stacks"] = int(stacks)
    return out


# -- heartbeat ride-alongs ---------------------------------------------------


def summary_ride_alongs(job_id: str) -> Dict[str, Dict[str, float]]:
    """Latency keys a worker folds into ``job_operator_summary`` (the
    same mechanism as the profiler's ``phase_seconds.*``): per-sink
    ``e2e_latency.*`` quantiles, per-operator ``wm_age_ms``, and
    worker-level ``critical_path.*`` / ``device_bytes.*`` under the
    ``__worker__`` pseudo-operator.  Refreshes the
    ``arroyo_device_state_bytes`` gauges as a side effect so the local
    /metrics scrape agrees with what heartbeats ship."""
    lat = active()
    out: Dict[str, Dict[str, float]] = {}
    if lat is None or (lat.job_id and lat.job_id != job_id):
        return out
    for op, q in lat.sink_quantiles().items():
        out[op] = {
            "e2e_latency.p50_ms": q["p50_ms"],
            "e2e_latency.p99_ms": q["p99_ms"],
            "e2e_latency.last_ms": q["last_ms"],
            "e2e_latency.count": q["count"],
        }
    with lat._lock:
        ages = {op: age for op, (age, _t) in lat._wm_age.items()}
    for op, age in ages.items():
        out.setdefault(op, {})["wm_age_ms"] = round(age * 1e3, 3)
    w = out.setdefault("__worker__", {})
    cp = lat.critical_path()
    for stage, secs in cp["stages"].items():
        w[f"critical_path.{stage}"] = secs
    from . import metrics as _m

    for table, nbytes in device_state_tables().items():
        w[f"device_bytes.{table}"] = float(nbytes)
        _m.device_state_bytes_gauge(job_id, table).set(nbytes)
    w["latency_sample_n"] = float(lat.sample_n)
    return out
