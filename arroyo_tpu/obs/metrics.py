"""Per-task prometheus metrics with the reference's metric names.

Names match /root/reference/arroyo-types/src/lib.rs:734-739 exactly
(arroyo_worker_messages_recv, …) and labels match TaskInfo::
metric_label_map (lib.rs:579-585: operator_id, subtask_idx,
operator_name) so existing dashboards / the API's rate() queries port
unchanged.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from prometheus_client import (CollectorRegistry, Counter, Gauge,
                               generate_latest)

MESSAGES_RECV = "arroyo_worker_messages_recv"
MESSAGES_SENT = "arroyo_worker_messages_sent"
BYTES_RECV = "arroyo_worker_bytes_recv"
BYTES_SENT = "arroyo_worker_bytes_sent"
TX_QUEUE_SIZE = "arroyo_worker_tx_queue_size"
TX_QUEUE_REM = "arroyo_worker_tx_queue_rem"

LABELS = ("job_id", "operator_id", "subtask_idx", "operator_name")

# one registry per process (worker); the admin server renders it
REGISTRY = CollectorRegistry()
_lock = threading.Lock()
_counters: Dict[str, Counter] = {}
_gauges: Dict[str, Gauge] = {}


def _counter(name: str, help_: str) -> Counter:
    with _lock:
        if name not in _counters:
            _counters[name] = Counter(name, help_, LABELS,
                                      registry=REGISTRY)
        return _counters[name]


def _gauge(name: str, help_: str) -> Gauge:
    with _lock:
        if name not in _gauges:
            _gauges[name] = Gauge(name, help_, LABELS, registry=REGISTRY)
        return _gauges[name]


def counter_for_task(task_info, name: str, help_: str = "") -> Counter:
    """counter_for_task (arroyo-metrics/src/lib.rs:9-21)."""
    return _counter(name, help_ or name).labels(
        job_id=task_info.job_id, operator_id=task_info.operator_id,
        subtask_idx=str(task_info.task_index),
        operator_name=getattr(task_info, "operator_name",
                              task_info.operator_id))


def gauge_for_task(task_info, name: str, help_: str = "") -> Gauge:
    """gauge_for_task (arroyo-metrics/src/lib.rs:23-35)."""
    return _gauge(name, help_ or name).labels(
        job_id=task_info.job_id, operator_id=task_info.operator_id,
        subtask_idx=str(task_info.task_index),
        operator_name=getattr(task_info, "operator_name",
                              task_info.operator_id))


class TaskMetrics:
    """The six per-task instruments every subtask maintains
    (arroyo-worker/src/metrics.rs)."""

    def __init__(self, task_info):
        self.messages_recv = counter_for_task(
            task_info, MESSAGES_RECV, "records received by this subtask")
        self.messages_sent = counter_for_task(
            task_info, MESSAGES_SENT, "records sent by this subtask")
        self.bytes_recv = counter_for_task(
            task_info, BYTES_RECV, "serialized bytes received")
        self.bytes_sent = counter_for_task(
            task_info, BYTES_SENT, "serialized bytes sent")
        self.tx_queue_size = gauge_for_task(
            task_info, TX_QUEUE_SIZE, "outbound queue capacity")
        self.tx_queue_rem = gauge_for_task(
            task_info, TX_QUEUE_REM, "outbound queue remaining slots")


def render_metrics(registry: Optional[CollectorRegistry] = None) -> bytes:
    return generate_latest(registry or REGISTRY)


def snapshot(name_prefix: str = "arroyo_worker_") -> Dict[str, float]:
    """In-process scrape: {metric{label=...}: value} for API proxying."""
    out: Dict[str, float] = {}
    for fam in REGISTRY.collect():
        if not fam.name.startswith(name_prefix.rstrip("_")):
            continue
        for s in fam.samples:
            if s.name.endswith("_created"):
                continue
            labels = ",".join(f"{k}={v}" for k, v in sorted(
                s.labels.items()))
            out[f"{s.name}{{{labels}}}"] = s.value
    return out


TABLE_SIZE = "arroyo_worker_table_size_keys"
# the reference's labels plus job_id: without it, same-named operators of
# different jobs sharing a process registry would clobber each other
TABLE_LABELS = ("job_id", "operator_id", "task_id", "table_char")
_table_gauge: Optional[Gauge] = None


def table_size_gauge(task_info, table_char: str) -> Gauge:
    """Per-table key-count gauge (arroyo-state/src/metrics.rs
    TABLE_SIZE_GAUGE: name + labels match the reference exactly)."""
    global _table_gauge
    with _lock:
        if _table_gauge is None:
            _table_gauge = Gauge(TABLE_SIZE, "Number of keys in the table",
                                 TABLE_LABELS, registry=REGISTRY)
    return _table_gauge.labels(
        job_id=task_info.job_id,
        operator_id=task_info.operator_id,
        task_id=str(task_info.task_index),
        table_char=table_char)
