"""Per-task prometheus metrics with the reference's metric names.

Names match /root/reference/arroyo-types/src/lib.rs:734-739 exactly
(arroyo_worker_messages_recv, …) and labels match TaskInfo::
metric_label_map (lib.rs:579-585: operator_id, subtask_idx,
operator_name) so existing dashboards / the API's rate() queries port
unchanged.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from prometheus_client import (CollectorRegistry, Counter, Gauge,
                               Histogram, generate_latest)

MESSAGES_RECV = "arroyo_worker_messages_recv"
MESSAGES_SENT = "arroyo_worker_messages_sent"
BYTES_RECV = "arroyo_worker_bytes_recv"
BYTES_SENT = "arroyo_worker_bytes_sent"
TX_QUEUE_SIZE = "arroyo_worker_tx_queue_size"
TX_QUEUE_REM = "arroyo_worker_tx_queue_rem"

# flight-recorder instruments (this file is the single name registry —
# the docs table in docs/operations.md mirrors it)
EVENT_TIME_LAG = "arroyo_worker_event_time_lag_seconds"
WATERMARK_LAG = "arroyo_worker_watermark_lag_seconds"
BATCH_LATENCY = "arroyo_worker_batch_processing_seconds"
QUEUE_WAIT = "arroyo_worker_queue_wait_seconds"
BACKPRESSURE_TIME = "arroyo_worker_backpressure_seconds_total"
KERNEL_TIME = "arroyo_worker_kernel_seconds_total"
CHECKPOINT_DURATION = "arroyo_worker_checkpoint_duration_seconds"
CHECKPOINT_BYTES = "arroyo_worker_checkpoint_bytes"
FRAME_BYTES = "arroyo_worker_frame_bytes"
FLUSH_LATENCY = "arroyo_worker_flush_seconds"
# chaining/coalescing (PR 4): fused-task size per head operator, and the
# number of record batches merged per coalesced flush at a task's input
CHAIN_MEMBERS = "arroyo_chain_members"
COALESCE_BATCHES = "arroyo_worker_coalesce_batches"
# event-loop scheduling lag (obs/profiler.py watchdog): per-worker
# gauges refreshed ~1/s from the ticker's rolling lag window, plus the
# count of stalls past the watchdog threshold (blocking-call episodes)
EVENT_LOOP_LAG = "arroyo_worker_event_loop_lag_seconds"
EVENT_LOOP_STALLS = "arroyo_worker_event_loop_stalls_total"
# sharded data plane (parallel/shuffle.py): implicit resharding/transfer
# events on device-resident state (the "no resharding" invariant — this
# counter staying 0 in steady state is MEASURED, not hoped), and the
# on-device all_to_all exchanges that replaced host shuffles
RESHARDS_TOTAL = "arroyo_worker_reshards_total"
SHUFFLE_COLLECTIVES = "arroyo_worker_shuffle_collectives_total"

LABELS = ("job_id", "operator_id", "subtask_idx", "operator_name")

# lag can span ms (steady state) to minutes (recovery backlog)
LAG_BUCKETS = (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
               30.0, 60.0, 300.0, 1800.0)
# per-batch host/device latencies: 100us up to multi-second stalls
LATENCY_BUCKETS = (0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
BYTES_BUCKETS = (1e3, 1e4, 1e5, 1e6, 4e6, 1.6e7, 6.4e7, 2.56e8)

_BUCKETS = {
    EVENT_TIME_LAG: LAG_BUCKETS,
    WATERMARK_LAG: LAG_BUCKETS,
    BATCH_LATENCY: LATENCY_BUCKETS,
    QUEUE_WAIT: LATENCY_BUCKETS,
    # checkpoints span sub-second (tiny state) to minutes (large device
    # tables over a remote tunnel) — the lag buckets' 1800s ceiling fits;
    # the latency buckets would collapse everything past 10s into +Inf
    CHECKPOINT_DURATION: LAG_BUCKETS,
    CHECKPOINT_BYTES: BYTES_BUCKETS,
    FRAME_BYTES: BYTES_BUCKETS,
    FLUSH_LATENCY: LATENCY_BUCKETS,
    # batches-per-flush is a small count: 1 = passthrough (no merge)
    COALESCE_BATCHES: (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0,
                       32.0, 64.0),
}

# one registry per process (worker); the admin server renders it
REGISTRY = CollectorRegistry()
_lock = threading.Lock()
_counters: Dict[str, Counter] = {}
_gauges: Dict[str, Gauge] = {}
_histograms: Dict[str, Histogram] = {}


def _counter(name: str, help_: str) -> Counter:
    with _lock:
        if name not in _counters:
            _counters[name] = Counter(name, help_, LABELS,
                                      registry=REGISTRY)
        return _counters[name]


def _gauge(name: str, help_: str) -> Gauge:
    with _lock:
        if name not in _gauges:
            _gauges[name] = Gauge(name, help_, LABELS, registry=REGISTRY)
        return _gauges[name]


def _histogram(name: str, help_: str) -> Histogram:
    with _lock:
        if name not in _histograms:
            _histograms[name] = Histogram(
                name, help_, LABELS,
                buckets=_BUCKETS.get(name, Histogram.DEFAULT_BUCKETS),
                registry=REGISTRY)
        return _histograms[name]


def counter_for_task(task_info, name: str, help_: str = "") -> Counter:
    """counter_for_task (arroyo-metrics/src/lib.rs:9-21)."""
    return _counter(name, help_ or name).labels(
        job_id=task_info.job_id, operator_id=task_info.operator_id,
        subtask_idx=str(task_info.task_index),
        operator_name=getattr(task_info, "operator_name",
                              task_info.operator_id))


def gauge_for_task(task_info, name: str, help_: str = "") -> Gauge:
    """gauge_for_task (arroyo-metrics/src/lib.rs:23-35)."""
    return _gauge(name, help_ or name).labels(
        job_id=task_info.job_id, operator_id=task_info.operator_id,
        subtask_idx=str(task_info.task_index),
        operator_name=getattr(task_info, "operator_name",
                              task_info.operator_id))


def histogram_for_task(task_info, name: str, help_: str = "") -> Histogram:
    """Labeled histogram child for one subtask (same label scheme as the
    counters, so rate()/histogram_quantile() queries join on labels)."""
    return _histogram(name, help_ or name).labels(
        job_id=task_info.job_id, operator_id=task_info.operator_id,
        subtask_idx=str(task_info.task_index),
        operator_name=getattr(task_info, "operator_name",
                              task_info.operator_id))


class TaskMetrics:
    """Per-task instruments every subtask maintains: the reference's six
    flat counters/gauges (arroyo-worker/src/metrics.rs) plus the flight
    recorder's lag/latency/backpressure histograms."""

    def __init__(self, task_info):
        self.messages_recv = counter_for_task(
            task_info, MESSAGES_RECV, "records received by this subtask")
        self.messages_sent = counter_for_task(
            task_info, MESSAGES_SENT, "records sent by this subtask")
        self.bytes_recv = counter_for_task(
            task_info, BYTES_RECV, "serialized bytes received")
        self.bytes_sent = counter_for_task(
            task_info, BYTES_SENT, "serialized bytes sent")
        self.tx_queue_size = gauge_for_task(
            task_info, TX_QUEUE_SIZE, "outbound queue capacity")
        self.tx_queue_rem = gauge_for_task(
            task_info, TX_QUEUE_REM, "outbound queue remaining slots")
        self.event_time_lag = histogram_for_task(
            task_info, EVENT_TIME_LAG,
            "processing-time minus max event time per received batch")
        self.watermark_lag = histogram_for_task(
            task_info, WATERMARK_LAG,
            "processing-time minus the operator's input watermark")
        self.batch_latency = histogram_for_task(
            task_info, BATCH_LATENCY,
            "wall time spent in process_batch per batch")
        self.queue_wait = histogram_for_task(
            task_info, QUEUE_WAIT,
            "time the task loop waited for input per message")
        self.backpressure_time = counter_for_task(
            task_info, BACKPRESSURE_TIME,
            "cumulative seconds blocked sending to full downstream queues")
        self.kernel_time = counter_for_task(
            task_info, KERNEL_TIME,
            "cumulative seconds in device-kernel dispatch for this subtask")
        self.checkpoint_duration = histogram_for_task(
            task_info, CHECKPOINT_DURATION,
            "subtask checkpoint duration (sync phase)")
        self.checkpoint_bytes = histogram_for_task(
            task_info, CHECKPOINT_BYTES,
            "bytes written per subtask checkpoint")
        self.coalesce_batches = histogram_for_task(
            task_info, COALESCE_BATCHES,
            "record batches merged per coalesced flush at this task's "
            "input (1 = passed through unmerged)")


def render_metrics(registry: Optional[CollectorRegistry] = None) -> bytes:
    return generate_latest(registry or REGISTRY)


def snapshot(name_prefix: str = "arroyo_worker_") -> Dict[str, float]:
    """In-process scrape: {metric{label=...}: value} for API proxying."""
    out: Dict[str, float] = {}
    for fam in REGISTRY.collect():
        if not fam.name.startswith(name_prefix.rstrip("_")):
            continue
        for s in fam.samples:
            if s.name.endswith("_created"):
                continue
            labels = ",".join(f"{k}={v}" for k, v in sorted(
                s.labels.items()))
            out[f"{s.name}{{{labels}}}"] = s.value
    return out


TABLE_SIZE = "arroyo_worker_table_size_keys"
# the reference's labels plus job_id: without it, same-named operators of
# different jobs sharing a process registry would clobber each other
TABLE_LABELS = ("job_id", "operator_id", "task_id", "table_char")
_table_gauge: Optional[Gauge] = None


def table_size_gauge(task_info, table_char: str) -> Gauge:
    """Per-table key-count gauge (arroyo-state/src/metrics.rs
    TABLE_SIZE_GAUGE: name + labels match the reference exactly)."""
    global _table_gauge
    with _lock:
        if _table_gauge is None:
            _table_gauge = Gauge(TABLE_SIZE, "Number of keys in the table",
                                 TABLE_LABELS, registry=REGISTRY)
    return _table_gauge.labels(
        job_id=task_info.job_id,
        operator_id=task_info.operator_id,
        task_id=str(task_info.task_index),
        table_char=table_char)


# -- event-loop watchdog instruments (obs/profiler.py) -----------------------

# worker-level (no operator label — scheduling lag is a property of the
# process's event loop, every subtask on it shares the number); the
# quantile label distinguishes the p50/p99 gauges the watchdog refreshes
_EVENT_LOOP_LABELS = ("job_id", "quantile")
_event_loop_gauge: Optional[Gauge] = None
_event_loop_stalls: Optional[Counter] = None


def event_loop_lag_gauge(job_id: str, quantile: str) -> Gauge:
    """Scheduling-lag gauge child (quantile is 'p50' or 'p99') — how
    late the loop wakes a sleeping coroutine, sampled continuously by
    the profiler's watchdog ticker."""
    global _event_loop_gauge
    with _lock:
        if _event_loop_gauge is None:
            _event_loop_gauge = Gauge(
                EVENT_LOOP_LAG,
                "event-loop scheduling lag (watchdog ticker wake delay)",
                _EVENT_LOOP_LABELS, registry=REGISTRY)
    return _event_loop_gauge.labels(job_id=job_id or "", quantile=quantile)


def event_loop_stalls_counter(job_id: str) -> Counter:
    """Stall episodes past the watchdog threshold — each one had its
    blocking stack captured (admin /profile/phases?fmt=json)."""
    global _event_loop_stalls
    with _lock:
        if _event_loop_stalls is None:
            _event_loop_stalls = Counter(
                EVENT_LOOP_STALLS,
                "event-loop stalls past the watchdog threshold",
                ("job_id",), registry=REGISTRY)
    return _event_loop_stalls.labels(job_id=job_id or "")


# -- sharded-data-plane instruments (parallel/shuffle.py) --------------------

# process-level (no operator label: resharding is detected at kernel
# dispatch sites that may run off-task, e.g. executor-offloaded
# transfers; the profiler's per-operator `reshard` phase carries the
# attribution, these counters carry the invariant)
_PLAIN_LABELS = ("job_id",)
_plain_counters: Dict[str, Counter] = {}


def _plain_counter(name: str, help_: str, job_id: str = "") -> Counter:
    with _lock:
        if name not in _plain_counters:
            _plain_counters[name] = Counter(name, help_, _PLAIN_LABELS,
                                            registry=REGISTRY)
    return _plain_counters[name].labels(job_id=job_id)


def reshard_counter(job_id: str = "") -> Counter:
    """Device arrays re-placed because their resident sharding did not
    match a kernel's explicit in_sharding — the sharded data plane's
    zero-in-steady-state invariant (docs/operations.md runbook)."""
    return _plain_counter(
        RESHARDS_TOTAL,
        "device arrays resharded at a kernel boundary (0 = invariant holds)",
        job_id)


def shuffle_collective_counter(job_id: str = "") -> Counter:
    """On-device all_to_all exchanges carrying co-located SHUFFLE edges
    (each one is a host shuffle that never happened)."""
    return _plain_counter(
        SHUFFLE_COLLECTIVES,
        "on-device all_to_all shuffle exchanges", job_id)


JOIN_DEVICE_GATHER = "arroyo_worker_join_device_gather_rows"
JOIN_HOST_GATHER = "arroyo_worker_join_host_gather_rows"


def join_gather_counter(path: str, job_id: str = "") -> Counter:
    """Join payload rows materialized per gather path: ``device`` =
    through resident payload planes (one fused dispatch per partition),
    ``host`` = numpy fancy-index of the host mirror (cold partitions,
    keys-only rings, the string sticky fallback, the legacy layout).
    With device payloads on, hot partitions must report ZERO host rows
    — the payload-residency invariant as a number."""
    name = JOIN_DEVICE_GATHER if path == "device" else JOIN_HOST_GATHER
    return _plain_counter(
        name, f"join payload rows materialized via the {path} gather",
        job_id)


SESSION_DEVICE_MERGE = "arroyo_worker_session_device_merge_rows"
SESSION_HOST_MERGE = "arroyo_worker_session_host_merge_rows"


def session_merge_counter(path: str, job_id: str = "") -> Counter:
    """Session-interval rows merged per path: ``device`` = through the
    vectorized all-keys union dispatch (state/session_state.py),
    ``host`` = the per-key python merge (the clamp fallback, span
    overflows, and the whole stream under ARROYO_SESSION_STATE=legacy).
    config5-shape jobs riding host is THE slow-path signature — the
    triage runbook (docs/operations.md) keys off this split."""
    name = SESSION_DEVICE_MERGE if path == "device" else SESSION_HOST_MERGE
    return _plain_counter(
        name, f"session interval rows merged via the {path} path",
        job_id)


FACTOR_SHARED_PANES = "arroyo_factor_shared_panes"
FACTOR_DERIVED_WINDOWS = "arroyo_factor_derived_windows"
_factor_shared: Optional[Gauge] = None
_factor_derived: Optional[Gauge] = None


def factor_shared_panes_gauge(job_id: str) -> Gauge:
    """Shared factor-pane operators in the running plan (one per
    correlated-window group the cost model decided to share;
    graph/factor_windows.py) — 0 when nothing factored or
    ARROYO_FACTOR_WINDOWS=0."""
    global _factor_shared
    with _lock:
        if _factor_shared is None:
            _factor_shared = Gauge(
                FACTOR_SHARED_PANES,
                "shared factor-pane operators in the running plan",
                ("job_id",), registry=REGISTRY)
    return _factor_shared.labels(job_id=job_id)


def factor_derived_windows_gauge(job_id: str) -> Gauge:
    """Derived-window consumers rolling shared factor panes into their
    query's (width, slide) output — 0 when nothing factored."""
    global _factor_derived
    with _lock:
        if _factor_derived is None:
            _factor_derived = Gauge(
                FACTOR_DERIVED_WINDOWS,
                "derived-window consumers over shared factor panes",
                ("job_id",), registry=REGISTRY)
    return _factor_derived.labels(job_id=job_id)


MESH_CARRIED_SHUFFLES = "arroyo_mesh_carried_shuffles"
_mesh_carried: Optional[Gauge] = None


def mesh_carried_gauge(job_id: str) -> Gauge:
    """Chain-interior SHUFFLE edges whose keyed exchange rides the mesh
    state's on-device all_to_all (graph/chaining.py ``shuffle_edges``
    when the mesh is active) — 0 when the mesh is off or no chain
    crosses a shuffle."""
    global _mesh_carried
    with _lock:
        if _mesh_carried is None:
            _mesh_carried = Gauge(
                MESH_CARRIED_SHUFFLES,
                "chain-interior shuffle edges carried by the device mesh",
                ("job_id",), registry=REGISTRY)
    return _mesh_carried.labels(job_id=job_id)


# -- latency-observatory instruments (obs/latency.py) ------------------------

SINK_E2E_LATENCY = "arroyo_sink_e2e_latency_seconds"
SINK_E2E_QUANTILE = "arroyo_sink_e2e_latency_quantile_seconds"
DEVICE_STATE_BYTES = "arroyo_device_state_bytes"
SLO_VIOLATIONS = "arroyo_slo_violations_total"
SLO_BURN_RATE = "arroyo_slo_burn_rate"

# e2e latency spans sub-ms (hot chained path) to tens of seconds (a
# held watermark on a wide window) — the lag buckets fit
_BUCKETS[SINK_E2E_LATENCY] = LAG_BUCKETS

_SINK_QUANTILE_LABELS = ("job_id", "operator_id", "operator_name",
                         "quantile")
_sink_quantile_gauge: Optional[Gauge] = None
_device_state_gauge: Optional[Gauge] = None
_slo_violations: Optional[Counter] = None
_slo_burn: Optional[Gauge] = None


def sink_latency_histogram(task_info) -> Histogram:
    """Per-sink end-to-end (emit-minus-ingest) latency of sampled
    records — the measurement behind the ROADMAP-4 SLO."""
    return histogram_for_task(
        task_info, SINK_E2E_LATENCY,
        "sampled record end-to-end latency (sink emit minus source "
        "ingest wall-clock)")


def sink_latency_quantile_gauge(task_info, quantile: str) -> Gauge:
    """Rolling-window p50/p99 gauges the observatory refreshes per
    sampled observation (histogram_quantile needs a scraper; these are
    readable in-process and ride the heartbeat rollup)."""
    global _sink_quantile_gauge
    with _lock:
        if _sink_quantile_gauge is None:
            _sink_quantile_gauge = Gauge(
                SINK_E2E_QUANTILE,
                "rolling-window end-to-end latency quantile per sink",
                _SINK_QUANTILE_LABELS, registry=REGISTRY)
    return _sink_quantile_gauge.labels(
        job_id=task_info.job_id, operator_id=task_info.operator_id,
        operator_name=getattr(task_info, "operator_name",
                              task_info.operator_id),
        quantile=quantile)


def device_state_bytes_gauge(job_id: str, table: str) -> Gauge:
    """Per-job device-resident state bytes by table (join payload
    rings, keys-only ring slots, pane planes, shuffle stacks…) — the
    device-memory ledger groundwork for co-scheduled-job accounting
    (ROADMAP-1)."""
    global _device_state_gauge
    with _lock:
        if _device_state_gauge is None:
            _device_state_gauge = Gauge(
                DEVICE_STATE_BYTES,
                "device-resident state bytes by table",
                ("job_id", "table"), registry=REGISTRY)
    return _device_state_gauge.labels(job_id=job_id or "", table=table)


def slo_violations_counter(job_id: str) -> Counter:
    """SLO evaluations that found a dimension out of budget (each one
    also lands in the controller's violation ledger with the measured
    vs target numbers)."""
    global _slo_violations
    with _lock:
        if _slo_violations is None:
            _slo_violations = Counter(
                SLO_VIOLATIONS, "latency-SLO violation evaluations",
                ("job_id",), registry=REGISTRY)
    return _slo_violations.labels(job_id=job_id or "")


def slo_burn_rate_gauge(job_id: str) -> Gauge:
    """Violating fraction of SLO evaluations over the trailing burn
    window (0 = healthy, 1 = burning the whole budget every tick) —
    the autoscaler's latency signal."""
    global _slo_burn
    with _lock:
        if _slo_burn is None:
            _slo_burn = Gauge(
                SLO_BURN_RATE, "SLO burn rate over the trailing window",
                ("job_id",), registry=REGISTRY)
    return _slo_burn.labels(job_id=job_id or "")


# -- autoscaler instruments --------------------------------------------------

# controller-side: every policy evaluation lands in decisions (labeled by
# the resulting action incl. hold/veto), blocked recommendations in
# vetoes (labeled by reason), and completed rescales in actuations
AUTOSCALER_DECISIONS = "arroyo_autoscaler_decisions_total"
AUTOSCALER_VETOES = "arroyo_autoscaler_vetoes_total"
AUTOSCALER_ACTUATIONS = "arroyo_autoscaler_actuations_total"
AUTOSCALER_PARALLELISM = "arroyo_autoscaler_target_parallelism"

_AUTOSCALER_LABELS = {
    AUTOSCALER_DECISIONS: ("job_id", "action"),
    AUTOSCALER_VETOES: ("job_id", "reason"),
    AUTOSCALER_ACTUATIONS: ("job_id", "direction"),
}
_AUTOSCALER_HELP = {
    AUTOSCALER_DECISIONS: "autoscaler policy evaluations by action",
    AUTOSCALER_VETOES: "autoscaler recommendations blocked, by reason",
    AUTOSCALER_ACTUATIONS: "autoscaler-driven rescales that completed",
}
_autoscaler_counters: Dict[str, Counter] = {}
_autoscaler_parallelism: Optional[Gauge] = None


def autoscaler_counter(name: str, job_id: str, value: str) -> Counter:
    """Labeled child of one autoscaler counter family (name must be one
    of the AUTOSCALER_* counter constants)."""
    with _lock:
        if name not in _autoscaler_counters:
            _autoscaler_counters[name] = Counter(
                name, _AUTOSCALER_HELP[name], _AUTOSCALER_LABELS[name],
                registry=REGISTRY)
    labels = _AUTOSCALER_LABELS[name]
    return _autoscaler_counters[name].labels(**{labels[0]: job_id,
                                                labels[1]: value})


def autoscaler_parallelism_gauge(job_id: str, operator_id: str) -> Gauge:
    """The parallelism the autoscaler last targeted per operator — plot
    against the worker throughput families to see elasticity."""
    global _autoscaler_parallelism
    with _lock:
        if _autoscaler_parallelism is None:
            _autoscaler_parallelism = Gauge(
                AUTOSCALER_PARALLELISM,
                "operator parallelism last targeted by the autoscaler",
                ("job_id", "operator_id"), registry=REGISTRY)
    return _autoscaler_parallelism.labels(job_id=job_id,
                                          operator_id=operator_id)


CHECKPOINT_TABLE_SECONDS = "arroyo_worker_checkpoint_table_seconds"
CHECKPOINT_TABLE_BYTES = "arroyo_worker_checkpoint_table_bytes"
_table_ckpt_gauges: Dict[str, Gauge] = {}


def checkpoint_table_gauge(task_info, table_char: str, which: str) -> Gauge:
    """Per-table checkpoint cost gauges, refreshed at every barrier:
    ``which`` is 'seconds' (serialize+write wall time) or 'bytes'
    (compressed file size).  Same label scheme as table_size_gauge so
    dashboards join the three per-table families."""
    name = (CHECKPOINT_TABLE_SECONDS if which == "seconds"
            else CHECKPOINT_TABLE_BYTES)
    with _lock:
        if name not in _table_ckpt_gauges:
            _table_ckpt_gauges[name] = Gauge(
                name, f"last checkpoint {which} for the table",
                TABLE_LABELS, registry=REGISTRY)
    return _table_ckpt_gauges[name].labels(
        job_id=task_info.job_id,
        operator_id=task_info.operator_id,
        task_id=str(task_info.task_index),
        table_char=table_char)


# -- heartbeat-sized rollups -------------------------------------------------

# summary keys are metric names with the arroyo_worker_ prefix stripped;
# histograms contribute their _sum/_count pair (enough for avg + rate
# math controller-side without shipping every bucket)
_SUMMARY_SKIP_SUFFIXES = ("_bucket", "_created")

# lag/latency histograms and the queue gauges ALSO ship per-subtask
# values (`key@idx`): the controller's rollup takes the worst subtask,
# and summing across co-located subtasks first would average a single
# hot subtask away — the exact signal the rollup exists to carry
_PER_SUBTASK_FAMS = ("event_time_lag_seconds", "watermark_lag_seconds",
                     "batch_processing_seconds", "queue_wait_seconds",
                     "tx_queue_size", "tx_queue_rem")


def job_operator_summary(job_id: str) -> Dict[str, Dict[str, float]]:
    """Compact per-operator rollup of this process's registry for one job
    — what a worker attaches to its heartbeat so the controller can serve
    job-level aggregation without scraping workers over HTTP.  When the
    phase profiler is armed, its per-operator phase/wait seconds ride
    along as ``phase_seconds.<phase>`` / ``wait_seconds.<phase>`` keys,
    and worker-level (operator-less) families — the event-loop lag
    gauges — land under the pseudo-operator ``__worker__``."""
    out: Dict[str, Dict[str, float]] = {}
    prefix = "arroyo_worker_"
    for fam in REGISTRY.collect():
        if not fam.name.startswith(prefix.rstrip("_")):
            continue
        for s in fam.samples:
            if s.name.endswith(_SUMMARY_SKIP_SUFFIXES):
                continue
            if s.labels.get("job_id") != job_id:
                continue
            op = s.labels.get("operator_id", "") or "__worker__"
            key = s.name[len(prefix):] if s.name.startswith(prefix) else s.name
            q = s.labels.get("quantile")
            if q:  # event-loop lag gauges: one key per quantile child
                key = f"{key}_{q}"
            g = out.setdefault(op, {})
            g[key] = g.get(key, 0.0) + s.value
            sub = s.labels.get("subtask_idx")
            if sub is not None and key.startswith(_PER_SUBTASK_FAMS):
                sk = f"{key}@{sub}"
                g[sk] = g.get(sk, 0.0) + s.value
    from . import profiler as _profiler

    prof = _profiler.active()
    if prof is not None and (not prof.job_id or prof.job_id == job_id):
        for (op, phase), secs in prof.work_snapshot().items():
            out.setdefault(op, {})[f"phase_seconds.{phase}"] = round(secs, 6)
        for (op, phase), secs in prof.wait_snapshot().items():
            out.setdefault(op, {})[f"wait_seconds.{phase}"] = round(secs, 6)
    # latency-observatory ride-alongs (e2e_latency.*, wm_age_ms,
    # critical_path.*, device_bytes.*) — same mechanism as the profiler's
    from . import latency as _latency

    for op, keys in _latency.summary_ride_alongs(job_id).items():
        out.setdefault(op, {}).update(keys)
    return out
