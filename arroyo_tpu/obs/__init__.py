"""Observability: logging, prometheus metrics, admin server, profiling.

The arroyo-server-common + arroyo-metrics analog
(/root/reference/arroyo-server-common/src/lib.rs:49-205,
/root/reference/arroyo-metrics/src/lib.rs:9-50).
"""

from .logging_setup import init_logging  # noqa: F401
from .metrics import (TaskMetrics, counter_for_task, gauge_for_task,  # noqa: F401
                      histogram_for_task, render_metrics)
from .admin import AdminServer  # noqa: F401
from . import tracing  # noqa: F401
