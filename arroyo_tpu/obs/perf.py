"""Lightweight performance accounting.

Two tiers:

* **Always-cheap per-operator accumulator** — every ``timed_device`` call
  made while a task has installed a :class:`KernelAccumulator` (the
  TaskRunner does this) adds its dispatch wall time to that operator's
  ``arroyo_worker_kernel_seconds_total`` counter and, for spans above a
  floor, to the flight-recorder trace ring.  Dispatch is *not* blocked
  on, so the cost is two ``perf_counter_ns`` reads per kernel — safe in
  production.
* **Blocking measurement mode**, enabled by ``ARROYO_TIMING=1``: blocks
  on the kernel result at the call site so the ``device_ns`` counter is
  true device time.  Serializes dispatch — use for measurement runs
  (bench.py's device_share), not production.
"""

from __future__ import annotations

import contextvars
import os
import time
from contextvars import ContextVar
from typing import Any, Dict, Optional

from . import profiler as _profiler

_COUNTERS: Dict[str, int] = {}
_NOTES: Dict[str, Any] = {}

# spans shorter than this don't earn a trace-ring entry (the counter
# still accumulates them); keeps micro-kernels from flooding the ring
_TRACE_FLOOR_NS = 50_000


class KernelAccumulator:
    """Per-subtask kernel-time sink: a prometheus counter child plus
    identity for trace spans.  Installed by the TaskRunner for the
    duration of its coroutine (contextvars flow through awaits, so every
    kernel the operator dispatches on the event loop lands here)."""

    __slots__ = ("task_id", "operator_id", "counter")

    def __init__(self, task_info, metrics=None):
        self.task_id = task_info.task_id
        self.operator_id = task_info.operator_id
        self.counter = getattr(metrics, "kernel_time", None)

    def add(self, ns: int) -> None:
        if self.counter is not None:
            self.counter.inc(ns / 1e9)
        if ns >= _TRACE_FLOOR_NS:
            from . import tracing

            end = tracing.now_us()
            tracing.record_span("kernel", "kernel", end - ns / 1e3,
                                ns / 1e3, tid=self.task_id)


_ACTIVE_TASK: ContextVar[Optional[KernelAccumulator]] = ContextVar(
    "arroyo_active_kernel_acc", default=None)


def set_active_task(acc: Optional[KernelAccumulator]):
    """Install the accumulator for the current (coroutine) context;
    returns a token for ``reset_active_task``."""
    return _ACTIVE_TASK.set(acc)


def reset_active_task(token) -> None:
    _ACTIVE_TASK.reset(token)


def active_operator_id() -> Optional[str]:
    """Operator id of the current (coroutine) context's task, or None
    off-task — lets state-layer code (join gather, ring maintenance)
    attribute profiler phases without threading ids through every
    call."""
    acc = _ACTIVE_TASK.get()
    return acc.operator_id if acc is not None else None


def run_offloaded(loop, fn, *args):
    """``loop.run_in_executor`` with contextvars propagated: executor
    threads don't inherit the caller's context, so kernels dispatched
    from an offloaded transfer would otherwise bypass the active task's
    accumulator and report zero kernel time exactly on the accelerator
    backends where offload is enabled."""
    ctx = contextvars.copy_context()
    return loop.run_in_executor(None, lambda: ctx.run(fn, *args))


def timing_enabled() -> bool:
    return bool(os.environ.get("ARROYO_TIMING"))


def reset() -> None:
    _COUNTERS.clear()
    _NOTES.clear()


def counter(key: str) -> int:
    """Counter read (ns-valued keys like ``device_ns``, and plain counts
    like ``kernel_dispatches`` — the number of device-kernel dispatches
    made through :func:`timed_device`, which bench.py turns into
    dispatches-per-event)."""
    return _COUNTERS.get(key, 0)


counter_ns = counter  # legacy name for the ns-valued keys


def count(key: str, n: int = 1) -> None:
    """Increment a plain process-wide counter (join-state merge/spill
    accounting, bench attribution).  Cheap: one dict update."""
    _COUNTERS[key] = _COUNTERS.get(key, 0) + n


def note(key: str, value: Any) -> None:
    _NOTES[key] = value


def get_note(key: str, default: Any = None) -> Any:
    return _NOTES.get(key, default)


def timed_device(call, *args):
    """Run a jitted kernel call.  Always: attribute dispatch wall time to
    the active task's kernel accumulator (cheap, non-blocking).  With
    ``ARROYO_TIMING=1``: additionally block until the result is ready and
    account true device time to the ``device_ns`` counter.  With the
    phase profiler armed, the span also lands in the phase table — as
    ``dispatch`` (host-side envelope) normally, as ``device_execute``
    when blocking — nested so the enclosing ``proc`` phase stays
    exclusive."""
    blocking = timing_enabled()
    acc = _ACTIVE_TASK.get()
    if not blocking and acc is None:
        return call(*args)
    prof = _profiler.active()
    frame = None
    if prof is not None:
        frame = prof.begin(
            acc.operator_id if acc is not None else "kernel",
            "device_execute" if blocking else "dispatch")
    _COUNTERS["kernel_dispatches"] = _COUNTERS.get(
        "kernel_dispatches", 0) + 1
    t0 = time.perf_counter_ns()
    try:
        out = call(*args)
        if blocking:
            import jax

            jax.block_until_ready(out)
    finally:
        dt = time.perf_counter_ns() - t0
        if frame is not None:
            prof.end(frame)
    if blocking:
        _COUNTERS["device_ns"] = _COUNTERS.get("device_ns", 0) + dt
    if acc is not None:
        acc.add(dt)
    return out
