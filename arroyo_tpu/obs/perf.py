"""Lightweight performance accounting, enabled by ``ARROYO_TIMING=1``.

Answers the two questions BASELINE.md's protocol needs (and the reference
answers with pyroscope + prometheus): how much of the wall-clock went to
device kernels vs the host loop, and what the end-to-end latency
distribution looks like.  Device time is measured by blocking on the
kernel result at the call site, so enabling timing serializes dispatch —
use for measurement runs, not production.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict

_COUNTERS: Dict[str, int] = {}
_NOTES: Dict[str, Any] = {}


def timing_enabled() -> bool:
    return bool(os.environ.get("ARROYO_TIMING"))


def reset() -> None:
    _COUNTERS.clear()
    _NOTES.clear()


def counter_ns(key: str) -> int:
    return _COUNTERS.get(key, 0)


def note(key: str, value: Any) -> None:
    _NOTES[key] = value


def get_note(key: str, default: Any = None) -> Any:
    return _NOTES.get(key, default)


def timed_device(call, *args):
    """Run a jitted kernel call; when timing is on, block until the result
    is ready and account the wall time to the ``device_ns`` counter."""
    if not timing_enabled():
        return call(*args)
    import jax

    t0 = time.perf_counter_ns()
    out = call(*args)
    jax.block_until_ready(out)
    _COUNTERS["device_ns"] = (_COUNTERS.get("device_ns", 0)
                              + time.perf_counter_ns() - t0)
    return out
