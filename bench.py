"""Benchmark: Nexmark q5 (hot items — sliding-window count + windowed max
join) end-to-end through the SQL-planned engine on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no numbers (BASELINE.md) — its README
claims "millions of events per second", so vs_baseline normalizes to 1M
events/sec (vs_baseline = events_per_sec / 1e6).
"""

import json
import os
import sys
import time

NUM_EVENTS = int(os.environ.get("BENCH_EVENTS", 2_000_000))
BATCH = int(os.environ.get("BENCH_BATCH", 65536))


Q5 = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000',
  num_events = '{n}', rate_limited = 'false', batch_size = '{b}'
);
WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
    FROM nexmark where bid is not null)
SELECT AuctionBids.auction as auction, AuctionBids.num as num
FROM (
  SELECT B1.auction, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
         as window, count(*) AS num
  FROM bids B1 GROUP BY 1, 2
) AS AuctionBids
JOIN (
  SELECT max(num) AS maxn, window
  FROM (
    SELECT count(*) AS num,
           HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
    FROM bids B2 GROUP BY B2.auction, 2
  ) AS CountBids
  GROUP BY 2
) AS MaxBids
ON AuctionBids.num = MaxBids.maxn and AuctionBids.window = MaxBids.window
"""


def main() -> None:
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import plan_sql

    os.environ.setdefault("BATCH_SIZE", str(BATCH))

    sql = Q5.format(n=NUM_EVENTS, b=BATCH)
    # warmup: compile all kernels on a small stream
    clear_sink("results")
    LocalRunner(plan_sql(sql.replace(str(NUM_EVENTS), "100000", 1))).run()

    clear_sink("results")
    prog = plan_sql(sql)
    t0 = time.perf_counter()
    LocalRunner(prog).run()
    dt = time.perf_counter() - t0
    outs = sink_output("results")
    n_out = sum(len(b) for b in outs)
    assert n_out > 0, "q5 produced no output"

    eps = NUM_EVENTS / dt
    print(json.dumps({
        "metric": "nexmark_q5_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/sec",
        "vs_baseline": round(eps / 1_000_000.0, 3),
    }))


if __name__ == "__main__":
    main()
