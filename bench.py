"""Nexmark benchmark suite over the SQL-planned engine on the available
accelerator (BASELINE.md configs):

  q1  stateless currency-conversion map over bids
  q5  hot items: sliding-window count + windowed max join   [headline]
  q7  highest bid: tumbling global max joined back to bids
  q8  monitor new users: persons joined to their auctions per window

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} for the
query named by BENCH_QUERY (default q5, the headline the driver records).
BENCH_ALL=1 runs every query, printing non-headline results to stderr.

``--autoscale`` runs the elasticity benchmark instead: an impulse flood
through a real controller with the closed-loop autoscaler enabled, the
JSON line carrying the decision timeline and throughput-vs-parallelism
samples (``autoscale`` key) rather than a steady-state headline.

Baseline: the reference publishes no numbers and its Rust CPU backend
cannot run in this image (no cargo toolchain, BASELINE.md) — so
``vs_baseline`` is measured against an honest, clearly-labeled CONTROL:
a straightforward single-thread numpy implementation of the same query
semantics over the same generator stream, timed in-process right before
the engine runs (see ``CONTROLS``).  The control is the "what you'd
write without the engine" number, not the reference.  BENCH_CONTROL=0
skips it (vs_baseline then omitted).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

NUM_EVENTS = int(os.environ.get("BENCH_EVENTS", 2_000_000))
# 128k-row batches measured consistently >= 64k on q5/q7/q8 (fewer
# per-batch host passes; and on a tunneled TPU, fewer larger transfers)
BATCH = int(os.environ.get("BENCH_BATCH", 131072))

# Backend-probe bounds: first TPU/tunnel init can take 20-40s legitimately,
# but the axon plugin has been observed to hang indefinitely — so every
# attempt is bounded and unrecoverable failure falls back to CPU fast
# rather than recording nothing (round-1 BENCH was rc=1 for exactly this).
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
PROBE_RETRIES = int(os.environ.get("BENCH_PROBE_RETRIES", 3))
PROBE_BACKOFF = float(os.environ.get("BENCH_PROBE_BACKOFF", 20))


def probe_backend():
    """Decide which jax backend to use WITHOUT risking a hang in this
    process: probe `jax.devices()` in a subprocess with a hard timeout,
    retry, and on unrecoverable failure force the CPU backend so the bench
    still records a number (tagged with its backend).

    Returns ``(backend, probe_failures)`` — every failed probe attempt is
    returned so the artifact records that an accelerator was TRIED, not
    just that CPU was used (a "backend: cpu" line with no recorded attempt
    reads as CPU-by-choice)."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu", []
    code = ("import jax; "
            "print(jax.default_backend(), len(jax.devices()))")
    failures = []
    for attempt in range(1, PROBE_RETRIES + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=PROBE_TIMEOUT)
        except subprocess.TimeoutExpired:
            err = f"timed out after {PROBE_TIMEOUT:.0f}s"
            failures.append({"attempt": f"probe {attempt}", "error": err})
            print(f"backend probe attempt {attempt}/{PROBE_RETRIES}: {err}",
                  file=sys.stderr)
            # a TIMEOUT means the plugin hung for the full bound — retrying
            # has never recovered one (r04/r05 burned 3 x 140s before every
            # run) and delays the real benchmark by minutes; only rc!=0
            # failures (transient tunnel flaps) are worth retrying.
            # BENCH_PROBE_RETRY_TIMEOUTS=1 restores the old behavior.
            if os.environ.get("BENCH_PROBE_RETRY_TIMEOUTS") != "1":
                break
            if attempt < PROBE_RETRIES:
                time.sleep(PROBE_BACKOFF)  # tunnel flaps recover in waves
            continue
        if r.returncode == 0 and r.stdout.strip():
            backend, ndev = r.stdout.split()[:2]
            print(f"backend probe: {backend} ({ndev} devices)",
                  file=sys.stderr)
            return backend, failures
        err = f"rc={r.returncode}: {r.stderr.strip()[-500:]}"
        failures.append({"attempt": f"probe {attempt}", "error": err})
        print(f"backend probe attempt {attempt}/{PROBE_RETRIES} failed "
              f"({err})", file=sys.stderr)
    print("backend probe: accelerator unavailable, falling back to CPU",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", "cpu")
    return "cpu", failures

SRC = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000',
  num_events = '{n}', rate_limited = 'false', batch_size = '{b}'
);
"""

Q1 = SRC + """
SELECT bid.auction as auction, bid.bidder as bidder,
       bid.price * 0.908 as price_dol, bid.datetime as datetime
FROM nexmark WHERE bid is not null
"""

Q5 = SRC + """
WITH bids as (SELECT bid.auction as auction, bid.datetime as datetime
    FROM nexmark where bid is not null)
SELECT AuctionBids.auction as auction, AuctionBids.num as num
FROM (
  SELECT B1.auction, HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND)
         as window, count(*) AS num
  FROM bids B1 GROUP BY 1, 2
) AS AuctionBids
JOIN (
  SELECT max(num) AS maxn, window
  FROM (
    SELECT count(*) AS num,
           HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) AS window
    FROM bids B2 GROUP BY B2.auction, 2
  ) AS CountBids
  GROUP BY 2
) AS MaxBids
ON AuctionBids.num = MaxBids.maxn and AuctionBids.window = MaxBids.window
"""

Q7 = SRC + """
WITH bids as (SELECT bid.auction as auction, bid.price as price,
                     bid.bidder as bidder, bid.datetime as datetime
    FROM nexmark where bid is not null)
SELECT B.auction as auction, B.price as price, B.bidder as bidder
FROM bids B
JOIN (
  SELECT max(price) AS maxprice, TUMBLE(INTERVAL '10' SECOND) as window
  FROM bids GROUP BY 2
) AS M
ON B.price = M.maxprice
WHERE B.datetime >= M.window_start AND B.datetime < M.window_end
"""

Q8 = SRC + """
SELECT P.id as id, P.np as np, A.na as na
FROM (
  SELECT person.id as id, TUMBLE(INTERVAL '10' SECOND) as window,
         count(*) as np
  FROM nexmark WHERE person is not null GROUP BY 1, 2
) AS P
JOIN (
  SELECT auction.seller as seller, TUMBLE(INTERVAL '10' SECOND) as window,
         count(*) as na
  FROM nexmark WHERE auction is not null GROUP BY 1, 2
) AS A
ON P.id = A.seller and P.window = A.window
"""

QUERIES = {"q1": Q1, "q5": Q5, "q7": Q7, "q8": Q8}


# -- measured single-thread control (the honest vs_baseline denominator) -----


def _control_events(n: int, want):
    """Generate the bench's nexmark stream once (same generator, same
    seed/proportions as the engine's source) and return the raw column
    arrays the controls aggregate."""
    import numpy as np

    from arroyo_tpu.connectors.nexmark import (
        NexmarkConfig,
        NexmarkGenerator,
        make_splits,
    )

    cfg = NexmarkConfig(num_events=n, rate_limited=False,
                        batch_size=BATCH, projection=list(want))
    split = make_splits(cfg, 0, 1)[0]
    gen = NexmarkGenerator(cfg, 0, split[0], split[1], split[2], seed=0)
    gen.set_rate(cfg.event_rate, 1)
    cols = {c: [] for c in want}
    cols["event_type"] = []
    ts_parts = []
    while gen.has_next:
        batch, _ = gen.next_batch(BATCH)
        for c in cols:
            cols[c].append(np.asarray(batch.columns[c]))
        ts_parts.append(batch.timestamp)
    out = {c: np.concatenate(v) for c, v in cols.items()}
    out["__ts"] = np.concatenate(ts_parts)
    return out


def _group_counts(keys, ends):
    """Single-thread (key, window_end) counts via lexsort+reduceat.
    Returns (uniq_keys, uniq_ends, counts)."""
    import numpy as np

    order = np.lexsort((ends, keys))
    k, e = keys[order], ends[order]
    first = np.ones(len(k), dtype=bool)
    first[1:] = (k[1:] != k[:-1]) | (e[1:] != e[:-1])
    starts = first.nonzero()[0]
    cnt = np.diff(np.append(starts, len(k)))
    return k[starts], e[starts], cnt


def _hop_expand(ts, slide, width):
    import numpy as np

    W = width // slide
    first_end = (ts // slide + 1) * slide
    return (first_end[:, None]
            + (np.arange(W, dtype=np.int64) * slide)[None, :])


def control_q5(n: int) -> int:
    """q5 semantics, single thread: hop-window counts per auction, per-
    window max, emit (auction, window) rows whose count equals the max."""
    import numpy as np

    ev = _control_events(n, ("bid_auction",))
    bid = ev["event_type"] == 2  # EVENT_BID
    auc = ev["bid_auction"][bid]
    ts = ev["__ts"][bid]
    ends = _hop_expand(ts, 2_000_000, 10_000_000)
    W = ends.shape[1]
    k, e, cnt = _group_counts(np.repeat(auc, W), ends.reshape(-1))
    # max count per window, then the equi-join back
    order = np.lexsort((cnt, e))
    es, cs = e[order], cnt[order]
    last = np.ones(len(es), dtype=bool)
    last[:-1] = es[1:] != es[:-1]
    uw, umax = es[last], cs[last]
    idx = np.searchsorted(uw, e)
    return int(np.sum(cnt == umax[idx]))


def control_q1(n: int) -> int:
    import numpy as np

    ev = _control_events(n, ("bid_auction", "bid_bidder", "bid_price"))
    bid = ev["event_type"] == 2
    price_dol = ev["bid_price"][bid] * 0.908
    return int(np.sum(price_dol >= 0))


def control_q7(n: int) -> int:
    import numpy as np

    ev = _control_events(n, ("bid_auction", "bid_price", "bid_bidder"))
    bid = ev["event_type"] == 2
    price, ts = ev["bid_price"][bid], ev["__ts"][bid]
    wend = (ts // 10_000_000 + 1) * 10_000_000
    order = np.lexsort((price, wend))
    ws, ps = wend[order], price[order]
    last = np.ones(len(ws), dtype=bool)
    last[:-1] = ws[1:] != ws[:-1]
    uw, umax = ws[last], ps[last]
    idx = np.searchsorted(uw, wend)
    return int(np.sum(price == umax[idx]))


def control_q8(n: int) -> int:
    import numpy as np

    ev = _control_events(n, ("person_id", "auction_seller"))
    ts = ev["__ts"]
    person, auction = ev["event_type"] == 0, ev["event_type"] == 1
    wend_p = (ts[person] // 10_000_000 + 1) * 10_000_000
    wend_a = (ts[auction] // 10_000_000 + 1) * 10_000_000
    pk, pe, pc = _group_counts(ev["person_id"][person], wend_p)
    ak, ae, ac = _group_counts(ev["auction_seller"][auction], wend_a)
    pa = set(zip(pk.tolist(), pe.tolist()))
    return sum(1 for s, w in zip(ak.tolist(), ae.tolist()) if (s, w) in pa)


CONTROLS = {"q1": control_q1, "q5": control_q5, "q7": control_q7,
            "q8": control_q8}


def run_control(name: str) -> dict:
    """Time the single-thread numpy control of query ``name`` over the
    same generated stream (generation included, as it is for the engine).
    Returns {} when disabled or unavailable."""
    if os.environ.get("BENCH_CONTROL", "1") in ("0", "false", "no"):
        return {}
    fn = CONTROLS.get(name)
    if fn is None:
        return {}
    n = min(NUM_EVENTS, int(os.environ.get("BENCH_CONTROL_EVENTS",
                                           1_000_000)))
    fn(min(n, 20_000))  # warmup: one-time imports/allocator costs, same
    # courtesy the engine run gets from its warm pass
    t0 = time.perf_counter()
    n_out = fn(n)
    dt = time.perf_counter() - t0
    assert n_out > 0, f"control {name} produced no output"
    return {"control_events_per_sec": round(n / dt, 1),
            "control": "numpy-singlethread",
            "control_events": n}


JOIN_STATE_COUNTERS = (
    "join_state_merges", "join_state_resorts", "join_state_compactions",
    "join_state_promotions", "join_state_demotions",
    "join_state_device_merges", "join_state_ring_regrows",
    "join_device_gather_rows", "join_host_gather_rows",
)

SESSION_COUNTERS = (
    "session_merge_dispatches", "session_merge_device_dispatches",
    "session_device_merge_rows", "session_host_merge_rows",
    "udaf_channel_rows", "udaf_host_rows",
)


def _gather_share(stats: dict) -> dict:
    """Device-gather share of materialized join rows (PR 15's payload
    residency as a measured number): rows emitted through resident
    payload planes over all rows emitted.  ``None`` when the run
    materialized no join rows at all."""
    dev = stats.get("join_device_gather_rows", 0)
    host = stats.get("join_host_gather_rows", 0)
    return {"device_gather_share":
            (round(dev / (dev + host), 4) if dev + host else None)}


def bench_parallelism() -> int:
    """Subtasks per operator for the throughput runs.  The in-process
    LocalRunner executes EVERY subtask on one event-loop thread — only
    XLA kernels and executor-offloaded source generation release the
    GIL — so extra subtasks add shuffle hops and queue churn without
    adding compute: measured on a 2-core box, q5/q7/q8 all run ~1.7-1.8x
    FASTER at parallelism 1 than 2 (r06).  Default to 1; distributed
    multi-worker runs (where parallelism means real cores) set
    BENCH_PARALLELISM explicitly."""
    env = os.environ.get("BENCH_PARALLELISM")
    if env:
        return max(1, int(env))
    return 1


def operator_flight_stats(before: dict, after: dict) -> dict:
    """Per-operator deltas of the flight-recorder counters across the
    timed runs (obs.metrics.job_operator_summary snapshots): where the
    kernel seconds, backpressure stalls, and per-batch latency landed —
    the per-operator breakdown the driver reads to see WHICH operator a
    regression lives in, not just that events/s moved."""
    ops = {}
    for op, cur in after.items():
        prev = before.get(op, {})
        d = {k: v - prev.get(k, 0.0) for k, v in cur.items()}
        row = {}
        for key, out in (("kernel_seconds_total", "kernel_seconds"),
                         ("backpressure_seconds_total",
                          "backpressure_seconds"),
                         ("messages_sent_total", "messages_sent")):
            if d.get(key, 0.0) > 0:
                row[out] = round(d[key], 4)
        for fam, out in (("batch_processing_seconds", "batch_latency_avg"),
                         ("event_time_lag_seconds", "event_time_lag_avg")):
            c = d.get(fam + "_count", 0.0)
            if c > 0:
                row[out] = round(d.get(fam + "_sum", 0.0) / c, 6)
        if row:
            ops[op] = row
    return ops


def preflight_validate(prog, metric: str) -> int:
    """Plan-validator pre-flight: a benchmark pipeline that fails
    graph-level validation OR shardcheck's sharding/transfer
    verification must exit non-zero with a structured error line, not
    run to a recorded 0 events/s (the round-5 failure mode was exactly
    a broken pipeline scoring zero silently).  Returns the plan
    report's ``predicted_reshards`` so the bench line can carry the
    static prediction next to the measured ``mesh.reshards`` counter —
    the same pairing the smoke drift gate asserts on."""
    from arroyo_tpu.analysis.plan_validator import errors_of, plan_report

    rep = plan_report(prog)
    errs = errors_of(rep["diagnostics"])
    if errs:
        print(json.dumps({
            "metric": metric, "value": 0, "unit": "events/sec",
            "error": "plan validation failed",
            "diagnostics": [d.to_json() for d in errs],
        }))
        sys.exit(2)
    return rep["predicted_reshards"]


def run_query(name: str, sql_template: str) -> dict:
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.coalesce import coalescing_enabled
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.graph.chaining import chaining_enabled
    from arroyo_tpu.obs import perf
    from arroyo_tpu.obs.metrics import job_operator_summary
    from arroyo_tpu.sql import plan_sql

    sql = sql_template.format(n=NUM_EVENTS, b=BATCH)
    # warmup: one full run of the SAME program (the jit cache is keyed by
    # the program's expression fns, so re-planning would recompile inside
    # the timed run), then best-of-2 timed runs — the remote-tunnel TPU's
    # server-side caches are flaky enough that single timed runs vary 2x;
    # peak sustained throughput is the stable, comparable number
    par = bench_parallelism()
    prog = plan_sql(sql, parallelism=par)
    predicted_reshards = preflight_validate(
        prog, f"nexmark_{name}_events_per_sec")
    clear_sink("results")
    LocalRunner(prog).run()

    flight_before = job_operator_summary("local-job")
    dispatches_before = perf.counter("kernel_dispatches")
    join_before = {k: perf.counter(k) for k in JOIN_STATE_COUNTERS}
    from arroyo_tpu.parallel import shuffle as _shuffle

    shuffle_before = _shuffle.shuffle_stats()
    n_runs = 2
    best_dt = None
    for _ in range(n_runs):
        clear_sink("results")
        # fresh per-buffer stats registry per run, so the aggregated
        # join-state shape reflects ONE run's buffers (not warmup's)
        perf.note("join_state_registry", {})
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)
    dt = best_dt
    dispatches = perf.counter("kernel_dispatches") - dispatches_before
    flight = operator_flight_stats(flight_before,
                                   job_operator_summary("local-job"))
    outs = sink_output("results")
    n_out = sum(len(b) for b in outs)
    assert n_out > 0, f"{name} produced no output"

    eps = NUM_EVENTS / dt
    result = {
        "metric": f"nexmark_{name}_events_per_sec",
        "value": round(eps, 1),
        "unit": "events/sec",
        "parallelism": par,
        # chaining/coalescing state + amortization evidence: kernel
        # dispatches per source event across the timed runs (the number
        # chaining + expression fusion + coalescing exists to reduce)
        "chain": chaining_enabled(),
        "coalesce": coalescing_enabled(),
        "dispatches_per_event": round(
            dispatches / max(NUM_EVENTS * n_runs, 1), 6),
    }
    # factor-window shape of THIS plan: how many correlated-window
    # groups the cost model shared (q5 after CSE holds ONE hop
    # aggregate, so its decision is "no correlated group" — the
    # correlated_windows family carries the factored-vs-unfactored
    # before/after numbers)
    decisions = [d.to_json() for d in getattr(prog, "factor_decisions", [])]
    result["factor"] = {
        "shared_panes": sum(1 for d in decisions if d["shared"]),
        "derived_windows": sum(len(d["members"]) for d in decisions
                               if d["shared"]),
        "decisions": decisions,
    }
    # sharded-data-plane evidence: mesh shape + the reshard invariant
    # (reshards MUST stay 0 across the timed runs — a nonzero value
    # means some kernel's inputs arrived mis-partitioned) and how many
    # host shuffles the on-device path replaced
    import jax as _jax

    from arroyo_tpu.parallel.mesh_window import mesh_key_shards

    shuffle_delta = {k: v - shuffle_before[k]
                     for k, v in _shuffle.shuffle_stats().items()}
    result["mesh"] = {
        "width": mesh_key_shards(),
        "devices": len(_jax.devices()),
        "reshards": shuffle_delta["reshards"],
        # shardcheck's plan-time prediction for the same counter — the
        # pair the smoke drift gate asserts equal in both directions
        "predicted_reshards": predicted_reshards,
        "shuffle_collectives": shuffle_delta["collectives"],
        "host_shuffle_routes": shuffle_delta["host_routes"],
    }
    if flight:
        result["operators"] = flight
    # join-state shape: merge-vs-resort dispatch counts across the timed
    # runs plus the last hot-partition/spill snapshot — the numbers the
    # partition-adaptive join state exists to move (state/join_state.py)
    join_stats = {k.replace("join_state_", ""):
                  perf.counter(k) - join_before[k]
                  for k in JOIN_STATE_COUNTERS}
    if any(join_stats.values()):
        from arroyo_tpu.state.join_state import aggregate_stats_registry

        join_stats.update(aggregate_stats_registry(
            perf.get_note("join_state_registry")))
        # payload-residency evidence for the q7/q8 headline lines: with
        # device payloads on, hot partitions must emit through the
        # resident planes (host rows come only from cold partitions,
        # keys-only rings, and the string sticky fallback)
        join_stats.update(_gather_share(join_stats))
        result["join_state"] = join_stats
    ctl = run_control(name)
    result.update(ctl)
    if "control_events_per_sec" in ctl:
        # vs_baseline = engine / measured single-thread control (see
        # module docstring; the reference's backend can't run here)
        result["vs_baseline"] = round(
            eps / ctl["control_events_per_sec"], 3)
    result.update(device_share(name, sql_template))
    result.update(phase_profile(name, sql_template))
    result.update(sanitize_overhead(name, sql_template))
    return result


def sanitize_overhead(name: str, sql_template: str) -> dict:
    """ARROYO_SANITIZE cost evidence: re-run a slice of the stream with
    the arroyosan runtime sanitizer off and on and record the relative
    slowdown.  The off run doubles as the zero-cost check — the
    sanitizer hook sites must compile down to `is not None` tests when
    disarmed (BENCH_SANITIZE=0 skips the measurement)."""
    if os.environ.get("BENCH_SANITIZE", "1") in ("0", "false", "no"):
        return {}
    from arroyo_tpu.connectors.memory import clear_sink
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import plan_sql

    n = min(NUM_EVENTS, 300_000)
    prog = plan_sql(sql_template.format(n=n, b=BATCH),
                    parallelism=bench_parallelism())
    prev = os.environ.get("ARROYO_SANITIZE")

    def timed(armed: str) -> float:
        os.environ["ARROYO_SANITIZE"] = armed
        clear_sink("results")
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        return time.perf_counter() - t0

    try:
        timed("0")  # warm (jit cache shared by both arms)
        dt_off = timed("0")
        dt_on = timed("1")
    finally:
        if prev is None:
            os.environ.pop("ARROYO_SANITIZE", None)
        else:
            os.environ["ARROYO_SANITIZE"] = prev
    return {"sanitize_overhead_pct": round(
        (dt_on - dt_off) / dt_off * 100.0, 2)}


def device_share(name: str, sql_template: str) -> dict:
    """Host/device wall-time split: re-run a slice of the stream with
    per-kernel blocking timers (ARROYO_TIMING serializes dispatch, so this
    runs separately from the throughput measurement)."""
    from arroyo_tpu.connectors.memory import clear_sink
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import perf
    from arroyo_tpu.sql import plan_sql

    n = min(NUM_EVENTS, 500_000)
    prog = plan_sql(sql_template.format(n=n, b=BATCH),
                    parallelism=bench_parallelism())
    # warm run of the SAME program first (the jit cache is keyed by the
    # program's expression fns, so the timed run never counts compiles)
    clear_sink("results")
    LocalRunner(prog).run()
    os.environ["ARROYO_TIMING"] = "1"
    try:
        perf.reset()
        clear_sink("results")
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        dt = time.perf_counter() - t0
    finally:
        os.environ.pop("ARROYO_TIMING", None)
    dev = perf.counter_ns("device_ns") / 1e9
    # device_ns sums per-operator timed_device spans; concurrent
    # operators (q8's two parallel aggregates) can overlap, so the share
    # may exceed 1 — report the raw ratio and mark overlap instead of
    # fabricating a negative host share.
    # host_time_share_DERIVED: the old wall-minus-device residual, kept
    # for continuity with BENCH_r0* history — the MEASURED
    # host_time_share now comes from phase_profile()'s phase sum
    share = round(dev / dt, 3)
    out = {"device_time_share": share,
           "host_time_share_derived": round(max(1 - dev / dt, 0.0), 3)}
    if share > 1:
        out["device_time_overlapped"] = True
    return out


def phase_profile(name: str, sql_template: str) -> dict:
    """Measured per-phase host-time table (obs/profiler.py): re-run a
    slice of the stream with the phase profiler armed and record where
    every microsecond of the hot path went — source decode, operator
    host compute, kernel dispatch, shuffle prep, coalesce merge,
    watermark/window fires, emission encode — plus the share of wall
    time NO phase accounts for (``unattributed_share``: the
    falsifiability check that keeps the instrumentation honest as the
    engine evolves).  ``host_time_share`` is now this measured phase
    sum over wall time (clamped to 1; executor-offloaded source
    generation overlaps the event loop, so the raw ``attributed_share``
    may exceed 1 and is reported alongside, like device_time_share).
    Profiler overhead is measured as armed-vs-off wall time on the same
    slice.  BENCH_PHASES=0 skips."""
    if os.environ.get("BENCH_PHASES", "1") in ("0", "false", "no"):
        return {}
    from arroyo_tpu.connectors.memory import clear_sink
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import profiler
    from arroyo_tpu.sql import plan_sql

    n = min(NUM_EVENTS, 500_000)
    prog = plan_sql(sql_template.format(n=n, b=BATCH),
                    parallelism=bench_parallelism())

    def timed() -> float:
        clear_sink("results")
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        return time.perf_counter() - t0

    timed()  # warm (compiles shared by both arms)
    dt_off = min(timed(), timed())  # best-of-2 on BOTH arms: the
    # overhead claim must not ride single-run noise
    prof = profiler.arm("local-job")
    try:
        dt_on = None
        for _ in range(2):
            prof.reset()
            dt = timed()
            if dt_on is None or dt < dt_on:
                dt_on, snap = dt, prof.snapshot()
    finally:
        profiler.disarm()
    # the snapshot's wall includes arm-to-run slack; use the run wall
    phases = snap["phases"]
    attributed = sum(phases.values())
    out = {
        "phases": {k: round(v, 4) for k, v in phases.items()},
        "phase_waits": {k: round(v, 4) for k, v in snap["waits"].items()},
        "phase_wall_secs": round(dt_on, 4),
        "attributed_share": round(attributed / dt_on, 4),
        "unattributed_share": round(
            max(1.0 - attributed / dt_on, 0.0), 4),
        "host_time_share": round(min(attributed / dt_on, 1.0), 3),
        "profile_overhead_pct": round(
            (dt_on - dt_off) / dt_off * 100.0, 2),
        "watchdog_stalls": snap["watchdog"]["stalls"],
        "event_loop_lag_p99_ms": round(
            snap["watchdog"]["lag_p99_secs"] * 1e3, 3),
    }
    # ingest throughput through the decode phase alone: events per
    # second of source_decode time (the number the vectorized serde
    # fast path exists to move; the decode microbench isolates the
    # same family outside the engine)
    decode_secs = phases.get("source_decode", 0.0)
    if decode_secs > 0:
        out["ingest_rows_per_s"] = round(n / decode_secs, 1)
    if attributed > dt_on:
        out["phases_overlapped"] = True  # executor-side source decode
        # runs concurrently with the loop — same caveat as
        # device_time_overlapped
    return out


def run_decode_microbench() -> dict:
    """Decode-family microbench: JSON lines -> Batch through each serde
    path (legacy per-row json.loads pivot, bulk one-shot array parse,
    pyarrow columnar reader with the schema-once lock) plus the egress
    mirror (Batch -> JSON payloads, template render vs per-row dumps).
    Isolates the formats.py layer from the engine so the BENCH_r0*
    trajectory shows the serde speedup independent of pipeline effects.
    The fast paths must emit identical rows (asserted here — a parity
    break is a bench failure, not a silent wrong-number).
    BENCH_DECODE=0 skips."""
    from arroyo_tpu.formats import JsonFormat, batch_to_rows

    import numpy as np

    n = int(os.environ.get("BENCH_DECODE_ROWS", 200_000))
    rng = np.random.default_rng(42)
    auction = rng.integers(1000, 2000, n)
    price = rng.integers(1, 10_000_000, n)
    bidder = rng.integers(0, 5000, n)
    payloads = [
        (f'{{"auction": {auction[i]}, "bidder": {bidder[i]}, '
         f'"price": {price[i]}, "channel": "ch{bidder[i] % 10}", '
         f'"ts": {1700000000000000 + i}}}').encode()
        for i in range(n)
    ]
    chunks = [payloads[i:i + BATCH] for i in range(0, n, BATCH)]

    def timed_decode(mode):
        prev = os.environ.get("ARROYO_FAST_DECODE")
        os.environ["ARROYO_FAST_DECODE"] = "0" if mode == "legacy" else "1"
        try:
            best, batches = None, None
            for _ in range(2):
                fmt = JsonFormat()  # fresh schema lock per run
                if mode == "bulk":
                    fmt._arrow_ok = False
                t0 = time.perf_counter()
                out = [fmt.batch(c, "ts") for c in chunks]
                dt = time.perf_counter() - t0
                if best is None or dt < best:
                    best, batches = dt, out
            return best, batches
        finally:
            if prev is None:
                os.environ.pop("ARROYO_FAST_DECODE", None)
            else:
                os.environ["ARROYO_FAST_DECODE"] = prev

    modes = ["legacy", "bulk"]
    try:
        import pyarrow.json  # noqa: F401
        modes.append("arrow")
    except ImportError:
        pass
    result = {"metric": "decode_microbench", "rows": n, "batch": BATCH}
    batches_by_mode = {}
    for mode in modes:
        dt, batches = timed_decode(mode)
        batches_by_mode[mode] = batches
        result[f"decode_{mode}_rows_per_s"] = round(n / dt, 1)
    for mode in modes[1:]:
        # parity is part of the bench contract: a fast path that drifts
        # from the legacy rows must fail loudly here. Compare every chunk:
        # the arrow path only engages its schema-once lock from chunk 1 on,
        # so first-chunk-only parity would miss exactly the locked path.
        for ci, (fast_b, legacy_b) in enumerate(
                zip(batches_by_mode[mode], batches_by_mode["legacy"])):
            assert batch_to_rows(fast_b) == batch_to_rows(legacy_b), \
                f"decode parity break: {mode} vs legacy (chunk {ci})"
        result[f"decode_{mode}_speedup"] = round(
            result[f"decode_{mode}_rows_per_s"]
            / result["decode_legacy_rows_per_s"], 2)

    # egress mirror: Batch -> JSON payloads
    batch = batches_by_mode[modes[-1]][0]

    def timed_encode(flag):
        prev = os.environ.get("ARROYO_FAST_DECODE")
        os.environ["ARROYO_FAST_DECODE"] = flag
        try:
            fmt = JsonFormat()
            best, out = None, None
            for _ in range(2):
                t0 = time.perf_counter()
                res = [fmt.serialize_batch(batch) for _ in range(10)]
                dt = time.perf_counter() - t0
                if best is None or dt < best:
                    best, out = dt, res[0]
            return best, out
        finally:
            if prev is None:
                os.environ.pop("ARROYO_FAST_DECODE", None)
            else:
                os.environ["ARROYO_FAST_DECODE"] = prev

    rows_enc = 10 * len(batch)
    dt_legacy, enc_legacy = timed_encode("0")
    dt_fast, enc_fast = timed_encode("1")
    assert enc_fast == enc_legacy, "egress parity break: fast vs legacy"
    result["encode_legacy_rows_per_s"] = round(rows_enc / dt_legacy, 1)
    result["encode_fast_rows_per_s"] = round(rows_enc / dt_fast, 1)
    result["encode_fast_speedup"] = round(dt_legacy / dt_fast, 2)
    return result


def emit_decode():
    """Decode-family microbench: returned for embedding in the headline
    line (serde-layer rows/s, fast vs legacy)."""
    if os.environ.get("BENCH_DECODE", "1") in ("0", "false", "no"):
        return None
    try:
        d = run_decode_microbench()
    except Exception as e:  # the headline must still print
        print(f"decode bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(d), file=sys.stderr)
    return d


LAT_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '{rate}', num_events = '{n}',
  rate_limited = 'true', batch_size = '{b}', base_time_micros = '{base}'
);
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""


def run_latency() -> dict:
    """End-to-end p50/p99 latency (BASELINE.md headline): run the q5-shaped
    hop aggregate against a RATE-LIMITED source and measure, per emitted
    pane, sink arrival wallclock minus the moment the pane became
    computable (its window end + allowed lateness reaching the source).
    """
    import numpy as np

    from arroyo_tpu.connectors.memory import (
        clear_sink,
        sink_arrivals,
        sink_output,
    )
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import perf
    from arroyo_tpu.sql import plan_sql

    rate = float(os.environ.get("BENCH_LAT_RATE", 100_000))
    secs = float(os.environ.get("BENCH_LAT_SECS", 6))
    if secs <= 0:
        return {}
    lat_batch = min(BATCH, 8192)
    base = int(time.time() * 1e6)
    sql = LAT_SQL.format(rate=int(rate), n=int(rate * secs),
                         b=lat_batch, base=base)
    prog = plan_sql(sql)
    preflight_validate(prog, "latency_e2e_ms")
    # warm run of the same program: compiles must not pollute the
    # measured latency distribution (jit cache is keyed by program fns)
    clear_sink("results")
    LocalRunner(prog).run()
    perf.reset()
    clear_sink("results")
    LocalRunner(prog).run()
    outs = sink_output("results")
    arrivals = sink_arrivals("results")
    # latency per pane = sink arrival minus the wallclock at which the
    # source emitted the event that made the pane computable (the first
    # event advancing the watermark past window end + lateness), read from
    # the source's emission log — pipeline latency, not rate-schedule
    # error.  The watermark wait (lateness + batch granularity) is part of
    # the measured latency.
    #
    # the end-of-stream flush emits every still-open pane regardless of the
    # watermark — not steady-state latency.  The flush arrives in one burst
    # at the very end, so drop output batches arriving within 250ms of the
    # last arrival and keep only in-stream-fired panes.
    from arroyo_tpu.sql.schema_provider import nexmark_lateness_micros

    emit_log = perf.get_note("nexmark_emit_log") or []
    emit_ts = np.array([t for t, _ in emit_log], dtype=np.int64)
    emit_wall = np.array([w for _, w in emit_log])
    lateness = nexmark_lateness_micros(rate)
    last_arrival = max(arrivals) if arrivals else 0.0
    samples = []
    for b, arr in zip(outs, arrivals):
        if arr > last_arrival - 0.25 or not len(emit_ts):
            continue
        wend = np.asarray(b.columns["window_end"], dtype=np.int64)
        idx = np.searchsorted(emit_ts, wend + lateness)
        ok = idx < len(emit_wall)
        samples.extend((arr - emit_wall[idx[ok]]).tolist())
    samples = np.asarray(samples)
    samples = np.maximum(samples, 0.0)  # clip scheduler jitter
    if not len(samples):
        return {}
    return {
        "latency_p50_ms": round(float(np.percentile(samples, 50)) * 1e3, 1),
        "latency_p99_ms": round(float(np.percentile(samples, 99)) * 1e3, 1),
        "latency_rate_events_per_sec": int(rate),
    }


def run_latency_family() -> dict:
    """Latency-observatory family (obs/latency.py): the record-level
    sampled measurement, as opposed to run_latency's external
    pane-computable clock.

    Three parts: (a) sampling overhead — the SAME unthrottled hop
    aggregate timed with the observatory disarmed vs armed at 1-in-64,
    best-of-3 each (the <2% budget is the acceptance bar for keeping
    sampling on in production); (b) a latency-vs-throughput curve —
    the rate-limited pipeline at fractions of BENCH_LAT_RATE, p50/p99
    from the observatory's per-sink rolling windows at each point;
    (c) the critical-path attribution at the headline rate."""
    from arroyo_tpu.config import reset_config
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import latency
    from arroyo_tpu.sql import plan_sql

    sample_n = int(os.environ.get("BENCH_LAT_SAMPLE_N", 64))

    def timed(prog, armed: bool) -> float:
        latency.disarm()
        if armed:
            os.environ["ARROYO_LATENCY_SAMPLE_N"] = str(sample_n)
        else:
            os.environ.pop("ARROYO_LATENCY_SAMPLE_N", None)
        reset_config()
        clear_sink("results")
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        dt = time.perf_counter() - t0
        assert sum(len(b) for b in sink_output("results")) > 0
        return dt

    # (a) overhead: unthrottled, so the stamp hooks sit on the hottest
    # possible path; one program -> one jit cache for both arms
    n_ovh = int(os.environ.get("BENCH_LAT_OVH_EVENTS", 400_000))
    base = int(time.time() * 1e6)
    ovh_sql = LAT_SQL.format(rate=1_000_000, n=n_ovh, b=8192,
                             base=base).replace(
        "rate_limited = 'true'", "rate_limited = 'false'")
    prog = plan_sql(ovh_sql)
    timed(prog, armed=False)  # warm: compiles stay out of both arms
    off = min(timed(prog, armed=False) for _ in range(3))
    on = min(timed(prog, armed=True) for _ in range(3))
    overhead_pct = round((on - off) / off * 100.0, 2)
    out = {
        "sample_n": sample_n,
        "overhead": {
            "events": n_ovh,
            "off_secs": round(off, 4),
            "on_secs": round(on, 4),
            "latency_overhead_pct": overhead_pct,
            "budget_pct": 2.0,
            "within_budget": overhead_pct < 2.0,
        },
    }

    # (b) the latency-vs-throughput curve: sampled p50/p99 as the offered
    # rate rises toward the headline rate
    rate_hi = float(os.environ.get("BENCH_LAT_RATE", 100_000))
    secs = float(os.environ.get("BENCH_LAT_CURVE_SECS", 3))
    fracs = [float(f) for f in os.environ.get(
        "BENCH_LAT_CURVE", "0.25,0.5,1.0").split(",")]
    curve = []
    for frac in fracs:
        rate = max(int(rate_hi * frac), 1000)
        n = int(rate * secs)
        sql = LAT_SQL.format(rate=rate, n=n, b=min(BATCH, 8192),
                             base=int(time.time() * 1e6))
        cprog = plan_sql(sql)
        timed(cprog, armed=True)  # warm per-shape compiles
        dt = timed(cprog, armed=True)
        lat = latency.active()
        sinks = lat.sink_quantiles() if lat is not None else {}
        q = next(iter(sinks.values()), {})
        curve.append({
            "rate_events_per_sec": rate,
            "achieved_events_per_sec": round(n / dt, 1),
            "p50_ms": q.get("p50_ms"),
            "p99_ms": q.get("p99_ms"),
            "samples": int(q.get("count", 0)),
        })
    out["curve"] = curve
    if curve:
        out["p50_ms"] = curve[-1]["p50_ms"]
        out["p99_ms"] = curve[-1]["p99_ms"]

    # (c) where the time went at the headline rate
    lat = latency.active()
    if lat is not None:
        cp = lat.critical_path()
        out["critical_path"] = {"dominant": cp["dominant"],
                                "dominant_share": cp["dominant_share"]}
    latency.disarm()
    os.environ.pop("ARROYO_LATENCY_SAMPLE_N", None)
    reset_config()
    return out


def emit_latency_family():
    """Latency family: returned for embedding in the headline line
    (sampled p50/p99 + rate curve + sampling-overhead budget)."""
    if os.environ.get("BENCH_LATENCY", "1") in ("0", "false", "no"):
        return None
    try:
        lf = run_latency_family()
    except Exception as e:  # the headline must still print
        print(f"latency bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(lf), file=sys.stderr)
    return lf


CONFIG5_SQL = """
CREATE TABLE ev (
  k BIGINT, v DOUBLE, ts BIGINT,
  event_time TIMESTAMP GENERATED ALWAYS AS
    (CAST(from_unixtime(ts) as TIMESTAMP))
) WITH (
  connector = 'kafka', bootstrap_servers = 'memory://bench5',
  topic = 'sess', type = 'source', format = 'json',
  event_time_field = 'event_time', batch_size = '{b}',
  max_messages = '{n}'
);
CREATE TABLE out WITH (connector = 'memory', name = 'results');
INSERT INTO out
SELECT k, median(v) as med, count(*) as cnt,
       session(INTERVAL '1' SECOND) as window
FROM ev GROUP BY 1, 4
"""


def _config5_produce(broker_name: str, n: int, t0_micros: int,
                     spacing_micros: int) -> None:
    """Fill the in-process kafka topic with n bursty-keyed JSON events:
    64 keys are active per block of 6400 events, then retire — so 1s-gap
    sessions continuously close as event time advances."""
    import json as _json

    import numpy as np

    from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker

    InMemoryKafkaBroker.reset(broker_name)
    broker = InMemoryKafkaBroker.get(broker_name)
    broker.create_topic("sess", partitions=1)
    P, burst = 64, 100
    i = np.arange(n, dtype=np.int64)
    keys = (i % P) + (i // (P * burst)) * P
    ts = t0_micros + i * spacing_micros
    vals = (i % 997).astype(np.float64) / 7.0
    for j in range(n):
        broker.produce("sess", _json.dumps(
            {"k": int(keys[j]), "v": float(vals[j]),
             "ts": int(ts[j]) * 1000}).encode(), partition=0)


def _session_stats(before: dict, n_events: int) -> dict:
    """Session-state counter deltas since ``before`` + the last state
    registry snapshot.  ``state_bounded`` asserts live session rows
    track the ACTIVE key horizon (64 keys/burst block, a handful of
    open sessions each), not the stream length — the contract the
    expire mask-compression exists to keep."""
    from arroyo_tpu.obs import perf
    from arroyo_tpu.state.session_state import aggregate_session_registry

    out = {k: perf.counter(k) - before[k] for k in SESSION_COUNTERS}
    total_merge = out["session_device_merge_rows"] + \
        out["session_host_merge_rows"]
    out["device_merge_share"] = round(
        out["session_device_merge_rows"] / total_merge, 4) \
        if total_merge else None
    reg = aggregate_session_registry(
        perf.get_note("session_state_registry"))
    if reg:
        out["state"] = reg
        out["state_bounded"] = reg["rows"] < 4096 and \
            reg["rows"] < max(n_events // 8, 1024)
    return out


def run_config5() -> dict:
    """BASELINE.md config #5: session-window aggregation with a UDAF
    (median) over the Kafka source with 1s periodic checkpointing ON.
    Throughput over a pre-filled topic; p50/p99 end-to-end latency from
    a separate rate-limited run where event time == scheduled produce
    wall time."""
    import tempfile

    import numpy as np

    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import SchemaProvider, plan_sql

    n = int(os.environ.get("BENCH_C5_EVENTS", 200_000))
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    sql = CONFIG5_SQL.format(b=4096, n=n)
    ckpt = tempfile.mkdtemp(prefix="bench5-ckpt-")

    # ONE program for warmup and timed runs: the jit cache is keyed by
    # the program's expression fns, so re-planning would put recompiles
    # inside the timed run (same discipline as run_query).  The warmup
    # topic holds fewer events than max_messages, so the warm run drains
    # it and exits via the idle-spin bound — a bounded one-time cost.
    # The single-partition topic caps SOURCE parallelism at 1; the keyed
    # session/aggregate stages still fan out.
    prog = plan_sql(sql, p, parallelism=bench_parallelism())
    preflight_validate(prog, "baseline5_session_udaf_kafka_events_per_sec")

    def timed_run():
        clear_sink("results")
        t0 = time.perf_counter()
        LocalRunner(prog, checkpoint_url=f"file://{ckpt}").run(
            checkpoint_interval_secs=1.0)
        dt = time.perf_counter() - t0
        outs = sink_output("results")
        n_out = sum(len(b) for b in outs)
        assert n_out > 0, "config5 produced no sessions"
        return dt, n_out

    # full-size warmup: a truncated topic under-warms — the end-of-run
    # flush aggregates every closed session in ONE segment dispatch, so
    # its padded-bucket shape scales with n and a smaller warmup leaves
    # that compile INSIDE the timed run (profiled at ~12% of wall)
    _config5_produce("bench5", n, 0, 10)
    clear_sink("results")
    LocalRunner(prog).run()
    _config5_produce("bench5", n, 0, 10)
    from arroyo_tpu.obs import perf

    before = {k: perf.counter(k) for k in SESSION_COUNTERS}
    perf.note("session_state_registry", {})
    dt, n_out = timed_run()
    result = {
        "metric": "baseline5_session_udaf_kafka_events_per_sec",
        "value": round(n / dt, 1),
        "unit": "events/sec",
        "sessions_emitted": n_out,
        "checkpoint_interval_secs": 1.0,
        # session-state shape of the timed run: merge dispatches + the
        # device/host row split the PR 19 state layout exists to move,
        # plus the hot-partition/staging snapshot and the bounded-state
        # verdict (state/session_state.py)
        "sessions": _session_stats(before, n),
    }

    # latency: produce in real time at a fixed rate; event time equals the
    # scheduled produce wall time, so a session row's computable moment is
    # wall_base + (window_end + lateness - t0) / 1e6
    # well below the config's drain capacity (~460k/s after the r4 merge
    # vectorization): latency at saturation is queueing delay, not
    # pipeline latency
    rate = float(os.environ.get("BENCH_C5_LAT_RATE", 50_000))
    secs = float(os.environ.get("BENCH_C5_LAT_SECS", 5))
    n_lat = int(rate * secs)
    # warm the latency program too (batch_size differs -> own compiles)
    lat_prog = plan_sql(CONFIG5_SQL.format(b=512, n=n_lat), p)
    _config5_produce("bench5", 4_000, 0, 10)
    clear_sink("results")
    LocalRunner(lat_prog).run()
    lat = _config5_latency(lat_prog, rate, n_lat,
                           checkpoint_url=f"file://{ckpt}")
    if lat:
        result["latency_p50_ms"] = lat["p50_ms"]
        result["latency_p99_ms"] = lat["p99_ms"]
        result["latency_rate_events_per_sec"] = lat["rate_events_per_sec"]
        # grouped view for the driver artifact, same shape as the q5
        # headline's latency object (flat keys stay for continuity)
        result["latency"] = lat
    return result


def _config5_latency(lat_prog, rate: float, n_lat: int,
                     checkpoint_url=None):
    """Rate-limited real-time latency run over the config5 topic with an
    already-warmed program: event time == scheduled produce wall time,
    so a session row's computable moment is wall_base + (window_end +
    lateness - t0) / 1e6.  Returns {p50_ms, p99_ms,
    rate_events_per_sec} or None when no steady-state samples landed."""
    import threading

    import numpy as np

    from arroyo_tpu.connectors.kafka import InMemoryKafkaBroker
    from arroyo_tpu.connectors.memory import (
        clear_sink,
        sink_arrivals,
        sink_output,
    )
    from arroyo_tpu.engine.engine import LocalRunner

    InMemoryKafkaBroker.reset("bench5")
    broker = InMemoryKafkaBroker.get("bench5")
    broker.create_topic("sess", partitions=1)
    # time.monotonic throughout: sink_arrivals records monotonic, so the
    # computable-moment math must live on the same clock
    wall_base = time.monotonic()
    t0_micros = int(time.time() * 1e6)

    def producer():
        import json as _json

        P, burst = 64, 100
        # chunked pacing: one wakeup per ~8ms burst — a per-message pace
        # at this rate would busy-spin and starve the engine of the GIL
        chunk = max(int(rate * 0.008), 1)
        for c0 in range(0, n_lat, chunk):
            target = wall_base + c0 / rate
            lag = target - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            for i in range(c0, min(c0 + chunk, n_lat)):
                ts = t0_micros + int(i / rate * 1e6)
                broker.produce("sess", _json.dumps(
                    {"k": (i % P) + (i // (P * burst)) * P,
                     "v": float(i % 997) / 7.0, "ts": ts * 1000}).encode(),
                    partition=0)

    th = threading.Thread(target=producer, daemon=True)
    clear_sink("results")
    th.start()
    runner = (LocalRunner(lat_prog, checkpoint_url=checkpoint_url)
              if checkpoint_url else LocalRunner(lat_prog))
    if checkpoint_url:
        runner.run(checkpoint_interval_secs=1.0)
    else:
        runner.run()
    th.join()
    outs = sink_output("results")
    arrivals = sink_arrivals("results")
    lateness = 1_000_000  # DDL-table default (TableDef dataclass default)
    last_arrival = max(arrivals) if arrivals else 0.0
    samples = []
    for b, arr in zip(outs, arrivals):
        if arr > last_arrival - 0.25:
            continue  # end-of-stream flush burst, not steady state
        wend = np.asarray(b.columns["window_end"], dtype=np.int64)
        computable = wall_base + (wend + lateness - t0_micros) / 1e6
        samples.extend(np.maximum(arr - computable, 0.0).tolist())
    if not samples:
        return None
    s = np.asarray(samples)
    return {"p50_ms": round(float(np.percentile(s, 50)) * 1e3, 1),
            "p99_ms": round(float(np.percentile(s, 99)) * 1e3, 1),
            "rate_events_per_sec": int(rate)}


def run_sessions_family() -> dict:
    """The ``sessions`` family: the config5 shape swept over the PR 19
    knob matrix — session state {device sorted-runs, legacy per-key
    dicts} x UDAF execution {vectorized channels, per-segment host
    loop} — so the artifact shows WHERE the config5 speedup comes from
    and that both axes produce identical rows.

    Each combo records events/s, the session-merge dispatch counts and
    device/host row split, the hot-partition/spill snapshot, and the
    bounded-state verdict; the two session-state modes additionally
    carry a short rate-limited latency block.  Before each timed run a
    small SANITIZED run cross-checks row parity: every combo must hash
    to the same sorted row digest."""
    import hashlib

    import numpy as np

    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import perf
    from arroyo_tpu.sql import SchemaProvider, plan_sql

    n = int(os.environ.get("BENCH_SESS_EVENTS", 120_000))
    p = SchemaProvider()
    p.register_udaf("median", np.median)
    prog = plan_sql(CONFIG5_SQL.format(b=4096, n=n), p,
                    parallelism=bench_parallelism())
    lat_rate = float(os.environ.get("BENCH_SESS_LAT_RATE", 30_000))
    lat_secs = float(os.environ.get("BENCH_SESS_LAT_SECS", 2))
    n_lat = int(lat_rate * lat_secs)
    lat_prog = plan_sql(CONFIG5_SQL.format(b=512, n=n_lat), p)

    knobs = ("ARROYO_SESSION_STATE", "ARROYO_UDAF_CHANNELS",
             "ARROYO_SANITIZE")
    saved = {k: os.environ.get(k) for k in knobs}

    def digest_rows():
        outs = sink_output("results")
        rows = []
        for b in outs:
            names = sorted(b.columns)
            for i in range(len(b)):
                rows.append(tuple(
                    round(float(b.columns[c][i]), 6) for c in names))
        return hashlib.sha256(repr(sorted(rows)).encode()).hexdigest()[:16]

    family: dict = {"events": n}
    digests = {}
    try:
        for state in ("device", "legacy"):
            for chan in ("on", "off"):
                combo = f"{state}_{'channels' if chan == 'on' else 'host'}"
                os.environ["ARROYO_SESSION_STATE"] = state
                os.environ["ARROYO_UDAF_CHANNELS"] = chan
                # parity probe: small run with the runtime sanitizer
                # armed; doubles as the per-combo warmup
                os.environ["ARROYO_SANITIZE"] = "1"
                _config5_produce("bench5", 6_000, 0, 10)
                clear_sink("results")
                LocalRunner(prog).run()
                digests[combo] = digest_rows()
                os.environ["ARROYO_SANITIZE"] = "0"
                _config5_produce("bench5", n, 0, 10)
                clear_sink("results")
                before = {k: perf.counter(k) for k in SESSION_COUNTERS}
                perf.note("session_state_registry", {})
                t0 = time.perf_counter()
                LocalRunner(prog).run()
                dt = time.perf_counter() - t0
                n_out = sum(len(b) for b in sink_output("results"))
                assert n_out > 0, f"sessions family {combo}: no output"
                entry = {
                    "events_per_sec": round(n / dt, 1),
                    "sessions_emitted": n_out,
                    "sessions": _session_stats(before, n),
                }
                if chan == "on":
                    lat = _config5_latency(lat_prog, lat_rate, n_lat)
                    if lat:
                        entry["latency"] = lat
                family[combo] = entry
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    family["parity_ok"] = len(set(digests.values())) == 1
    family["row_digests"] = digests
    dev = family.get("device_channels", {}).get("events_per_sec", 0)
    leg = family.get("legacy_host", {}).get("events_per_sec", 0)
    if leg:
        family["speedup_vs_legacy_host"] = round(dev / leg, 2)
    return family


def emit_sessions_family():
    """Sessions family: returned for embedding in the headline line."""
    if os.environ.get("BENCH_SESSIONS", "1") in ("0", "false", "no"):
        return None
    try:
        sf = run_sessions_family()
    except Exception as e:  # the headline must still print
        print(f"sessions bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps({"sessions_family": sf}), file=sys.stderr)
    return sf


# -- kernel-level accelerator microbench ------------------------------------
#
# The full Nexmark pipeline needs a stable accelerator for minutes; the
# tunnel often can't provide that.  This microbench is the falsifiable
# fallback: it exercises exactly the device hot path the engine uses —
# the keyed-bin update kernel (one packed host->device transfer per step,
# scatter-add into resident state), the pane-emission gather/reduce, the
# Pallas scatter path where supported, plus raw transfer bandwidth and
# dispatch latency — and completes in seconds, so a flaky tunnel can
# still yield a device datapoint.


def run_kernel_microbench() -> dict:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from arroyo_tpu.ops import keyed_bins as kb

    backend = jax.default_backend()
    dev = jax.devices()[0]
    out = {"backend": backend, "device": str(dev),
           "jax": jax.__version__, "numpy": np.__version__}

    def timeit(fn, warmup=3, iters=20):
        for _ in range(warmup):
            fn()
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters

    # dispatch latency: tiny jitted op round-trip
    one = jax.device_put(jnp.float32(1.0), dev)
    f = jax.jit(lambda x: x + 1)
    jax.block_until_ready(f(one))
    out["dispatch_ms"] = round(
        timeit(lambda: jax.block_until_ready(f(one)), iters=50) * 1e3, 3)

    # host->device transfer bandwidth (8 MB f32, the engine's batch scale)
    buf = np.random.default_rng(0).standard_normal(
        (2 * 1024 * 1024,)).astype(np.float32)
    dt = timeit(lambda: jax.block_until_ready(jax.device_put(buf, dev)),
                warmup=2, iters=10)
    out["h2d_MBps"] = round(buf.nbytes / dt / 1e6, 1)

    # device->host: both directions matter and the tunnel is asymmetric
    # (measured ~70 ms FIXED latency per readback vs 1.5 GB/s h2d) —
    # per-transfer latency (tiny array) and bandwidth (8 MB) separately.
    # jax Arrays cache their host copy after the first np.asarray, so
    # each readback goes through a fresh jitted no-op result.
    bump = jax.jit(lambda x: x + 1)
    buf_d = jax.block_until_ready(jax.device_put(buf, dev))
    tiny_d = jax.block_until_ready(jax.device_put(
        np.zeros(16, np.float32), dev))
    jax.block_until_ready(bump(tiny_d))
    out["d2h_lat_ms"] = round(
        timeit(lambda: np.asarray(bump(tiny_d)), warmup=2, iters=10)
        * 1e3, 3)
    jax.block_until_ready(bump(buf_d))
    dt = timeit(lambda: np.asarray(bump(buf_d)), warmup=2, iters=10)
    out["d2h_MBps"] = round(buf.nbytes / dt / 1e6, 1)

    # update kernel: the q5-shaped hot loop.  C keys x B bins resident
    # state, n pre-aggregated (key,bin) cells per step, one i32[2, n]
    # index + one f64[k+1, n] value transfer per step — exactly
    # KeyedBinState.update's device path (keyed_bins.py:61-95), with
    # i32 counts state as the engine holds it.
    kinds = ("count", "sum", "max")
    C, B, n = 8192, 16, 16384
    kern = kb._update_kernel(kinds, C, B, n)
    values = jax.device_put(jnp.stack(
        [jnp.full((C, B), kb._init_value(kb.AggKind(k)), jnp.float64)
         for k in kinds]), dev)
    counts = jax.device_put(jnp.zeros((C, B), jnp.int32), dev)
    rng = np.random.default_rng(1)
    idx_np = np.empty((2, n), np.int32)
    idx_np[0] = rng.integers(0, C, n)
    idx_np[1] = rng.integers(0, B, n)
    packed_np = np.empty((1 + len(kinds), n), np.float64)
    packed_np[0] = 1.0
    packed_np[1:] = rng.standard_normal((len(kinds), n))

    state = [values, counts]

    def step():
        # two transfers per step (indices stay i32, values exact f64)
        idx = jax.device_put(idx_np, dev)
        packed = jax.device_put(packed_np, dev)
        v, c = kern(state[0], state[1], idx, packed)
        state[0], state[1] = v, c
        jax.block_until_ready(c)

    dt = timeit(step, warmup=3, iters=20)
    out["update_step_ms"] = round(dt * 1e3, 3)
    out["update_cells_per_sec"] = round(n / dt, 1)

    # emit kernel: k panes gathered from the ring and reduced per key
    k = 64
    W = 5
    ring = np.tile(np.arange(W, dtype=np.int32), (k, 1))
    bin_ok = np.ones((k, W), dtype=bool)
    ek = kb._emit_kernel(kinds, C, B, W, k)
    ring_d = jax.device_put(ring, dev)
    ok_d = jax.device_put(bin_ok, dev)

    def estep():
        r, cnt = ek(state[0], state[1], ring_d, ok_d)
        jax.block_until_ready(cnt)

    dt = timeit(estep, warmup=3, iters=20)
    out["emit_step_ms"] = round(dt * 1e3, 3)
    out["emit_key_panes_per_sec"] = round(C * k / dt, 1)

    # join kernels: sort/probe/expand on device (ops/join.py — the q8
    # windowed-join hot path).  Two numbers: the device kernels alone
    # (state in, indices computed, one block — what a resident-state
    # engine pays), and the full join_pairs including result readback
    # (what the host-materializing engine pays; on the tunnel the ~70 ms
    # fixed per-readback latency dominates it — see d2h_lat_ms).
    from arroyo_tpu.ops import join as dj

    os.environ["ARROYO_DEVICE_JOIN"] = "on"
    nl = nr = 8192
    jrng = np.random.default_rng(2)
    lk = jrng.integers(0, 4096, nl).astype(np.uint64)
    rk = jrng.integers(0, 4096, nr).astype(np.uint64)
    nlp, nrp = dj._bucket(nl), dj._bucket(nr)
    lk_p = np.full(nlp, dj.SENTINEL, np.uint64)
    lk_p[:nl] = lk
    rk_p = np.full(nrp, dj.SENTINEL, np.uint64)
    rk_p[:nr] = rk
    sk, pk = dj._sort_kernel(nlp), dj._probe_kernel(nlp, nrp, True)
    _, lks_d = sk(lk_p)
    _, rks_d = sk(rk_p)
    _, counts_d, cum_d = pk(lks_d, rks_d, nl, nr)
    m = dj._bucket(int(np.asarray(counts_d)[:nl].sum()))
    ek = dj._expand_kernel(nlp, m)

    def jkernels():
        lo_d, lks = sk(lk_p)
        ro_d, rks = sk(rk_p)
        start_d, cnt_d, cm_d = pk(lks, rks, nl, nr)
        jax.block_until_ready(ek(start_d, cm_d))

    dt = timeit(jkernels, warmup=3, iters=20)
    out["join_kernels_ms"] = round(dt * 1e3, 3)
    out["join_kernel_rows_per_sec"] = round((nl + nr) / dt, 1)

    def jstep():
        dj.join_pairs(lk, rk)

    dt = timeit(jstep, warmup=3, iters=10)
    out["join_step_ms"] = round(dt * 1e3, 3)
    out["join_rows_per_sec"] = round((nl + nr) / dt, 1)

    # resident-ring probe + payload materialization (PR 15): the
    # pre-PR-15 hot path — emulated-u64 ring probe, pair readback, host
    # fancy-index payload gather — vs the split-hash i32 ring with the
    # fused expand+verify+gather dispatch.  On an accelerator the new
    # path must win >= 5x (the u64 compares are emulated there and the
    # per-match readback pays d2h_lat_ms); on CPU the pair of numbers
    # still records and ``ring_probe_parity`` carries the gate.
    ns = nq = 16384
    from arroyo_tpu.types import hash_u64

    srng = np.random.default_rng(3)
    # realistic keys: full-entropy u64 hashes of an 8k id space (~2
    # state rows per key), exactly what key_by feeds the join state —
    # the split-hash layout relies on top-32 entropy, which real
    # key_hash columns always have
    skeys = np.sort(hash_u64(srng.integers(0, 8192, ns)))
    sts = srng.integers(0, 1 << 40, ns)
    scols = {"v0": srng.standard_normal(ns),
             "v1": srng.integers(0, 1 << 50, ns),
             "v2": srng.standard_normal(ns),
             "v3": srng.integers(0, 1 << 30, ns)}
    qk = np.sort(hash_u64(srng.integers(0, 8192, nq)))
    cap = dj._bucket(ns)
    mq = dj._bucket(nq)
    # baseline ring: u64 keys, probe kernels on u64, gather on host
    ring64 = np.full(cap, dj.SENTINEL, np.uint64)
    ring64[:ns] = skeys
    ring64_d = jax.device_put(ring64, dev)
    qp = np.full(mq, dj.SENTINEL, np.uint64)
    qp[:nq] = qk
    pk64 = dj._probe_kernel(mq, cap, dj._merged_probe())
    start0, counts0, _ = pk64(qp, ring64_d, nq, ns)
    total = int(np.asarray(counts0)[:nq].sum())
    mb = dj._bucket(total)
    ex64 = dj._expand_kernel(mq, mb)

    def u64_host():
        start_d, cnt_d, cum_d = pk64(qp, ring64_d, nq, ns)
        lidx_d, ridx_d = ex64(start_d, cum_d)
        lidx = np.asarray(lidx_d)[:total]
        ridx = np.asarray(ridx_d)[:total]
        rows = {c: v[ridx] for c, v in scols.items()}
        rows["ts"] = sts[ridx]
        return lidx, ridx, rows

    dt = timeit(u64_host, warmup=3, iters=10)
    out["ring_probe_u64_host_ms"] = round(dt * 1e3, 3)

    # payload planes engage because sorted_cols is passed explicitly —
    # the ARROYO_JOIN_PAYLOAD_DEVICE knob gates the buffer layer, not
    # these kernel-level calls
    ring = dj.stage_ring(skeys, device=dev, sorted_ts=sts,
                         sorted_cols=scols)

    def split_fused():
        hit = dj.probe_ring(ring, qk, ns)
        t = int(hit.counts.sum())
        lidx, ridx, valid, gf, gi = dj.expand_gather(ring, hit, t)
        ts2, cols2 = dj.unpack_payload(ring, gf, gi)
        return lidx, ridx, valid, ts2, cols2

    dt2 = timeit(split_fused, warmup=3, iters=10)
    out["ring_probe_split_fused_ms"] = round(dt2 * 1e3, 3)
    out["ring_probe_rows"] = total
    out["ring_probe_speedup"] = round(dt / dt2, 2)
    # parity: the fused path must emit exactly the baseline's pairs and
    # payload bytes (the verify plane may only kill non-matches; this
    # fixture has none by construction of the exact u64 baseline probe)
    bl, br, brows = u64_host()
    fl, fr, fvalid, fts, fcols = split_fused()
    out["ring_probe_parity"] = bool(
        fvalid.all() and (bl == fl).all() and (br == fr).all()
        and (brows["ts"] == fts).all()
        and all((brows[c] == fcols[c]).all() for c in scols))

    # ring-pane emission kernel (long-window bin-sharded sweep): on a
    # single chip the mesh degenerates to 1 shard but the kernel (cumsum
    # sweep + halo plumbing) is the one the engine runs at W>=64
    try:
        from arroyo_tpu.parallel.ring_panes import _ring_step_2d

        Cr, Lr, Wr = 1024, 512, 300
        rfn, rsharding = _ring_step_2d("sum", 1, Cr, Lr, Wr)
        rbins = jax.device_put(
            jnp.asarray(rng.standard_normal((Cr, Lr)), jnp.float64),
            rsharding)

        def rstep():
            jax.block_until_ready(rfn(rbins))

        dt = timeit(rstep, warmup=3, iters=20)
        out["ring_step_ms"] = round(dt * 1e3, 3)
        out["ring_key_bins_per_sec"] = round(Cr * Lr / dt, 1)
    except Exception as e:
        out["ring_error"] = f"{type(e).__name__}: {e}"[:300]

    # pallas path: the engine's fused custom-kernel state update
    # (pallas_kernels.update_bin_state — x32 scatter + f64 apply).
    # Engine default is OFF per this very comparison (pallas_enabled);
    # the microbench force-enables it so the artifact keeps recording
    # both paths side by side.
    prev_pallas = os.environ.get("ARROYO_PALLAS")
    try:
        os.environ["ARROYO_PALLAS"] = "1"
        from arroyo_tpu.ops import pallas_kernels as pk

        if pk.pallas_enabled():
            slots = idx_np[0]
            bins = idx_np[1]
            weights = packed_np.astype(np.float32)

            def pstep():
                v, c = pk.update_bin_state(
                    state[0], state[1], slots, bins, weights, C, B)
                state[0], state[1] = v, c
                jax.block_until_ready(c)

            dt = timeit(pstep, warmup=3, iters=20)
            out["pallas_update_step_ms"] = round(dt * 1e3, 3)
            out["pallas_update_cells_per_sec"] = round(n / dt, 1)
        else:
            out["pallas"] = "disabled"
    except Exception as e:  # pallas failure must not kill the microbench
        out["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
    finally:
        if prev_pallas is None:
            os.environ.pop("ARROYO_PALLAS", None)
        else:
            os.environ["ARROYO_PALLAS"] = prev_pallas
    return out


def run_join_stress() -> dict:
    """Join-stress family: a skewed (Zipf-ish) keyed two-stream INNER
    join with LONG event-time TTL — the shape where the legacy flat join
    buffers collapsed (every arriving batch re-sorted the whole opposite
    buffer; every watermark re-materialized both sides).  Records
    events/s, the merge-vs-resort dispatch split, the hot/spill state
    shape, and whether state stayed bounded (valid-range eviction must
    hold resident rows near 2 * TTL_rate, not grow with the stream)."""
    import numpy as np

    from arroyo_tpu import Stream
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import perf
    from arroyo_tpu.types import hash_u64

    n = int(os.environ.get("BENCH_JOIN_STRESS_EVENTS", 400_000))
    ttl = 30_000_000  # 30s event time; 1ms/event -> ~30k live rows/side
    base = 1_700_000_000_000_000

    def zipf_map(side: int):
        def fn(cols):
            c = np.asarray(cols["counter"], dtype=np.int64)
            if side == 1:
                # probe side: uniform keys, so output stays ~linear while
                # the skewed build side's hot partitions carry the stress
                key = (hash_u64(c + 7_919) % np.uint64(100_000)).astype(
                    np.int64)
            else:
                u = (hash_u64(c) >> np.uint64(11)).astype(
                    np.float64) / float(1 << 53)
                u = np.maximum(u, 1e-12)
                # Zipf(s~1) ranks over 100k keys: the head keys take a
                # constant fraction of rows — the PanJoin skew scenario
                key = np.exp(u * np.log(100_000.0)).astype(np.int64)
            return {"k": key, f"v{side}": c}

        return fn

    def build():
        left = (Stream.source("impulse", {
            "event_rate": 1e9, "message_count": n,
            "event_time_interval_micros": 1000,
            "base_time_micros": base, "batch_size": 8192})
            .watermark(max_lateness_micros=0)
            .udf(zipf_map(0), name="zl").key_by("k"))
        right = (Stream.source("impulse", {
            "event_rate": 1e9, "message_count": n,
            "event_time_interval_micros": 1000,
            "base_time_micros": base, "batch_size": 8192},
            program=left.program)
            .watermark(max_lateness_micros=0)
            .udf(zipf_map(1), name="zr").key_by("k"))
        return left.join_with_expiration(
            right, ttl, ttl, name="stress_join").sink(
            "memory", {"name": "join_stress"})

    from arroyo_tpu.state.join_state import aggregate_stats_registry

    prog = build()
    clear_sink("join_stress")
    LocalRunner(prog).run()  # warm (compiles, allocator)
    before = {k: perf.counter(k) for k in JOIN_STATE_COUNTERS}
    clear_sink("join_stress")
    perf.note("join_state_registry", {})  # this run's buffers only
    t0 = time.perf_counter()
    LocalRunner(build()).run()
    dt = time.perf_counter() - t0
    out_rows = sum(len(b) for b in sink_output("join_stress"))
    stats = {k.replace("join_state_", ""):
             perf.counter(k) - before[k] for k in JOIN_STATE_COUNTERS}
    snap = aggregate_stats_registry(perf.get_note("join_state_registry"))
    stats.update(_gather_share(stats))
    live_rows = snap.get("rows")
    # payload rings are pow2(partition rows incl. the <= 2x dead-row
    # estimate lag), so their summed capacity must ALSO track the TTL
    # horizon: a ring that regrows without demoting/compacting (a
    # payload-plane leak) blows this bound long before host state does
    ring_cap = snap.get("ring_cap_rows", 0)
    return {
        "metric": "join_stress_events_per_sec",
        "value": round(2 * n / dt, 1), "unit": "events/sec",
        "events": 2 * n, "output_rows": out_rows,
        "ttl_micros": ttl,
        "join_state": {**stats, **snap},
        # bounded-state check: resident rows (both sides summed, with
        # the dead-estimate's up-to-8-eviction lag) must track the TTL
        # horizon (~ttl/interval per side), not the stream length —
        # and so must the device payload-ring capacity
        "state_bounded": (live_rows is not None
                          and live_rows < 6 * (ttl // 1000)
                          and ring_cap < 12 * (ttl // 1000)),
    }


def run_autoscale_bench() -> dict:
    """``--autoscale`` mode: elasticity, not steady state.  Run an
    impulse flood through a real controller with the autoscaler enabled
    on the bottleneck aggregate and record (a) the decision timeline and
    (b) throughput-vs-parallelism samples, so BENCH_* artifacts show how
    the system tracks load, not just its peak."""
    import asyncio

    from arroyo_tpu import AggKind, AggSpec, Stream
    from arroyo_tpu.autoscale import BacklogDrainPolicy, PolicyConfig
    from arroyo_tpu.controller.controller import ControllerServer
    from arroyo_tpu.controller.scheduler import InProcessScheduler
    from arroyo_tpu.controller.state_machine import JobState

    n = int(os.environ.get("BENCH_AUTOSCALE_EVENTS", 400_000))
    rate = float(os.environ.get("BENCH_AUTOSCALE_RATE", 30_000.0))
    os.environ.setdefault("HEARTBEAT_INTERVAL_SECS", "0.2")
    # the explicit --autoscale flag wins over an ambient escape hatch:
    # without this, ARROYO_AUTOSCALE=0 in the environment would crash
    # the elasticity benchmark instead of measuring it
    os.environ["ARROYO_AUTOSCALE"] = "1"
    import arroyo_tpu.config as _cfg

    _cfg.reset_config()

    out_path = os.path.join(tempfile.mkdtemp(prefix="arroyo_as_"),
                            "out.jsonl")

    async def scenario():
        from arroyo_tpu.types import now_micros

        ctrl = ControllerServer(InProcessScheduler())
        await ctrl.start()
        prog = (
            # backlog replay: event times start 10 minutes behind the
            # wall clock, so the watermark-lag signal drives catch-up
            # provisioning while the rate limit keeps the run long
            # enough to capture a decision timeline
            Stream.source("impulse", {"event_rate": rate,
                                      "message_count": n,
                                      "event_time_interval_micros": 1000,
                                      "base_time_micros":
                                          now_micros() - 600_000_000,
                                      "batch_size": 256}, parallelism=1)
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 8}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(
                500 * 1000, [AggSpec(AggKind.COUNT, None, "cnt")],
                parallelism=1)
            .sink("single_file", {"path": out_path}, parallelism=1)
        )
        agg_id = next(node.operator_id for node in prog.nodes()
                      if "aggregator" in node.operator_id)
        t0 = time.perf_counter()
        job_id = await ctrl.submit_job(prog, n_workers=1)
        points = []
        try:
            scaler = ctrl.autoscalers[job_id]
            scaler.policy = BacklogDrainPolicy(PolicyConfig(
                interval_secs=0.3, high_water=0.3, up_sustain=1,
                lag_warn_secs=0.5, lag_high_secs=5.0,
                up_cooldown_secs=8.0, down_cooldown_secs=600.0,
                max_parallelism=1,
                per_op={agg_id: {"min": 1, "max": 4}}))
            scaler.set_enabled(True)
            while not ctrl.jobs[job_id].fsm.state.terminal:
                await asyncio.sleep(0.5)
                roll = {r["operator_id"]: r
                        for r in ctrl.job_rollup(job_id)}
                par = {node.operator_id: node.parallelism
                       for node in prog.nodes()}
                # mid-rescale the rollup can omit the aggregate (workers
                # restarting): record null, never a substituted total
                agg_rate = roll.get(agg_id, {}).get("records_per_sec")
                points.append({
                    "t": round(time.perf_counter() - t0, 2),
                    "parallelism": par[agg_id],
                    "total_parallelism": sum(par.values()),
                    "records_per_sec": (None if agg_rate is None
                                        else round(agg_rate, 1)),
                    "backpressure": roll.get(agg_id, {}).get(
                        "backpressure"),
                })
            state = await ctrl.wait_for_state(job_id, JobState.FINISHED,
                                              timeout=10)
            dt = time.perf_counter() - t0
            timeline = [d.to_json() for d in scaler.ledger.decisions()
                        if d.action != "hold"]
            return {
                "state": state.value, "wall_secs": round(dt, 2),
                "events": n,
                "events_per_sec": round(n / dt, 1),
                "final_parallelism": prog.node(agg_id).parallelism,
                "actuations": scaler.ledger.actuations,
                "vetoes": scaler.ledger.vetoes,
                "decision_timeline": timeline[-64:],
                "throughput_vs_parallelism": points,
            }
        finally:
            await ctrl.scheduler.stop_workers(job_id)
            await ctrl.stop()

    result = asyncio.run(scenario())
    with open(out_path) as f:
        produced = sum(json.loads(line)["cnt"] for line in f)
    result["output_events"] = produced
    result["exactly_once"] = produced == n
    return {"metric": "autoscale_elasticity", "unit": "decisions",
            "value": result["actuations"], "autoscale": result}


def run_correlated_windows() -> dict:
    """Correlated-windows family (factor-window sharing,
    graph/factor_windows.py): K in {2, 4, 8} sliding aggregates over
    the SAME input/keys with distinct widths (shared 2s slide), each K
    measured with factoring on (ARROYO_FACTOR_WINDOWS=auto) and off
    (=0).  Records events/s, pane-update kernel-dispatch counts per
    event, and the factor decision (shared_panes / derived_windows /
    cost_model_decision) per point.  The claim under test: factored
    per-event cost grows ~O(panes) — the shared ring pays ONE update
    per batch regardless of K — while unfactored cost grows ~O(K)
    (K private rings, K scatters).  ``cost_o_panes_ok`` asserts the
    factored dispatch growth from K=2 to K=8 stays well below the
    unfactored growth."""
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs import perf
    from arroyo_tpu.sql import plan_sql

    n = int(os.environ.get("BENCH_CORRELATED_EVENTS", 300_000))
    widths = [10, 4, 20, 6, 16, 8, 30, 14]  # seconds; slide 2s for all

    def sql_for(k: int) -> str:
        # 8k batches (not the headline 128k): pane firing must happen
        # continuously mid-stream, or the whole family degenerates to
        # one final flush and measures nothing about steady-state cost
        parts = [SRC.format(n=n, b=8192)]
        for i in range(k):
            parts.append(
                f"CREATE TABLE cw{i} (auction BIGINT, window_end BIGINT,"
                f" num BIGINT, tot BIGINT) WITH (connector = 'memory',"
                f" name = 'cw{i}', type = 'sink');")
            parts.append(
                f"INSERT INTO cw{i}\n"
                f"SELECT bid.auction as auction,\n"
                f"  HOP(INTERVAL '2' SECOND, INTERVAL '{widths[i]}'"
                f" SECOND) as window,\n"
                f"  count(*) AS num, sum(bid.price) AS tot\n"
                f"FROM nexmark WHERE bid is not null GROUP BY 1, 2;")
        return "\n".join(parts)

    prev = os.environ.get("ARROYO_FACTOR_WINDOWS")

    def measure(k: int, flag: str) -> dict:
        os.environ["ARROYO_FACTOR_WINDOWS"] = flag
        prog = plan_sql(sql_for(k), parallelism=bench_parallelism())
        preflight_validate(prog, "correlated_windows")
        decisions = [d.to_json()
                     for d in getattr(prog, "factor_decisions", [])]
        shared = [d for d in decisions if d["shared"]]
        for i in range(k):
            clear_sink(f"cw{i}")
        LocalRunner(prog).run()  # warm (compiles shared by both arms)
        before = {c: perf.counter(c)
                  for c in ("kernel_dispatches", "pane_update_rows",
                            "pane_update_dispatches")}
        for i in range(k):
            clear_sink(f"cw{i}")
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        dt = time.perf_counter() - t0
        delta = {c: perf.counter(c) - v for c, v in before.items()}
        rows = sum(sum(len(b) for b in sink_output(f"cw{i}"))
                   for i in range(k))
        assert rows > 0, f"correlated_windows k={k} produced no output"
        return {
            "events_per_sec": round(n / dt, 1),
            "dispatches_per_event": round(
                delta["kernel_dispatches"] / max(n, 1), 6),
            # rows entering pane-update state per source event: ~K
            # unfactored (every private ring sees every event), ~1 +
            # O(panes) factored (derived rings see fired pane cells)
            "pane_update_rows_per_event": round(
                delta["pane_update_rows"] / max(n, 1), 4),
            "pane_update_dispatches": delta["pane_update_dispatches"],
            "output_rows": rows,
            "factor": {
                "shared_panes": len(shared),
                "derived_windows": sum(len(d["members"]) for d in shared),
                "pane_micros": (shared[0]["pane_micros"]
                                if shared else None),
                "cost_model_decision": (shared[0]["reason"] if shared
                                        else (decisions[0]["reason"]
                                              if decisions else
                                              "no_correlated_group")),
            },
        }

    points = []
    try:
        for k in (2, 4, 8):
            factored = measure(k, "auto")
            unfactored = measure(k, "0")
            assert factored["factor"]["shared_panes"] == 1, \
                f"k={k}: the factor pass did not share"
            assert factored["factor"]["derived_windows"] == k
            points.append({"k": k, "factored": factored,
                           "unfactored": unfactored})
            print(json.dumps({"correlated_windows_point": points[-1]}),
                  file=sys.stderr)
    finally:
        if prev is None:
            os.environ.pop("ARROYO_FACTOR_WINDOWS", None)
        else:
            os.environ["ARROYO_FACTOR_WINDOWS"] = prev

    by_k = {p["k"]: p for p in points}
    growth_f = (by_k[8]["factored"]["pane_update_rows_per_event"]
                / max(by_k[2]["factored"]["pane_update_rows_per_event"],
                      1e-12))
    growth_u = (by_k[8]["unfactored"]["pane_update_rows_per_event"]
                / max(by_k[2]["unfactored"]["pane_update_rows_per_event"],
                      1e-12))
    return {
        "metric": "correlated_windows",
        "events": n,
        "points": points,
        # K doubled twice (2 -> 8): unfactored pane-update work scales
        # ~4x (K private rings each consuming every event); factored
        # stays ~O(panes) — the shared ring consumes each event once and
        # the derived rings consume fired pane CELLS, whose count tracks
        # the pane grid, not K x events.  The margin absorbs the
        # real-but-small per-K derived-cell cost.
        "update_rows_growth_factored_2_to_8": round(growth_f, 3),
        "update_rows_growth_unfactored_2_to_8": round(growth_u, 3),
        "cost_o_panes_ok": bool(growth_f <= max(0.5 * growth_u, 1.25)),
        "speedup_at_8": round(
            by_k[8]["factored"]["events_per_sec"]
            / max(by_k[8]["unfactored"]["events_per_sec"], 1e-9), 3),
    }


def emit_correlated_windows():
    """Correlated-windows family: returned for embedding in the
    headline line (``BENCH_FACTOR=0`` skips)."""
    if os.environ.get("BENCH_FACTOR", "1") in ("0", "false", "no"):
        return None
    try:
        cw = run_correlated_windows()
    except Exception as e:  # the headline must still print
        print(f"correlated-windows bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(cw), file=sys.stderr)
    return cw


def main_mesh_child() -> None:
    """One point of the mesh-scaling sweep: q5 (and a reduced join-
    stress run) at ONE mesh width, in its own process — XLA's device
    count and the mesh shape are frozen at backend init, so the sweep
    cannot share a process across widths.  Prints one JSON line with
    events/s plus the sharded-data-plane counters (reshards MUST be 0:
    the no-resharding invariant, measured per width)."""
    os.environ.setdefault("BATCH_SIZE", str(BATCH))
    os.environ.setdefault("STATE_CAPACITY", str(1 << 17))
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.parallel import shuffle as _shuffle
    from arroyo_tpu.parallel.mesh_window import mesh_key_shards
    from arroyo_tpu.sql import plan_sql

    width = int(os.environ["BENCH_MESH_CHILD"])
    n = int(os.environ.get("BENCH_MESH_EVENTS", 300_000))
    prog = plan_sql(QUERIES["q5"].format(n=n, b=BATCH),
                    parallelism=bench_parallelism())
    preflight_validate(prog, "mesh_scaling_q5")
    clear_sink("results")
    LocalRunner(prog).run()  # warm: compiles out of the timed window
    before = _shuffle.shuffle_stats()
    best = None
    for _ in range(2):
        clear_sink("results")
        t0 = time.perf_counter()
        LocalRunner(prog).run()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    assert sum(len(b) for b in sink_output("results")) > 0, \
        "mesh-sweep q5 produced no output"
    delta = {k: v - before[k]
             for k, v in _shuffle.shuffle_stats().items()}
    out = {
        "width": width,
        "devices": len(jax.devices()),
        "mesh_width": mesh_key_shards(),
        "events": n,
        "events_per_sec": round(n / best, 1),
        "reshards": delta["reshards"],
        "collectives": delta["collectives"],
        "host_shuffle_routes": delta["host_routes"],
    }
    if os.environ.get("BENCH_MESH_JOIN", "1") not in ("0", "false", "no"):
        os.environ.setdefault("BENCH_JOIN_STRESS_EVENTS", "120000")
        # the sweep measures MESH behavior: resident join rings (and
        # their spread over the mesh, join_state.ring_devices) are part
        # of it, so the device-join auto=off-on-cpu policy is overridden
        # for this child only
        os.environ.setdefault("ARROYO_DEVICE_JOIN", "on")
        try:
            js = run_join_stress()
            out["join_stress_events_per_sec"] = js["value"]
            out["join_state"] = {
                k: js.get("join_state", {}).get(k)
                for k in ("hot_partitions", "ring_devices")}
        except Exception as e:  # the q5 point must still print
            out["join_stress_error"] = f"{type(e).__name__}: {e}"[:200]
    print(json.dumps(out))


def run_mesh_scaling(backend: str):
    """Mesh-scaling bench family (ROADMAP 1): q5 + the join-stress
    family swept across mesh widths, one bounded subprocess per width.
    On the CPU box widths are fake XLA host devices
    (``--xla_force_host_platform_device_count``); on a TPU box the real
    chips carry the mesh.  Records events/s per width, scaling
    efficiency vs width 1, and the reshard/collective counters.
    ``BENCH_MESH_SWEEP=0`` skips."""
    if os.environ.get("BENCH_MESH_SWEEP", "1") in ("0", "false", "no"):
        return None
    widths = [int(w) for w in os.environ.get(
        "BENCH_MESH_WIDTHS", "1,2,4,8").split(",") if w.strip()]
    timeout = float(os.environ.get("BENCH_MESH_TIMEOUT", 420))
    points = []
    for w in widths:
        env = dict(os.environ, BENCH_MESH_CHILD=str(w),
                   ARROYO_MESH=str(w) if w > 1 else "off",
                   BENCH_ALL="0")
        env.pop("BENCH_CHILD", None)
        if backend == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{max(widths)}").strip()
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, timeout=timeout, text=True)
        except subprocess.TimeoutExpired:
            points.append({"width": w, "error": "timeout"})
            continue
        if r.returncode == 0 and r.stdout.strip():
            points.append(json.loads(r.stdout.strip().splitlines()[-1]))
        else:
            points.append({"width": w, "error": f"rc={r.returncode}"})
        print(json.dumps({"mesh_scaling_point": points[-1]}),
              file=sys.stderr)
    base = next((p.get("events_per_sec") for p in points
                 if p.get("width") == 1 and "events_per_sec" in p), None)
    for p in points:
        if base and "events_per_sec" in p:
            p["speedup_vs_width1"] = round(p["events_per_sec"] / base, 3)
            p["scaling_efficiency"] = round(
                p["events_per_sec"] / (base * max(p["width"], 1)), 3)
    return {"metric": "mesh_scaling", "widths": widths, "points": points}


def emit_mesh_scaling(backend: str):
    """Mesh-scaling family: returned for embedding in the headline line
    (events/s per mesh width + reshard/collective counters)."""
    try:
        ms = run_mesh_scaling(backend)
    except Exception as e:  # the headline must still print
        print(f"mesh-scaling bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    if ms is not None:
        print(json.dumps(ms), file=sys.stderr)
    return ms


def main_kernels_child() -> None:
    import jax  # noqa: F401  (fail fast if the backend is unreachable)

    print(json.dumps(run_kernel_microbench()))


def main_child() -> None:
    """The actual benchmark, run inside a supervised subprocess."""
    os.environ.setdefault("BATCH_SIZE", str(BATCH))
    # pre-size keyed state near the expected Nexmark key cardinality so the
    # timed run never pays a capacity-growth recompile (config.py hint);
    # 2M-event q5 sees >32k distinct auctions, so 128k slots (~67 MB of
    # f64 state at B=16) keeps the whole run growth-free
    os.environ.setdefault("STATE_CAPACITY", str(1 << 17))
    # initialize the jax backend before any asyncio loop runs: the axon
    # TPU-tunnel plugin's device discovery can deadlock when first
    # triggered from inside a running event loop
    import jax

    # persistent compilation cache: the tunnel backend's jit cache has been
    # observed to evict mid-run (recompiles of identical shapes cost ~0.4s
    # each through the tunnel); a disk cache makes every compile a one-time
    # cost across bench invocations
    from arroyo_tpu.engine.aot import enable_persistent_cache

    enable_persistent_cache(
        suffix="cpu" if os.environ.get("JAX_PLATFORMS", "") == "cpu"
        else "acc")

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the axon sitecustomize plugin imports jax at interpreter start
        # and can override the env var; config wins (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    backend = jax.default_backend()  # tag results with the REAL backend
    print(f"backend: {backend} ({len(jax.devices())} devices)",
          file=sys.stderr)
    if backend == "tpu" and os.environ.get("PALLAS_AXON_POOL_IPS"):
        # the axon TPU is reached through a high-latency tunnel: pin
        # elementwise expression kernels to the host CPU backend (they are
        # bandwidth-bound, not MXU work) and keep the keyed window state
        # on the TPU — override with ARROYO_EXPR_DEVICE=default
        os.environ.setdefault("ARROYO_EXPR_DEVICE", "cpu")
        # joins too: the device join sorts uint64 key hashes, and the TPU
        # has no native 64-bit integers — the emulated-u64 argsort measured
        # 537 ms/step vs sub-ms host numpy at 16k rows (see
        # BENCH_TPU_KERNELS_r04.json join_step_ms) — override with
        # ARROYO_DEVICE_JOIN=auto/on
        os.environ.setdefault("ARROYO_DEVICE_JOIN", "off")
        print("axon tunnel detected: expressions pinned to host "
              f"(ARROYO_EXPR_DEVICE={os.environ['ARROYO_EXPR_DEVICE']}, "
              f"ARROYO_DEVICE_JOIN={os.environ['ARROYO_DEVICE_JOIN']})",
              file=sys.stderr)
    headline = os.environ.get("BENCH_QUERY", "q5")
    if headline not in QUERIES:
        raise SystemExit(f"unknown BENCH_QUERY {headline!r}; "
                         f"choose from {sorted(QUERIES)}")
    if os.environ.get("BENCH_ALL", "1") not in ("0", "false", "no", ""):
        # one child process per query: queries measured in a shared
        # process degrade the later ones (allocator growth, jit-cache
        # churn — q5 measured ~2x lower after three predecessors).
        # Every per-query result is EMBEDDED in the single headline JSON
        # line so the driver artifact carries all BASELINE configs, not
        # just q5 (round-3 verdict: stderr-only results are unrecorded).
        queries = {}
        for name in sorted(QUERIES):
            if name == headline:
                continue
            env = dict(os.environ, BENCH_CHILD="1", BENCH_ALL="0",
                       BENCH_QUERY=name, BENCH_LAT_SECS="0",
                       BENCH_CONFIG5="0", BENCH_JOIN_STRESS="0",
                       BENCH_MESH_SWEEP="0", BENCH_FACTOR="0",
                       BENCH_LATENCY="0", BENCH_SESSIONS="0")
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)], env=env,
                    stdout=subprocess.PIPE, timeout=BENCH_TIMEOUT,
                    text=True)
                if r.returncode == 0 and r.stdout.strip():
                    queries[name] = json.loads(
                        r.stdout.strip().splitlines()[-1])
                else:
                    queries[name] = {"error": f"rc={r.returncode}"}
            except subprocess.TimeoutExpired:
                queries[name] = {"error": "timeout"}
            print(json.dumps({name: queries[name]}), file=sys.stderr)
        headline_result = run_query(headline, QUERIES[headline])
        headline_result["backend"] = backend
        headline_result.update(run_latency())
        lf = emit_latency_family()
        if lf is not None:
            headline_result["latency"] = lf
        headline_result["queries"] = queries
        c5 = emit_config5(backend)
        if c5 is not None:
            headline_result["config5"] = c5
        sf = emit_sessions_family()
        if sf is not None:
            headline_result["sessions_family"] = sf
        js = emit_join_stress()
        if js is not None:
            headline_result["join_stress"] = js
        dec = emit_decode()
        if dec is not None:
            headline_result["decode"] = dec
        ms = emit_mesh_scaling(backend)
        if ms is not None:
            headline_result["mesh_scaling"] = ms
        cw = emit_correlated_windows()
        if cw is not None:
            headline_result["correlated_windows"] = cw
        print(json.dumps(headline_result))
    else:
        result = run_query(headline, QUERIES[headline])
        result["backend"] = backend
        result.update(run_latency())
        lf = emit_latency_family()
        if lf is not None:
            result["latency"] = lf
        c5 = emit_config5(backend)
        if c5 is not None:
            result["config5"] = c5
        sf = emit_sessions_family()
        if sf is not None:
            result["sessions_family"] = sf
        js = emit_join_stress()
        if js is not None:
            result["join_stress"] = js
        dec = emit_decode()
        if dec is not None:
            result["decode"] = dec
        ms = emit_mesh_scaling(backend)
        if ms is not None:
            result["mesh_scaling"] = ms
        cw = emit_correlated_windows()
        if cw is not None:
            result["correlated_windows"] = cw
        print(json.dumps(result))


def emit_join_stress():
    """Join-stress family: returned for embedding in the headline line
    (skewed long-TTL join throughput + state-shape evidence)."""
    if os.environ.get("BENCH_JOIN_STRESS", "1") in ("0", "false", "no"):
        return None
    try:
        js = run_join_stress()
    except Exception as e:  # the headline must still print
        print(f"join-stress bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(js), file=sys.stderr)
    return js


def emit_config5(backend: str):
    """BASELINE config #5: returned for embedding + stderr + artifact."""
    if os.environ.get("BENCH_CONFIG5", "1") in ("0", "false", "no"):
        return None
    try:
        c5 = run_config5()
    except Exception as e:  # the headline must still print
        print(f"config5 bench failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    c5["backend"] = backend
    print(json.dumps(c5), file=sys.stderr)
    # backend-qualified artifact path: a tunnel-TPU run must not clobber
    # the CPU baseline artifact (they differ by ~16x through the tunnel)
    name = ("BENCH_CONFIG5.json" if backend == "cpu"
            else f"BENCH_CONFIG5_{backend.upper()}.json")
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               name), "w") as f:
            json.dump(c5, f)
            f.write("\n")
    except OSError:
        pass
    return c5


BENCH_TIMEOUT = float(os.environ.get("BENCH_TIMEOUT", 2400))


def host_fingerprint() -> dict:
    """Machine/env fingerprint so cross-round artifact numbers can be
    attributed (round-3 verdict: q5 1.75M->1.48M was unattributable with
    no recorded environment).  No jax import — the supervisor must never
    risk a hang."""
    import platform

    fp = {
        "hostname": platform.node(),
        "machine": platform.machine(),
        "python": platform.python_version(),
        "cpu_count": os.cpu_count(),
        "tunnel": bool(os.environ.get("PALLAS_AXON_POOL_IPS")),
    }
    try:
        r = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        if r.returncode == 0:
            fp["git"] = r.stdout.strip()
    except Exception:
        pass
    try:
        with open("/proc/loadavg") as f:
            fp["loadavg_1m"] = float(f.read().split()[0])
    except OSError:
        pass
    return fp


KERNEL_BENCH_TIMEOUT = float(os.environ.get("BENCH_KERNEL_TIMEOUT", 420))


def run_kernel_bench_supervised() -> dict:
    """Kernel microbench on BOTH the accelerator (if not user-forced cpu)
    and the CPU, each in its own bounded subprocess.  The accelerator
    attempt runs even when the full-bench probe failed: the microbench
    only needs the tunnel alive for seconds, and a device-kernel number
    (or the recorded failure) is the falsifiable TPU evidence the full
    pipeline can't always provide."""
    out = {}
    targets = [("cpu", dict(os.environ, BENCH_KERNELS_CHILD="1",
                            JAX_PLATFORMS="cpu"))]
    if os.environ.get("BENCH_FORCED_CPU") != "1":
        acc = dict(os.environ, BENCH_KERNELS_CHILD="1")
        acc.pop("JAX_PLATFORMS", None)
        targets.insert(0, ("accelerator", acc))
    for label, env in targets:
        if label == "cpu":
            env.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, timeout=KERNEL_BENCH_TIMEOUT,
                text=True)
        except subprocess.TimeoutExpired:
            out[label] = {"error": "timed out after "
                          f"{KERNEL_BENCH_TIMEOUT:.0f}s"}
            continue
        if r.returncode == 0 and r.stdout.strip():
            out[label] = json.loads(r.stdout.strip().splitlines()[-1])
        else:
            out[label] = {"error": f"rc={r.returncode}"}
    return out


def main() -> None:
    """Supervisor: never imports jax itself (so it can never hang on a
    flaky accelerator tunnel); runs the bench in a bounded subprocess and
    falls back to CPU if the accelerator attempt hangs or dies."""
    headline = os.environ.get("BENCH_QUERY", "q5")
    if headline not in QUERIES:
        raise SystemExit(f"unknown BENCH_QUERY {headline!r}; "
                         f"choose from {sorted(QUERIES)}")
    user_forced_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    _, probe_failures = probe_backend()  # may force JAX_PLATFORMS=cpu
    if user_forced_cpu:
        # the kernel microbench honors an EXPLICIT user cpu choice; a
        # probe failure does NOT set this — the microbench needs only
        # seconds of tunnel uptime, so it retries the accelerator even
        # when the full bench could not
        os.environ["BENCH_FORCED_CPU"] = "1"
    env = dict(os.environ, BENCH_CHILD="1")
    cpu_env = dict(env, JAX_PLATFORMS="cpu")
    cpu_env.pop("PALLAS_AXON_POOL_IPS", None)  # disable axon sitecustomize
    attempts = ([("cpu", cpu_env)] if env.get("JAX_PLATFORMS") == "cpu"
                else [("accelerator", env), ("cpu", cpu_env)])
    last_err = "unknown"
    # every failed attempt — probe and bench — lands in the artifact so a
    # "backend: cpu" line always shows whether an accelerator was tried
    failed_attempts = list(probe_failures)
    line = None
    for label, attempt in attempts:
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=attempt,
                stdout=subprocess.PIPE, timeout=BENCH_TIMEOUT, text=True)
        except subprocess.TimeoutExpired:
            last_err = f"{label} bench timed out after {BENCH_TIMEOUT:.0f}s"
            failed_attempts.append({"attempt": label, "error": last_err})
            print(last_err, file=sys.stderr)
            continue
        if r.returncode == 0 and r.stdout.strip():
            line = json.loads(r.stdout.strip().splitlines()[-1])
            break
        last_err = f"{label} bench exited rc={r.returncode}"
        failed_attempts.append({"attempt": label, "error": last_err})
        print(last_err, file=sys.stderr)
    if line is None:
        line = {
            "metric": "nexmark_%s_events_per_sec" % headline,
            "value": 0, "unit": "events/sec", "vs_baseline": 0.0,
            "error": last_err,
        }
    if failed_attempts:
        line["failed_attempts"] = failed_attempts
    if os.environ.get("BENCH_KERNELS", "1") not in ("0", "false", "no"):
        line["kernel_bench"] = run_kernel_bench_supervised()
    line["fingerprint"] = host_fingerprint()
    print(json.dumps(line))
    if "error" in line:
        # every attempt failed: the JSON error line above is the
        # artifact, but the process must still exit non-zero — round 5
        # recorded rc=0 with a run_async traceback in the tail, and the
        # driver read it as a healthy 0 events/s datapoint
        sys.exit(1)


if __name__ == "__main__":
    if "--autoscale" in sys.argv[1:]:
        # elasticity mode runs in-process on the forced-CPU path (it
        # measures the control loop, not kernels) and emits its own line
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            print(json.dumps(run_autoscale_bench()))
        except Exception as e:  # same driver contract as the main bench
            import traceback

            traceback.print_exc()
            print(json.dumps({
                "metric": "autoscale_elasticity", "value": 0,
                "error": f"{type(e).__name__}: {e}"[:500],
            }))
            sys.exit(1)
    elif os.environ.get("BENCH_KERNELS_CHILD"):
        main_kernels_child()
    elif os.environ.get("BENCH_MESH_CHILD"):
        main_mesh_child()
    elif os.environ.get("BENCH_CHILD"):
        main_child()
    else:
        try:
            main()
        except Exception as e:  # driver contract: the supervisor always
            # emits one machine-readable line on unexpected failure
            # (SystemExit/KeyboardInterrupt propagate — misconfig and ^C
            # must surface as a non-zero rc, not a zero datapoint)
            import traceback

            traceback.print_exc()
            print(json.dumps({
                "metric": "nexmark_%s_events_per_sec" % os.environ.get(
                    "BENCH_QUERY", "q5"),
                "value": 0, "unit": "events/sec", "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}"[:500],
            }))
            sys.exit(1)
