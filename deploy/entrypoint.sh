#!/bin/sh
# Role selector: api (REST + controller in one process), worker, node.
set -e
case "${1:-api}" in
  api)        exec python -m arroyo_tpu.api.rest ;;
  controller) exec python -m arroyo_tpu.controller.controller ;;
  worker)     exec python -m arroyo_tpu.worker.server ;;
  node)       exec python -m arroyo_tpu.node.daemon ;;
  *)          exec "$@" ;;
esac
