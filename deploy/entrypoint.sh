#!/bin/sh
# Role selector: api (REST + controller in one process), worker, node —
# all routes through the single `python -m arroyo_tpu` entry point.
set -e
case "${1:-api}" in
  api|controller|worker|node|run) exec python -m arroyo_tpu "$@" ;;
  *)                              exec "$@" ;;
esac
