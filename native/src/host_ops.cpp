// Native host runtime ops for arroyo_tpu.
//
// The reference implements its entire host data plane in Rust; here the
// Python host runtime offloads its per-batch hot loops to this library
// (loaded via ctypes, with numpy-based fallbacks kept in sync — see
// arroyo_tpu/native/__init__.py):
//
//  * splitmix64 key hashing (must match arroyo_tpu.types.hash_u64 bit-for-
//    bit: sharding and checkpoint key ranges depend on it),
//  * composite multi-column hash combining,
//  * shuffle partition routing: key_hash -> destination shard, stable
//    counting-sort order and per-destination bounds in one O(n) pass
//    (replaces argsort+searchsorted in the collector fan-out; semantics of
//    server_for_hash per arroyo-types/src/lib.rs:822-836),
//  * event-time window-bin assignment fused with liveness filtering (the
//    host half of the device bin-ring update).

#include <cstdint>
#include <cstring>

extern "C" {

// bump when any exported signature changes so the Python loader rebuilds
// a stale cached .so instead of calling through a mismatched ABI
int64_t arroyo_abi_version() { return 2; }

static inline uint64_t splitmix64(uint64_t z) {
    z += 0x9E3779B97F4A7C15ULL;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

// out[i] = splitmix64(in[i]); matches types.hash_u64
void arroyo_hash_u64(const uint64_t* in, uint64_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) out[i] = splitmix64(in[i]);
}

// acc[i] = splitmix64(acc[i] * 31 + h[i]); matches types.hash_columns
void arroyo_hash_combine(uint64_t* acc, const uint64_t* h, int64_t n) {
    for (int64_t i = 0; i < n; i++)
        acc[i] = splitmix64(acc[i] * 31ULL + h[i]);
}

// Key-range partition routing (server_for_hash semantics):
//   dest[i]  = min(n_parts-1, kh[i] / (U64_MAX / n_parts))
//   order    = stable permutation sorting rows by dest (counting sort)
//   bounds   = [n_parts+1] prefix offsets into order per destination
void arroyo_partition_route(const uint64_t* kh, int64_t n, int32_t n_parts,
                            int32_t* dest, int64_t* order, int64_t* bounds) {
    const uint64_t range = 0xFFFFFFFFFFFFFFFFULL / (uint64_t)n_parts;
    for (int64_t i = 0; i < n; i++) {
        uint64_t d = kh[i] / range;
        if (d >= (uint64_t)n_parts) d = n_parts - 1;
        dest[i] = (int32_t)d;
    }
    // counting sort: stable, O(n + n_parts)
    for (int32_t p = 0; p <= n_parts; p++) bounds[p] = 0;
    for (int64_t i = 0; i < n; i++) bounds[dest[i] + 1]++;
    for (int32_t p = 0; p < n_parts; p++) bounds[p + 1] += bounds[p];
    int64_t* cursor = new int64_t[n_parts];
    std::memcpy(cursor, bounds, n_parts * sizeof(int64_t));
    for (int64_t i = 0; i < n; i++) order[cursor[dest[i]]++] = i;
    delete[] cursor;
}

// Window-bin assignment for the keyed bin-ring update:
//   bins[i] = (ts[i] / slide) % ring  for rows at or after the liveness
//   threshold (min live absolute bin); dead rows get live[i] = 0.
// Returns the number of live rows; fills abs_min/abs_max over live rows.
int64_t arroyo_assign_bins(const int64_t* ts, int64_t n, int64_t slide,
                           int64_t ring, int64_t threshold, /* INT64_MIN if none */
                           int32_t* bins, uint8_t* live,
                           int64_t* abs_min, int64_t* abs_max) {
    int64_t lo = INT64_MAX, hi = INT64_MIN, count = 0;
    for (int64_t i = 0; i < n; i++) {
        // floor division (numpy // semantics), not C++ truncation
        int64_t ab = ts[i] >= 0 ? ts[i] / slide
                                : -((-ts[i] + slide - 1) / slide);
        uint8_t ok = ab >= threshold;
        live[i] = ok;
        int64_t m = ab % ring;
        bins[i] = (int32_t)(m < 0 ? m + ring : m);
        if (ok) {
            count++;
            if (ab < lo) lo = ab;
            if (ab > hi) hi = ab;
        }
    }
    *abs_min = lo;
    *abs_max = hi;
    return count;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Persistent key directory: open-addressing hash table key_hash -> slot.
//
// Replaces the sorted-array + np.searchsorted directory maintenance in
// ops/keyed_bins.py (directory_insert): one O(n) linear-probe pass per
// batch instead of O(n log C) binary search + merge sort.  The Python side
// keeps slot_to_key/key_sorted as the checkpointable source of truth and
// rebuilds this table on restore via arroyo_dir_load.
// ---------------------------------------------------------------------------

extern "C" {

struct ArroyoDir {
    uint64_t* keys;
    int64_t* slots;   // -1 = empty
    uint64_t cap;     // power of two
    uint64_t mask;
    uint64_t size;
};

static void dir_alloc(ArroyoDir* d, uint64_t cap) {
    d->keys = new uint64_t[cap];
    d->slots = new int64_t[cap];
    d->cap = cap;
    d->mask = cap - 1;
    d->size = 0;
    for (uint64_t i = 0; i < cap; i++) d->slots[i] = -1;
}

void* arroyo_dir_new(int64_t cap_hint) {
    uint64_t cap = 64;
    while ((int64_t)cap < cap_hint * 2) cap <<= 1;
    ArroyoDir* d = new ArroyoDir;
    dir_alloc(d, cap);
    return d;
}

void arroyo_dir_free(void* h) {
    ArroyoDir* d = (ArroyoDir*)h;
    delete[] d->keys;
    delete[] d->slots;
    delete d;
}

static void dir_grow(ArroyoDir* d) {
    uint64_t* ok = d->keys;
    int64_t* os = d->slots;
    uint64_t ocap = d->cap;
    dir_alloc(d, ocap << 1);
    for (uint64_t i = 0; i < ocap; i++) {
        if (os[i] < 0) continue;
        uint64_t j = splitmix64(ok[i]) & d->mask;
        while (d->slots[j] >= 0) j = (j + 1) & d->mask;
        d->keys[j] = ok[i];
        d->slots[j] = os[i];
        d->size++;
    }
    delete[] ok;
    delete[] os;
}

// Bulk load explicit (key, slot) pairs (checkpoint restore).
void arroyo_dir_load(void* h, const uint64_t* keys, const int64_t* slots,
                     int64_t n) {
    ArroyoDir* d = (ArroyoDir*)h;
    for (int64_t i = 0; i < n; i++) {
        if ((d->size + 1) * 10 > d->cap * 7) dir_grow(d);
        uint64_t j = splitmix64(keys[i]) & d->mask;
        while (d->slots[j] >= 0 && d->keys[j] != keys[i])
            j = (j + 1) & d->mask;
        if (d->slots[j] < 0) d->size++;
        d->keys[j] = keys[i];
        d->slots[j] = slots[i];
    }
}

// Lookup-or-insert a batch.  Unknown keys get sequential slots starting at
// next_slot, in first-appearance order; their hashes are appended to
// out_new_keys.  Returns the number of new keys.
int64_t arroyo_dir_insert(void* h, const uint64_t* kh, int64_t n,
                          int64_t next_slot, int64_t* out_slots,
                          uint64_t* out_new_keys) {
    ArroyoDir* d = (ArroyoDir*)h;
    int64_t n_new = 0;
    for (int64_t i = 0; i < n; i++) {
        if ((d->size + 1) * 10 > d->cap * 7) dir_grow(d);
        uint64_t k = kh[i];
        uint64_t j = splitmix64(k) & d->mask;
        while (d->slots[j] >= 0 && d->keys[j] != k) j = (j + 1) & d->mask;
        if (d->slots[j] < 0) {
            d->keys[j] = k;
            d->slots[j] = next_slot + n_new;
            d->size++;
            out_new_keys[n_new++] = k;
        }
        out_slots[i] = d->slots[j];
    }
    return n_new;
}

// Lookup only (emission-time key recovery); missing keys -> -1.
void arroyo_dir_lookup(void* h, const uint64_t* kh, int64_t n,
                       int64_t* out_slots) {
    ArroyoDir* d = (ArroyoDir*)h;
    for (int64_t i = 0; i < n; i++) {
        uint64_t k = kh[i];
        uint64_t j = splitmix64(k) & d->mask;
        while (d->slots[j] >= 0 && d->keys[j] != k) j = (j + 1) & d->mask;
        out_slots[i] = d->slots[j] < 0 ? -1 : d->slots[j];
    }
}

// ---------------------------------------------------------------------------
// (slot, bin) cell pre-aggregation — the two-phase local half
// (TumblingLocalAggregator analog) in one O(n) hash pass, replacing the
// np.lexsort + reduceat path in ops/keyed_bins.py preaggregate().
//
//   kinds[c]: 0 = additive (sum/count), 1 = min, 2 = max
//   vals is [n_ch, n] C-contiguous; live rows only are aggregated.
//   Outputs are in first-appearance order; returns n_cells.
// ---------------------------------------------------------------------------

int64_t arroyo_agg_cells(const int64_t* slots, const int32_t* bins,
                         const uint8_t* live, int64_t n, int64_t ring,
                         const double* vals, const uint8_t* kinds,
                         int32_t n_ch,
                         int64_t* out_slot, int32_t* out_bin,
                         double* out_cnt, double* out_vals) {
    uint64_t cap = 64;
    while ((int64_t)cap < n * 2) cap <<= 1;
    const uint64_t mask = cap - 1;
    uint64_t* ckey = new uint64_t[cap];
    int64_t* cidx = new int64_t[cap];  // -1 = empty, else cell index
    for (uint64_t i = 0; i < cap; i++) cidx[i] = -1;

    int64_t n_cells = 0;
    for (int64_t i = 0; i < n; i++) {
        if (live && !live[i]) continue;
        uint64_t key = (uint64_t)slots[i] * (uint64_t)ring + (uint64_t)bins[i];
        uint64_t j = splitmix64(key) & mask;
        while (cidx[j] >= 0 && ckey[j] != key) j = (j + 1) & mask;
        int64_t c = cidx[j];
        if (c < 0) {
            c = n_cells++;
            ckey[j] = key;
            cidx[j] = c;
            out_slot[c] = slots[i];
            out_bin[c] = bins[i];
            out_cnt[c] = 1.0;
            for (int32_t ch = 0; ch < n_ch; ch++)
                out_vals[ch * n + c] = vals[ch * n + i];
        } else {
            out_cnt[c] += 1.0;
            for (int32_t ch = 0; ch < n_ch; ch++) {
                double v = vals[ch * n + i];
                double* acc = &out_vals[ch * n + c];
                if (kinds[ch] == 1) { if (v < *acc) *acc = v; }
                else if (kinds[ch] == 2) { if (v > *acc) *acc = v; }
                else *acc += v;
            }
        }
    }
    delete[] ckey;
    delete[] cidx;
    return n_cells;
}

}  // extern "C"
