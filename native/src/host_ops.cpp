// Native host runtime ops for arroyo_tpu.
//
// The reference implements its entire host data plane in Rust; here the
// Python host runtime offloads its per-batch hot loops to this library
// (loaded via ctypes, with numpy-based fallbacks kept in sync — see
// arroyo_tpu/native/__init__.py):
//
//  * splitmix64 key hashing (must match arroyo_tpu.types.hash_u64 bit-for-
//    bit: sharding and checkpoint key ranges depend on it),
//  * composite multi-column hash combining,
//  * shuffle partition routing: key_hash -> destination shard, stable
//    counting-sort order and per-destination bounds in one O(n) pass
//    (replaces argsort+searchsorted in the collector fan-out; semantics of
//    server_for_hash per arroyo-types/src/lib.rs:822-836),
//  * event-time window-bin assignment fused with liveness filtering (the
//    host half of the device bin-ring update).

#include <cstdint>
#include <cstring>

extern "C" {

static inline uint64_t splitmix64(uint64_t z) {
    z += 0x9E3779B97F4A7C15ULL;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

// out[i] = splitmix64(in[i]); matches types.hash_u64
void arroyo_hash_u64(const uint64_t* in, uint64_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) out[i] = splitmix64(in[i]);
}

// acc[i] = splitmix64(acc[i] * 31 + h[i]); matches types.hash_columns
void arroyo_hash_combine(uint64_t* acc, const uint64_t* h, int64_t n) {
    for (int64_t i = 0; i < n; i++)
        acc[i] = splitmix64(acc[i] * 31ULL + h[i]);
}

// Key-range partition routing (server_for_hash semantics):
//   dest[i]  = min(n_parts-1, kh[i] / (U64_MAX / n_parts))
//   order    = stable permutation sorting rows by dest (counting sort)
//   bounds   = [n_parts+1] prefix offsets into order per destination
void arroyo_partition_route(const uint64_t* kh, int64_t n, int32_t n_parts,
                            int32_t* dest, int64_t* order, int64_t* bounds) {
    const uint64_t range = 0xFFFFFFFFFFFFFFFFULL / (uint64_t)n_parts;
    for (int64_t i = 0; i < n; i++) {
        uint64_t d = kh[i] / range;
        if (d >= (uint64_t)n_parts) d = n_parts - 1;
        dest[i] = (int32_t)d;
    }
    // counting sort: stable, O(n + n_parts)
    for (int32_t p = 0; p <= n_parts; p++) bounds[p] = 0;
    for (int64_t i = 0; i < n; i++) bounds[dest[i] + 1]++;
    for (int32_t p = 0; p < n_parts; p++) bounds[p + 1] += bounds[p];
    int64_t* cursor = new int64_t[n_parts];
    std::memcpy(cursor, bounds, n_parts * sizeof(int64_t));
    for (int64_t i = 0; i < n; i++) order[cursor[dest[i]]++] = i;
    delete[] cursor;
}

// Window-bin assignment for the keyed bin-ring update:
//   bins[i] = (ts[i] / slide) % ring  for rows at or after the liveness
//   threshold (min live absolute bin); dead rows get live[i] = 0.
// Returns the number of live rows; fills abs_min/abs_max over live rows.
int64_t arroyo_assign_bins(const int64_t* ts, int64_t n, int64_t slide,
                           int64_t ring, int64_t threshold, /* INT64_MIN if none */
                           int32_t* bins, uint8_t* live,
                           int64_t* abs_min, int64_t* abs_max) {
    int64_t lo = INT64_MAX, hi = INT64_MIN, count = 0;
    for (int64_t i = 0; i < n; i++) {
        // floor division (numpy // semantics), not C++ truncation
        int64_t ab = ts[i] >= 0 ? ts[i] / slide
                                : -((-ts[i] + slide - 1) / slide);
        uint8_t ok = ab >= threshold;
        live[i] = ok;
        int64_t m = ab % ring;
        bins[i] = (int32_t)(m < 0 ? m + ring : m);
        if (ok) {
            count++;
            if (ab < lo) lo = ab;
            if (ab > hi) hi = ab;
        }
    }
    *abs_min = lo;
    *abs_max = hi;
    return count;
}

}  // extern "C"
