#!/usr/bin/env bash
# smoke.sh — <60s pre-snapshot gate.
#
# Round 5 shipped a Nexmark source that crashed on every run: the bench
# recorded 0 events/s and nothing pointed at the failing operator.  This
# gate catches that class of regression before a snapshot lands:
#
#   1. arroyolint (tools/lint.sh): zero unwaived static-analysis
#      findings — the checkpoint-arity pass catches exactly the round-5
#      producer/consumer mismatch before anything runs;
#   2. a tiny Nexmark pipeline end-to-end through the SQL planner and
#      LocalRunner — non-zero exit on any source crash or empty sink
#      (the plan-time validator also gates this via Engine);
#   3. the metrics scrape must be non-empty and contain the
#      flight-recorder histogram families (an empty scrape means the
#      obs wiring regressed even if the pipeline "ran");
#   4. tests/test_obs.py — the observability contract suite.
#
# Usage: tools/smoke.sh   (from anywhere; runs on CPU for determinism)
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

bash tools/lint.sh

python - <<'PY'
import sys

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs.metrics import render_metrics
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '50000',
  rate_limited = 'false', batch_size = '4096'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""

clear_sink("results")
LocalRunner(plan_sql(SQL)).run()
rows = sum(len(b) for b in sink_output("results"))
if rows <= 0:
    sys.exit("smoke: nexmark pipeline produced no output "
             "(silent-source-crash regression)")

text = render_metrics().decode()
if not text.strip():
    sys.exit("smoke: /metrics scrape is empty")
for family in ("arroyo_worker_messages_recv",
               "arroyo_worker_event_time_lag_seconds_bucket",
               "arroyo_worker_batch_processing_seconds_bucket",
               "arroyo_worker_queue_wait_seconds_bucket"):
    if family not in text:
        sys.exit(f"smoke: metrics scrape is missing {family}")
print(f"smoke: nexmark ok ({rows} result rows), metrics scrape ok")
PY

exec python -m pytest tests/test_obs.py -q -p no:cacheprovider
