#!/usr/bin/env bash
# smoke.sh — <60s pre-snapshot gate.
#
# Round 5 shipped a Nexmark source that crashed on every run: the bench
# recorded 0 events/s and nothing pointed at the failing operator.  This
# gate catches that class of regression before a snapshot lands:
#
#   1. arroyolint (tools/lint.sh): zero unwaived static-analysis
#      findings — the checkpoint-arity pass catches exactly the round-5
#      producer/consumer mismatch before anything runs;
#   2. a tiny Nexmark pipeline end-to-end through the SQL planner and
#      LocalRunner — non-zero exit on any source crash or empty sink
#      (the plan-time validator also gates this via Engine);
#   3. the metrics scrape must be non-empty and contain the
#      flight-recorder histogram families (an empty scrape means the
#      obs wiring regressed even if the pipeline "ran");
#   4. the autoscaler: a deterministic ramp trace through the policy
#      simulator must scale up the bottleneck (and only it), and the
#      REST GET/PUT /v1/jobs/{id}/autoscaler surface must round-trip;
#   5. serde fast-vs-legacy: a tiny single_file JSON pipeline must
#      emit byte-identical rows with the vectorized decode/encode
#      fast paths on (default) and with ARROYO_FAST_DECODE=0 — the
#      end-to-end decode-parity gate;
#   6. mesh-on vs mesh-off: the q5-shaped hop aggregate AND the
#      two-stream join on an 8-fake-device mesh (ARROYO_MESH=auto vs
#      off, sanitizer armed) must emit identical rows — and the
#      shardcheck MODEL-DRIFT gate holds: the static plan report must
#      predict 0 reshards, the live reshard_transfers counter must
#      agree (drift_check fails on disagreement in EITHER direction),
#      and the comparator is proven able to fire on seeded
#      disagreements;
#   7. factored-vs-unfactored: a two-window correlated query must
#      actually factor (one shared pane ring), emit identical rows
#      with ARROYO_FACTOR_WINDOWS=auto vs =0, sanitizer armed, and
#      hold the static-vs-runtime reshard drift gate over the
#      factor->derived pane edges on the 8-device mesh;
#   8. arroyosan: a sanitized tiny-Nexmark run (ARROYO_SANITIZE=1,
#      chaining on, periodic checkpoints) must complete with zero
#      invariant violations — the runtime protocol contract;
#   9. the phase profiler: an armed steady-state Nexmark run must
#      attribute >=85% of wall time to named phases (best-of-2) with
#      zero event-loop stalls (unattributed time means the
#      instrumentation drifted off the hot path);
#  10. the latency observatory: a sanitized tiny-Nexmark run with
#      1-in-N sampling armed and an SLO configured must record >=1
#      sampled e2e latency per sink with zero sanitizer violations
#      (the stamp never flips a schema signature), attribute the
#      critical path to a named stage, and round-trip the SLO verdict
#      through REST GET/PUT /v1/jobs/{id}/slo + GET .../latency;
#  11. tests/test_obs.py + tests/test_profiler.py +
#      tests/test_latency.py — the observability contract suites;
#  12. session run state: the SAME tiny sessionized Nexmark query
#      (session-gap window, count + avg) under ARROYO_SESSION_STATE=
#      device vs =legacy, sanitizer armed, must emit IDENTICAL rows —
#      the shared-checkpoint contract behind the device-resident
#      interval runs — with the session_device_merge_rows counter
#      proving the device union kernel actually merged when armed and
#      stayed silent under legacy.
#
# Budget: the whole gate stays under ~90s.
#
# Usage: tools/smoke.sh   (from anywhere; runs on CPU for determinism)
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

bash tools/lint.sh

python - <<'PY'
import sys

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs.metrics import render_metrics
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '50000',
  rate_limited = 'false', batch_size = '4096'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""

clear_sink("results")
LocalRunner(plan_sql(SQL)).run()
rows = sum(len(b) for b in sink_output("results"))
if rows <= 0:
    sys.exit("smoke: nexmark pipeline produced no output "
             "(silent-source-crash regression)")

text = render_metrics().decode()
if not text.strip():
    sys.exit("smoke: /metrics scrape is empty")
for family in ("arroyo_worker_messages_recv",
               "arroyo_worker_event_time_lag_seconds_bucket",
               "arroyo_worker_batch_processing_seconds_bucket",
               "arroyo_worker_queue_wait_seconds_bucket"):
    if family not in text:
        sys.exit(f"smoke: metrics scrape is missing {family}")
print(f"smoke: nexmark ok ({rows} result rows), metrics scrape ok")
PY

python - <<'PY'
# chain-on vs chain-off equivalence gate: the SAME tiny Nexmark pipeline
# must produce the SAME rows with and without operator chaining, and
# chaining must actually collapse queue hops (fewer tasks than operators)
import os
import sys

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""


def run(chain: str):
    os.environ["ARROYO_CHAIN"] = chain
    clear_sink("results")
    runner = LocalRunner(plan_sql(SQL))
    runner.run()
    rows = sorted(
        (int(a), int(w), int(n))
        for b in sink_output("results")
        for a, w, n in zip(b.columns["auction"], b.columns["window_end"],
                           b.columns["num"]))
    return rows, len(runner.engine.subtasks)


rows_on, tasks_on = run("1")
rows_off, tasks_off = run("0")
os.environ.pop("ARROYO_CHAIN", None)
if not rows_on:
    sys.exit("smoke: chained nexmark produced no output")
if rows_on != rows_off:
    sys.exit(f"smoke: chain-on output diverges from chain-off "
             f"({len(rows_on)} vs {len(rows_off)} rows)")
if tasks_on >= tasks_off:
    sys.exit(f"smoke: chaining did not collapse queue hops "
             f"({tasks_on} tasks with chains vs {tasks_off} without)")
print(f"smoke: chain equivalence ok ({len(rows_on)} rows; "
      f"{tasks_on} tasks chained vs {tasks_off} unchained)")
PY

python - <<'PY'
# join-state equivalence gate: a tiny two-stream join must produce
# IDENTICAL rows with (a) the partition-adaptive sorted-run state
# (default) vs the legacy flat-buffer state — the same-rows contract
# that lets the layouts share checkpoints — and (b) device payload
# rings ON vs OFF (ARROYO_JOIN_PAYLOAD_DEVICE, sanitizer armed, hot
# floor lowered so rings actually promote): PR 15's fully
# device-resident emission path against the host gather, with the
# join_device_gather_rows counter proving which path each run took
import os
import sys

os.environ["ARROYO_SANITIZE"] = "1"
os.environ["ARROYO_DEVICE_JOIN"] = "on"
# tiny stream: ~8 rows land per partition per append, so the default
# 4096-row EWMA hot floor would never promote a ring — drop it so the
# device path actually engages inside the smoke budget
os.environ["ARROYO_JOIN_HOT_MIN_ROWS"] = "16"

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import perf
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '20000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
WITH b AS (SELECT bid.auction AS auction, bid.price AS price
           FROM nexmark WHERE bid is not null AND bid.price > 40000000),
     a AS (SELECT auction.id AS id, auction.reserve AS reserve
           FROM nexmark WHERE auction is not null)
SELECT X.auction AS auction, X.price AS price, Y.reserve AS reserve
FROM b X JOIN a Y ON X.auction = Y.id
"""


def run(layout: str, payload: str):
    os.environ["ARROYO_JOIN_STATE"] = layout
    os.environ["ARROYO_JOIN_PAYLOAD_DEVICE"] = payload
    clear_sink("results")
    d0 = perf.counter("join_device_gather_rows")
    runner = LocalRunner(plan_sql(SQL))
    runner.run()
    san = runner.engine.sanitizer
    if san is None or san.violations:
        sys.exit(f"smoke: join gate sanitizer problem (layout={layout}, "
                 f"payload={payload}, "
                 f"violations={getattr(san, 'violations', None)})")
    dev_rows = perf.counter("join_device_gather_rows") - d0
    return dev_rows, sorted(
        (int(a), int(p), int(r))
        for b in sink_output("results")
        for a, p, r in zip(b.columns["auction"], b.columns["price"],
                           b.columns["reserve"]))


dev_on, rows_on = run("partitioned", "auto")
dev_off, rows_off = run("partitioned", "off")
_, rows_legacy = run("legacy", "off")
for k in ("ARROYO_JOIN_STATE", "ARROYO_JOIN_PAYLOAD_DEVICE",
          "ARROYO_JOIN_HOT_MIN_ROWS", "ARROYO_DEVICE_JOIN"):
    os.environ.pop(k, None)
if not rows_on:
    sys.exit("smoke: partitioned join produced no output")
if rows_on != rows_off:
    sys.exit(f"smoke: device-payload join output diverges from host "
             f"gather ({len(rows_on)} vs {len(rows_off)} rows)")
if rows_on != rows_legacy:
    sys.exit(f"smoke: partitioned join state diverges from legacy "
             f"({len(rows_on)} vs {len(rows_legacy)} rows)")
if dev_on <= 0:
    sys.exit("smoke: payload-on join never emitted through the device "
             "gather (join_device_gather_rows == 0 — the payload rings "
             "did not engage)")
if dev_off != 0:
    sys.exit(f"smoke: payload-off join still device-gathered "
             f"{dev_off} rows (the knob does not disarm the planes)")
print(f"smoke: join-state equivalence ok ({len(rows_on)} rows, "
      f"device-payload == host-gather == legacy; {dev_on} rows via "
      "device planes when armed)")
PY

python - <<'PY'
# session-state equivalence gate: the SAME tiny sessionized Nexmark
# query must produce IDENTICAL rows with the device-resident interval
# runs (ARROYO_SESSION_STATE=device, default) and the legacy per-key
# host dict (=legacy), sanitizer armed — the same-rows contract that
# lets both layouts share checkpoints — with session_device_merge_rows
# proving the vectorized union kernel merged when armed and never ran
# under legacy
import os
import sys

os.environ["ARROYO_SANITIZE"] = "1"

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import perf
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '20000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
SELECT bid.auction as auction,
       session(INTERVAL '1' SECOND) as window,
       count(*) AS num,
       avg(bid.price) AS mean_price
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""


def run(mode: str):
    os.environ["ARROYO_SESSION_STATE"] = mode
    clear_sink("results")
    d0 = perf.counter("session_device_merge_rows")
    runner = LocalRunner(plan_sql(SQL))
    runner.run()
    san = runner.engine.sanitizer
    if san is None or san.violations:
        sys.exit(f"smoke: session gate sanitizer problem (mode={mode}, "
                 f"violations={getattr(san, 'violations', None)})")
    dev_rows = perf.counter("session_device_merge_rows") - d0
    return dev_rows, sorted(
        (int(a), int(w), int(n), round(float(m), 6))
        for b in sink_output("results")
        for a, w, n, m in zip(b.columns["auction"], b.columns["window_end"],
                              b.columns["num"], b.columns["mean_price"]))


dev_on, rows_dev = run("device")
dev_off, rows_legacy = run("legacy")
for k in ("ARROYO_SESSION_STATE", "ARROYO_SANITIZE"):
    os.environ.pop(k, None)
if not rows_dev:
    sys.exit("smoke: sessionized nexmark produced no output")
if rows_dev != rows_legacy:
    sys.exit(f"smoke: device session state diverges from legacy "
             f"({len(rows_dev)} vs {len(rows_legacy)} rows)")
if dev_on <= 0:
    sys.exit("smoke: armed run never merged through the device union "
             "kernel (session_device_merge_rows == 0 — the interval "
             "runs did not engage)")
if dev_off != 0:
    sys.exit(f"smoke: legacy run still pushed {dev_off} rows through "
             "the device merge (the knob does not disarm the runs)")
print(f"smoke: session-state equivalence ok ({len(rows_dev)} rows, "
      f"device == legacy; {dev_on} interval rows through the union "
      "kernel when armed)")
PY

python - <<'PY'
# fast-vs-legacy serde gate: a tiny single_file JSON pipeline must emit
# byte-identical output rows with the vectorized decode/encode fast
# paths on (ARROYO_FAST_DECODE=1, default) and with the full legacy
# escape (=0) — the end-to-end half of the decode parity matrix
# (tests/test_formats.py covers the fixture-level half)
import json
import os
import sys
import tempfile

from arroyo_tpu import Stream
from arroyo_tpu.engine.engine import LocalRunner

tmp = tempfile.mkdtemp(prefix="smoke-serde-")
src = os.path.join(tmp, "in.jsonl")
with open(src, "w") as f:
    for i in range(4000):
        row = {"x": i, "price": i * 0.25, "tag": f"{i:05d}",
               "flag": (i % 3 == 0) if i % 5 else None}
        f.write(json.dumps(row) + "\n")


def run(flag):
    os.environ["ARROYO_FAST_DECODE"] = flag
    dst = os.path.join(tmp, f"out-{flag}.jsonl")
    prog = (
        Stream.source("single_file", {"path": src})
        .map(lambda c: {"x": c["x"], "price": c["price"],
                        "doubled": c["x"] * 2}, name="proj")
        .sink("single_file", {"path": dst})
    )
    LocalRunner(prog).run()
    return sorted(open(dst).read().splitlines())


rows_fast = run("1")
rows_legacy = run("0")
os.environ.pop("ARROYO_FAST_DECODE", None)
if len(rows_fast) != 4000:
    sys.exit(f"smoke: serde pipeline lost rows ({len(rows_fast)}/4000)")
if rows_fast != rows_legacy:
    diff = next(i for i, (a, b) in
                enumerate(zip(rows_fast, rows_legacy)) if a != b)
    sys.exit("smoke: fast-decode output diverges from legacy at row "
             f"{diff}: {rows_fast[diff]!r} vs {rows_legacy[diff]!r}")
print(f"smoke: serde fast-vs-legacy ok ({len(rows_fast)} identical rows)")
PY

python - <<'PY'
# mesh-on-vs-off equivalence gate (sharded data plane): the SAME tiny
# Nexmark q5-shaped hop aggregate AND the two-stream join, on an
# 8-fake-device CPU mesh with ARROYO_MESH=auto vs =off, sanitizer
# armed — identical rows required, and the mesh run must hold the
# no-resharding invariant (reshard counter == 0)
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["ARROYO_SANITIZE"] = "1"
os.environ["ARROYO_DEVICE_JOIN"] = "on"  # exercise mesh-placed rings

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import perf
from arroyo_tpu.parallel.shuffle import RESHARDS
from arroyo_tpu.sql import plan_sql

Q5_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""
JOIN_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '20000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
WITH b AS (SELECT bid.auction AS auction, bid.price AS price
           FROM nexmark WHERE bid is not null AND bid.price > 40000000),
     a AS (SELECT auction.id AS id, auction.reserve AS reserve
           FROM nexmark WHERE auction is not null)
SELECT X.auction AS auction, X.price AS price, Y.reserve AS reserve
FROM b X JOIN a Y ON X.auction = Y.id
"""


def run(sql, cols, mesh):
    os.environ["ARROYO_MESH"] = mesh
    clear_sink("results")
    runner = LocalRunner(plan_sql(sql))
    runner.run()
    san = runner.engine.sanitizer
    if san is None or san.violations:
        sys.exit(f"smoke: mesh gate sanitizer problem (mesh={mesh}, "
                 f"violations={getattr(san, 'violations', None)})")
    return sorted(
        tuple(int(r[c][i]) for c in cols)
        for r in (b.columns for b in sink_output("results"))
        for i in range(len(next(iter(r.values())))))


from arroyo_tpu.parallel.mesh_window import mesh_key_shards

os.environ["ARROYO_MESH"] = "auto"
if mesh_key_shards() != 8:
    sys.exit("smoke: 8-device CPU mesh did not come up "
             f"(mesh_key_shards={mesh_key_shards()})")

# shardcheck model-drift gate, half 1: the STATIC prediction for the
# exact plans this gate is about to run live.  The plans must prove
# predicted_reshards == 0 with zero shardcheck errors BEFORE any
# engine starts; after the runs, drift_check holds the prediction
# against the observed reshard_transfers delta in both directions.
from arroyo_tpu.analysis import shardcheck as _sc

predicted = 0
for label, sql in (("q5", Q5_SQL), ("join", JOIN_SQL)):
    rep = _sc.analyze(plan_sql(sql), nk=mesh_key_shards())
    if rep.errors():
        sys.exit(f"smoke: shardcheck rejected the {label} smoke plan: "
                 + "; ".join(d.render() for d in rep.errors()))
    predicted += rep.predicted_reshards

r0 = perf.counter(RESHARDS)
q5_mesh = run(Q5_SQL, ("auction", "window_end", "num"), "auto")
q5_off = run(Q5_SQL, ("auction", "window_end", "num"), "off")
if not q5_mesh:
    sys.exit("smoke: mesh q5 produced no output")
if q5_mesh != q5_off:
    sys.exit(f"smoke: mesh-on q5 diverges from mesh-off "
             f"({len(q5_mesh)} vs {len(q5_off)} rows)")
j_mesh = run(JOIN_SQL, ("auction", "price", "reserve"), "auto")
j_off = run(JOIN_SQL, ("auction", "price", "reserve"), "off")
if not j_mesh:
    sys.exit("smoke: mesh join produced no output")
if j_mesh != j_off:
    sys.exit(f"smoke: mesh-on join diverges from mesh-off "
             f"({len(j_mesh)} vs {len(j_off)} rows)")
reshards = perf.counter(RESHARDS) - r0
drift = _sc.drift_check(predicted, reshards, "mesh smoke plans")
if drift is not None:
    sys.exit(f"smoke: {drift}")
# half 2: the comparator itself must fail on disagreement in EITHER
# direction — a gate that cannot fire is no gate
if _sc.drift_check(0, 1) is None or _sc.drift_check(1, 0) is None:
    sys.exit("smoke: shardcheck drift_check passed a seeded "
             "disagreement — the drift gate is toothless")
os.environ.pop("ARROYO_MESH", None)
print(f"smoke: mesh equivalence ok (q5 {len(q5_mesh)} rows, join "
      f"{len(j_mesh)} rows, mesh == single-device, "
      f"predicted {predicted} == observed {reshards} reshards)")
PY

python - <<'PY'
# factored-vs-unfactored equivalence gate (factor-window sharing): a
# tiny TWO-window correlated query (same input/keys, different widths)
# on the 8-fake-device mesh, ARROYO_FACTOR_WINDOWS=auto vs =0, with the
# sanitizer armed — the factored plan must actually factor (one shared
# pane ring), emit IDENTICAL rows, and hold the no-resharding invariant
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["ARROYO_SANITIZE"] = "1"

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import perf
from arroyo_tpu.parallel.shuffle import RESHARDS
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
CREATE TABLE f1 (auction BIGINT, window_end BIGINT, num BIGINT) WITH (
  connector = 'memory', name = 'fw_a', type = 'sink');
CREATE TABLE f2 (auction BIGINT, window_end BIGINT, tot BIGINT) WITH (
  connector = 'memory', name = 'fw_b', type = 'sink');
INSERT INTO f1
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
INSERT INTO f2
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '4' SECOND) as window,
       sum(bid.price) AS tot
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
"""


def run(flag):
    os.environ["ARROYO_FACTOR_WINDOWS"] = flag
    prog = plan_sql(SQL)
    n_factor = sum(1 for nd in prog.nodes()
                   if nd.operator.kind.value == "window_factor")
    if flag == "auto" and n_factor != 1:
        sys.exit(f"smoke: factor pass did not share ({n_factor} factor "
                 "nodes; expected 1) — the gate would compare nothing")
    if flag == "0" and n_factor != 0:
        sys.exit("smoke: ARROYO_FACTOR_WINDOWS=0 still factored")
    clear_sink("fw_a")
    clear_sink("fw_b")
    runner = LocalRunner(prog)
    runner.run()
    san = runner.engine.sanitizer
    if san is None or san.violations:
        sys.exit(f"smoke: factor gate sanitizer problem (factor={flag}, "
                 f"violations={getattr(san, 'violations', None)})")
    out = []
    for name, cols in (("fw_a", ("auction", "window_end", "num")),
                       ("fw_b", ("auction", "window_end", "tot"))):
        out.append(sorted(
            tuple(int(b.columns[c][i]) for c in cols)
            for b in sink_output(name) for i in range(len(b))))
    return out


# shardcheck drift gate over the FACTORED plan: the factor->derived
# pane edges are exactly the handoff the static model verifies 1:1 —
# predicted must be 0 and the live counter must agree
from arroyo_tpu.analysis import shardcheck as _sc
from arroyo_tpu.parallel.mesh_window import mesh_key_shards

os.environ["ARROYO_FACTOR_WINDOWS"] = "auto"
rep = _sc.analyze(plan_sql(SQL), nk=mesh_key_shards())
if rep.errors():
    sys.exit("smoke: shardcheck rejected the factored smoke plan: "
             + "; ".join(d.render() for d in rep.errors()))

r0 = perf.counter(RESHARDS)
rows_on = run("auto")
rows_off = run("0")
os.environ.pop("ARROYO_FACTOR_WINDOWS", None)
if not rows_on[0] or not rows_on[1]:
    sys.exit("smoke: factored correlated-window query produced no output")
if rows_on != rows_off:
    sys.exit(f"smoke: factored output diverges from unfactored "
             f"({[len(r) for r in rows_on]} vs "
             f"{[len(r) for r in rows_off]} rows)")
reshards = perf.counter(RESHARDS) - r0
drift = _sc.drift_check(rep.predicted_reshards, reshards,
                        "factored correlated-window plan")
if drift is not None:
    sys.exit(f"smoke: {drift}")
print(f"smoke: factor-window equivalence ok "
      f"({len(rows_on[0])}+{len(rows_on[1])} identical rows, 1 shared "
      f"pane ring, predicted {rep.predicted_reshards} == observed "
      f"{reshards} reshards)")
PY

python - <<'PY'
# arroyosan gate: the SAME tiny Nexmark pipeline, chained, with the
# runtime sanitizer armed and periodic checkpoints driving the barrier
# protocol — it must complete with output and ZERO invariant violations
import os
import sys

os.environ["ARROYO_SANITIZE"] = "1"
os.environ["ARROYO_CHAIN"] = "1"

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""

clear_sink("results")
runner = LocalRunner(plan_sql(SQL))
runner.run(checkpoint_interval_secs=0.3)
rows = sum(len(b) for b in sink_output("results"))
if rows <= 0:
    sys.exit("smoke: sanitized nexmark produced no output")
san = runner.engine.sanitizer
if san is None:
    sys.exit("smoke: ARROYO_SANITIZE=1 did not arm the sanitizer")
if san.violations:
    sys.exit(f"smoke: sanitized run recorded {san.violations} "
             "invariant violation(s)")
from arroyo_tpu.analysis.sanitizer import recent_events

if not recent_events(1):
    sys.exit("smoke: sanitizer recorded no protocol events — the "
             "hook sites are not wired")
print(f"smoke: sanitized nexmark ok ({rows} rows, 0 violations)")
PY

python - <<'PY'
# phase-profiler gate: a tiny Nexmark run with the profiler armed must
# account for >=85% of wall time in named phases (unattributed_share <
# 0.15) with ZERO event-loop stalls — keeps the phase instrumentation
# honest as the engine evolves (an engine change that moves hot-path
# work outside the choke points shows up here as unattributed time)
import sys
import time

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import profiler
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '1200000',
  rate_limited = 'false', batch_size = '8192'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""
# 1.2M events (was 400k, was 50k): the vectorized ingest path keeps
# shortening the wall — one-time engine start/stop (~20-40ms, honestly
# not a phase) must stay a rounding error of the profiled window, and
# on a loaded/virtualized box 400k no longer dwarfed it — the gate
# measures STEADY-STATE attribution; still ~1-2s profiled

prog = plan_sql(SQL)
clear_sink("results")
LocalRunner(prog).run()  # warm: compiles stay out of the profiled run
prof = profiler.arm("local-job")
# best-of-2 attribution (same precedent as tests/test_profiler's
# best-of-N): one run on a loaded/virtualized box can carry scheduler
# gaps no phase legitimately owns — the gate checks the
# instrumentation's coverage, not the box's scheduling luck
best_unattributed, snap = None, None
for _ in range(2):
    prof.reset()
    clear_sink("results")
    t0 = time.perf_counter()
    LocalRunner(prog).run()
    wall = time.perf_counter() - t0
    s = prof.snapshot()
    u = max(1.0 - sum(s["phases"].values()) / wall, 0.0)
    if best_unattributed is None or u < best_unattributed:
        best_unattributed, snap = u, s
profiler.disarm()
if sum(len(b) for b in sink_output("results")) <= 0:
    sys.exit("smoke: profiled nexmark produced no output")
attributed = sum(snap["phases"].values())
unattributed = best_unattributed
if unattributed >= 0.15:
    sys.exit(f"smoke: profiler left {unattributed:.1%} of wall time "
             f"unattributed (phases: {snap['phases']})")
stalls = snap["watchdog"]["stalls"]
if stalls:
    sys.exit(f"smoke: watchdog recorded {stalls} event-loop stall(s): "
             f"{snap['watchdog']['recent_stalls']}")
print(f"smoke: profiler ok ({1.0 - unattributed:.1%} of wall attributed "
      f"across {len(snap['phases'])} phases, 0 stalls)")
PY

python - <<'PY'
import asyncio
import sys

from arroyo_tpu.autoscale import BacklogDrainPolicy, PolicyConfig
from arroyo_tpu.autoscale.sim import PolicySimulator, SimCluster, \
    SimOperator, ramp

# 1. simulator smoke: a sustained ramp must scale up ONLY the bottleneck
sim = PolicySimulator(
    BacklogDrainPolicy(PolicyConfig(interval_secs=10, up_sustain=2,
                                    up_cooldown_secs=30)),
    SimCluster([SimOperator("src", 1e9), SimOperator("agg", 10_000.0),
                SimOperator("sink", 1e9)]))
res = sim.run(ramp(5_000, 30_000, over_secs=60), steps=12)
ups = [d for d in res.actuations if d.action == "scale_up"]
if not ups:
    sys.exit("smoke: autoscaler simulator never scaled up on a ramp")
if {d.operator_id for d in ups} != {"agg"}:
    sys.exit(f"smoke: autoscaler scaled non-bottleneck operators: {ups}")
if sim.cluster.parallelism["src"] != 1 or sim.cluster.parallelism["sink"] != 1:
    sys.exit("smoke: autoscaler touched pinned-calm operators")

# 2. REST surface: GET/PUT round-trip against a live ApiServer
async def rest_check():
    import httpx

    from arroyo_tpu import Stream
    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer, Job
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    ctrl = ControllerServer(InProcessScheduler())
    await ctrl.start()
    api = ApiServer(ctrl)
    port = await api.start()
    prog = Stream.source("impulse", {"message_count": 10}).sink(
        "blackhole", {})
    ctrl.jobs["smoke"] = Job("smoke", prog, "file:///tmp/smoke-ckpt", 1)
    try:
        async with httpx.AsyncClient(
                base_url=f"http://127.0.0.1:{port}", timeout=10) as c:
            r = await c.get("/v1/jobs/smoke/autoscaler")
            assert r.status_code == 200, r.text
            assert r.json()["enabled"] is False
            r = await c.put("/v1/jobs/smoke/autoscaler",
                            json={"enabled": True,
                                  "policy": {"high_water": 0.6}})
            assert r.status_code == 200, r.text
            body = r.json()
            assert body["enabled"] and body["policy"]["high_water"] == 0.6
            r = await c.get("/v1/jobs/missing/autoscaler")
            assert r.status_code == 404
    finally:
        await api.stop()
        await ctrl.stop()

asyncio.run(rest_check())
print("smoke: autoscaler simulator + REST surface ok")
PY

python - <<'PY'
# latency-observatory gate: sampling armed + SLO configured on a
# sanitized tiny-Nexmark run — every sink must record sampled e2e
# latencies (stamps survived source -> coalesce -> window fire -> sink
# with the sanitizer proving no schema signature flipped), the
# critical path must attribute to a named stage, and the SLO verdict
# must round-trip through the REST surface
import asyncio
import os
import sys

os.environ["ARROYO_SANITIZE"] = "1"
os.environ["ARROYO_LATENCY_SAMPLE_N"] = "64"
os.environ["ARROYO_SLO_P99_MS"] = "60000"

from arroyo_tpu.config import reset_config

reset_config()

from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import LocalRunner
from arroyo_tpu.obs import latency, profiler
from arroyo_tpu.sql import plan_sql

SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '30000',
  rate_limited = 'false', batch_size = '2048',
  base_time_micros = '1700000000000000'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""

profiler.arm("local-job")  # folds compute/queue phases into the path
clear_sink("results")
runner = LocalRunner(plan_sql(SQL))
runner.run()
rows = sum(len(b) for b in sink_output("results"))
if rows <= 0:
    sys.exit("smoke: latency-gate nexmark produced no output")
san = runner.engine.sanitizer
if san is None or san.violations:
    sys.exit(f"smoke: latency gate sanitizer problem (violations="
             f"{getattr(san, 'violations', None)}) — the stamp side "
             "channel broke a runtime invariant")
lat = latency.active()
if lat is None:
    sys.exit("smoke: ARROYO_LATENCY_SAMPLE_N did not arm the "
             "observatory")
snap = lat.snapshot()
if snap["records_sampled"] <= 0:
    sys.exit("smoke: sources sampled no records")
sinks = snap["sinks"]
if not sinks or any(q["count"] < 1 for q in sinks.values()):
    sys.exit(f"smoke: a sink recorded no sampled e2e latency "
             f"(sinks={sinks}) — the stamp died in transit")
cp = snap["critical_path"]
if cp["total_secs"] <= 0 or not cp["dominant"]:
    sys.exit(f"smoke: critical path attributed nothing ({cp})")
profiler.disarm()


async def rest_check():
    import httpx

    from arroyo_tpu import Stream
    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import ControllerServer, Job
    from arroyo_tpu.controller.scheduler import InProcessScheduler

    ctrl = ControllerServer(InProcessScheduler())
    api = ApiServer(ctrl)
    port = await api.start()
    prog = Stream.source("impulse", {"message_count": 10}).sink(
        "blackhole", {})
    ctrl.jobs["smoke"] = Job("smoke", prog, "file:///tmp/smoke-ckpt", 1)
    job = ctrl.jobs["smoke"]
    assert job.slo.configured(), "env SLO did not seed the job"
    try:
        async with httpx.AsyncClient(
                base_url=f"http://127.0.0.1:{port}", timeout=10) as c:
            r = await c.get("/v1/jobs/smoke/slo")
            assert r.status_code == 200, r.text
            assert r.json()["slo"]["p99_ms"] == 60000.0
            r = await c.put("/v1/jobs/smoke/slo",
                            json={"p99_ms": 0.25})
            assert r.status_code == 200, r.text
            job.slo_eval.evaluate(1.0, None)  # 1ms > 0.25ms: violates
            r = await c.get("/v1/jobs/smoke/slo")
            body = r.json()
            assert body["last"]["violating"], body
            assert body["violations_total"] == 1, body
            r = await c.get("/v1/jobs/smoke/latency")
            assert r.status_code == 200, r.text
            data = r.json()
            assert data["slo"]["last"]["violating"], data
            assert "critical_path" in data and "sinks" in data
    finally:
        await api.stop()


asyncio.run(rest_check())
latency.disarm()
for k in ("ARROYO_LATENCY_SAMPLE_N", "ARROYO_SLO_P99_MS"):
    os.environ.pop(k, None)
sink_stats = "; ".join(
    f"{op}: p50={q['p50_ms']}ms p99={q['p99_ms']}ms n={int(q['count'])}"
    for op, q in sinks.items())
print(f"smoke: latency observatory ok ({snap['records_sampled']} "
      f"sampled of {snap['records_seen']} records; {sink_stats}; "
      f"dominant stage {cp['dominant']} "
      f"{cp['dominant_share']:.0%}; SLO REST round-trip ok)")
PY

exec python -m pytest tests/test_obs.py tests/test_profiler.py \
    tests/test_latency.py -q \
    -p no:cacheprovider
