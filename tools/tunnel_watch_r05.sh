#!/bin/bash
# Round-5 tunnel watcher.  Probe the axon tunnel every ~7 min; whenever
# it is alive, run the full bench and land the artifact at the repo
# root (BENCH_TPU_FULL_r05.json) so the driver's end-of-round
# auto-commit captures it.  Unlike the r04 watcher this one does NOT
# exit after the first success: a later capture carries a later git
# sha (more optimizer work), so we re-capture at most once every
# RECAP_SECS while the tunnel stays up, keeping the newest artifact.
# Every capture also snapshots to a timestamped file in /tmp for
# forensics.  A "hold" file (/tmp/bench_hold) pauses capture while the
# builder needs the single CPU core for clean same-box measurements.
cd /root/repo
RECAP_SECS=${RECAP_SECS:-4800}
last_ok=0
for i in $(seq 1 400); do
  if [ -f /tmp/bench_hold ]; then
    echo "attempt $i held $(date)" >> /tmp/tunnel_watch.log
    sleep 300
    continue
  fi
  now=$(date +%s)
  if [ $((now - last_ok)) -lt "$RECAP_SECS" ]; then
    sleep 300
    continue
  fi
  if timeout 90 python -c "import jax; assert jax.default_backend() == 'tpu'" 2>/dev/null; then
    echo "tunnel alive at attempt $i, $(date)" >> /tmp/tunnel_watch.log
    tmp=$(mktemp /tmp/bench_r05.XXXXXX)
    timeout 3600 python bench.py > "$tmp" 2>/tmp/bench_r05_tpu.err
    rc=$?
    echo "bench rc=$rc at $(date)" >> /tmp/tunnel_watch.log
    if [ $rc -eq 0 ] && python -c "
import json,sys
d=json.load(open(sys.argv[1]))
assert d.get('backend')=='tpu', 'not a tpu capture'
" "$tmp" 2>>/tmp/tunnel_watch.log; then
      cp "$tmp" "/tmp/bench_tpu_$(date +%s).json"
      mv "$tmp" /root/repo/BENCH_TPU_FULL_r05.json
      last_ok=$(date +%s)
      echo "captured BENCH_TPU_FULL_r05.json at $(date)" >> /tmp/tunnel_watch.log
    else
      rm -f "$tmp"
    fi
  else
    echo "attempt $i down $(date)" >> /tmp/tunnel_watch.log
  fi
  sleep 400
done
