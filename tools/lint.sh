#!/usr/bin/env bash
# lint.sh — arroyolint gate: zero unwaived static-analysis findings.
#
# Runs every arroyolint pass (checkpoint-state arity, blocking-calls-
# in-async, implicit host-device syncs, trace purity, proto drift) over
# the package and fails on any finding that is neither inline-waived
# (# arroyolint: disable=<pass> -- reason) nor accepted in
# tools/arroyolint_baseline.json.  Wired into tools/smoke.sh so the
# <60s pre-snapshot gate rejects the round-5 bug class before a commit
# lands.
#
# Usage: tools/lint.sh [extra arroyolint args]
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
exec python -m arroyo_tpu.analysis "$@"
