#!/usr/bin/env bash
# lint.sh — arroyolint gate: zero unwaived static-analysis findings.
#
# Runs every arroyolint pass over the package and fails on any finding
# that is neither inline-waived (# arroyolint: disable=<pass> --
# reason) nor accepted in tools/arroyolint_baseline.json.  shardcheck
# (plan-time sharding & transfer verification: the route-shift wiring
# audit + a representative-plan sweep that must predict 0 reshards)
# and recompile-hazard (jit cache-key hazards in ops/ and parallel/)
# run FIRST — a sharding-contract or compile-storm regression
# invalidates every number the later invariants protect; then
# checkpoint-state arity, blocking-calls-in-async, implicit
# host-device syncs, trace purity, proto drift, per-row serde loops,
# the arroyosan await-point race detector and the barrier/watermark
# protocol checker.  Wired into tools/smoke.sh so the pre-snapshot
# gate rejects the round-5 bug class (and the PR 3 await-race class,
# and the PR 9 funnel class) before a commit lands.
#
# The baseline is a ratchet: burned down 57 -> 16 -> 0 — every
# accepted finding is now a reasoned inline waiver at its site, and
# --max-baseline 0 keeps it that way: new findings must be fixed or
# inline-waived with a reason, never silently accepted.
#
# Usage: tools/lint.sh [extra arroyolint args]
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
exec python -m arroyo_tpu.analysis --max-baseline 0 "$@"
