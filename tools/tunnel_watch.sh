#!/bin/bash
cd /root/repo
for i in $(seq 1 120); do
  if timeout 90 python -c "import jax; assert jax.default_backend() == 'tpu'" 2>/dev/null; then
    echo "tunnel alive at attempt $i, $(date)" >> /tmp/tunnel_watch.log
    timeout 3000 python bench.py > /root/repo/BENCH_TPU_FUSED_r04.json 2>/tmp/bench_fused_tpu.err
    rc=$?
    echo "bench rc=$rc at $(date)" >> /tmp/tunnel_watch.log
    if [ $rc -ne 0 ]; then rm -f /root/repo/BENCH_TPU_FUSED_r04.json; continue; fi
    exit 0
  fi
  echo "attempt2 $i down $(date)" >> /tmp/tunnel_watch.log
  sleep 400
done
exit 1
