#!/bin/bash
# Probe the axon tunnel every ~7 min for the rest of the round; on
# recovery run the full bench ONCE and land the artifact in the repo
# root so the driver's end-of-round auto-commit captures it.  The
# artifact is written to a temp path and moved into place only on
# success, so a killed or failed run can never leave a partial JSON
# that reads as a genuine capture; bench failures back off like probe
# failures instead of burning the attempt budget.
cd /root/repo
for i in $(seq 1 120); do
  if timeout 90 python -c "import jax; assert jax.default_backend() == 'tpu'" 2>/dev/null; then
    echo "tunnel alive at attempt $i, $(date)" >> /tmp/tunnel_watch.log
    tmp=$(mktemp /tmp/bench_fused.XXXXXX)
    timeout 3000 python bench.py > "$tmp" 2>/tmp/bench_fused_tpu.err
    rc=$?
    echo "bench rc=$rc at $(date)" >> /tmp/tunnel_watch.log
    if [ $rc -eq 0 ] && python -c "import json,sys; json.load(open(sys.argv[1]))" "$tmp" 2>/dev/null; then
      mv "$tmp" /root/repo/BENCH_TPU_FUSED_r04.json
      exit 0
    fi
    rm -f "$tmp"
  else
    echo "attempt $i down $(date)" >> /tmp/tunnel_watch.log
  fi
  sleep 400
done
exit 1
