"""Observability: metric names/labels parity, admin server endpoints,
logfmt JSON logging."""

import asyncio
import json
import logging

import httpx
import pytest

from arroyo_tpu.obs.admin import AdminServer
from arroyo_tpu.obs.logging_setup import LogfmtJsonFormatter, init_logging
from arroyo_tpu.obs.metrics import (REGISTRY, TaskMetrics, render_metrics,
                                    snapshot)
from arroyo_tpu.types import TaskInfo


def _ti(idx=0):
    return TaskInfo("job-m", "op-1", "window-agg", idx, 2)


def test_metric_names_match_reference():
    m = TaskMetrics(_ti())
    m.messages_recv.inc(10)
    m.messages_sent.inc(4)
    m.bytes_sent.inc(100)
    m.tx_queue_size.set(4096)
    text = render_metrics().decode()
    # exact names from arroyo-types/src/lib.rs:734-739
    for name in ("arroyo_worker_messages_recv",
                 "arroyo_worker_messages_sent",
                 "arroyo_worker_bytes_recv",
                 "arroyo_worker_bytes_sent",
                 "arroyo_worker_tx_queue_size",
                 "arroyo_worker_tx_queue_rem"):
        assert name in text, name
    # labels from TaskInfo::metric_label_map (lib.rs:579-585)
    assert 'operator_id="op-1"' in text
    assert 'subtask_idx="0"' in text
    assert 'operator_name="window-agg"' in text


def test_engine_run_populates_metrics():
    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import LocalRunner

    prog = (Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 300,
                                      "batch_size": 64})
            .map(lambda c: {"counter": c["counter"]}, name="m")
            .sink("blackhole", {}))
    LocalRunner(prog).run()
    snap = snapshot()
    recv = {k: v for k, v in snap.items()
            if k.startswith("arroyo_worker_messages_recv")}
    # map + sink subtasks each count 300 records received
    assert any(v >= 300 for v in recv.values()), snap


def test_admin_server_endpoints():
    async def scenario():
        admin = AdminServer("worker", details=lambda: {"tasks": 3})
        port = await admin.start()
        async with httpx.AsyncClient(
                base_url=f"http://127.0.0.1:{port}") as c:
            r = await c.get("/status")
            assert r.json()["status"] == "ok"
            assert r.json()["service"] == "arroyo-worker"
            r = await c.get("/name")
            assert r.text == "arroyo-worker"
            r = await c.get("/details")
            assert r.json()["details"] == {"tasks": 3}
            r = await c.get("/metrics")
            assert r.status_code == 200
            assert "arroyo_worker" in r.text
        await admin.stop()

    asyncio.new_event_loop().run_until_complete(scenario())


def test_logfmt_json_formatter():
    fmt = LogfmtJsonFormatter()
    rec = logging.LogRecord("arroyo.engine", logging.WARNING, "f.py", 1,
                            "task %s failed", ("op-1",), None)
    rec.job_id = "j1"
    out = json.loads(fmt.format(rec))
    assert out["level"] == "warning"
    assert out["message"] == "task op-1 failed"
    assert out["target"] == "arroyo.engine"
    assert out["job_id"] == "j1"
    assert out["ts"].endswith("Z")


def test_init_logging_sets_excepthook(monkeypatch):
    import sys

    old = sys.excepthook
    try:
        init_logging("test-svc")
        assert sys.excepthook is not old  # panic hook installed
    finally:
        sys.excepthook = old


def test_admin_profile_capture():
    """POST /debug/profile captures a jax profiler (Perfetto) trace — the
    pyroscope continuous-profiling analog."""
    import asyncio
    import urllib.request
    import json as _json

    import jax.numpy as jnp

    from arroyo_tpu.obs.admin import AdminServer

    async def scenario(tmp):
        admin = AdminServer("test")
        port = await admin.start()

        async def work():
            # some device work inside the profiling window
            for _ in range(20):
                (jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()
                await asyncio.sleep(0.01)

        async def capture():
            loop = asyncio.get_event_loop()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/debug/profile",
                data=_json.dumps({"seconds": 0.5, "dir": tmp}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            return await loop.run_in_executor(
                None, lambda: _json.loads(
                    urllib.request.urlopen(req, timeout=30).read()))

        _, resp = await asyncio.gather(work(), capture())
        await admin.stop()
        return resp

    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        resp = asyncio.run(scenario(tmp))
    assert resp["traces"], f"no trace files captured: {resp}"


def test_tx_queue_gauges_wired():
    """Backpressure visibility: the collector keeps the tx-queue
    capacity/remaining gauges current (round-1 gap: gauges existed but
    were never set)."""
    from arroyo_tpu import Stream
    from arroyo_tpu.connectors.memory import clear_sink
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs.metrics import snapshot
    import numpy as np
    from arroyo_tpu.types import Batch

    clear_sink("qg")
    ts = np.arange(500, dtype=np.int64)
    prog = (Stream.source("memory", {"batches": [
                Batch(ts, {"v": ts.copy()})]})
            .map(lambda c: {"v": c["v"]}, name="m")
            .sink("memory", {"name": "qg"}))
    LocalRunner(prog).run()
    snap = snapshot()
    sizes = {k: v for k, v in snap.items()
             if k.startswith("arroyo_worker_tx_queue_size")}
    rems = {k: v for k, v in snap.items()
            if k.startswith("arroyo_worker_tx_queue_rem")}
    assert any(v > 0 for v in sizes.values()), sizes
    assert any(v > 0 for v in rems.values()), rems


def test_table_size_gauge_updates_at_checkpoint(tmp_path):
    """arroyo_worker_table_size_keys (the reference's per-table state-size
    gauge) reflects key counts after a checkpoint barrier."""
    import asyncio

    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.obs.metrics import snapshot
    from arroyo_tpu.types import StopMode

    prog = (Stream.source("impulse", {"event_rate": 50_000.0,
                                      "message_count": 50_000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 512})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 9}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("blackhole", {}))

    async def run():
        eng = Engine.for_local(prog, "gauge-job",
                               checkpoint_url=f"file://{tmp_path}/ck")
        running = eng.start()
        await asyncio.sleep(0.1)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        vals = snapshot("arroyo_worker_table_size_keys")
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass
        return vals

    vals = asyncio.run(run())
    assert vals, "no table-size gauges recorded"
    assert any(v > 0 for v in vals.values())
