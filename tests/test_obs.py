"""Observability: metric names/labels parity, admin server endpoints,
logfmt JSON logging."""

import asyncio
import json
import logging

import httpx
import pytest

from arroyo_tpu.obs.admin import AdminServer
from arroyo_tpu.obs.logging_setup import LogfmtJsonFormatter, init_logging
from arroyo_tpu.obs.metrics import (REGISTRY, TaskMetrics, render_metrics,
                                    snapshot)
from arroyo_tpu.types import TaskInfo


def _ti(idx=0):
    return TaskInfo("job-m", "op-1", "window-agg", idx, 2)


def test_metric_names_match_reference():
    m = TaskMetrics(_ti())
    m.messages_recv.inc(10)
    m.messages_sent.inc(4)
    m.bytes_sent.inc(100)
    m.tx_queue_size.set(4096)
    text = render_metrics().decode()
    # exact names from arroyo-types/src/lib.rs:734-739
    for name in ("arroyo_worker_messages_recv",
                 "arroyo_worker_messages_sent",
                 "arroyo_worker_bytes_recv",
                 "arroyo_worker_bytes_sent",
                 "arroyo_worker_tx_queue_size",
                 "arroyo_worker_tx_queue_rem"):
        assert name in text, name
    # labels from TaskInfo::metric_label_map (lib.rs:579-585)
    assert 'operator_id="op-1"' in text
    assert 'subtask_idx="0"' in text
    assert 'operator_name="window-agg"' in text


def test_engine_run_populates_metrics():
    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import LocalRunner

    prog = (Stream.source("impulse", {"event_rate": 0.0,
                                      "message_count": 300,
                                      "batch_size": 64})
            .map(lambda c: {"counter": c["counter"]}, name="m")
            .sink("blackhole", {}))
    LocalRunner(prog).run()
    snap = snapshot()
    recv = {k: v for k, v in snap.items()
            if k.startswith("arroyo_worker_messages_recv")}
    # map + sink subtasks each count 300 records received
    assert any(v >= 300 for v in recv.values()), snap


def test_admin_server_endpoints():
    async def scenario():
        admin = AdminServer("worker", details=lambda: {"tasks": 3})
        port = await admin.start()
        async with httpx.AsyncClient(
                base_url=f"http://127.0.0.1:{port}") as c:
            r = await c.get("/status")
            assert r.json()["status"] == "ok"
            assert r.json()["service"] == "arroyo-worker"
            r = await c.get("/name")
            assert r.text == "arroyo-worker"
            r = await c.get("/details")
            assert r.json()["details"] == {"tasks": 3}
            r = await c.get("/metrics")
            assert r.status_code == 200
            assert "arroyo_worker" in r.text
        await admin.stop()

    asyncio.new_event_loop().run_until_complete(scenario())


def test_logfmt_json_formatter():
    fmt = LogfmtJsonFormatter()
    rec = logging.LogRecord("arroyo.engine", logging.WARNING, "f.py", 1,
                            "task %s failed", ("op-1",), None)
    rec.job_id = "j1"
    out = json.loads(fmt.format(rec))
    assert out["level"] == "warning"
    assert out["message"] == "task op-1 failed"
    assert out["target"] == "arroyo.engine"
    assert out["job_id"] == "j1"
    assert out["ts"].endswith("Z")


def test_init_logging_sets_excepthook(monkeypatch):
    import sys

    old = sys.excepthook
    try:
        init_logging("test-svc")
        assert sys.excepthook is not old  # panic hook installed
    finally:
        sys.excepthook = old


@pytest.mark.slow
def test_admin_profile_capture():
    """POST /debug/profile captures a jax profiler (Perfetto) trace — the
    pyroscope continuous-profiling analog."""
    import asyncio
    import urllib.request
    import json as _json

    import jax.numpy as jnp

    from arroyo_tpu.obs.admin import AdminServer

    async def scenario(tmp):
        admin = AdminServer("test")
        port = await admin.start()

        async def work():
            # some device work inside the profiling window
            for _ in range(20):
                (jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()
                await asyncio.sleep(0.01)

        async def capture():
            loop = asyncio.get_event_loop()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/debug/profile",
                data=_json.dumps({"seconds": 0.5, "dir": tmp}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            return await loop.run_in_executor(
                None, lambda: _json.loads(
                    urllib.request.urlopen(req, timeout=30).read()))

        _, resp = await asyncio.gather(work(), capture())
        await admin.stop()
        return resp

    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        resp = asyncio.run(scenario(tmp))
    assert resp["traces"], f"no trace files captured: {resp}"


def test_tx_queue_gauges_wired():
    """Backpressure visibility: the collector keeps the tx-queue
    capacity/remaining gauges current (round-1 gap: gauges existed but
    were never set)."""
    from arroyo_tpu import Stream
    from arroyo_tpu.connectors.memory import clear_sink
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.obs.metrics import snapshot
    import numpy as np
    from arroyo_tpu.types import Batch

    clear_sink("qg")
    ts = np.arange(500, dtype=np.int64)
    prog = (Stream.source("memory", {"batches": [
                Batch(ts, {"v": ts.copy()})]})
            .map(lambda c: {"v": c["v"]}, name="m")
            .sink("memory", {"name": "qg"}))
    LocalRunner(prog).run()
    snap = snapshot()
    sizes = {k: v for k, v in snap.items()
             if k.startswith("arroyo_worker_tx_queue_size")}
    rems = {k: v for k, v in snap.items()
            if k.startswith("arroyo_worker_tx_queue_rem")}
    assert any(v > 0 for v in sizes.values()), sizes
    assert any(v > 0 for v in rems.values()), rems


def test_table_size_gauge_updates_at_checkpoint(tmp_path):
    """arroyo_worker_table_size_keys (the reference's per-table state-size
    gauge) reflects key counts after a checkpoint barrier."""
    import asyncio

    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.obs.metrics import snapshot
    from arroyo_tpu.types import StopMode

    prog = (Stream.source("impulse", {"event_rate": 50_000.0,
                                      "message_count": 50_000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 512})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 9}, name="b")
            .key_by("bucket")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("blackhole", {}))

    async def run():
        eng = Engine.for_local(prog, "gauge-job",
                               checkpoint_url=f"file://{tmp_path}/ck")
        running = eng.start()
        await asyncio.sleep(0.1)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        vals = snapshot("arroyo_worker_table_size_keys")
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass
        return vals

    vals = asyncio.run(run())
    assert vals, "no table-size gauges recorded"
    assert any(v > 0 for v in vals.values())


# ---------------------------------------------------------------------------
# flight recorder: lag/latency histograms, trace spans, checkpoint cost
# ---------------------------------------------------------------------------


def test_lag_and_latency_histograms_populated():
    """The per-operator flight-recorder histograms (event-time lag,
    watermark lag, batch latency, queue wait) fill in during a normal
    watermarked run."""
    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import LocalRunner

    prog = (Stream.source("impulse", {"event_rate": 100_000.0,
                                      "message_count": 20_000,
                                      "event_time_interval_micros": 100,
                                      "batch_size": 512})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"]}, name="lagmap")
            .sink("blackhole", {}))
    LocalRunner(prog).run()
    snap = snapshot()

    def count_of(metric):
        return sum(v for k, v in snap.items()
                   if k.startswith(metric + "_count"))

    for metric in ("arroyo_worker_event_time_lag_seconds",
                   "arroyo_worker_watermark_lag_seconds",
                   "arroyo_worker_batch_processing_seconds",
                   "arroyo_worker_queue_wait_seconds"):
        assert count_of(metric) > 0, (metric, sorted(snap)[:40])
    # histograms render with reference-compatible names + labels
    text = render_metrics().decode()
    assert 'arroyo_worker_event_time_lag_seconds_bucket{' in text
    assert 'operator_name=' in text


def test_admin_trace_endpoint_serves_chrome_trace():
    """GET /trace returns Chrome-trace JSON (Perfetto-loadable): ph=X
    complete events with ts/dur microseconds, filterable by category."""
    from arroyo_tpu.obs import tracing

    async def scenario():
        tracing.reset()
        with tracing.span("checkpoint.sync", "checkpoint", tid="op-1-0",
                          args={"epoch": 3}):
            pass
        with tracing.span("kernel", "kernel", tid="op-2-0"):
            pass
        admin = AdminServer("worker")
        port = await admin.start()
        async with httpx.AsyncClient(
                base_url=f"http://127.0.0.1:{port}") as c:
            r = await c.get("/trace")
            assert r.status_code == 200
            doc = r.json()
            evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
            names = {e["name"] for e in evs}
            assert {"checkpoint.sync", "kernel"} <= names
            ck = next(e for e in evs if e["name"] == "checkpoint.sync")
            assert ck["args"]["epoch"] == 3
            assert ck["tid"] == "op-1-0"
            assert ck["dur"] >= 0 and ck["ts"] > 0
            # category filter
            r = await c.get("/trace", params={"cat": "kernel"})
            names = {e["name"] for e in r.json()["traceEvents"]
                     if e["ph"] == "X"}
            assert names == {"kernel"}
        await admin.stop()

    asyncio.new_event_loop().run_until_complete(scenario())


def test_checkpoint_metrics_and_spans(tmp_path):
    """After a checkpointed run: per-subtask checkpoint duration/bytes
    histogram samples, per-table cost gauges, and checkpoint trace spans
    all appear."""
    from arroyo_tpu import Stream
    from arroyo_tpu.engine.engine import Engine
    from arroyo_tpu.graph.logical import AggKind, AggSpec
    from arroyo_tpu.obs import tracing
    from arroyo_tpu.types import StopMode

    tracing.reset()
    prog = (Stream.source("impulse", {"event_rate": 50_000.0,
                                      "message_count": 50_000,
                                      "event_time_interval_micros": 1000,
                                      "batch_size": 512})
            .watermark(max_lateness_micros=0)
            .map(lambda c: {"counter": c["counter"],
                            "bucket": c["counter"] % 9}, name="ckb")
            .key_by("bucket")
            .tumbling_aggregate(1_000_000,
                                [AggSpec(AggKind.COUNT, None, "cnt")])
            .sink("blackhole", {}))

    async def run():
        eng = Engine.for_local(prog, "ckpt-metrics-job",
                               checkpoint_url=f"file://{tmp_path}/ck")
        running = eng.start()
        await asyncio.sleep(0.1)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    asyncio.new_event_loop().run_until_complete(run())
    snap = snapshot()
    dur = {k: v for k, v in snap.items()
           if k.startswith("arroyo_worker_checkpoint_duration_seconds_count")
           and "ckpt-metrics-job" in k}
    assert any(v > 0 for v in dur.values()), sorted(snap)[:40]
    tbl = snapshot("arroyo_worker_checkpoint_table_bytes")
    assert any(v > 0 and "ckpt-metrics-job" in k for k, v in tbl.items()), tbl
    cats = {s[0] for s in tracing.spans("checkpoint")}
    assert "checkpoint.sync" in cats
    assert "checkpoint.table" in cats


def test_kernel_time_attributed_per_operator():
    """timed_device dispatch time lands in the active task's
    arroyo_worker_kernel_seconds_total counter (the always-cheap
    per-operator accumulator generalizing ARROYO_TIMING)."""
    from arroyo_tpu.obs import perf

    ti = TaskInfo("kacc-job", "op-k", "kernels", 0, 1)
    tm = TaskMetrics(ti)
    acc = perf.KernelAccumulator(ti, tm)
    token = perf.set_active_task(acc)
    try:
        out = perf.timed_device(lambda x: x * 2, 21)
    finally:
        perf.reset_active_task(token)
    assert out == 42
    vals = {k: v for k, v in snapshot(
        "arroyo_worker_kernel_seconds").items()
        if "kacc-job" in k and "_total" in k}
    assert any(v > 0 for v in vals.values()), vals


def test_controller_job_rollup_aggregates_heartbeat_snapshots():
    """The controller folds per-worker heartbeat summaries into job-level
    per-operator rollups: counters sum across workers, rates come from
    sample deltas, lag is worst-across-workers, backpressure from the
    queue gauges."""
    from arroyo_tpu import Stream
    from arroyo_tpu.controller.controller import (ControllerServer, Job,
                                                  WorkerInfo)

    prog = (Stream.source("impulse", {"event_rate": 1.0,
                                      "message_count": 1})
            .sink("blackhole", {}))
    ctrl = ControllerServer.__new__(ControllerServer)  # no sockets needed
    ctrl.jobs = {}
    job = Job("rj", prog, "file:///tmp/x", 1)
    ctrl.jobs["rj"] = job
    w = WorkerInfo("w0", "", "", 1)
    w.prev_snapshot = {"opA": {"messages_sent_total": 100.0,
                               "event_time_lag_seconds_sum": 1.0,
                               "event_time_lag_seconds_count": 10.0}}
    w.prev_time = 100.0
    w.metric_snapshot = {"opA": {"messages_sent_total": 300.0,
                                 "messages_recv_total": 300.0,
                                 "event_time_lag_seconds_sum": 3.0,
                                 "event_time_lag_seconds_count": 20.0,
                                 "tx_queue_size": 100.0,
                                 "tx_queue_rem": 25.0,
                                 "kernel_seconds_total": 1.5}}
    w.snapshot_time = 102.0
    w2 = WorkerInfo("w1", "", "", 1)
    w2.metric_snapshot = {"opA": {"messages_sent_total": 50.0,
                                  "event_time_lag_seconds_sum": 50.0,
                                  "event_time_lag_seconds_count": 10.0}}
    w2.snapshot_time = 102.0
    job.workers = {"w0": w, "w1": w2}
    (agg,) = ctrl.job_rollup("rj")
    assert agg["operator_id"] == "opA"
    assert agg["workers"] == 2
    assert agg["messages_sent"] == 350.0
    assert agg["records_per_sec"] == pytest.approx(100.0)  # (300-100)/2s
    # worst lag across workers: w0's window avg 0.2s vs w1's lifetime 5s
    assert agg["event_time_lag"] == pytest.approx(5.0)
    assert agg["backpressure"] == pytest.approx(0.75)
    assert agg["kernel_seconds"] == pytest.approx(1.5)


def test_job_rollup_lag_is_worst_subtask_not_worker_average():
    """Workers ship per-subtask histogram pairs (`fam_sum@idx`) so the
    rollup reports the worst co-located subtask, not the worker-wide
    average that would hide one hot subtask among idle siblings."""
    from arroyo_tpu import Stream
    from arroyo_tpu.controller.controller import (ControllerServer, Job,
                                                  WorkerInfo)

    prog = (Stream.source("impulse", {"event_rate": 1.0,
                                      "message_count": 1})
            .sink("blackhole", {}))
    ctrl = ControllerServer.__new__(ControllerServer)
    ctrl.jobs = {}
    job = Job("rj2", prog, "file:///tmp/x", 1)
    ctrl.jobs["rj2"] = job
    w = WorkerInfo("w0", "", "", 1)
    # one worker hosting 4 subtasks: three at 0.1s avg lag, one at 60s.
    # The flat worker-summed pair averages to ~15.1s; the per-subtask
    # pairs must surface 60s.
    snap = {"event_time_lag_seconds_sum": 60.3,
            "event_time_lag_seconds_count": 4.0,
            # one subtask saturated (rem 0), three idle: summed gauges
            # say backpressure 0.25, worst subtask says 1.0
            "tx_queue_size": 400.0, "tx_queue_rem": 300.0}
    for i, (s, c) in enumerate([(0.1, 1.0), (0.1, 1.0), (0.1, 1.0),
                                (60.0, 1.0)]):
        snap[f"event_time_lag_seconds_sum@{i}"] = s
        snap[f"event_time_lag_seconds_count@{i}"] = c
        snap[f"tx_queue_size@{i}"] = 100.0
        snap[f"tx_queue_rem@{i}"] = 0.0 if i == 3 else 100.0
    w.metric_snapshot = {"opA": snap}
    w.snapshot_time = 102.0
    job.workers = {"w0": w}
    (agg,) = ctrl.job_rollup("rj2")
    assert agg["event_time_lag"] == pytest.approx(60.0)
    assert agg["backpressure"] == pytest.approx(1.0)
    assert "_bp_worst" not in agg

    # legacy/flat payloads (no @ keys) still roll up via the summed pair
    w.metric_snapshot = {"opA": {"event_time_lag_seconds_sum": 60.3,
                                 "event_time_lag_seconds_count": 4.0}}
    (agg,) = ctrl.job_rollup("rj2")
    assert agg["event_time_lag"] == pytest.approx(60.3 / 4.0)


def test_job_operator_summary_ships_per_subtask_lag_pairs():
    """The heartbeat summary carries per-subtask `_sum@idx/_count@idx`
    pairs for the lag/latency families alongside the worker-summed flat
    pair (which bench.py and legacy consumers keep reading)."""
    from arroyo_tpu.obs.metrics import job_operator_summary

    TaskMetrics(TaskInfo("subjob", "opS", "opS", 0, 2)) \
        .event_time_lag.observe(0.1)
    TaskMetrics(TaskInfo("subjob", "opS", "opS", 1, 2)) \
        .event_time_lag.observe(60.0)
    g = job_operator_summary("subjob")["opS"]
    assert g["event_time_lag_seconds_count"] == pytest.approx(2.0)
    assert g["event_time_lag_seconds_sum"] == pytest.approx(60.1)
    assert g["event_time_lag_seconds_sum@0"] == pytest.approx(0.1)
    assert g["event_time_lag_seconds_sum@1"] == pytest.approx(60.0)
    assert g["event_time_lag_seconds_count@1"] == pytest.approx(1.0)
