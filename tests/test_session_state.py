"""Device-resident session window state (state/session_state.py, PR 19).

The correctness spine the ISSUE names:

- sanitized device-vs-legacy parity: identical rows out of the session
  operator under both state layouts, on fuzzed multi-batch streams;
- the max-session clamp falls back per key to the authoritative host
  merge — bit-for-bit with legacy (the union-span>MAX condition is
  EXACTLY the legacy clamp condition, ops/session.py docstring);
- state stays bounded under session churn (expire mask-compresses rows
  out; nothing leaks);
- checkpoint interchange: both layouts snapshot as the same KEYED
  ``[(time, key, sessions)]`` entries, so epochs restore legacy->device
  and device->legacy, and rescale's key-range entry filter applies
  (2 -> 3 split emulated at the table level + a full engine
  crash/restore flip in both directions);
- the vectorized interval-union kernel agrees with a brute-force
  oracle on fuzzed inputs.
"""

import asyncio
import json
import os

import numpy as np
import pytest

from arroyo_tpu import AggKind, AggSpec, Batch, SessionWindow, Stream
from arroyo_tpu.connectors.memory import clear_sink, sink_output
from arroyo_tpu.engine.engine import Engine, LocalRunner
from arroyo_tpu.obs import perf
from arroyo_tpu.state.session_state import SessionRunState
from arroyo_tpu.state.tables import KeyedState
from arroyo_tpu.types import StopMode

MS = 1_000
SEC = 1_000_000

AGGS = [AggSpec(AggKind.COUNT, None, "cnt"),
        AggSpec(AggKind.SUM, "v", "total"),
        AggSpec(AggKind.MIN, "v", "lo"),
        AggSpec(AggKind.MAX, "v", "hi"),
        AggSpec(AggKind.AVG, "v", "mean")]


def _run_sessions(batches, mode, gap=300 * MS, aggs=AGGS, sink="ss_out"):
    """Run the session pipeline with ARROYO_SESSION_STATE=mode; the
    sanitizer is armed by conftest for every run."""
    prev = os.environ.get("ARROYO_SESSION_STATE")
    os.environ["ARROYO_SESSION_STATE"] = mode
    try:
        clear_sink(sink)
        prog = (Stream.source("memory", {"batches": batches})
                .watermark(max_lateness_micros=0)
                .key_by("k")
                .window(SessionWindow(gap), aggs)
                .sink("memory", {"name": sink}))
        LocalRunner(prog).run()
        outs = sink_output(sink)
        return Batch.concat(outs) if outs else None
    finally:
        if prev is None:
            os.environ.pop("ARROYO_SESSION_STATE", None)
        else:
            os.environ["ARROYO_SESSION_STATE"] = prev


def _rows(out):
    if out is None:
        return []
    names = sorted(out.columns)
    return sorted(
        tuple(round(float(out.columns[c][i]), 9) for c in names)
        for i in range(len(out)))


def _session_batches(rng, n_batches=4, n=1200, n_keys=40, span=4 * SEC):
    """Bursty per-key event times so sessions both merge and close."""
    batches = []
    t0 = 0
    for _ in range(n_batches):
        ts = np.sort(rng.integers(t0, t0 + span, n)).astype(np.int64)
        batches.append(Batch(ts, {
            "k": rng.integers(0, n_keys, n).astype(np.int64),
            "v": rng.integers(1, 100, n).astype(np.int64)}))
        t0 += span + rng.integers(0, SEC)
    return batches


def test_device_vs_legacy_parity_fuzz(rng):
    """The acceptance spine: identical rows out of the session operator
    under device sorted-run state vs the legacy per-key dict path, with
    the sanitizer armed, on a fuzzed multi-batch stream."""
    batches = _session_batches(rng)
    dev = _run_sessions(batches, "device")
    leg = _run_sessions(batches, "legacy")
    assert dev is not None and leg is not None
    assert _rows(dev) == _rows(leg)
    assert len(dev) > 50  # non-vacuous: real session churn happened


def test_device_parity_single_key_dense(rng):
    """One hot key with dense timestamps: maximal interval-merge work
    per dispatch (every batch touches the same resident run)."""
    batches = []
    t0 = 0
    for _ in range(3):
        ts = np.sort(rng.integers(t0, t0 + 2 * SEC, 500)).astype(np.int64)
        batches.append(Batch(ts, {"k": np.zeros(500, np.int64),
                                  "v": np.ones(500, np.int64)}))
        t0 += 3 * SEC  # gap > session gap: prior session closes
    dev = _run_sessions(batches, "device")
    leg = _run_sessions(batches, "legacy")
    assert _rows(dev) == _rows(leg)
    assert len(dev) >= 3


def test_clamp_fallback_parity_and_counted(rng):
    """Events chaining past MAX_SESSION_SIZE route through the per-key
    host fallback (the union-span>MAX flag) and must match legacy
    bit-for-bit; the fallback is COUNTED (session_host_merge_rows), so
    a config5 triage can see sessions riding host."""
    from arroyo_tpu.engine.operators_window import MAX_SESSION_SIZE_MICROS

    MAX = MAX_SESSION_SIZE_MICROS
    ts1 = np.arange(0, MAX - 5 * SEC + 1, 9 * SEC, dtype=np.int64)
    ts2 = np.array([MAX - 1, MAX + 2], dtype=np.int64)
    batches = [
        Batch(ts1, {"k": np.full(len(ts1), 7, np.int64),
                    "v": np.ones(len(ts1), np.int64)}),
        Batch(ts2, {"k": np.full(2, 7, np.int64),
                    "v": np.ones(2, np.int64)})]
    before = perf.counter("session_host_merge_rows")
    dev = _run_sessions(batches, "device", gap=10 * SEC)
    host_rows = perf.counter("session_host_merge_rows") - before
    leg = _run_sessions(batches, "legacy", gap=10 * SEC)
    assert _rows(dev) == _rows(leg)
    assert host_rows > 0, \
        "clamp chain must exercise the counted host fallback"


# ---------------------------------------------------------------------------
# table-level: union oracle, bounded churn, snapshot interchange
# ---------------------------------------------------------------------------


def _oracle_merge(sessions, st, en):
    """Brute-force insert [st, en) into a sorted interval list, merging
    on touch-or-overlap (the union kernel's st <= prev_en rule)."""
    sessions = sorted(sessions + [(st, en)])
    out = [sessions[0]]
    for s, e in sessions[1:]:
        ps, pe = out[-1]
        if s <= pe:
            out[-1] = (ps, max(pe, e))
        else:
            out.append((s, e))
    return out


def test_merge_intervals_matches_oracle_fuzz(rng):
    state = SessionRunState(n_partitions=8, max_span=1 << 62)
    oracle = {}
    gap = 50
    for _ in range(30):
        nk = int(rng.integers(1, 12))
        keys = rng.choice(
            np.arange(1, 25, dtype=np.uint64), nk, replace=False)
        ikh, ist, ien, itm = [], [], [], []
        for k in np.sort(keys):
            for _ in range(int(rng.integers(1, 5))):
                t = int(rng.integers(0, 10_000))
                ikh.append(k)
                ist.append(t)
                ien.append(t + gap)
                itm.append(t)
        order = np.lexsort((np.array(ist), np.array(ikh, dtype=np.uint64)))
        ikh = np.array(ikh, dtype=np.uint64)[order]
        ist = np.array(ist, dtype=np.int64)[order]
        ien = np.array(ien, dtype=np.int64)[order]
        itm = np.array(itm, dtype=np.int64)[order]
        flagged = state.merge_intervals(ikh, ist, ien, itm)
        assert len(flagged) == 0
        for k, s, e in zip(ikh.tolist(), ist.tolist(), ien.tolist()):
            oracle[k] = _oracle_merge(oracle.get(k, []), s, e)
    for k, expect in oracle.items():
        assert state.get(np.uint64(k)) == expect, k
    assert state.n_keys() == len(oracle)


def test_expire_fires_and_stays_bounded(rng):
    """Session churn: repeated merge + expire cycles mask-compress rows
    out; fired sessions match the oracle and the table drains to empty
    (the state_bounded contract)."""
    state = SessionRunState(n_partitions=4, max_span=1 << 62)
    n_fired = 0
    live = {}
    t0 = 0
    for _round in range(12):
        keys = np.sort(rng.choice(
            np.arange(1, 30, dtype=np.uint64), 8, replace=False))
        st = np.array([t0 + int(rng.integers(0, 50)) for _ in keys],
                      dtype=np.int64)
        ikh = keys
        ien = st + 40
        state.merge_intervals(ikh, st, ien, st.copy())
        for k, s, e in zip(ikh.tolist(), st.tolist(), ien.tolist()):
            live[k] = _oracle_merge(live.get(k, []), s, e)
        t0 += 200  # next round starts past every open end
        fk, fs, fe, removed = state.expire(t0)
        got = sorted(zip(fk.tolist(), fs.tolist(), fe.tolist()))
        expect = sorted((k, s, e) for k, ivs in live.items()
                        for s, e in ivs if e <= t0)
        assert got == expect
        for k in list(live):
            live[k] = [iv for iv in live[k] if iv[1] > t0]
            if not live[k]:
                del live[k]
                assert k in [int(r) for r in removed]
        n_fired += len(got)
    assert not live
    assert len(state) == 0 and state.stats()["rows"] == 0
    assert n_fired >= 12 * 8  # every inserted session fired exactly once


def test_snapshot_interchange_both_directions(rng):
    """Both layouts emit the same KEYED [(time, key, sessions)] entry
    form: device snapshot restores into the legacy dict table and back,
    preserving every key's sessions and timestamps."""
    state = SessionRunState(n_partitions=8, max_span=1 << 62)
    for k in range(1, 20):
        kh = np.uint64(k * 1031)
        n = int(rng.integers(1, 4))
        sts = np.sort(rng.choice(
            np.arange(0, 50, dtype=np.int64) * 100, n, replace=False))
        state.merge_intervals(
            np.full(n, kh, dtype=np.uint64), sts.astype(np.int64),
            (sts + 60).astype(np.int64),
            np.full(n, int(sts.max()), np.int64))
    snap = state.snapshot()

    legacy = KeyedState()
    legacy.restore(snap)
    assert legacy.n_keys() == state.n_keys()
    for t, k, v in snap:
        assert legacy.get(k) == state.get(k)
        assert legacy.get_time(k) == state.get_time(k)

    back = SessionRunState(n_partitions=2, max_span=1 << 62)
    back.restore(legacy.snapshot())
    assert back.n_keys() == state.n_keys()
    for _t, k, _v in snap:
        assert back.get(k) == state.get(k)
        assert back.get_time(k) == state.get_time(k)


def test_rescale_entry_filter_2_to_3(rng):
    """Rescale restores each subtask from a key-range FILTER of the
    snapshot entries (state/backend.py _deserialize_rows): emulate the
    2 -> 3 split at the table level — three disjoint filtered restores
    must partition the key set exactly, with no key owned twice."""
    state = SessionRunState(n_partitions=8, max_span=1 << 62)
    keys = rng.choice(np.arange(1, 1 << 20, dtype=np.uint64), 64,
                      replace=False)
    for kh in keys:
        t = int(rng.integers(0, 1000))
        state.merge_intervals(
            np.array([kh], dtype=np.uint64),
            np.array([t], dtype=np.int64),
            np.array([t + 10], dtype=np.int64),
            np.array([t], dtype=np.int64))
    snap = state.snapshot()
    hi = 1 << 20
    cuts = [0, hi // 3, 2 * hi // 3, hi]
    shards = []
    for i in range(3):
        part = SessionRunState(n_partitions=4, max_span=1 << 62)
        part.restore([(t, k, v) for (t, k, v) in snap
                      if cuts[i] <= int(k) < cuts[i + 1]])
        shards.append(part)
    owned = [set(int(k) for k, _v in s.items()) for s in shards]
    assert not (owned[0] & owned[1]) and not (owned[1] & owned[2]) \
        and not (owned[0] & owned[2])
    assert owned[0] | owned[1] | owned[2] == set(int(k) for k in keys)
    for s in shards:
        for k, sessions in s.items():
            assert sessions == state.get(np.uint64(k))


# ---------------------------------------------------------------------------
# full engine: checkpoint under one layout, restore under the other
# ---------------------------------------------------------------------------


def _session_restore_flip(tmp_path, first_mode, second_mode):
    url = f"file://{tmp_path}/ckpt"
    out_path = f"{tmp_path}/out.jsonl"
    job = f"session-flip-{first_mode}-{second_mode}"
    total = 2000

    def build():
        return (Stream.source("impulse", {
                    "event_rate": 30_000.0, "message_count": total,
                    "event_time_interval_micros": 1000, "batch_size": 100})
                .watermark(max_lateness_micros=0)
                .map(lambda c: {"counter": c["counter"],
                                "bucket": c["counter"] % 7}, name="b")
                .key_by("bucket")
                .window(SessionWindow(20 * MS),
                        [AggSpec(AggKind.COUNT, None, "cnt"),
                         AggSpec(AggKind.SUM, "counter", "sum_c")])
                .sink("single_file", {"path": out_path}))

    async def run_with_crash():
        eng = Engine.for_local(build(), job, checkpoint_url=url)
        running = eng.start()
        await asyncio.sleep(0.04)
        await running.checkpoint(1)
        assert await running.wait_for_checkpoint(1)
        await running.stop(StopMode.IMMEDIATE)
        try:
            await running.join()
        except RuntimeError:
            pass

    async def run_restored():
        eng = Engine.for_local(build(), job, checkpoint_url=url,
                               restore_epoch=1)
        running = eng.start()
        await running.join()

    prev = os.environ.get("ARROYO_SESSION_STATE")
    try:
        os.environ["ARROYO_SESSION_STATE"] = first_mode
        asyncio.run(run_with_crash())
        os.environ["ARROYO_SESSION_STATE"] = second_mode
        asyncio.run(run_restored())
    finally:
        if prev is None:
            os.environ.pop("ARROYO_SESSION_STATE", None)
        else:
            os.environ["ARROYO_SESSION_STATE"] = prev

    rows = [json.loads(l) for l in open(out_path)]
    # exactly-once across the layout flip: every event counted once
    assert sum(r["cnt"] for r in rows) == total
    assert sum(r["sum_c"] for r in rows) == total * (total - 1) // 2
    seen = set()
    for r in rows:
        key = (r["bucket"], r["window_start"])
        assert key not in seen, f"duplicate session emission {key}"
        seen.add(key)


def test_checkpoint_device_then_restore_legacy(tmp_path):
    """Open sessions checkpointed by the sorted-run layout restore into
    the legacy dict layout exactly-once (rollback interchange)."""
    _session_restore_flip(tmp_path, "device", "legacy")


def test_checkpoint_legacy_then_restore_device(tmp_path):
    """Legacy-epoch checkpoints upgrade in place into the sorted-run
    layout on restore (forward interchange: the get_session_state
    in-place upgrade path)."""
    _session_restore_flip(tmp_path, "legacy", "device")
