"""Pallas MXU scatter kernel vs numpy reference and vs the XLA scatter path.

On CPU the kernel runs in interpret mode (same code path as TPU, minus
mosaic compilation), so these tests validate kernel semantics everywhere.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from arroyo_tpu.graph.logical import AggKind, AggSpec
from arroyo_tpu.ops.keyed_bins import KeyedBinState
from arroyo_tpu.ops.pallas_kernels import (CHUNK, HAVE_PALLAS, pad_batch,
                                           scatter_add_channels)

pytestmark = pytest.mark.skipif(not HAVE_PALLAS, reason="no pallas")


def _ref_scatter(slots, bins, w, C, B):
    out = np.zeros((w.shape[0], C, B), dtype=np.float64)
    for i, (s, b) in enumerate(zip(slots, bins)):
        out[:, s, b] += w[:, i]
    return out


def test_scatter_add_matches_numpy():
    rng = np.random.default_rng(7)
    C, B, n = 64, 16, 1000
    slots = rng.integers(0, C, n)
    bins = rng.integers(0, B, n)
    w = np.stack([np.ones(n), rng.normal(size=n) * 50]).astype(np.float32)
    s, b, wp = pad_batch(slots, bins, w)
    got = np.asarray(scatter_add_channels(s, b, wp, C, B))
    want = _ref_scatter(slots, bins, w, C, B)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_scatter_add_large_tiled():
    rng = np.random.default_rng(11)
    C, B, n = 2048, 32, 3 * CHUNK + 17  # exercises C tiling + chunk padding
    slots = rng.integers(0, C, n)
    bins = rng.integers(0, B, n)
    w = np.ones((1, n), dtype=np.float32)
    s, b, wp = pad_batch(slots, bins, w)
    got = np.asarray(scatter_add_channels(s, b, wp, C, B))
    want = _ref_scatter(slots, bins, w, C, B)
    np.testing.assert_allclose(got, want, rtol=1e-4)


def _run_state(monkeypatch, use_pallas: bool):
    monkeypatch.setenv("ARROYO_PALLAS", "1" if use_pallas else "0")
    aggs = (AggSpec(kind=AggKind.COUNT, column=None, output="n"),
            AggSpec(kind=AggKind.SUM, column="price", output="total"))
    st = KeyedBinState(aggs, slide_micros=1_000_000,
                       width_micros=5_000_000, capacity=64)
    rng = np.random.default_rng(3)
    for _ in range(4):
        m = 700
        kh = rng.integers(0, 40, m).astype(np.uint64)
        ts = rng.integers(0, 20_000_000, m).astype(np.int64)
        price = rng.uniform(1, 100, m)
        st.update(kh, ts, {"price": price})
    out = st.fire_panes(watermark=50_000_000, final=True)
    assert out is not None
    keys, cols, wend, cnts = out
    order = np.lexsort((keys, wend))
    return (keys[order], {k: v[order] for k, v in cols.items()},
            wend[order], cnts[order])


def test_keyed_bin_state_pallas_equals_xla(monkeypatch):
    k1, c1, w1, n1 = _run_state(monkeypatch, use_pallas=False)
    k2, c2, w2, n2 = _run_state(monkeypatch, use_pallas=True)
    np.testing.assert_array_equal(k1, k2)
    np.testing.assert_array_equal(w1, w2)
    np.testing.assert_array_equal(n1, n2)
    np.testing.assert_array_equal(c1["n"], c2["n"])
    np.testing.assert_allclose(c1["total"], c2["total"], rtol=1e-4)
