"""Phase-attributed profiler (obs/profiler.py): accounting sums to wall
time, the stall watchdog catches blocking calls in the act, rollups
round-trip heartbeat -> controller -> REST, and the disarmed path adds
nothing."""

import asyncio
import time

import httpx
import pytest

from arroyo_tpu.obs import profiler

NEXMARK_SQL = """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000000', num_events = '600000',
  rate_limited = 'false', batch_size = '8192',
  base_time_micros = '1700000000000000'
);
SELECT bid.auction as auction,
       TUMBLE(INTERVAL '2' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
"""
# 600k events / 8k batches (was 120k / 2k): the sums-to-wall claim is
# about STEADY-STATE attribution, and the vectorized ingest kept
# shrinking the 120k wall until one-time engine start/stop + scheduler
# gaps (honestly not phases) were >15% of it on a loaded box — the
# same runway widening smoke's profiler gate got in PR 9.  The 0.85
# acceptance bar is unchanged.


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    profiler.disarm()


def _run_pipeline():
    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import plan_sql

    prog = plan_sql(NEXMARK_SQL)
    clear_sink("results")
    t0 = time.perf_counter()
    LocalRunner(prog).run()
    dt = time.perf_counter() - t0
    rows = sum(len(b) for b in sink_output("results"))
    assert rows > 0
    return dt


@pytest.mark.slow
def test_phase_accounting_sums_to_wall():
    """The work phases must account for (nearly) all of the run's wall
    time on a tiny pipeline — the invariant that keeps every future
    engine change inside the phase table's attribution."""
    _run_pipeline()  # warm: compiles must not inflate the profiled run
    prof = profiler.arm("local-job")
    # best-of-3: the claim is "a clean run attributes >=85%", and one
    # run on a loaded CI box can lose several percent to scheduling
    # gaps the phases legitimately don't own (observed 0.80-0.92 under
    # the conftest 8-device mesh vs ~0.99 standalone single-device —
    # same spread before and after the vectorized-ingest change) — the
    # retries keep the bound honest without making the gate flaky
    share, snap = 0.0, None
    for _ in range(3):
        prof.reset()
        dt = _run_pipeline()
        s = prof.snapshot()
        if sum(s["phases"].values()) / dt > share:
            share, snap = sum(s["phases"].values()) / dt, s
        if share >= 0.9:
            break
    # >=85% from below (the acceptance/smoke bar); the upper bound
    # tolerates executor-side source generation overlapping the event
    # loop (prefetch)
    assert 0.85 <= share <= 1.5, (share, snap["phases"])
    # the table names the expected choke points
    for phase in ("source_decode", "proc", "dispatch", "watermark"):
        assert snap["phases"].get(phase, 0.0) > 0.0, snap["phases"]
    # waits are reported apart from work (queue_wait overlaps tasks and
    # must never be summed into the attribution)
    assert "queue_wait" in snap["waits"]
    assert max(1.0 - share, 0.0) < 0.15, (share, snap)


def test_phase_nesting_is_exclusive():
    """A child frame's full span (waits included) subtracts from its
    parent, so nested phases never double-count."""
    prof = profiler.arm("t")
    prof.reset()
    outer = prof.begin("op", "proc")
    time.sleep(0.02)
    inner = prof.begin("op", "dispatch")
    time.sleep(0.03)
    prof.end(inner)
    wait = prof.begin("op", "send_wait", wait=True)
    time.sleep(0.02)
    prof.end(wait)
    prof.end(outer)
    work = prof.work_snapshot()
    waits = prof.wait_snapshot()
    assert 0.025 <= work[("op", "dispatch")] <= 0.06
    assert 0.015 <= waits[("op", "send_wait")] <= 0.05
    # proc is exclusive: ~0.02, never the inclusive ~0.07
    assert work[("op", "proc")] < 0.04
    total = sum(work.values()) + sum(waits.values())
    assert 0.06 <= total <= 0.12  # sums to the elapsed 7ms+2ms+... 70ms


def test_watchdog_catches_blocking_sleep():
    """An injected time.sleep on the event loop must be caught IN THE
    ACT: a stall event naming the blocking frame — the runtime
    cross-check of arroyolint's async-blocking pass."""
    prof = profiler.arm("wd-test")
    prof.watchdog.reset()

    async def scenario():
        prof.watchdog.ensure_ticker()
        await asyncio.sleep(0.1)  # let the ticker + sampler spin up
        time.sleep(0.5)  # the blocking call (deliberate, see docstring)
        await asyncio.sleep(0.2)  # stall ends; sampler re-arms

    asyncio.run(scenario())
    stats = prof.watchdog.stats()
    assert stats["stalls"] >= 1, stats
    stacks = "".join(s["stack"] for s in prof.watchdog.stalls)
    assert "time.sleep" in stacks or "scenario" in stacks, stacks
    # one episode records once, not once per sampler poll
    assert stats["stalls"] <= 2, stats


def test_watchdog_quiet_loop_records_no_stalls():
    prof = profiler.arm("wd-quiet")
    prof.watchdog.reset()

    async def scenario():
        prof.watchdog.ensure_ticker()
        for _ in range(10):
            await asyncio.sleep(0.02)

    asyncio.run(scenario())
    assert prof.watchdog.stats()["stalls"] == 0


def test_rollup_roundtrip_heartbeat_controller_rest(run_async):
    """Phase rollups ride the existing heartbeat piggyback: worker
    summary (with phase_seconds keys) -> controller fold -> REST
    profile_rollups."""
    from arroyo_tpu.api.rest import ApiServer
    from arroyo_tpu.controller.controller import (ControllerServer, Job,
                                                  WorkerInfo)
    from arroyo_tpu.controller.scheduler import InProcessScheduler
    from arroyo_tpu.rpc.transport import _ser_msgpack

    from arroyo_tpu import Stream

    summary = {
        "agg_1": {
            "messages_sent_total": 100.0,
            "kernel_seconds_total": 0.5,
            "phase_seconds.proc": 1.5,
            "phase_seconds.dispatch": 0.25,
            "wait_seconds.queue_wait": 3.0,
        },
        "__worker__": {
            "event_loop_lag_seconds_p50": 0.001,
            "event_loop_lag_seconds_p99": 0.02,
            "event_loop_stalls_total": 2.0,
        },
    }

    async def scenario():
        ctrl = ControllerServer(InProcessScheduler())
        prog = Stream.source("impulse", {"message_count": 10}).sink(
            "blackhole", {})
        job = Job("pj", prog, "file:///tmp/pj-ckpt", 1)
        w = WorkerInfo("w1", "127.0.0.1:1", "127.0.0.1:2", 4)
        job.workers["w1"] = w
        ctrl.jobs["pj"] = job
        await ctrl._heartbeat({"job_id": "pj", "worker_id": "w1",
                               "time": 0,
                               "metrics": _ser_msgpack(summary)})
        data = ctrl.job_profile_rollup("pj")
        ops = {o["operator_id"]: o for o in data["operators"]}
        assert ops["agg_1"]["phases"]["proc"] == 1.5
        assert ops["agg_1"]["waits"]["queue_wait"] == 3.0
        # host excludes the kernel-bound dispatch span; device IS that
        # span (never the kernel_seconds counter, which measures the
        # same wall and would double-count)
        assert ops["agg_1"]["host_seconds"] == 1.5
        assert ops["agg_1"]["device_seconds"] == 0.25
        assert 0.85 <= ops["agg_1"]["host_share"] <= 0.86
        assert data["worker"]["event_loop_stalls"] == 2.0
        assert data["worker"]["event_loop_lag_p99_secs"] == 0.02

        api = ApiServer(ctrl)
        port = await api.start()
        try:
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}",
                    timeout=10) as c:
                r = await c.get("/v1/pipelines/pj/jobs/pj/profile_rollups")
                assert r.status_code == 200, r.text
                body = r.json()
                assert body["source"] == "heartbeat"
                got = {o["operator_id"]: o for o in body["operators"]}
                assert got["agg_1"]["phases"]["proc"] == 1.5
                assert body["worker"]["event_loop_stalls"] == 2.0
                r = await c.get(
                    "/v1/pipelines/x/jobs/missing/profile_rollups")
                assert r.status_code == 404
        finally:
            await api.stop()

    run_async(scenario())


def test_armed_summary_carries_phase_keys():
    """job_operator_summary merges the live profiler's buckets as the
    phase_seconds./wait_seconds. keys the heartbeat ships."""
    from arroyo_tpu.obs.metrics import job_operator_summary

    prof = profiler.arm("local-job")
    prof.reset()
    prof.add("op_x", "proc", 0.25)
    prof.add("op_x", "queue_wait", 0.5, wait=True)
    out = job_operator_summary("local-job")
    assert out["op_x"]["phase_seconds.proc"] == 0.25
    assert out["op_x"]["wait_seconds.queue_wait"] == 0.5


def test_off_path_records_nothing():
    """Disarmed (the default): no profiler exists, the hook sites see
    None, and a full pipeline run creates no buckets anywhere."""
    assert profiler.active() is None
    _run_pipeline()
    assert profiler.active() is None
    from arroyo_tpu.obs.metrics import job_operator_summary

    out = job_operator_summary("local-job")
    for op, keys in out.items():
        for k in keys:
            assert not k.startswith(("phase_seconds.", "wait_seconds.")), \
                (op, k)


def test_admin_profile_phases_endpoint(run_async):
    from arroyo_tpu.obs.admin import AdminServer

    async def scenario():
        admin = AdminServer("worker")
        port = await admin.start()
        try:
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}") as c:
                # disarmed: empty folded text, enabled=false json
                r = await c.get("/profile/phases")
                assert r.status_code == 200 and r.text == ""
                r = await c.get("/profile/phases?fmt=json")
                assert r.json() == {"enabled": False}

                prof = profiler.arm("jobA")
                prof.add("op_y", "proc", 0.125)
                prof.add("op_y", "net_flush", 0.03, wait=True)
                r = await c.get("/profile/phases")
                assert "jobA;op_y;proc 125000" in r.text
                assert "(wait)" in r.text
                r = await c.get("/profile/phases?fmt=json")
                j = r.json()
                assert j["enabled"] is True
                assert j["operators"]["op_y"]["phases"]["proc"] == 0.125
                assert "watchdog" in j
        finally:
            await admin.stop()

    run_async(scenario())


def test_debug_profile_capture_is_bounded(run_async, monkeypatch):
    """POST /debug/profile start/stop: every start arms a max-duration
    watchdog (a forgotten stop can no longer trace forever) and the
    stop response lists the capture directory."""
    import jax

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.__setitem__(
                            "start", calls["start"] + 1))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__(
                            "stop", calls["stop"] + 1))

    from arroyo_tpu.obs.admin import AdminServer

    async def scenario(tmpdir):
        admin = AdminServer("worker")
        port = await admin.start()
        try:
            async with httpx.AsyncClient(
                    base_url=f"http://127.0.0.1:{port}",
                    timeout=10) as c:
                # explicit start -> stop returns the dir listing
                r = await c.post("/debug/profile", json={
                    "action": "start", "dir": tmpdir})
                assert r.json()["started"] is True
                r = await c.post("/debug/profile", json={
                    "action": "start", "dir": tmpdir})
                assert "already in progress" in r.json()["error"]
                import os

                with open(os.path.join(tmpdir, "cap.xplane.pb"),
                          "w") as f:
                    f.write("x")
                # stop carries no dir: the listing must walk the
                # capture's START dir, not the stop request's default
                r = await c.post("/debug/profile",
                                 json={"action": "stop"})
                j = r.json()
                assert j["stopped"] is True and j["dir"] == tmpdir
                assert any(f.endswith("cap.xplane.pb")
                           for f in j["files"]), j
                assert calls == {"start": 1, "stop": 1}

                # forgotten stop: the watchdog auto-stops at max_seconds
                r = await c.post("/debug/profile", json={
                    "action": "start", "dir": tmpdir,
                    "max_seconds": 0.2})
                assert r.json()["started"] is True
                await asyncio.sleep(0.5)
                assert calls == {"start": 2, "stop": 2}  # auto-stopped
                r = await c.post("/debug/profile",
                                 json={"action": "stop"})
                assert "no capture" in r.json()["error"]
        finally:
            await admin.stop()

    import tempfile

    run_async(scenario(tempfile.mkdtemp(prefix="prof-cap-")))
