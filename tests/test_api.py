"""REST API end-to-end: boot controller + ApiServer, exercise the public
HTTP surface the way the reference's integ binary does
(/root/reference/integ/src/main.rs:25-120): create a connection table,
create a pipeline, wait for Running, see checkpoints, stop gracefully.
"""

import asyncio
import json

import httpx
import pytest

from arroyo_tpu.api.rest import ApiServer
from arroyo_tpu.controller.controller import ControllerServer


@pytest.fixture()
def api_env(tmp_path, monkeypatch):
    monkeypatch.setenv("CHECKPOINT_URL", f"file://{tmp_path}/ckpt")

    async def boot():
        controller = ControllerServer()
        await controller.start()
        api = ApiServer(controller)
        port = await api.start()
        return controller, api, port

    loop = asyncio.new_event_loop()
    controller, api, port = loop.run_until_complete(boot())
    yield loop, controller, f"http://127.0.0.1:{port}"
    loop.run_until_complete(api.stop())
    loop.run_until_complete(controller.stop())
    loop.close()


def _run(loop, coro):
    return loop.run_until_complete(coro)


QUERY = """
CREATE TABLE impulse WITH (connector = 'impulse', event_rate = '1000',
  message_count = '5000', batch_size = '256');
SELECT counter, counter * 2 as doubled FROM impulse WHERE counter % 2 = 0
"""


def test_rest_lifecycle(api_env):
    loop, controller, base = api_env

    async def scenario():
        async with httpx.AsyncClient(base_url=base, timeout=30) as c:
            r = await c.get("/api/v1/ping")
            assert r.status_code == 200 and r.json()["pong"]

            # connector catalog
            r = await c.get("/v1/connectors")
            names = {x["id"] for x in r.json()["data"]}
            assert {"impulse", "nexmark", "kafka"} <= names

            # validate: good and bad SQL
            r = await c.post("/v1/pipelines/validate",
                             json={"query": QUERY})
            assert r.status_code == 200
            graph = r.json()["graph"]
            assert graph["nodes"] and graph["edges"]
            r = await c.post("/v1/pipelines/validate",
                             json={"query": "SELEC nonsense"})
            assert r.status_code == 400

            # create pipeline -> job runs
            r = await c.post("/v1/pipelines",
                             json={"name": "evens", "query": QUERY})
            assert r.status_code == 200, r.text
            pl = r.json()
            job_id = pl["jobs"][0]["id"]

            # poll job state through the API until terminal
            for _ in range(200):
                r = await c.get("/v1/jobs")
                job = next(j for j in r.json()["data"]
                           if j["id"] == job_id)
                if job["state"] in ("Finished", "Stopped", "Failed"):
                    break
                await asyncio.sleep(0.1)
            assert job["state"] == "Finished", job

            # pipeline listing + detail
            r = await c.get("/v1/pipelines")
            assert any(p["id"] == pl["id"] for p in r.json()["data"])
            r = await c.get(f"/v1/pipelines/{pl['id']}")
            assert r.json()["name"] == "evens"
            r = await c.get(f"/v1/pipelines/{pl['id']}/jobs")
            assert r.json()["data"][0]["id"] == job_id

            # errors endpoint: none for a clean run
            r = await c.get(f"/v1/pipelines/{pl['id']}/jobs/{job_id}/errors")
            assert r.json()["data"] == []

            # delete
            r = await c.request("DELETE", f"/v1/pipelines/{pl['id']}")
            assert r.status_code == 200
            r = await c.get(f"/v1/pipelines/{pl['id']}")
            assert r.status_code == 404

            # 404 / 405 semantics
            r = await c.get("/v1/nope")
            assert r.status_code == 404
            r = await c.request("DELETE", "/v1/jobs")
            assert r.status_code == 405

    _run(loop, scenario())


def test_connection_tables_and_sql_integration(api_env):
    loop, controller, base = api_env

    async def scenario():
        async with httpx.AsyncClient(base_url=base, timeout=30) as c:
            # unknown connector rejected
            r = await c.post("/v1/connection_tables", json={
                "name": "x", "connector": "noope", "config": {}})
            assert r.status_code == 400
            # invalid config rejected with 422
            r = await c.post("/v1/connection_tables", json={
                "name": "x", "connector": "impulse",
                "config": {"event_rate": "not-a-number"}})
            assert r.status_code == 422
            # test endpoint mirrors validation without persisting
            r = await c.post("/v1/connection_tables/test", json={
                "connector": "impulse", "config": {"event_rate": 10}})
            assert r.json()["ok"] is True

            # valid: saved table is visible to the SQL planner by name
            r = await c.post("/v1/connection_tables", json={
                "name": "ticks", "connector": "impulse",
                "config": {"event_rate": 1000, "message_count": 1000,
                           "batch_size": 128}})
            assert r.status_code == 200, r.text
            tid = r.json()["id"]
            r = await c.get("/v1/connection_tables")
            assert any(t["name"] == "ticks" for t in r.json()["data"])

            # duplicate name -> 409
            r = await c.post("/v1/connection_tables", json={
                "name": "ticks", "connector": "impulse",
                "config": {"event_rate": 1}})
            assert r.status_code == 409

            # pipeline referencing the saved table (no CREATE TABLE in SQL)
            r = await c.post("/v1/pipelines", json={
                "name": "from-saved",
                "query": "SELECT counter FROM ticks"})
            assert r.status_code == 200, r.text
            job_id = r.json()["jobs"][0]["id"]
            for _ in range(200):
                r = await c.get("/v1/jobs")
                job = next(j for j in r.json()["data"]
                           if j["id"] == job_id)
                if job["state"] in ("Finished", "Stopped", "Failed"):
                    break
                await asyncio.sleep(0.1)
            assert job["state"] == "Finished", job

            r = await c.request("DELETE", f"/v1/connection_tables/{tid}")
            assert r.status_code == 200
            r = await c.request("DELETE", f"/v1/connection_tables/{tid}")
            assert r.status_code == 404

    _run(loop, scenario())


def test_output_tailing_sse(api_env):
    """GrpcSink output reaches the REST SSE endpoint (jobs.rs:465+)."""
    loop, controller, base = api_env

    async def scenario():
        sql = """
        CREATE TABLE impulse WITH (connector = 'impulse',
          event_rate = '500', message_count = '3000', batch_size = '64');
        SELECT counter FROM impulse
        """
        async with httpx.AsyncClient(base_url=base, timeout=30) as c:
            r = await c.post("/v1/pipelines",
                             json={"name": "tail", "query": sql,
                                   "preview": True})
            assert r.status_code == 200, r.text
            job_id = r.json()["jobs"][0]["id"]

            rows = 0
            async with c.stream(
                    "GET", f"/v1/pipelines/{r.json()['id']}/jobs/{job_id}"
                    f"/output") as resp:
                assert resp.status_code == 200
                async for line in resp.aiter_lines():
                    if not line.startswith("data: "):
                        continue
                    event = json.loads(line[len("data: "):])
                    if event.get("done") or rows >= 100:
                        break
                    rows += len(event.get("rows", []))
            # the 6s paced run guarantees the subscription observes
            # live data, not just a clean termination
            assert rows >= 100, rows

    _run(loop, scenario())


def test_openapi_spec(api_env):
    """GET /api/v1/openapi.json describes the live route table."""
    loop, _ctrl, url = api_env

    async def fetch():
        async with httpx.AsyncClient() as c:
            return (await c.get(f"{url}/api/v1/openapi.json")).json()

    spec = _run(loop, fetch())
    assert spec["openapi"].startswith("3.")
    paths = spec["paths"]
    assert "/v1/pipelines" in paths
    assert "post" in paths["/v1/pipelines"] and "get" in paths["/v1/pipelines"]
    assert "/v1/pipelines/{id}" in paths
    assert paths["/v1/pipelines/{id}"]["get"]["parameters"][0]["name"] == "id"
    assert "/v1/connection_tables" in paths


def test_connection_profiles_and_schema_test(api_env):
    """Connection profiles (shared connector config merged into tables)
    and JSON-schema validation (connection_profiles.rs / test_schema)."""
    loop, _ctrl, url = api_env

    async def go():
        async with httpx.AsyncClient() as c:
            r = await c.post(f"{url}/v1/connection_profiles", json={
                "name": "kafka-prod", "connector": "kafka",
                "config": {"bootstrap_servers": "memory://prof"}})
            assert r.status_code == 200, r.text
            prof = r.json()
            listed = (await c.get(
                f"{url}/v1/connection_profiles")).json()["data"]
            assert [p["name"] for p in listed] == ["kafka-prod"]

            # table config merges the profile's connector settings
            r = await c.post(f"{url}/v1/connection_tables", json={
                "name": "evts", "connector": "kafka",
                "connection_profile_id": prof["id"],
                "config": {"topic": "t1"}})
            assert r.status_code == 200, r.text
            assert r.json()["config"]["bootstrap_servers"] == "memory://prof"

            # profile/connector mismatch is a conflict
            r = await c.post(f"{url}/v1/connection_tables", json={
                "name": "evts2", "connector": "impulse",
                "connection_profile_id": prof["id"], "config": {}})
            assert r.status_code == 409

            r = await c.post(
                f"{url}/v1/connection_tables/schemas/test", json={
                    "schema": {"type": "object", "properties": {
                        "id": {"type": "integer"},
                        "name": {"type": ["string", "null"]},
                        "at": {"type": "string", "format": "date-time"},
                        "nested": {"type": "object", "properties": {
                            "x": {"type": "number"}}},
                    }}})
            j = r.json()
            assert j["ok"], j
            types = {c_["name"]: c_["type"] for c_ in j["columns"]}
            assert types == {"id": "bigint", "name": "text",
                             "at": "timestamp", "nested.x": "double"}

            r = await c.post(
                f"{url}/v1/connection_tables/schemas/test",
                json={"schema": {"type": "array"}})
            assert not r.json()["ok"]

    _run(loop, go())


@pytest.mark.slow
def test_checkpoint_details_endpoint(api_env, tmp_path):
    """Per-operator checkpoint detail lists the parquet files an epoch
    wrote (get_checkpoint_details analog)."""
    loop, ctrl, url = api_env

    async def go():
        async with httpx.AsyncClient() as c:
            r = await c.post(f"{url}/v1/pipelines", json={
                "name": "ck", "query": """
CREATE TABLE impulse WITH (connector = 'impulse', event_rate = '3000',
  message_count = '100000', batch_size = '512');
SELECT counter % 5 as k, count(*) as cnt FROM impulse
GROUP BY 1, tumble(interval '1 second')"""})
            assert r.status_code == 200, r.text
            pid = r.json()["id"]
            jid = r.json()["jobs"][0]["id"]
            # wait for a finished checkpoint epoch
            epoch = None
            for _ in range(300):
                ck = (await c.get(
                    f"{url}/v1/pipelines/{pid}/jobs/{jid}/checkpoints")
                ).json()
                epoch = ck.get("last_successful_epoch")
                if epoch:
                    break
                await asyncio.sleep(0.1)
            assert epoch, ck
            r = await c.get(
                f"{url}/v1/pipelines/{pid}/jobs/{jid}/checkpoints/"
                f"{epoch}/operator_checkpoint_groups")
            j = r.json()
            assert j["epoch"] == epoch
            assert j["data"], j  # at least one operator wrote state
            assert all(g["bytes"] > 0 for g in j["data"])
            await c.patch(f"{url}/v1/pipelines/{pid}",
                          json={"stop": "immediate"})

    _run(loop, go())


@pytest.mark.slow
def test_rest_rescale_running_pipeline(api_env):
    """PATCH /v1/pipelines/{id} with a new parallelism on a RUNNING job
    drives the controller's live rescale (checkpoint-stop, re-shard,
    resume) through the public API; the job still finishes cleanly."""
    loop, controller, base = api_env

    sql = """
    CREATE TABLE impulse WITH (connector = 'impulse',
      event_rate = '8000', message_count = '40000', batch_size = '256',
      event_time_interval_micros = '1000');
    SELECT counter % 5 as bucket, TUMBLE(INTERVAL '1' SECOND) as window,
           count(*) as cnt
    FROM impulse GROUP BY 1, 2
    """

    async def scenario():
        async with httpx.AsyncClient(base_url=base) as c:
            r = await c.post("/v1/pipelines",
                             json={"name": "rescale-me", "query": sql})
            assert r.status_code == 200, r.text
            pl = r.json()
            job_id = pl["jobs"][0]["id"]

            # wait until Running, let it make progress
            for _ in range(200):
                r = await c.get("/v1/jobs")
                job = next(j for j in r.json()["data"] if j["id"] == job_id)
                if job["state"] == "Running":
                    break
                await asyncio.sleep(0.05)
            assert job["state"] == "Running", job
            await asyncio.sleep(0.8)

            r = await c.patch(f"/v1/pipelines/{pl['id']}",
                              json={"parallelism": 2})
            assert r.status_code == 200, r.text
            assert r.json()["parallelism"] == 2
            # the console distinguishes a LIVE rescale from a stored-
            # default update, and renders the refreshed graph
            assert r.json()["rescaled_jobs"] == [job_id]
            r = await c.get(f"/v1/pipelines/{pl['id']}")
            assert {n["parallelism"] for n in r.json()["graph"]["nodes"]} \
                == {2}

            # out-of-range parallelism is a 400, not an unbounded restart
            r = await c.patch(f"/v1/pipelines/{pl['id']}",
                              json={"parallelism": 9999})
            assert r.status_code == 400

            for _ in range(400):
                r = await c.get("/v1/jobs")
                job = next(j for j in r.json()["data"] if j["id"] == job_id)
                if job["state"] in ("Finished", "Stopped", "Failed"):
                    break
                await asyncio.sleep(0.1)
            assert job["state"] == "Finished", job

            # rescaling a pipeline whose job is terminal must not 500
            # (the FSM rejects transitions on terminal jobs): 200 with
            # an empty rescaled_jobs, and only the stored default moves
            r = await c.patch(f"/v1/pipelines/{pl['id']}",
                              json={"parallelism": 3})
            assert r.status_code == 200, r.text
            assert r.json()["rescaled_jobs"] == []

    _run(loop, scenario())


def test_rest_metrics_history_persists(api_env):
    """The API's sampler writes per-operator metrics history to sqlite
    and serves it back — a fresh console session (no in-browser state)
    can reconstruct throughput charts for a job that already ran."""
    loop, controller, base = api_env

    sql = """
    CREATE TABLE impulse WITH (connector = 'impulse',
      event_rate = '4000', message_count = '20000', batch_size = '256');
    SELECT counter, counter * 2 as doubled FROM impulse
    """

    async def scenario():
        async with httpx.AsyncClient(base_url=base) as c:
            r = await c.post("/v1/pipelines",
                             json={"name": "hist", "query": sql})
            assert r.status_code == 200, r.text
            pl = r.json()
            pid, job_id = pl["id"], pl["jobs"][0]["id"]

            # wait for the job to finish (several sampler ticks elapse)
            for _ in range(400):
                r = await c.get("/v1/jobs")
                job = next(j for j in r.json()["data"]
                           if j["id"] == job_id)
                if job["state"] in ("Finished", "Failed"):
                    break
                await asyncio.sleep(0.05)
            assert job["state"] == "Finished", job

            r = await c.get(
                f"/v1/pipelines/{pid}/jobs/{job_id}/metrics_history")
            assert r.status_code == 200
            data = r.json()["data"]
            assert data, "no metrics history sampled"
            # cumulative messages_sent must be monotone per operator and
            # show real progress (the 2s sampler may miss the final tick
            # before the job leaves the controller, so not the full count)
            monotone_ok, any_sent = True, 0.0
            for s in data:
                pts = s["points"]
                assert len(pts) >= 1
                for a, b in zip(pts, pts[1:]):
                    monotone_ok &= b[1] >= a[1]
                any_sent = max(any_sent, pts[-1][1])
            assert monotone_ok and any_sent >= 5000
    _run(loop, scenario())


@pytest.mark.slow
def test_generated_client_black_box_lifecycle(api_env):
    """Spec-validated, runtime-GENERATED client (api/client.py) drives a
    full pipeline lifecycle — every call goes through an operation the
    live /api/v1/openapi.json declares, the reference integ binary's
    generated-client discipline (integ/src/main.rs:25-120)."""
    loop, _ctrl, base = api_env

    from arroyo_tpu.api.client import (ApiError, generate_client,
                                       validate_spec)

    async def scenario():
        async with httpx.AsyncClient(timeout=30) as http:
            client = await generate_client(base, http)
            # the spec validated clean (generate_client raises otherwise);
            # prove the validator actually bites on a broken spec
            broken = json.loads(json.dumps(client.spec))
            broken["paths"]["/v1/pipelines/{id}"]["get"].pop("parameters")
            assert any("undeclared" in p for p in validate_spec(broken))

            assert (await client.ping())["pong"]
            ops = set(client.operations)
            assert {"create_pipeline", "list_jobs", "get_pipeline",
                    "delete_pipeline", "job_checkpoints",
                    "autoscaler_status", "autoscaler_update"} <= ops

            got = await client.validate_pipeline(body={"query": QUERY})
            assert got["graph"]["nodes"]

            pl = await client.create_pipeline(
                body={"name": "genclient", "query": QUERY})
            job_id = pl["jobs"][0]["id"]

            # autoscaler surface through the generated client: the job
            # starts with the loop disabled; a PUT round-trips a policy
            # knob merge and the enable flag
            st = await client.autoscaler_status(jid=job_id)
            assert st["enabled"] is False and st["decisions"] == []
            st = await client.autoscaler_update(
                jid=job_id, body={"enabled": True,
                                  "policy": {"high_water": 0.55}})
            assert st["enabled"] and st["policy"]["high_water"] == 0.55
            st = await client.autoscaler_update(jid=job_id,
                                                body={"enabled": False})
            assert st["enabled"] is False
            for _ in range(200):
                jobs = (await client.list_jobs())["data"]
                job = next(j for j in jobs if j["id"] == job_id)
                if job["state"] in ("Finished", "Stopped", "Failed"):
                    break
                await asyncio.sleep(0.1)
            assert job["state"] == "Finished", job

            detail = await client.get_pipeline(id=pl["id"])
            assert detail["name"] == "genclient"
            cks = await client.job_checkpoints(pid=pl["id"], jid=job_id)
            assert "data" in cks
            await client.delete_pipeline(id=pl["id"])
            try:
                await client.get_pipeline(id=pl["id"])
                assert False, "deleted pipeline still resolves"
            except ApiError as e:
                assert e.status == 404

    _run(loop, scenario())


def test_pipeline_detail_carries_graph_for_console_overlay(api_env):
    """/v1/pipelines/{id} returns the stored DAG (the console's live
    per-operator overlay renders it; list view stays lean)."""
    loop, _ctrl, base = api_env

    async def scenario():
        async with httpx.AsyncClient(base_url=base, timeout=30) as c:
            r = await c.post("/v1/pipelines",
                             json={"name": "dag", "query": QUERY})
            pid = r.json()["id"]
            detail = (await c.get(f"/v1/pipelines/{pid}")).json()
            g = detail["graph"]
            assert g and g["nodes"] and g["edges"]
            ids = {n["operator_id"] for n in g["nodes"]}
            assert all(e["src"] in ids and e["dst"] in ids
                       for e in g["edges"])
            listing = (await c.get("/v1/pipelines")).json()["data"]
            assert all("graph" not in p for p in listing)
            # console ships the overlay + checkpoint-detail machinery
            html = (await c.get("/")).text
            for needle in ("updateDagOverlay", "ov_bp_", "jobdag",
                           "ckptDetail", "operator_checkpoint_groups"):
                assert needle in html, needle

    _run(loop, scenario())


def test_preview_pipeline_streams_output_and_reaps(api_env):
    """preview: true (reference pipelines.rs:191-198) — connector sinks
    swap to the preview sink, parallelism forces 1, output streams via
    the SSE endpoint, and the job auto-stops after ttl_secs."""
    loop, ctrl, base = api_env

    q = """
    CREATE TABLE f WITH (connector = 'single_file',
      path = '/tmp/should_not_be_written.jsonl', type = 'sink');
    CREATE TABLE impulse WITH (connector = 'impulse',
      event_rate = '500', message_count = '3000', batch_size = '64');
    INSERT INTO f SELECT counter FROM impulse
    """

    async def scenario():
        async with httpx.AsyncClient(base_url=base, timeout=30) as c:
            r = await c.post("/v1/pipelines", json={
                "name": "pv", "query": q, "preview": True,
                "parallelism": 4, "ttl_secs": 20})
            assert r.status_code == 200, r.text
            pl = r.json()
            assert pl["preview"] is True
            g = pl["graph"]
            sinks = [n for n in g["nodes"] if "sink" in n["operator_id"]]
            assert sinks and all(n["parallelism"] == 1
                                 for n in g["nodes"])
            jid = pl["jobs"][0]["id"]
            # output reaches the SSE tail (preview sink -> controller);
            # the 6s paced run leaves plenty of stream to observe
            rows = []
            async with c.stream(
                    "GET",
                    f"/v1/pipelines/{pl['id']}/jobs/{jid}/output") as s:
                async for line in s.aiter_lines():
                    if line.startswith("data: "):
                        ev = json.loads(line[6:])
                        rows.extend(ev.get("rows") or [])
                        if ev.get("done") or len(rows) >= 300:
                            break
            assert len(rows) >= 300
            assert {r_["counter"] for r_ in rows} <= set(range(3000))
    _run(loop, scenario())
    import os
    assert not os.path.exists("/tmp/should_not_be_written.jsonl"), \
        "preview must not write to the real connector sink"


def test_preview_ttl_reaps_job(api_env):
    """A preview pipeline left running auto-stops after ttl_secs."""
    loop, ctrl, base = api_env

    q = """
    CREATE TABLE impulse WITH (connector = 'impulse',
      event_rate = '50', message_count = '10000000', batch_size = '32');
    SELECT counter FROM impulse
    """

    async def scenario():
        from arroyo_tpu.controller.state_machine import JobState

        async with httpx.AsyncClient(base_url=base, timeout=30) as c:
            r = await c.post("/v1/pipelines", json={
                "name": "reap", "query": q, "preview": True,
                "ttl_secs": 2})
            jid = r.json()["jobs"][0]["id"]
            state = await ctrl.wait_for_state(
                jid, JobState.STOPPED, JobState.FINISHED, timeout=45)
            assert state in (JobState.STOPPED, JobState.FINISHED), state

    _run(loop, scenario())


def test_cli_run_executes_sql(tmp_path):
    """`python -m arroyo_tpu run q.sql` executes locally and streams
    result rows as JSON lines (the reference binary's run UX)."""
    import os
    import subprocess
    import sys

    q = tmp_path / "q.sql"
    q.write_text(
        "CREATE TABLE impulse WITH (connector='impulse', "
        "event_rate='0', message_count='6', batch_size='2');"
        "SELECT counter FROM impulse WHERE counter % 2 = 0")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-m", "arroyo_tpu", "run", str(q)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-500:]
    rows = [json.loads(x) for x in r.stdout.strip().splitlines()]
    assert [row["counter"] for row in rows] == [0, 2, 4]


@pytest.mark.slow
def test_black_box_api_process(tmp_path):
    """Deploy-grade smoke: boot the real `api` role as an OS process
    (python -m arroyo_tpu api — controller + REST in one), drive a
    preview pipeline over plain HTTP through the spec-generated client,
    and observe streamed output.  The closest analog of running the
    reference's docker image and pointing integ at it."""
    import os
    import socket
    import subprocess
    import sys
    import time as _time

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    api_port, ctrl_port = free_port(), free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               API_PORT=str(api_port), API_HOST="127.0.0.1",
               CONTROLLER_PORT=str(ctrl_port),
               CONTROLLER_HOST="127.0.0.1",
               CHECKPOINT_URL=f"file://{tmp_path}/ckpt")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "arroyo_tpu", "api"], env=env,
        cwd="/root/repo", stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    base = f"http://127.0.0.1:{api_port}"
    try:
        from arroyo_tpu.api.client import generate_client

        async def scenario():
            async with httpx.AsyncClient(timeout=30) as http:
                for _ in range(100):  # wait for the process to listen
                    try:
                        r = await http.get(base + "/api/v1/ping")
                        if r.status_code == 200:
                            break
                    except httpx.TransportError:
                        await asyncio.sleep(0.2)
                else:
                    raise AssertionError("api process never came up")
                client = await generate_client(base, http)
                pl = await client.create_pipeline(body={
                    "name": "bb", "preview": True, "query": (
                        "CREATE TABLE impulse WITH (connector='impulse',"
                        " event_rate='0', message_count='500',"
                        " batch_size='64');"
                        "SELECT counter FROM impulse")})
                jid = pl["jobs"][0]["id"]
                for _ in range(150):
                    jobs = (await client.list_jobs())["data"]
                    job = next(j for j in jobs if j["id"] == jid)
                    if job["state"] in ("Finished", "Stopped", "Failed"):
                        break
                    await asyncio.sleep(0.2)
                assert job["state"] == "Finished", job

        asyncio.run(scenario())
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
