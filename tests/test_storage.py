"""utils/storage.py coverage: the gs:// / s3:// FsspecStorage paths run
against an in-memory fake fsspec (gcsfs/s3fs are not in this image), so
the cloud storage layer is exercised — put/get/list/size/delete,
root/prefix handling, and the clear not-installed error — without any
cloud dependency or network."""

import io
import sys

import pytest

from arroyo_tpu.utils.storage import (
    FsspecStorage,
    LocalStorage,
    MemoryStorage,
    StorageProvider,
)


class _FakeWriteFile(io.BytesIO):
    def __init__(self, fs, path):
        super().__init__()
        self._fs, self._path = fs, path

    def close(self):
        self._fs.store[self._path] = self.getvalue()
        super().close()

    def __exit__(self, *exc):
        self.close()


class _FakeFS:
    """Minimal fsspec filesystem: flat path->bytes store."""

    def __init__(self, scheme):
        self.scheme = scheme
        self.store = {}

    def open(self, path, mode="rb"):
        if "w" in mode:
            return _FakeWriteFile(self, path)
        if path not in self.store:
            raise FileNotFoundError(path)
        return io.BytesIO(self.store[path])

    def exists(self, path):
        return path in self.store or any(
            k.startswith(path + "/") for k in self.store)

    def rm(self, path, recursive=False):
        if recursive:
            doomed = [k for k in self.store
                      if k == path or k.startswith(path + "/")]
            if not doomed:
                raise FileNotFoundError(path)
            for k in doomed:
                del self.store[k]
            return
        if path not in self.store:
            raise FileNotFoundError(path)
        del self.store[path]

    def find(self, base):
        if base not in self.store and not any(
                k.startswith(base + "/") for k in self.store):
            raise FileNotFoundError(base)
        return sorted(k for k in self.store
                      if k == base or k.startswith(base + "/"))

    def size(self, path):
        return len(self.store[path])


class _FakeFsspecModule:
    def __init__(self):
        self.filesystems = {}

    def filesystem(self, scheme):
        return self.filesystems.setdefault(scheme, _FakeFS(scheme))


@pytest.fixture
def fake_fsspec(monkeypatch):
    mod = _FakeFsspecModule()
    monkeypatch.setitem(sys.modules, "fsspec", mod)
    return mod


@pytest.mark.parametrize("scheme", ["gs", "s3"])
def test_fsspec_storage_roundtrip(fake_fsspec, scheme):
    store = StorageProvider.for_url(f"{scheme}://bucket/ckpt")
    assert isinstance(store, FsspecStorage)
    assert store.scheme == scheme
    assert store.root == "bucket/ckpt"

    assert not store.exists("job/epoch-1/data.parquet")
    path = store.put("job/epoch-1/data.parquet", b"\x00" * 64)
    assert path == "bucket/ckpt/job/epoch-1/data.parquet"
    assert store.exists("job/epoch-1/data.parquet")
    assert store.get("job/epoch-1/data.parquet") == b"\x00" * 64
    assert store.size("job/epoch-1/data.parquet") == 64
    # the fake records writes under the bucket-qualified path (what the
    # real gcsfs/s3fs would receive)
    fs = fake_fsspec.filesystems[scheme]
    assert "bucket/ckpt/job/epoch-1/data.parquet" in fs.store
    assert store.local_path("job/epoch-1/data.parquet") is None
    assert store.url_for("job/epoch-1/data.parquet").startswith(
        f"{scheme}://bucket/ckpt/")


@pytest.mark.parametrize("scheme", ["gs", "s3"])
def test_fsspec_storage_list_is_root_relative(fake_fsspec, scheme):
    store = StorageProvider.for_url(f"{scheme}://bucket/root")
    store.put("job/epoch-1/op-a/t.parquet", b"a")
    store.put("job/epoch-1/op-b/t.parquet", b"bb")
    store.put("job/epoch-2/op-a/t.parquet", b"ccc")
    assert store.list("job/epoch-1") == [
        "job/epoch-1/op-a/t.parquet", "job/epoch-1/op-b/t.parquet"]
    # missing prefixes list as empty, matching LocalStorage semantics
    assert store.list("job/epoch-9") == []


@pytest.mark.parametrize("scheme", ["gs", "s3"])
def test_fsspec_storage_delete(fake_fsspec, scheme):
    store = StorageProvider.for_url(f"{scheme}://bucket/root")
    store.put("a/x", b"1")
    store.put("a/y", b"2")
    store.put("b/z", b"3")
    store.delete_if_present("a/x")
    store.delete_if_present("a/x")  # second delete must be a no-op
    assert not store.exists("a/x") and store.exists("a/y")
    store.delete_prefix("a")
    store.delete_prefix("a")  # idempotent on a missing prefix too
    assert store.list("a") == []
    assert store.get("b/z") == b"3"


def test_fsspec_storage_trailing_slash_root(fake_fsspec):
    store = StorageProvider.for_url("gs://bucket/deep/prefix/")
    assert store.root == "bucket/deep/prefix"
    store.put("k", b"v")
    assert store.get("k") == b"v"
    assert store.list("") == ["k"]


def test_fsspec_storage_missing_key_raises(fake_fsspec):
    store = StorageProvider.for_url("s3://bucket/root")
    with pytest.raises(FileNotFoundError):
        store.get("nope")


def test_fsspec_missing_dependency_is_a_clear_error(monkeypatch):
    """Without gcsfs/s3fs installed the provider must fail at
    construction with an actionable message, not at import."""
    monkeypatch.delitem(sys.modules, "fsspec", raising=False)
    monkeypatch.setattr("builtins.__import__", _blocking_import(
        "fsspec"))
    with pytest.raises(RuntimeError, match="gcsfs"):
        StorageProvider.for_url("gs://bucket/x")
    with pytest.raises(RuntimeError, match="s3fs"):
        StorageProvider.for_url("s3://bucket/x")


def _blocking_import(blocked):
    real_import = __import__

    def imp(name, *a, **kw):
        if name == blocked:
            raise ImportError(f"No module named {name!r}")
        return real_import(name, *a, **kw)

    return imp


def test_scheme_dispatch_unchanged(fake_fsspec, tmp_path):
    """for_url keeps returning the right provider class per scheme."""
    assert isinstance(StorageProvider.for_url(str(tmp_path)),
                      LocalStorage)
    assert isinstance(StorageProvider.for_url(f"file://{tmp_path}"),
                      LocalStorage)
    assert isinstance(StorageProvider.for_url("memory://t1"),
                      MemoryStorage)
    assert isinstance(StorageProvider.for_url("gs://b/x"),
                      FsspecStorage)
    with pytest.raises(ValueError, match="unsupported storage scheme"):
        StorageProvider.for_url("ftp://nope/x")
