"""C++ host library vs numpy fallback parity (bit-exact where required).

The native library carries sharding-critical semantics (splitmix64, key
ranges), so these tests compare it directly against the pure-numpy
reference implementations on randomized inputs.
"""

import numpy as np
import pytest

from arroyo_tpu import native
from arroyo_tpu.types import _py_hash_u64, server_for_hash_array


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(42)


def test_native_loaded():
    # the image ships g++, so the library must build and load
    assert native.HAVE_NATIVE


def test_hash_u64_bit_exact(rng):
    x = rng.integers(0, 2**63, 100_000, dtype=np.uint64)
    x[:5] = [0, 1, 2**64 - 1, 2**63, 12345]
    np.testing.assert_array_equal(native.hash_u64(x), _py_hash_u64(x))


def test_hash_combine_bit_exact(rng):
    a = rng.integers(0, 2**63, 50_000, dtype=np.uint64)
    h = rng.integers(0, 2**63, 50_000, dtype=np.uint64)
    with np.errstate(over="ignore"):
        want = _py_hash_u64(a * np.uint64(31) + h)
    np.testing.assert_array_equal(native.hash_combine(a, h), want)


@pytest.mark.parametrize("n_parts", [1, 2, 3, 7, 16])
def test_partition_route_matches_reference(rng, n_parts):
    kh = rng.integers(0, 2**64, 20_000, dtype=np.uint64)
    kh[:3] = [0, 2**64 - 1, 2**63]
    dest, order, bounds = native.partition_route(kh, n_parts)
    np.testing.assert_array_equal(
        dest, server_for_hash_array(kh, n_parts).astype(np.int32))
    # order is a permutation, stable within each destination
    assert sorted(order) == list(range(len(kh)))
    for p in range(n_parts):
        seg = order[bounds[p]:bounds[p + 1]]
        assert (dest[seg] == p).all()
        assert (np.diff(seg) > 0).all()  # stability = ascending row index
    assert bounds[0] == 0 and bounds[-1] == len(kh)


def test_assign_bins_matches_numpy(rng):
    ts = rng.integers(0, 10**9, 30_000).astype(np.int64)
    slide, ring, thr = 1_000_000, 16, 250
    bins, live, n_live, lo, hi = native.assign_bins(ts, slide, ring, thr)
    abs_bins = ts // slide
    want_live = abs_bins >= thr
    np.testing.assert_array_equal(live, want_live)
    np.testing.assert_array_equal(bins, (abs_bins % ring).astype(np.int32))
    assert n_live == int(want_live.sum())
    assert lo == int(abs_bins[want_live].min())
    assert hi == int(abs_bins[want_live].max())


def test_assign_bins_negative_ts_floor_semantics():
    ts = np.array([-1, -1_000_000, -1_500_000, 0, 999_999], dtype=np.int64)
    bins, live, n_live, lo, hi = native.assign_bins(ts, 1_000_000, 8, None)
    abs_bins = ts // 1_000_000  # numpy floors
    np.testing.assert_array_equal(bins, (abs_bins % 8).astype(np.int32))
    assert lo == int(abs_bins.min()) and hi == int(abs_bins.max())


def test_assign_bins_all_dead():
    ts = np.arange(5, dtype=np.int64)
    bins, live, n_live, lo, hi = native.assign_bins(ts, 1, 8, 100)
    assert n_live == 0 and lo is None and hi is None


def test_collector_split_parity(rng):
    """partition_route drives the collector; segments must reassemble the
    batch exactly."""
    kh = rng.integers(0, 2**64, 5_000, dtype=np.uint64)
    for n in (2, 5):
        _, order, bounds = native.partition_route(kh, n)
        pieces = [order[bounds[p]:bounds[p + 1]] for p in range(n)]
        got = np.concatenate([kh[p] for p in pieces])
        assert sorted(got.tolist()) == sorted(kh.tolist())


def test_native_dir_matches_sorted_directory(rng):
    """NativeDir.insert agrees with the numpy sorted-array directory on
    slots, new-key order, and lookups across growth."""
    from arroyo_tpu.native import NativeDir

    d = NativeDir(16)
    # reference model
    seen = {}
    next_slot = 0
    for round_ in range(5):
        kh = rng.integers(0, 2**64, 3_000, dtype=np.uint64)
        kh = kh[rng.integers(0, 1_000, 3_000)]  # heavy duplicates
        slots, new_keys = d.insert(kh, next_slot)
        expect_new = []
        expect_slots = []
        for k in kh.tolist():
            if k not in seen:
                seen[k] = next_slot + len(expect_new)
                expect_new.append(k)
            expect_slots.append(seen[k])
        next_slot += len(expect_new)
        assert new_keys.tolist() == expect_new
        assert slots.tolist() == expect_slots
    probe = np.array(list(seen)[:100] + [1, 2, 3], dtype=np.uint64)
    got = d.lookup(probe)
    want = np.array([seen.get(int(k), -1) for k in probe], dtype=np.int64)
    np.testing.assert_array_equal(got, want)


def test_agg_cells_matches_preaggregate(rng):
    """Native (slot,bin)-cell aggregation is a lossless reordering of the
    lexsort+reduceat preaggregate path for every channel kind."""
    from arroyo_tpu.native import agg_cells
    from arroyo_tpu.ops.keyed_bins import preaggregate

    n = 4_000
    ring = 16
    slots = rng.integers(0, 200, n).astype(np.int64)
    bins = rng.integers(0, ring, n).astype(np.int32)
    kinds = ("sum", "min", "max", "count")
    vals = rng.random((len(kinds), n)).astype(np.float32)
    live = (rng.random(n) < 0.8)

    cs, cb, cc, cv = agg_cells(slots, bins, live, ring, vals, kinds)
    idx = live.nonzero()[0]
    es, eb, ec, ev = preaggregate(slots[idx], bins[idx], kinds, vals[:, idx])

    # same cells, possibly different order: compare as sorted tuples
    def canon(s, b, c, v):
        order = np.lexsort((b, s))
        return (s[order], b[order], c[order], v[:, order])

    cs2, cb2, cc2, cv2 = canon(cs, cb, cc, cv)
    es2, eb2, ec2, ev2 = canon(es, eb, ec, ev)
    np.testing.assert_array_equal(cs2, es2)
    np.testing.assert_array_equal(cb2, eb2)
    np.testing.assert_array_equal(cc2, ec2)
    np.testing.assert_allclose(cv2, ev2, rtol=1e-5)


def test_projection_pushdown_output_identical():
    """The planner-injected source projection must not change query
    results — only skip generating unused columns."""
    import json

    from arroyo_tpu.connectors.memory import clear_sink, sink_output
    from arroyo_tpu.engine.engine import LocalRunner
    from arroyo_tpu.sql import plan_sql
    from arroyo_tpu.types import Batch

    sql = """
    CREATE TABLE nexmark WITH (
      connector = 'nexmark', event_rate = '1000000', num_events = '20000',
      rate_limited = 'false', batch_size = '4096',
      base_time_micros = '1600000000000000'
    );
    SELECT bid.auction as auction,
           HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
           count(*) AS num
    FROM nexmark WHERE bid is not null GROUP BY 1, 2
    """

    def run(prog):
        clear_sink("results")
        LocalRunner(prog).run()
        rows = Batch.concat(sink_output("results"))
        return sorted(zip(rows.columns["auction"].tolist(),
                          rows.columns["window_start"].tolist(),
                          rows.columns["num"].tolist()))

    prog = plan_sql(sql)
    src_cfg = prog.sources()[0].operator.spec.config
    # event time rides the batch timestamp, so only the key + presence
    # columns are needed
    assert src_cfg.get("projection") == ["bid_auction", "event_type"]
    with_pushdown = run(prog)

    prog_full = plan_sql(sql)
    prog_full.sources()[0].operator.spec.config.pop("projection")
    without = run(prog_full)
    assert with_pushdown == without and len(with_pushdown) > 0


def test_projection_pushdown_struct_and_join_keep_columns():
    """A bare struct reference keeps the whole struct's columns; a join
    records both sides' column usage (reviewer-found leaks)."""
    from arroyo_tpu.sql import plan_sql

    # bare struct passthrough: bid's fields must survive pushdown
    prog = plan_sql("""
    CREATE TABLE nexmark WITH (connector = 'nexmark', num_events = '100',
                               rate_limited = 'false');
    SELECT bid FROM nexmark WHERE bid is not null
    """)
    proj = prog.sources()[0].operator.spec.config.get("projection")
    assert proj is not None
    assert {"bid_auction", "bid_bidder", "bid_price",
            "bid_datetime"} <= set(proj)

    # join: columns used only in SELECT resolve against the joined schema
    # and must still reach each side's source projection
    prog2 = plan_sql("""
    CREATE TABLE nexmark WITH (connector = 'nexmark', num_events = '100',
                               rate_limited = 'false');
    SELECT P.name as name, A.seller as seller
    FROM (SELECT person.name as name, person.id as id,
                 TUMBLE(INTERVAL '10' SECOND) as window
          FROM nexmark WHERE person is not null GROUP BY 1, 2, 3) P
    JOIN (SELECT auction.seller as seller,
                 TUMBLE(INTERVAL '10' SECOND) as window
          FROM nexmark WHERE auction is not null GROUP BY 1, 2) A
    ON P.id = A.seller and P.window = A.window
    """)
    projs = [n.operator.spec.config.get("projection")
             for n in prog2.sources()]
    assert any(p and "person_name" in p for p in projs)
    assert any(p and "auction_seller" in p for p in projs)
