"""C++ host library vs numpy fallback parity (bit-exact where required).

The native library carries sharding-critical semantics (splitmix64, key
ranges), so these tests compare it directly against the pure-numpy
reference implementations on randomized inputs.
"""

import numpy as np
import pytest

from arroyo_tpu import native
from arroyo_tpu.types import _py_hash_u64, server_for_hash_array


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(42)


def test_native_loaded():
    # the image ships g++, so the library must build and load
    assert native.HAVE_NATIVE


def test_hash_u64_bit_exact(rng):
    x = rng.integers(0, 2**63, 100_000, dtype=np.uint64)
    x[:5] = [0, 1, 2**64 - 1, 2**63, 12345]
    np.testing.assert_array_equal(native.hash_u64(x), _py_hash_u64(x))


def test_hash_combine_bit_exact(rng):
    a = rng.integers(0, 2**63, 50_000, dtype=np.uint64)
    h = rng.integers(0, 2**63, 50_000, dtype=np.uint64)
    with np.errstate(over="ignore"):
        want = _py_hash_u64(a * np.uint64(31) + h)
    np.testing.assert_array_equal(native.hash_combine(a, h), want)


@pytest.mark.parametrize("n_parts", [1, 2, 3, 7, 16])
def test_partition_route_matches_reference(rng, n_parts):
    kh = rng.integers(0, 2**64, 20_000, dtype=np.uint64)
    kh[:3] = [0, 2**64 - 1, 2**63]
    dest, order, bounds = native.partition_route(kh, n_parts)
    np.testing.assert_array_equal(
        dest, server_for_hash_array(kh, n_parts).astype(np.int32))
    # order is a permutation, stable within each destination
    assert sorted(order) == list(range(len(kh)))
    for p in range(n_parts):
        seg = order[bounds[p]:bounds[p + 1]]
        assert (dest[seg] == p).all()
        assert (np.diff(seg) > 0).all()  # stability = ascending row index
    assert bounds[0] == 0 and bounds[-1] == len(kh)


def test_assign_bins_matches_numpy(rng):
    ts = rng.integers(0, 10**9, 30_000).astype(np.int64)
    slide, ring, thr = 1_000_000, 16, 250
    bins, live, n_live, lo, hi = native.assign_bins(ts, slide, ring, thr)
    abs_bins = ts // slide
    want_live = abs_bins >= thr
    np.testing.assert_array_equal(live, want_live)
    np.testing.assert_array_equal(bins, (abs_bins % ring).astype(np.int32))
    assert n_live == int(want_live.sum())
    assert lo == int(abs_bins[want_live].min())
    assert hi == int(abs_bins[want_live].max())


def test_assign_bins_negative_ts_floor_semantics():
    ts = np.array([-1, -1_000_000, -1_500_000, 0, 999_999], dtype=np.int64)
    bins, live, n_live, lo, hi = native.assign_bins(ts, 1_000_000, 8, None)
    abs_bins = ts // 1_000_000  # numpy floors
    np.testing.assert_array_equal(bins, (abs_bins % 8).astype(np.int32))
    assert lo == int(abs_bins.min()) and hi == int(abs_bins.max())


def test_assign_bins_all_dead():
    ts = np.arange(5, dtype=np.int64)
    bins, live, n_live, lo, hi = native.assign_bins(ts, 1, 8, 100)
    assert n_live == 0 and lo is None and hi is None


def test_collector_split_parity(rng):
    """partition_route drives the collector; segments must reassemble the
    batch exactly."""
    kh = rng.integers(0, 2**64, 5_000, dtype=np.uint64)
    for n in (2, 5):
        _, order, bounds = native.partition_route(kh, n)
        pieces = [order[bounds[p]:bounds[p + 1]] for p in range(n)]
        got = np.concatenate([kh[p] for p in pieces])
        assert sorted(got.tolist()) == sorted(kh.tolist())
